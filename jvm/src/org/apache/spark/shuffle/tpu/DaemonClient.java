package org.apache.spark.shuffle.tpu;

import java.io.DataInputStream;
import java.io.DataOutputStream;
import java.io.IOException;
import java.net.Socket;
import java.nio.ByteBuffer;
import java.nio.ByteOrder;
import java.nio.charset.StandardCharsets;

/**
 * Client for the TPU shuffle daemon protocol (docs/SHIM_PROTOCOL.md).
 *
 * Frame layout (little-endian): u32 op | u64 headerLen | u64 bodyLen | header | body.
 * Control headers are JSON; the batched fetch (op 3/4) uses the binary batch
 * header of the AM protocol. The Python twin of this class is
 * sparkucx_tpu.shuffle.daemon.DaemonClient, which is covered by tests.
 */
public final class DaemonClient implements AutoCloseable {
  public static final int OP_CREATE_SHUFFLE = 16;
  public static final int OP_OPEN_MAP_WRITER = 17;
  public static final int OP_WRITE_PARTITION = 18;
  public static final int OP_COMMIT_MAP = 19;
  public static final int OP_RUN_EXCHANGE = 20;
  public static final int OP_REMOVE_SHUFFLE = 21;
  public static final int OP_FETCH = 3;          // AM FetchBlockReq
  public static final int OP_FETCH_ACK = 4;      // AM FetchBlockReqAck

  /** Shared frame ceiling — MUST equal MAX_FRAME_BYTES in
   * sparkucx_tpu/core/definitions.py (the daemon drops any connection whose
   * frame claims more; fixture 10_oversized_frame.bin pins both sides). */
  public static final long MAX_FRAME_BYTES = 1L << 31;

  /** True when a frame header's declared sizes exceed the shared ceiling —
   * the reject condition both the daemon and this client apply before
   * allocating anything.  Written without the naive sum so two huge positive
   * lengths cannot wrap the long negative and sneak past the guard. */
  static boolean frameTooLarge(long headerLen, long bodyLen) {
    return headerLen < 0 || bodyLen < 0
        || headerLen > MAX_FRAME_BYTES
        || bodyLen > MAX_FRAME_BYTES - headerLen;
  }

  private final Socket socket;
  private final DataOutputStream out;
  private final DataInputStream in;

  public DaemonClient(String host, int port) throws IOException {
    this.socket = new Socket(host, port);
    this.socket.setTcpNoDelay(true);
    this.out = new DataOutputStream(socket.getOutputStream());
    this.in = new DataInputStream(socket.getInputStream());
  }

  /**
   * Pure frame encoder: u32 op | u64 headerLen | u64 bodyLen | header | body,
   * little-endian. Exposed static so the golden wire fixtures
   * (jvm/fixtures, FixtureCheck.java, tests/test_daemon.py) byte-check the
   * exact encoding without a socket.
   */
  static byte[] encodeFrame(int op, String jsonHeader, byte[] body) {
    byte[] header = jsonHeader == null ? new byte[0] : jsonHeader.getBytes(StandardCharsets.UTF_8);
    byte[] payload = body == null ? new byte[0] : body;
    ByteBuffer bb = ByteBuffer.allocate(20 + header.length + payload.length)
        .order(ByteOrder.LITTLE_ENDIAN);
    bb.putInt(op).putLong(header.length).putLong(payload.length);
    bb.put(header).put(payload);
    return bb.array();
  }

  // JSON header builders — the exact bytes each op puts on the wire, shared by
  // the client methods and FixtureCheck so a format drift fails the fixtures.
  static String headerCreateShuffle(int shuffleId, int numMappers, int numReducers) {
    return String.format("{\"shuffle_id\": %d, \"num_mappers\": %d, \"num_reducers\": %d}",
        shuffleId, numMappers, numReducers);
  }

  static String headerOpenMapWriter(int shuffleId, int mapId) {
    return String.format("{\"shuffle_id\": %d, \"map_id\": %d}", shuffleId, mapId);
  }

  static String headerWritePartition(int writer, int reduceId) {
    return String.format("{\"writer\": %d, \"reduce_id\": %d}", writer, reduceId);
  }

  static String headerCommitMap(int writer) {
    return String.format("{\"writer\": %d}", writer);
  }

  static String headerShuffleId(int shuffleId) {
    return String.format("{\"shuffle_id\": %d}", shuffleId);
  }

  /** Batched fetch request body: u64 tag | u32 count | (i32 shuffle, i32 map, i32 reduce)*n. */
  static byte[] fetchRequestBody(long tag, int shuffleId, int[] mapIds, int[] reduceIds) {
    int n = mapIds.length;
    ByteBuffer req = ByteBuffer.allocate(12 + 12 * n).order(ByteOrder.LITTLE_ENDIAN);
    req.putLong(tag);
    req.putInt(n);
    for (int i = 0; i < n; i++) {
      req.putInt(shuffleId).putInt(mapIds[i]).putInt(reduceIds[i]);
    }
    return req.array();
  }

  private synchronized byte[][] call(int op, String jsonHeader, byte[] body) throws IOException {
    out.write(encodeFrame(op, jsonHeader, body));
    out.flush();
    byte[] frameHeader = new byte[20];
    in.readFully(frameHeader);
    ByteBuffer bb = ByteBuffer.wrap(frameHeader).order(ByteOrder.LITTLE_ENDIAN);
    bb.getInt(); // reply op
    long hlenL = bb.getLong();
    long blenL = bb.getLong();
    // the shared wire ceiling, plus the JVM's own array bound: a frame AT
    // the 2 GiB limit is wire-legal but not int-addressable here, so it gets
    // the same controlled close instead of a NegativeArraySizeException
    if (frameTooLarge(hlenL, blenL)
        || hlenL > Integer.MAX_VALUE || blenL > Integer.MAX_VALUE) {
      socket.close();
      throw new IOException(
          "reply frame too large (header " + hlenL + " + body " + blenL
              + " B vs limit " + MAX_FRAME_BYTES + ")");
    }
    int hlen = (int) hlenL;
    int blen = (int) blenL;
    byte[] replyHeader = new byte[hlen];
    byte[] replyBody = new byte[blen];
    in.readFully(replyHeader);
    in.readFully(replyBody);
    return new byte[][] {replyHeader, replyBody};
  }

  private byte[][] controlCall(int op, String jsonHeader, byte[] body) throws IOException {
    byte[][] reply = call(op, jsonHeader, body);
    String ack = new String(reply[0], StandardCharsets.UTF_8);
    if (!ack.contains("\"ok\": true") && !ack.contains("\"ok\":true")) {
      throw new IOException("daemon error: " + ack);
    }
    return reply;
  }

  public void createShuffle(int shuffleId, int numMappers, int numReducers) throws IOException {
    controlCall(OP_CREATE_SHUFFLE, headerCreateShuffle(shuffleId, numMappers, numReducers), null);
  }

  public int openMapWriter(int shuffleId, int mapId) throws IOException {
    byte[][] reply = controlCall(OP_OPEN_MAP_WRITER, headerOpenMapWriter(shuffleId, mapId), null);
    String ack = new String(reply[0], StandardCharsets.UTF_8);
    // ack is json.dumps output: {"ok": true, "writer": N} — skip the space
    // after the colon, then take the digit run
    int p = ack.indexOf("\"writer\":") + 9;
    while (p < ack.length() && !Character.isDigit(ack.charAt(p))) p++;
    int q = p;
    while (q < ack.length() && Character.isDigit(ack.charAt(q))) q++;
    if (p == q) throw new IOException("malformed OpenMapWriter ack: " + ack);
    return Integer.parseInt(ack.substring(p, q));
  }

  public void writePartition(int writer, int reduceId, byte[] data, int off, int len)
      throws IOException {
    byte[] chunk = new byte[len];
    System.arraycopy(data, off, chunk, 0, len);
    controlCall(OP_WRITE_PARTITION, headerWritePartition(writer, reduceId), chunk);
  }

  public long[] commitMap(int writer) throws IOException {
    byte[][] reply = controlCall(OP_COMMIT_MAP, headerCommitMap(writer), null);
    ByteBuffer bb = ByteBuffer.wrap(reply[1]).order(ByteOrder.LITTLE_ENDIAN);
    long[] lengths = new long[reply[1].length / 8];
    for (int i = 0; i < lengths.length; i++) lengths[i] = bb.getLong();
    return lengths;
  }

  public void runExchange(int shuffleId) throws IOException {
    controlCall(OP_RUN_EXCHANGE, headerShuffleId(shuffleId), null);
  }

  /** Batched fetch: returns one byte[] per requested block; null marks a miss. */
  public byte[][] fetchBlocks(int shuffleId, int[] mapIds, int[] reduceIds) throws IOException {
    byte[][] reply = call(OP_FETCH, null, fetchRequestBody(0L, shuffleId, mapIds, reduceIds));
    ByteBuffer hdr = ByteBuffer.wrap(reply[0]).order(ByteOrder.LITTLE_ENDIAN);
    hdr.getLong();             // tag echo
    int count = hdr.getInt();
    long[] sizes = new long[count];
    for (int i = 0; i < count; i++) sizes[i] = hdr.getLong();
    byte[][] blocks = new byte[count][];
    int pos = 0;
    for (int i = 0; i < count; i++) {
      if (sizes[i] < 0) { blocks[i] = null; continue; }
      blocks[i] = new byte[(int) sizes[i]];
      System.arraycopy(reply[1], pos, blocks[i], 0, (int) sizes[i]);
      pos += (int) sizes[i];
    }
    return blocks;
  }

  public void removeShuffle(int shuffleId) throws IOException {
    controlCall(OP_REMOVE_SHUFFLE, headerShuffleId(shuffleId), null);
  }

  @Override
  public void close() throws IOException {
    socket.close();
  }
}
