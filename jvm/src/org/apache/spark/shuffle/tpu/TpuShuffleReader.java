package org.apache.spark.shuffle.tpu;

import java.io.ByteArrayInputStream;
import java.io.IOException;
import java.io.SequenceInputStream;
import java.util.ArrayList;
import java.util.Collections;
import java.util.List;

import org.apache.spark.InterruptibleIterator;
import org.apache.spark.TaskContext;
import org.apache.spark.serializer.DeserializationStream;
import org.apache.spark.serializer.SerializerInstance;
import org.apache.spark.shuffle.ShuffleReadMetricsReporter;
import org.apache.spark.shuffle.ShuffleReader;

import scala.Product2;
import scala.collection.Iterator;

/**
 * Reduce-side reader: batched OP_FETCH of every (map, reduce) block in
 * [startPartition, endPartition) x [startMapIndex, endMapIndex), then the
 * dependency serializer's deserialization stream — the reader pipeline of
 * compat/spark_3_0/UcxShuffleReader.scala:137-199 with the daemon replacing the
 * ShuffleBlockFetcherIterator + UcxShuffleClient pair. The map range is AQE's
 * partial-map read contract (endMapIndex == Integer.MAX_VALUE means all maps);
 * ignoring it would return data from maps outside the requested range.
 * Aggregation/ordering are left to Spark (the dependency's aggregator runs
 * above the reader in 3.x).
 */
public class TpuShuffleReader<K, C> implements ShuffleReader<K, C> {
  private final DaemonClient daemon;
  private final TpuShuffleManager.TpuShuffleHandle<K, ?, C> handle;
  private final int startMapIndex;
  private final int endMapIndex;
  private final int startPartition;
  private final int endPartition;
  private final ShuffleReadMetricsReporter metrics;

  public TpuShuffleReader(
      DaemonClient daemon, TpuShuffleManager.TpuShuffleHandle<K, ?, C> handle,
      int startMapIndex, int endMapIndex,
      int startPartition, int endPartition, ShuffleReadMetricsReporter metrics) {
    this.daemon = daemon;
    this.handle = handle;
    this.startMapIndex = startMapIndex;
    this.endMapIndex = endMapIndex;
    this.startPartition = startPartition;
    this.endPartition = endPartition;
    this.metrics = metrics;
  }

  @Override
  @SuppressWarnings("unchecked")
  public Iterator<Product2<K, C>> read() {
    try {
      int mapStart = Math.max(0, startMapIndex);
      int mapEnd = Math.min(handle.numMaps, endMapIndex);  // MAX_VALUE -> all maps
      int numMaps = Math.max(0, mapEnd - mapStart);
      List<ByteArrayInputStream> chunks = new ArrayList<>();
      long t0 = System.nanoTime();
      for (int p = startPartition; p < endPartition; p++) {
        int[] mapIds = new int[numMaps];
        int[] reduceIds = new int[numMaps];
        for (int m = 0; m < numMaps; m++) {
          mapIds[m] = mapStart + m;
          reduceIds[m] = p;
        }
        byte[][] blocks = daemon.fetchBlocks(handle.shuffleId(), mapIds, reduceIds);
        for (byte[] b : blocks) {
          if (b != null && b.length > 0) {
            chunks.add(new ByteArrayInputStream(b));
            metrics.incRemoteBytesRead(b.length);
            metrics.incRemoteBlocksFetched(1);
          }
        }
      }
      metrics.incFetchWaitTime((System.nanoTime() - t0) / 1_000_000);
      SerializerInstance ser = handle.dependency.serializer().newInstance();
      SequenceInputStream all =
          new SequenceInputStream(Collections.enumeration(chunks));
      DeserializationStream stream = ser.deserializeStream(all);
      return (Iterator<Product2<K, C>>) (Iterator<?>)
          new InterruptibleIterator<>(TaskContext.get(), stream.asKeyValueIterator());
    } catch (IOException e) {
      throw new RuntimeException("TPU shuffle fetch failed", e);
    }
  }
}
