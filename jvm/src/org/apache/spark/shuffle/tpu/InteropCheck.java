package org.apache.spark.shuffle.tpu;

import java.util.Arrays;

/**
 * Live Java <-> Python interop gate: drives a running shuffle daemon
 * (python -m sparkucx_tpu.shuffle.daemon) through a full
 * create -> write -> commit -> exchange -> fetch -> remove cycle with the real
 * {@link DaemonClient}, asserting every decoded value. This covers the DECODE
 * side of the protocol that the byte-fixture checks cannot (FixtureCheck only
 * proves encoding) — a daemon ack format drift fails here.
 *
 * Usage: java org.apache.spark.shuffle.tpu.InteropCheck [host] [port]
 */
public final class InteropCheck {
  static void check(boolean cond, String what) {
    if (!cond) {
      System.err.println("FAIL: " + what);
      System.exit(1);
    }
    System.out.println("ok: " + what);
  }

  public static void main(String[] args) throws Exception {
    String host = args.length > 0 ? args[0] : "127.0.0.1";
    int port = args.length > 1 ? Integer.parseInt(args[1]) : 1338;
    int sid = 42, M = 2, R = 3;

    try (DaemonClient c = new DaemonClient(host, port)) {
      c.createShuffle(sid, M, R);

      byte[][] payloads = new byte[M][];
      for (int m = 0; m < M; m++) {
        int w = c.openMapWriter(sid, m);
        check(w == m, "openMapWriter handle " + m);
        payloads[m] = new byte[100 * (m + 1)];
        Arrays.fill(payloads[m], (byte) (m + 1));
        // stream partition 1 in two chunks (repeated WRITE_PARTITION)
        c.writePartition(w, 1, payloads[m], 0, 50);
        c.writePartition(w, 1, payloads[m], 50, payloads[m].length - 50);
        long[] lengths = c.commitMap(w);
        check(lengths.length == R, "commit lengths count map " + m);
        check(lengths[1] == payloads[m].length, "commit length map " + m);
        check(lengths[0] == 0 && lengths[2] == 0, "empty partitions map " + m);
      }

      c.runExchange(sid);

      byte[][] blocks = c.fetchBlocks(sid, new int[] {0, 1}, new int[] {1, 1});
      check(blocks.length == 2, "fetch count");
      check(Arrays.equals(blocks[0], payloads[0]), "fetch map 0 bytes");
      check(Arrays.equals(blocks[1], payloads[1]), "fetch map 1 bytes");

      byte[][] miss = c.fetchBlocks(sid, new int[] {0}, new int[] {2});
      check(miss[0] != null && miss[0].length == 0, "empty partition fetch");

      c.removeShuffle(sid);
      System.out.println("interop cycle complete");
    }
  }
}
