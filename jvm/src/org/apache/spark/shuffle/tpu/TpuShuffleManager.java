package org.apache.spark.shuffle.tpu;

import java.io.IOException;
import java.io.OutputStream;
import java.io.ByteArrayInputStream;
import java.io.InputStream;
import java.util.Iterator;

import org.apache.spark.ShuffleDependency;
import org.apache.spark.SparkConf;
import org.apache.spark.TaskContext;
import org.apache.spark.shuffle.ShuffleBlockResolver;
import org.apache.spark.shuffle.ShuffleHandle;
import org.apache.spark.shuffle.ShuffleManager;
import org.apache.spark.shuffle.ShuffleReadMetricsReporter;
import org.apache.spark.shuffle.ShuffleReader;
import org.apache.spark.shuffle.ShuffleWriteMetricsReporter;
import org.apache.spark.shuffle.ShuffleWriter;
import org.apache.spark.storage.BlockManagerId;

/**
 * The {@code spark.shuffle.manager} entry point delegating the shuffle data
 * plane to the TPU runtime daemon (sparkucx_tpu.shuffle.daemon).
 *
 * Role parity with the reference plugin (its class is named in
 * spark.shuffle.manager the same way — compat/spark_3_0/UcxShuffleManager.scala:25):
 * registerShuffle forwards dimensions to the daemon, getWriter streams partition
 * bytes over OP_WRITE_PARTITION (the staged-store write path), and getReader
 * pulls post-exchange blocks with the batched OP_FETCH — the daemon side of all
 * of these is exercised by tests/test_daemon.py.
 *
 * NOTE: compiles against spark-core 3.x (provided); see jvm/README.md. The
 * generics/SPI surface here intentionally stays minimal — serialization uses the
 * dependency's serializer exactly as stock Spark writers do.
 */
public class TpuShuffleManager implements ShuffleManager {
  private final SparkConf conf;
  private volatile DaemonClient client;

  public TpuShuffleManager(SparkConf conf) {
    this.conf = conf;
  }

  private DaemonClient daemon() throws IOException {
    DaemonClient c = client;
    if (c == null) {
      synchronized (this) {
        if (client == null) {
          String host = conf.get("spark.shuffle.tpu.daemon.host", "127.0.0.1");
          int port = conf.getInt("spark.shuffle.tpu.daemon.port", 1338);
          client = new DaemonClient(host, port);
        }
        c = client;
      }
    }
    return c;
  }

  static final class TpuShuffleHandle<K, V, C> extends ShuffleHandle {
    final ShuffleDependency<K, V, C> dependency;
    final int numMaps;

    TpuShuffleHandle(int shuffleId, int numMaps, ShuffleDependency<K, V, C> dependency) {
      super(shuffleId);
      this.numMaps = numMaps;
      this.dependency = dependency;
    }
  }

  @Override
  public <K, V, C> ShuffleHandle registerShuffle(
      int shuffleId, ShuffleDependency<K, V, C> dependency) {
    try {
      daemon().createShuffle(
          shuffleId,
          dependency.rdd().getNumPartitions(),
          dependency.partitioner().numPartitions());
    } catch (IOException e) {
      throw new RuntimeException("TPU shuffle daemon unreachable", e);
    }
    return new TpuShuffleHandle<>(shuffleId, dependency.rdd().getNumPartitions(), dependency);
  }

  @Override
  @SuppressWarnings("unchecked")
  public <K, V> ShuffleWriter<K, V> getWriter(
      ShuffleHandle handle, long mapId, TaskContext context,
      ShuffleWriteMetricsReporter metrics) {
    TpuShuffleHandle<K, V, ?> h = (TpuShuffleHandle<K, V, ?>) handle;
    // Spark 2.4 passes the map partition index here; Spark 3.x passes the
    // globally unique long task attempt id. The daemon's map slot is the
    // 0..numMaps-1 INDEX, which in both generations is context.partitionId()
    // — the same re-keying the reference applies to survive the 2.4->3.0
    // mapId change (compat/spark_3_0/UcxShuffleBlockResolver.scala:28-39
    // registers by partitionId, "not Spark 3's unique mapId"). The long mapId
    // still travels to MapStatus, which 3.x keys on (jvm/README.md compat
    // section).
    try {
      return new TpuShuffleWriter<>(daemon(), h, context.partitionId(), mapId, metrics);
    } catch (IOException e) {
      throw new RuntimeException(e);
    }
  }

  @Override
  @SuppressWarnings("unchecked")
  public <K, C> ShuffleReader<K, C> getReader(
      ShuffleHandle handle, int startMapIndex, int endMapIndex,
      int startPartition, int endPartition, TaskContext context,
      ShuffleReadMetricsReporter metrics) {
    TpuShuffleHandle<K, ?, C> h = (TpuShuffleHandle<K, ?, C>) handle;
    try {
      return new TpuShuffleReader<>(
          daemon(), h, startMapIndex, endMapIndex, startPartition, endPartition, metrics);
    } catch (IOException e) {
      throw new RuntimeException(e);
    }
  }

  @Override
  public boolean unregisterShuffle(int shuffleId) {
    try {
      daemon().removeShuffle(shuffleId);
      return true;
    } catch (IOException e) {
      return false;
    }
  }

  @Override
  public ShuffleBlockResolver shuffleBlockResolver() {
    // Blocks live in the daemon; local disk resolution is never used. Mirrors
    // the reference disabling readHostLocalDisk (buildlib/test.sh:123).
    return null;
  }

  @Override
  public void stop() {
    DaemonClient c = client;
    if (c != null) {
      try {
        c.close();
      } catch (IOException ignored) {
      }
    }
  }
}
