package org.apache.spark.shuffle.tpu;

import java.io.ByteArrayOutputStream;
import java.io.IOException;
import java.util.Iterator;

import org.apache.spark.scheduler.MapStatus;
import org.apache.spark.scheduler.MapStatus$;
import org.apache.spark.SparkEnv;
import org.apache.spark.serializer.SerializationStream;
import org.apache.spark.serializer.SerializerInstance;
import org.apache.spark.shuffle.ShuffleWriteMetricsReporter;
import org.apache.spark.shuffle.ShuffleWriter;
import org.apache.spark.storage.BlockManagerId;

import scala.Option;
import scala.Product2;
import scala.collection.JavaConverters;

/**
 * Map-side writer: partitions records with the dependency's partitioner,
 * serializes each bucket with the dependency's serializer, and streams buckets
 * to the daemon in increasing partition order (the staged store enforces the
 * same sequential protocol the reference writer does,
 * NvkvShuffleMapOutputWriter.scala:108).
 */
public class TpuShuffleWriter<K, V> extends ShuffleWriter<K, V> {
  private final DaemonClient daemon;
  private final TpuShuffleManager.TpuShuffleHandle<K, V, ?> handle;
  /** Daemon map slot: the map task's 0..numMaps-1 partition index. */
  private final int mapIndex;
  /** Spark's mapId as handed to getWriter — the long task attempt id on 3.x,
   * the map index on 2.4; MapStatus is keyed by it either way. */
  private final long mapId;
  private final ShuffleWriteMetricsReporter metrics;
  private long[] partitionLengths;
  private boolean stopped = false;

  public TpuShuffleWriter(
      DaemonClient daemon, TpuShuffleManager.TpuShuffleHandle<K, V, ?> handle,
      int mapIndex, long mapId, ShuffleWriteMetricsReporter metrics) {
    this.daemon = daemon;
    this.handle = handle;
    this.mapIndex = mapIndex;
    this.mapId = mapId;
    this.metrics = metrics;
  }

  @Override
  public void write(scala.collection.Iterator<Product2<K, V>> records) throws IOException {
    int numPartitions = handle.dependency.partitioner().numPartitions();
    SerializerInstance ser = handle.dependency.serializer().newInstance();

    // Bucket serialize: one buffer per partition, then ship in ascending order.
    ByteArrayOutputStream[] buckets = new ByteArrayOutputStream[numPartitions];
    SerializationStream[] streams = new SerializationStream[numPartitions];
    Iterator<Product2<K, V>> it = JavaConverters.asJavaIterator(records);
    while (it.hasNext()) {
      Product2<K, V> rec = it.next();
      int p = handle.dependency.partitioner().getPartition(rec._1());
      if (buckets[p] == null) {
        buckets[p] = new ByteArrayOutputStream();
        streams[p] = ser.serializeStream(buckets[p]);
      }
      streams[p].writeKey(rec._1(), null);
      streams[p].writeValue(rec._2(), null);
      metrics.incRecordsWritten(1);
    }

    int writer = daemon.openMapWriter(handle.shuffleId(), mapIndex);
    for (int p = 0; p < numPartitions; p++) {
      if (buckets[p] == null) continue;
      streams[p].close();
      byte[] data = buckets[p].toByteArray();
      daemon.writePartition(writer, p, data, 0, data.length);
      metrics.incBytesWritten(data.length);
    }
    partitionLengths = daemon.commitMap(writer);
  }

  @Override
  public Option<MapStatus> stop(boolean success) {
    if (stopped) return Option.empty();
    stopped = true;
    if (!success || partitionLengths == null) return Option.empty();
    BlockManagerId id = SparkEnv.get().blockManager().shuffleServerId();
    return Option.apply(MapStatus$.MODULE$.apply(id, partitionLengths, mapId));
  }
}
