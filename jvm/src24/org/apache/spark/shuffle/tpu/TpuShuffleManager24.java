package org.apache.spark.shuffle.tpu;

import java.io.IOException;

import org.apache.spark.ShuffleDependency;
import org.apache.spark.SparkConf;
import org.apache.spark.TaskContext;
import org.apache.spark.shuffle.ShuffleBlockResolver;
import org.apache.spark.shuffle.ShuffleHandle;
import org.apache.spark.shuffle.ShuffleManager;
import org.apache.spark.shuffle.ShuffleReadMetricsReporter;
import org.apache.spark.shuffle.ShuffleReader;
import org.apache.spark.shuffle.ShuffleWriteMetricsReporter;
import org.apache.spark.shuffle.ShuffleWriter;

/**
 * The {@code spark.shuffle.manager} entry point for SPARK 2.4 — the analogue
 * of the reference's {@code compat/spark_2_4/UcxShuffleManager.scala:21-35},
 * compiled against the 2.4-signature {@link ShuffleManager} stub
 * (jvm/stubs24) in its own CI leg.
 *
 * The daemon protocol is generation-agnostic by construction (jvm/README.md
 * "Spark 2.4 vs 3.x"), so this class is a signature adapter over the SAME
 * machinery the 3.x shim uses:
 *
 * <ul>
 *   <li>{@code registerShuffle(id, numMaps, dep)} — 2.4 hands numMaps
 *       explicitly; forwarded to the daemon instead of being derived from
 *       the RDD;
 *   <li>{@code getWriter(handle, mapId int, ctx)} — 2.4's mapId IS the map
 *       partition index, exactly what the daemon's map slot wants (the
 *       re-keying note in TpuShuffleManager.getWriter);
 *   <li>{@code getReader(handle, startPartition, endPartition, ctx)} — no
 *       AQE map range on 2.4: the full range {@code [0, numMaps)}, no
 *       metrics reporters (no-op reporters are supplied so the shared
 *       writer/reader classes keep their accounting calls).
 * </ul>
 */
public class TpuShuffleManager24 implements ShuffleManager {
  private final SparkConf conf;
  private volatile DaemonClient client;

  public TpuShuffleManager24(SparkConf conf) {
    this.conf = conf;
  }

  private DaemonClient daemon() throws IOException {
    DaemonClient c = client;
    if (c == null) {
      synchronized (this) {
        if (client == null) {
          String host = conf.get("spark.shuffle.tpu.daemon.host", "127.0.0.1");
          int port = conf.getInt("spark.shuffle.tpu.daemon.port", 1338);
          client = new DaemonClient(host, port);
        }
        c = client;
      }
    }
    return c;
  }

  /** 2.4 has no separate read/write metrics reporter plumbing on this SPI —
   * the shared writer/reader classes get no-op sinks. */
  static final class NoopWriteMetrics implements ShuffleWriteMetricsReporter {
    @Override public void incBytesWritten(long v) {}
    @Override public void incRecordsWritten(long v) {}
  }

  static final class NoopReadMetrics implements ShuffleReadMetricsReporter {
    @Override public void incRemoteBlocksFetched(long v) {}
    @Override public void incRemoteBytesRead(long v) {}
    @Override public void incFetchWaitTime(long v) {}
  }

  @Override
  public <K, V, C> ShuffleHandle registerShuffle(
      int shuffleId, int numMaps, ShuffleDependency<K, V, C> dependency) {
    try {
      daemon().createShuffle(
          shuffleId, numMaps, dependency.partitioner().numPartitions());
    } catch (IOException e) {
      throw new RuntimeException("TPU shuffle daemon unreachable", e);
    }
    return new TpuShuffleManager.TpuShuffleHandle<>(shuffleId, numMaps, dependency);
  }

  @Override
  @SuppressWarnings("unchecked")
  public <K, V> ShuffleWriter<K, V> getWriter(
      ShuffleHandle handle, int mapId, TaskContext context) {
    TpuShuffleManager.TpuShuffleHandle<K, V, ?> h =
        (TpuShuffleManager.TpuShuffleHandle<K, V, ?>) handle;
    // 2.4's int mapId is already the 0..numMaps-1 index the daemon keys on;
    // it also serves as the MapStatus id on this generation.
    try {
      return new TpuShuffleWriter<>(daemon(), h, mapId, mapId, new NoopWriteMetrics());
    } catch (IOException e) {
      throw new RuntimeException(e);
    }
  }

  @Override
  @SuppressWarnings("unchecked")
  public <K, C> ShuffleReader<K, C> getReader(
      ShuffleHandle handle, int startPartition, int endPartition, TaskContext context) {
    TpuShuffleManager.TpuShuffleHandle<K, ?, C> h =
        (TpuShuffleManager.TpuShuffleHandle<K, ?, C>) handle;
    try {
      // no AQE on 2.4: always the full map range
      return new TpuShuffleReader<>(
          daemon(), h, 0, Integer.MAX_VALUE, startPartition, endPartition,
          new NoopReadMetrics());
    } catch (IOException e) {
      throw new RuntimeException(e);
    }
  }

  @Override
  public boolean unregisterShuffle(int shuffleId) {
    try {
      daemon().removeShuffle(shuffleId);
      return true;
    } catch (IOException e) {
      return false;
    }
  }

  @Override
  public ShuffleBlockResolver shuffleBlockResolver() {
    return null;  // blocks live in the daemon (TpuShuffleManager's rationale)
  }

  @Override
  public void stop() {
    DaemonClient c = client;
    if (c != null) {
      try {
        c.close();
      } catch (IOException ignored) {
      }
    }
  }
}
