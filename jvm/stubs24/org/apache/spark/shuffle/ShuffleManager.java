package org.apache.spark.shuffle;

import org.apache.spark.ShuffleDependency;
import org.apache.spark.TaskContext;

/**
 * Compile-only stub of the SPARK 2.4 ShuffleManager SPI — the signature set
 * the reference's compat/spark_2_4 tree overrides
 * (compat/spark_2_4/UcxShuffleManager.scala:21-35):
 *
 * <ul>
 *   <li>{@code registerShuffle} takes an explicit {@code numMaps};
 *   <li>{@code getWriter}'s mapId is the {@code int} map partition index
 *       (3.x made it a {@code long} task attempt id);
 *   <li>{@code getReader} has no map range (AQE) and no metrics reporter
 *       parameters.
 * </ul>
 *
 * Compiled INSTEAD OF the 3.x stub (same fully-qualified name) for the
 * jvm/src24 tree — classpath order in scripts/run_integration.sh and
 * .github/workflows/ci.yml puts this stub first for that compile.  All other
 * SPI stubs (ShuffleWriter, ShuffleReader, ShuffleHandle, ...) are shared:
 * those surfaces did not change shape across the generations.
 */
public interface ShuffleManager {
  <K, V, C> ShuffleHandle registerShuffle(
      int shuffleId, int numMaps, ShuffleDependency<K, V, C> dependency);
  <K, V> ShuffleWriter<K, V> getWriter(ShuffleHandle handle, int mapId, TaskContext context);
  <K, C> ShuffleReader<K, C> getReader(
      ShuffleHandle handle, int startPartition, int endPartition, TaskContext context);
  boolean unregisterShuffle(int shuffleId);
  ShuffleBlockResolver shuffleBlockResolver();
  void stop();
}
