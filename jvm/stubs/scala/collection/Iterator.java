package scala.collection;

/** Compile-only stub declaring only the members the shim touches (see the
 * org.apache.spark.SparkConf stub header). */
public interface Iterator<A> {
  boolean hasNext();
  A next();
}
