package scala.collection;

/** Compile-only stub of the static-forwarder surface (see the
 * org.apache.spark.SparkConf stub header). */
public final class JavaConverters {
  public static <A> java.util.Iterator<A> asJavaIterator(scala.collection.Iterator<A> it) {
    throw new UnsupportedOperationException("stub");
  }
}
