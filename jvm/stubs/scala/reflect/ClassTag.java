package scala.reflect;

/** Compile-only stub (see the org.apache.spark.SparkConf stub header). */
public interface ClassTag<T> {}
