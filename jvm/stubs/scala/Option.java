package scala;

/** Compile-only stub of scala.Option's static-forwarder surface (see the
 * org.apache.spark.SparkConf stub header). */
public abstract class Option<A> {
  public static <A> Option<A> empty() { throw new UnsupportedOperationException("stub"); }
  public static <A> Option<A> apply(A value) { throw new UnsupportedOperationException("stub"); }
}
