package scala;

/** Compile-only stub (see the org.apache.spark.SparkConf stub header). */
public interface Product2<T1, T2> {
  T1 _1();
  T2 _2();
}
