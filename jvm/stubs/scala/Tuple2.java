package scala;

/** Compile-only stub (see the org.apache.spark.SparkConf stub header). */
public class Tuple2<T1, T2> implements Product2<T1, T2> {
  @Override public T1 _1() { throw new UnsupportedOperationException("stub"); }
  @Override public T2 _2() { throw new UnsupportedOperationException("stub"); }
}
