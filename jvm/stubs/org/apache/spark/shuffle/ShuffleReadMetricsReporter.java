package org.apache.spark.shuffle;

/** Compile-only stub (see SparkConf stub header). */
public interface ShuffleReadMetricsReporter {
  void incRemoteBytesRead(long v);
  void incRemoteBlocksFetched(long v);
  void incFetchWaitTime(long v);
}
