package org.apache.spark.shuffle;

import org.apache.spark.ShuffleDependency;
import org.apache.spark.TaskContext;

/** Compile-only stub of the Spark 3.2+ ShuffleManager SPI (see SparkConf stub
 * header). */
public interface ShuffleManager {
  <K, V, C> ShuffleHandle registerShuffle(int shuffleId, ShuffleDependency<K, V, C> dependency);
  <K, V> ShuffleWriter<K, V> getWriter(
      ShuffleHandle handle, long mapId, TaskContext context, ShuffleWriteMetricsReporter metrics);
  <K, C> ShuffleReader<K, C> getReader(
      ShuffleHandle handle, int startMapIndex, int endMapIndex,
      int startPartition, int endPartition, TaskContext context,
      ShuffleReadMetricsReporter metrics);
  boolean unregisterShuffle(int shuffleId);
  ShuffleBlockResolver shuffleBlockResolver();
  void stop();
}
