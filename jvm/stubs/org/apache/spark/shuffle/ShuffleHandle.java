package org.apache.spark.shuffle;

/** Compile-only stub (see SparkConf stub header). */
public abstract class ShuffleHandle {
  private final int shuffleId;
  public ShuffleHandle(int shuffleId) { this.shuffleId = shuffleId; }
  public int shuffleId() { return shuffleId; }
}
