package org.apache.spark.shuffle;

/** Compile-only stub (see SparkConf stub header). */
public interface ShuffleReader<K, C> {
  scala.collection.Iterator<scala.Product2<K, C>> read();
}
