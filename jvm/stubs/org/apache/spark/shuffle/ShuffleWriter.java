package org.apache.spark.shuffle;

import java.io.IOException;
import org.apache.spark.scheduler.MapStatus;

/** Compile-only stub (see SparkConf stub header). */
public abstract class ShuffleWriter<K, V> {
  public abstract void write(scala.collection.Iterator<scala.Product2<K, V>> records) throws IOException;
  public abstract scala.Option<MapStatus> stop(boolean success);
}
