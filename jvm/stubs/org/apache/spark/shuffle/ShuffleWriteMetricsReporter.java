package org.apache.spark.shuffle;

/** Compile-only stub (see SparkConf stub header). */
public interface ShuffleWriteMetricsReporter {
  void incRecordsWritten(long v);
  void incBytesWritten(long v);
}
