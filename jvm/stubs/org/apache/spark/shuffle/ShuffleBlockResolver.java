package org.apache.spark.shuffle;

/** Compile-only stub (see SparkConf stub header). */
public interface ShuffleBlockResolver {}
