package org.apache.spark;

import org.apache.spark.storage.BlockManager;

/** Compile-only stub (see SparkConf stub header). */
public class SparkEnv {
  public static SparkEnv get() { throw new UnsupportedOperationException("stub"); }
  public BlockManager blockManager() { throw new UnsupportedOperationException("stub"); }
}
