package org.apache.spark.storage;

/** Compile-only stub (see SparkConf stub header). */
public class BlockManager {
  public BlockManagerId shuffleServerId() { throw new UnsupportedOperationException("stub"); }
}
