package org.apache.spark.storage;

/** Compile-only stub (see SparkConf stub header). */
public class BlockManagerId {}
