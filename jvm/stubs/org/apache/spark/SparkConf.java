package org.apache.spark;

/** Compile-only stub mirroring the spark-core 3.x signatures the shim uses.
 * Never shipped: the real provided-scope spark-core supplies this class at
 * runtime (see jvm/README.md). */
public class SparkConf {
  public String get(String key, String defaultValue) { throw new UnsupportedOperationException("stub"); }
  public int getInt(String key, int defaultValue) { throw new UnsupportedOperationException("stub"); }
}
