package org.apache.spark.rdd;

/** Compile-only stub (see SparkConf stub header). */
public class RDD<T> {
  public int getNumPartitions() { throw new UnsupportedOperationException("stub"); }
}
