package org.apache.spark;

/** Compile-only stub (see SparkConf stub header). */
public abstract class Partitioner {
  public abstract int numPartitions();
  public abstract int getPartition(Object key);
}
