package org.apache.spark;

/** Compile-only stub (see SparkConf stub header). */
public class InterruptibleIterator<T> implements scala.collection.Iterator<T> {
  public InterruptibleIterator(TaskContext context, scala.collection.Iterator<T> delegate) {}
  @Override public boolean hasNext() { throw new UnsupportedOperationException("stub"); }
  @Override public T next() { throw new UnsupportedOperationException("stub"); }
}
