package org.apache.spark;

/** Compile-only stub (see SparkConf stub header). */
public abstract class TaskContext {
  public static TaskContext get() { throw new UnsupportedOperationException("stub"); }
}
