package org.apache.spark;

/** Compile-only stub (see SparkConf stub header). */
public abstract class TaskContext {
  public static TaskContext get() { throw new UnsupportedOperationException("stub"); }
  /** The map task's partition index within its stage (0..numMaps-1). */
  public abstract int partitionId();
  /** Globally unique task attempt id — what Spark 3.x passes as getWriter's mapId. */
  public abstract long taskAttemptId();
}
