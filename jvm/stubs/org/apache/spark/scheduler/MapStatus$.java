package org.apache.spark.scheduler;

import org.apache.spark.storage.BlockManagerId;

/** Compile-only stub of the MapStatus companion object's static forwarder
 * surface (see SparkConf stub header). */
public final class MapStatus$ {
  public static final MapStatus$ MODULE$ = new MapStatus$();
  public MapStatus apply(BlockManagerId loc, long[] uncompressedSizes, long mapTaskId) {
    throw new UnsupportedOperationException("stub");
  }
}
