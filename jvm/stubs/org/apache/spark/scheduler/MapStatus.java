package org.apache.spark.scheduler;

/** Compile-only stub (see SparkConf stub header). */
public interface MapStatus {}
