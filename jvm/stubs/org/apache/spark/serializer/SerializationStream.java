package org.apache.spark.serializer;

/** Compile-only stub (see SparkConf stub header). */
public abstract class SerializationStream {
  public abstract <T> SerializationStream writeKey(T key, scala.reflect.ClassTag<T> tag);
  public abstract <T> SerializationStream writeValue(T value, scala.reflect.ClassTag<T> tag);
  public abstract void close();
}
