package org.apache.spark.serializer;

/** Compile-only stub (see SparkConf stub header). */
public abstract class Serializer {
  public abstract SerializerInstance newInstance();
}
