package org.apache.spark.serializer;

/** Compile-only stub (see SparkConf stub header). */
public abstract class DeserializationStream {
  public abstract scala.collection.Iterator<scala.Tuple2<Object, Object>> asKeyValueIterator();
  public abstract void close();
}
