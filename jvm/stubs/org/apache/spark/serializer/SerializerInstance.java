package org.apache.spark.serializer;

import java.io.InputStream;
import java.io.OutputStream;

/** Compile-only stub (see SparkConf stub header). */
public abstract class SerializerInstance {
  public abstract SerializationStream serializeStream(OutputStream s);
  public abstract DeserializationStream deserializeStream(InputStream s);
}
