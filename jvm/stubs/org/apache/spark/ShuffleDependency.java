package org.apache.spark;

import org.apache.spark.rdd.RDD;
import org.apache.spark.serializer.Serializer;

/** Compile-only stub (see SparkConf stub header). */
public class ShuffleDependency<K, V, C> {
  public RDD<?> rdd() { throw new UnsupportedOperationException("stub"); }
  public Partitioner partitioner() { throw new UnsupportedOperationException("stub"); }
  public Serializer serializer() { throw new UnsupportedOperationException("stub"); }
}
