"""TpuShuffleManager (L7/L6) — the plugin boundary.

Counterpart of ``UcxShuffleManager`` + ``CommonUcxShuffleManager``
(compat/spark_3_0/UcxShuffleManager.scala:25-80, CommonUcxShuffleManager.scala:37-124):
the single object a host engine (Spark via the JVM shim, or the benchmark CLI)
instantiates to run shuffles.  API mirrors Spark's ``ShuffleManager`` SPI —
``register_shuffle`` / ``get_writer`` / ``get_reader`` / ``unregister_shuffle`` /
``stop`` — with the fork's staged-store components wired in the same places:

* construction starts the transport asynchronously like the reference's setup
  thread (CommonUcxShuffleManager.scala:45-62); here init is synchronous because
  there is no SparkEnv to spin-wait on,
* ``get_writer`` injects the staged-store writer
  (NvkvShuffleExecutorComponents.createMapOutputWriter,
  DpuShuffleExecutorComponents.scala:52-59),
* ``get_reader`` returns the windowed fetch reader
  (UcxShuffleManager.getReader, compat/spark_3_0/UcxShuffleManager.scala:55-60),
* writer commit triggers the resolver's block registration
  (writeIndexFileAndCommit hook) and the MapperInfo transport commit,
* ``run_exchange``/``exchange_ready`` expose the superstep boundary — the piece
  with no reference counterpart because UCX pulls blocks one by one while the
  TPU plane moves them in one collective (SURVEY.md section 7 "push/pull
  mismatch").
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.transport import ExecutorId
from sparkucx_tpu.memory.pool import MemoryPool
from sparkucx_tpu.shuffle.reader import TpuShuffleReader, default_deserializer
from sparkucx_tpu.shuffle.resolver import TpuShuffleBlockResolver, ring_neighbors
from sparkucx_tpu.shuffle.writer import TpuShuffleMapOutputWriter
from sparkucx_tpu.transport.tpu import TpuShuffleCluster


class TpuShuffleManager:
    """Single-controller manager: owns the cluster and per-executor components."""

    def __init__(
        self,
        conf: Optional[TpuShuffleConf] = None,
        num_executors: Optional[int] = None,
        mesh=None,
    ) -> None:
        self.conf = conf or TpuShuffleConf()
        self.cluster = TpuShuffleCluster(self.conf, num_executors=num_executors, mesh=mesh)
        self.pool = MemoryPool(self.conf)
        self.pool.preallocate_from_conf()
        self.resolvers: List[TpuShuffleBlockResolver] = [
            TpuShuffleBlockResolver(self.conf, t, t.store) for t in self.cluster.transports
        ]
        self._shuffle_dims: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._stopped = False
        self._unregister_hooks: List[Callable[[int], None]] = []

    @property
    def num_executors(self) -> int:
        return self.cluster.num_executors

    # -- ShuffleManager SPI -------------------------------------------------

    def register_shuffle(
        self,
        shuffle_id: int,
        num_mappers: int,
        num_reducers: int,
        map_owner: Optional[List[ExecutorId]] = None,
    ) -> None:
        """registerShuffle (SortShuffleManager base behavior the reference
        inherits; dependency bookkeeping only)."""
        meta = self.cluster.create_shuffle(shuffle_id, num_mappers, num_reducers, map_owner)
        with self._lock:
            self._shuffle_dims[shuffle_id] = (num_mappers, num_reducers, meta)

    def get_writer(self, shuffle_id: int, map_id: int) -> TpuShuffleMapOutputWriter:
        """getWriter (compat/spark_3_0/UcxShuffleManager.scala:32-53): returns the
        staged-store map-output writer for the executor owning this map task."""
        _, num_reducers, meta = self._dims(shuffle_id)
        owner = meta.map_owner[map_id]
        transport = self.cluster.transport(owner)
        writer = TpuShuffleMapOutputWriter(
            transport.store, transport, shuffle_id, map_id, num_reducers
        )
        resolver = self.resolvers[owner]
        orig_commit = writer.commit_all_partitions

        def commit_and_register():
            lengths = orig_commit()
            resolver.on_map_committed(shuffle_id, map_id, num_reducers)
            return lengths

        writer.commit_all_partitions = commit_and_register
        return writer

    def get_reader(
        self,
        shuffle_id: int,
        start_partition: int,
        end_partition: int,
        executor_id: Optional[ExecutorId] = None,
        deserializer: Callable = default_deserializer,
        aggregator=None,
        key_ordering: bool = False,
        merge_combiners=None,
    ) -> TpuShuffleReader:
        """getReader (compat/spark_3_0/UcxShuffleManager.scala:55-60).  The reduce
        range must be owned by one executor (contiguous ownership); defaults to
        the owner of ``start_partition``."""
        num_mappers, _, meta = self._dims(shuffle_id)
        if executor_id is None:
            executor_id = meta.owner_of_reduce(start_partition)
        transport = self.cluster.transport(executor_id)

        def block_sizes(m: int, r: int) -> int:
            info = meta.mapper_infos.get(m)
            return info.partitions[r][1] if info is not None else 0

        replica_of = None
        if self.conf.replication_factor > 0:
            # failover candidates derive from the same ring the replicator
            # pushes to — no placement-metadata exchange needed
            executors = list(range(self.cluster.num_executors))
            factor = self.conf.replication_factor

            def replica_of(primary):
                return ring_neighbors(primary, executors, factor)

        holders_of = None
        if self.conf.serve_hot_threshold_fetches_per_sec > 0:
            # popularity-aware load spreading: ask the primary who else holds
            # its hot blocks (HotSetPull), so reducers rotate across holders
            holders_of = getattr(transport, "hot_holders", None)

        return TpuShuffleReader(
            transport,
            executor_id,
            shuffle_id,
            start_partition,
            end_partition,
            num_mappers,
            block_sizes,
            max_blocks_per_request=self.conf.max_blocks_per_request,
            pool=self.pool,
            deserializer=deserializer,
            aggregator=aggregator,
            key_ordering=key_ordering,
            fetch_retries=self.conf.fetch_retries,
            credit_bytes=self.conf.wire_credit_bytes,
            replica_of=replica_of,
            holders_of=holders_of,
            fetch_deadline_ms=self.conf.fetch_deadline_ms,
            fetch_backoff_ms=self.conf.fetch_backoff_ms,
            fetch_hedge_ms=self.conf.fetch_hedge_ms,
            fetch_hedge_max_ms=self.conf.fetch_hedge_max_ms,
            memory_budget=self.conf.reduce_memory_budget,
            spill_dir=self.conf.spill_dir,
            merge_combiners=merge_combiners,
        )

    def add_unregister_hook(self, fn: Callable[[int], None]) -> None:
        """Subscribe to shuffle teardown.  Hooks fire after the store tiers
        dropped the shuffle, so a subscriber (the query lineage cache) observing
        the callback can trust that no tier can still serve those blocks."""
        with self._lock:
            self._unregister_hooks.append(fn)

    def unregister_shuffle(self, shuffle_id: int) -> None:
        """unregisterShuffle -> resolver.removeShuffle
        (CommonUcxShuffleManager.scala:103-106)."""
        with self._lock:
            self._shuffle_dims.pop(shuffle_id, None)
            hooks = list(self._unregister_hooks)
        for resolver in self.resolvers:
            resolver.remove_shuffle(shuffle_id)
        # cluster-level metadata (store shuffles were removed via resolvers)
        self.cluster.drop_meta(shuffle_id)
        for fn in hooks:
            fn(shuffle_id)

    def stop(self) -> None:
        """stop() closes transports/resolvers (CommonUcxShuffleManager.scala:111-124)."""
        if self._stopped:
            return
        self._stopped = True
        for resolver in self.resolvers:
            resolver.stop()
        for t in self.cluster.transports:
            t.close()
        self.pool.close()

    # -- superstep boundary -------------------------------------------------

    def run_exchange(self, shuffle_id: int) -> None:
        """Run the collective superstep once all map tasks committed."""
        self.cluster.run_exchange(shuffle_id)

    def exchange_ready(self, shuffle_id: int) -> bool:
        meta = self._dims(shuffle_id)[2]
        return len(meta.mapper_infos) == meta.num_mappers

    # ----------------------------------------------------------------------

    def _dims(self, shuffle_id: int):
        with self._lock:
            dims = self._shuffle_dims.get(shuffle_id)
        if dims is None:
            raise KeyError(f"shuffle {shuffle_id} not registered")
        return dims

    def __enter__(self) -> "TpuShuffleManager":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
