"""Block resolver (L4) — map-side commit hook + local block serving.

Counterpart of ``CommonUcxShuffleBlockResolver`` + the compat resolvers
(CommonUcxShuffleBlockResolver.scala:37-77, compat/spark_3_0/UcxShuffleBlockResolver.scala:28-97)
and of the vendored ``IndexShuffleBlockResolver``'s role as the block-id ->
bytes authority (IndexShuffleBlockResolver.scala:219-262).

Responsibilities:

* after a map task commits, register its blocks with the transport so the
  peer-serving path can serve them (writeIndexFileAndCommitCommon,
  CommonUcxShuffleBlockResolver.scala:37-61),
* ``get_block_data``: serve a local block either from the *staged store / post-
  exchange shard* (``serve_from_store=True``, the reference's DPU-fetch arm) or
  straight from the store's staging memory (the direct-NVKV arm) — the
  ``spark.dpuTest.enabled`` A/B switch (UcxShuffleBlockResolver.scala:86-97),
* track shuffles for cleanup (``removeShuffle`` -> ``unregisterShuffle``,
  CommonUcxShuffleBlockResolver.scala:63-77).
"""

from __future__ import annotations

import threading
from typing import List, Sequence, Set, Tuple

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import Block, ShuffleBlockId
from sparkucx_tpu.core.operation import BlockNotFoundError, TransportError
from sparkucx_tpu.core.transport import ShuffleTransport
from sparkucx_tpu.store.hbm_store import HbmBlockStore


def ring_neighbors(executor_id, executors: Sequence, factor: int) -> List:
    """The ``factor`` ring successors of ``executor_id`` in the sorted
    executor ring — where this executor's sealed rounds are replicated
    (``spark.shuffle.tpu.replication.factor``), and therefore where a reducer
    re-resolves a block when its primary dies.  Shared by the replicator
    (transport/peer.py) and the reader's failover path so both sides derive
    the same placement from membership alone, with no placement-metadata
    exchange (the redistribution-plan determinism of arXiv:2112.01075)."""
    ring = sorted(set(executors))
    if executor_id not in ring or len(ring) < 2 or factor <= 0:
        return []
    idx = ring.index(executor_id)
    out = []
    for k in range(1, min(factor, len(ring) - 1) + 1):
        out.append(ring[(idx + k) % len(ring)])
    return out


def widened_ring_neighbors(
    executor_id, executors: Sequence, base_factor: int, hot_factor: int
) -> Tuple[List, List]:
    """Ring placement for a popularity-promoted (hot) block's replica set:
    ``(base, extra)`` where ``base`` is the fault-tolerance floor
    (``ring_neighbors`` at ``replication.factor``) and ``extra`` the
    ADDITIONAL successors a hot promotion widens onto
    (``spark.shuffle.tpu.serve.hotReplicas``, never narrower than the
    floor).  Derived from membership alone — the same determinism contract
    as :func:`ring_neighbors`, so the promoting server, its peers, and any
    reader agree on the widened set without a placement exchange."""
    base = ring_neighbors(executor_id, executors, base_factor)
    widened = ring_neighbors(executor_id, executors, max(hot_factor, base_factor))
    extra = [e for e in widened if e not in base]
    return base, extra


def degraded_plan(num_executors: int, alive: Sequence) -> Tuple[int, List, int]:
    """Deterministic placement of an ``num_executors``-wide exchange onto the
    surviving executors: ``(m, phys, waves)`` where ``m`` is the pow2 floor of
    the survivor count, ``phys`` the first ``m`` survivors in sorted order
    (the shrunk mesh, one chip each), and ``waves = ceil(n / m)`` the number
    of sub-exchange passes.  Logical executor ``l`` is processed in wave
    ``l // m`` on physical slot ``l % m`` — contiguous waves, so each wave's
    receiver regions are contiguous slices of every sender's staging.

    Shared by the exchange re-planner (transport/tpu.py) and anything that
    must agree on where a lost executor's work landed, so — like
    ``ring_neighbors`` — every party derives the same placement from
    membership alone (the redistribution-scheduling determinism of
    arXiv:2112.01075 applied to replica->staging placement)."""
    survivors = sorted(set(alive))
    if not survivors:
        raise TransportError("no surviving executors to plan a degraded exchange on")
    m = 1 << (len(survivors).bit_length() - 1)  # pow2 floor
    phys = survivors[:m]
    waves = -(-num_executors // m)
    return m, phys, waves


class _StoreBackedBlock(Block):
    """A registered Block serving lazily from the staged store — the analogue of
    the file-backed positioned-read blocks the reference registers
    (CommonUcxShuffleBlockResolver.scala:37-61 FileBackedMemoryBlock)."""

    def __init__(self, store: HbmBlockStore, shuffle_id: int, map_id: int, reduce_id: int) -> None:
        super().__init__()
        self._store = store
        self._key = (shuffle_id, map_id, reduce_id)

    def get_size(self) -> int:
        return self._store.block_length(*self._key)

    def get_block(self, dest) -> None:
        import numpy as np

        payload = self._store.read_block(*self._key)
        view = np.frombuffer(dest, dtype=np.uint8) if not isinstance(dest, np.ndarray) else dest.reshape(-1).view(np.uint8)
        view[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)


class TpuShuffleBlockResolver:
    def __init__(
        self,
        conf: TpuShuffleConf,
        transport: ShuffleTransport,
        store: HbmBlockStore,
    ) -> None:
        self.conf = conf
        self.transport = transport
        self.store = store
        self._shuffles: Set[int] = set()  #: guarded by self._lock
        self._lock = threading.Lock()

    def on_map_committed(self, shuffle_id: int, map_id: int, num_reducers: int) -> None:
        """Register each non-empty partition with the transport for peer serving
        (the writeIndexFileAndCommit hook, CommonUcxShuffleBlockResolver.scala:37-61)."""
        with self._lock:
            self._shuffles.add(shuffle_id)
        for r in range(num_reducers):
            if self.store.block_length(shuffle_id, map_id, r) > 0:
                self.transport.register(
                    ShuffleBlockId(shuffle_id, map_id, r),
                    _StoreBackedBlock(self.store, shuffle_id, map_id, r),
                )

    def get_block_data(self, shuffle_id: int, map_id: int, reduce_id: int) -> bytes:
        """Local serving of a block (IndexShuffleBlockResolver.getBlockData role).

        ``serve_from_store`` True -> read back through the staged store (the
        reference fetches back from the DPU); False -> same memory, but callers
        that bypass the store registry hit the registered Block instead
        (UcxShuffleBlockResolver.scala:86-97 A/B).

        An unknown shuffle/map raises the typed, addressed
        :class:`BlockNotFoundError` (never a bare KeyError), so callers can
        tell "retryable: not yet committed / peer lost" from programming
        errors."""
        if self.conf.serve_from_store:
            try:
                return self.store.read_block(shuffle_id, map_id, reduce_id)
            except BlockNotFoundError:
                raise
            except TransportError as e:
                if "unknown shuffle" in str(e):
                    raise BlockNotFoundError(shuffle_id, map_id, reduce_id, str(e)) from e
                raise
        blk = None
        if hasattr(self.transport, "registered_block"):
            blk = self.transport.registered_block(ShuffleBlockId(shuffle_id, map_id, reduce_id))
        if blk is None:
            raise BlockNotFoundError(shuffle_id, map_id, reduce_id, "not registered")
        return blk.get_memory_block().to_bytes()

    def replica_executors(self, primary_executor, executors: Sequence) -> List:
        """Where a block whose primary executor died can be re-resolved: the
        primary's replication-ring successors among ``executors`` (empty at
        ``replication.factor = 0``)."""
        return ring_neighbors(primary_executor, executors, self.conf.replication_factor)

    def remove_shuffle(self, shuffle_id: int) -> None:
        """removeShuffle -> unregister all the shuffle's blocks
        (CommonUcxShuffleBlockResolver.scala:63-77)."""
        with self._lock:
            self._shuffles.discard(shuffle_id)
        self.transport.unregister_shuffle(shuffle_id)
        self.store.remove_shuffle(shuffle_id)

    def stop(self) -> None:
        with self._lock:
            doomed = list(self._shuffles)
        for sid in doomed:
            self.remove_shuffle(sid)
