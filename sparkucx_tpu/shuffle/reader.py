"""Reduce-side reader (L5) — fetch iterator with windowing, metrics, aggregation.

Counterpart of ``UcxShuffleReader`` + ``UcxShuffleClient``
(compat/spark_3_0/UcxShuffleReader.scala:74-199, UcxShuffleClient.scala:17-96):

* batch fetch of this reducer's blocks, split into request windows of
  ``max_blocks_per_request`` (the client's recursive-halving splitter,
  UcxShuffleClient.scala:53-58, here a plain chunking),
* a pull loop that spins ``transport.progress()`` while results are pending and
  charges the wait to ``fetch_wait_time`` — the reference reflects into Spark's
  private results queue to do this (UcxShuffleReader.scala:110-134); our iterator
  owns its queue so no reflection is needed,
* then the standard deserialize -> aggregate -> sort pipeline
  (UcxShuffleReader.scala:137-199), with pluggable deserializer/aggregator/
  ordering instead of Spark's Serializer/Aggregator/ExternalSorter.

Metrics mirror ``ShuffleReadMetricsReporter``: records_read, remote_bytes_read,
fetch_wait_time (UcxShuffleReader.scala:118-123,148-153).
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from sparkucx_tpu.core.block import MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import (
    ExecutorLostError,
    OperationStatus,
    Request,
    TenantQuotaExceededError,
    TransportError,
    UnknownTenantError,
)
from sparkucx_tpu.core.transport import ExecutorId, ShuffleTransport
from sparkucx_tpu.memory.pool import MemoryPool
from sparkucx_tpu.utils.trace import TRACER, instant

#: The fail-fast arm of the failure taxonomy (docs/API.md "Failure
#: semantics", machine-checked by analysis ERROR_TAXONOMY): faults every
#: replica answers identically (tenant admission) or that name an executor
#: the membership plane already declared dead.  Retrying burns the failover
#: budget to hit the same wall — the retry path re-raises these immediately.
_FAIL_FAST_ERRORS = (TenantQuotaExceededError, UnknownTenantError, ExecutorLostError)


@dataclass
class ShuffleReadMetrics:
    """UcxShuffleReader.scala:118-123,148-153 reporter fields (+ retry count,
    which the reference has no analogue for — it never retries)."""

    records_read: int = 0
    remote_bytes_read: int = 0
    remote_blocks_fetched: int = 0
    fetch_wait_ns: int = 0
    blocks_retried: int = 0
    #: combine/sort runs spilled to disk (the ExternalSorter spill counter)
    spills: int = 0
    #: blocks served by a replica executor after the primary died / hung
    failovers: int = 0
    #: fetch windows or retry attempts abandoned at the fetch deadline
    fetch_timeouts: int = 0
    #: duplicate fetches issued to replica holders for straggling blocks
    hedges_issued: int = 0
    #: hedged fetches that beat the straggling primary (replica bytes won)
    hedge_wins: int = 0
    #: hedged fetches the primary beat (hedge buffer quarantined)
    hedge_losses: int = 0


class BlockFetchResult:
    """One fetched block.

    ``data`` is served zero-copy: a read-only memoryview of the fetch buffer,
    valid while the result is attached to it.  The streaming ``read()`` path
    calls ``release()`` once the block's deserializer is exhausted, so record
    decoding never copies the payload a second time.  When the fetch iterator
    advances past a result nobody released, it ``detach()``es it — copying the
    bytes out only if the buffer is pooled (about to be recycled), so the
    ``data`` *property* stays valid for collect-into-list consumers; only a
    captured memoryview object itself goes stale at that point.  Constructing
    with a plain ``bytes`` payload keeps the old copying contract."""

    __slots__ = ("block_id", "_data", "_buf", "_pooled", "_san", "_released")

    def __init__(
        self,
        block_id: ShuffleBlockId,
        data,
        buf: Optional[MemoryBlock] = None,
        pooled: bool = False,
        sanitizer=None,
    ) -> None:
        self.block_id = block_id
        self._data = data
        self._buf = buf
        self._pooled = pooled
        self._san = sanitizer
        self._released = False
        if sanitizer is not None:
            sanitizer.export_view(buf)

    @property
    def data(self):
        if self._released and self._san is not None:
            self._san.check_view_released(
                f"BlockFetchResult({self.block_id.name}).data"
            )
        return self._data

    def release(self) -> None:
        """Consumer is done with ``data``: hand the fetch buffer back without
        any copy.  ``data`` must not be touched afterwards — under sanitize
        mode a later ``data`` access raises; in normal mode a pooled result
        degrades to ``b""``.  Idempotent in BOTH modes (the fetch iterator's
        ``finally: detach()`` safety net depends on it)."""
        buf, self._buf = self._buf, None
        if buf is not None:
            if self._san is not None:
                self._san.release_view(buf)
            if self._pooled:
                self._data = b""
                self._released = True
            buf.close()

    def detach(self) -> None:
        """Make ``data`` outlive the buffer: copy it out if (and only if) the
        buffer is pooled, then hand the buffer back.  Idempotent; ``data``
        stays valid afterwards (it is a private copy), so this never trips
        the use-after-release check."""
        buf, self._buf = self._buf, None
        if buf is not None:
            if self._pooled:
                self._data = bytes(self._data)
            if self._san is not None:
                self._san.release_view(buf)
            buf.close()


def default_deserializer(payload: bytes) -> Iterable[Any]:
    """Record stream per block (the Spark serializer-stream analogue).

    Decodes the typed, NON-EXECUTING wire format of utils/codec.py — block
    payloads arrive from peers over sockets, and the default codec must not
    be an arbitrary-code-execution surface the way Spark's JavaSerializer
    (or pickle) is.  Malformed frames raise ``ValueError``.  For trusted
    single-host runs needing arbitrary Python objects, pass
    :func:`pickle_deserializer` explicitly."""
    from sparkucx_tpu.utils.codec import decode_records

    yield from decode_records(payload)


def serialize_records(records: Iterable[Any]) -> bytes:
    """Writer-side twin of ``default_deserializer`` (typed safe codec)."""
    from sparkucx_tpu.utils.codec import encode_records

    return encode_records(records)


def pickle_deserializer(payload: bytes) -> Iterable[Any]:
    """OPT-IN pickle record stream — executes whatever the bytes describe, so
    use it only when every peer is trusted (single-host runs, tests needing
    arbitrary object graphs).  Never the default: block payloads are
    peer-controlled socket bytes (see parallel/bootstrap.py's rule)."""
    import io
    import pickle

    if not payload:
        return
    bio = io.BytesIO(payload)
    while bio.tell() < len(payload):
        try:
            yield pickle.load(bio)
        except EOFError:
            return


def pickle_serialize_records(records: Iterable[Any]) -> bytes:
    """Writer-side twin of :func:`pickle_deserializer` (opt-in, trusted runs)."""
    import io
    import pickle

    bio = io.BytesIO()
    for rec in records:
        pickle.dump(rec, bio, protocol=pickle.HIGHEST_PROTOCOL)
    return bio.getvalue()


class TpuShuffleReader:
    """Reads the blocks of reduce partitions [start_partition, end_partition)
    for one reducer — ``ShuffleReader.read()`` (UcxShuffleReader.scala:74)."""

    def __init__(
        self,
        transport: ShuffleTransport,
        executor_id: ExecutorId,
        shuffle_id: int,
        start_partition: int,
        end_partition: int,
        num_mappers: int,
        block_sizes: Callable[[int, int], int],
        max_blocks_per_request: int = 50,
        pool: Optional[MemoryPool] = None,
        deserializer: Callable[[bytes], Iterable[Any]] = default_deserializer,
        aggregator: Optional[Callable[[Any, Any], Any]] = None,
        key_ordering: bool = False,
        sender_of: Optional[Callable[[int], ExecutorId]] = None,
        fetch_retries: int = 1,
        memory_budget: int = 64 << 20,
        spill_dir: Optional[str] = None,
        merge_combiners: Optional[Callable[[Any, Any], Any]] = None,
        credit_bytes: int = 0,
        replica_of: Optional[Callable[[ExecutorId], Sequence[ExecutorId]]] = None,
        fetch_deadline_ms: int = 0,
        fetch_backoff_ms: int = 50,
        fetch_hedge_ms: int = 0,
        fetch_hedge_max_ms: int = 0,
        holders_of: Optional[Callable[[ExecutorId, int], Sequence[ExecutorId]]] = None,
    ) -> None:
        self.transport = transport
        self.executor_id = executor_id
        self.shuffle_id = shuffle_id
        self.start_partition = start_partition
        self.end_partition = end_partition
        self.num_mappers = num_mappers
        self.block_sizes = block_sizes
        self.max_blocks_per_request = max(1, max_blocks_per_request)
        self.pool = pool
        self.deserializer = deserializer
        self.aggregator = aggregator
        self.key_ordering = key_ordering
        self.sender_of = sender_of or (lambda m: self.executor_id)
        self.fetch_retries = max(0, fetch_retries)
        self.memory_budget = memory_budget
        self.spill_dir = spill_dir
        self.merge_combiners = merge_combiners
        #: byte budget for credit-based fetch pipelining: issue request
        #: windows ahead of consumption while their result-buffer bytes fit
        #: the budget (``spark.shuffle.tpu.wire.creditBytes``); 0 = the
        #: historical strictly-serial window loop.  Credits account DECODED
        #: bytes (``block_sizes`` is the logical block size, which is what
        #: the result buffers hold) — wire compression (``compress.codec``)
        #: shrinks what travels, never what this budget meters, so a codec
        #: change cannot silently over-issue receive buffers.
        self.credit_bytes = max(0, credit_bytes)
        #: primary executor -> its replica executors (replication-ring
        #: successors; shuffle/resolver.ring_neighbors) — where a block is
        #: re-resolved when the primary dies.  None/empty = no failover.
        self.replica_of = replica_of
        #: per-window (and per retry attempt) completion deadline; a window
        #: that misses it is failed locally and enters the retry/failover path
        #: instead of spinning forever on a hung peer.  0 = wait forever.
        self.fetch_deadline_ms = max(0, fetch_deadline_ms)
        #: base for the jittered, doubling backoff between retry attempts
        self.fetch_backoff_ms = max(0, fetch_backoff_ms)
        #: hedged-fetch floor (``fetch.hedgeMs``): with a window still
        #: incomplete after max(floor, observed rx stall p99), a DUPLICATE
        #: request for each straggling block goes to a replica holder; the
        #: first completion wins bit-identically and the loser's buffer is
        #: quarantined via ``_abandoned``.  0 = hedging off (the default).
        self.fetch_hedge_ms = max(0, fetch_hedge_ms)
        #: hedge-delay ceiling (``fetch.hedgeMaxMs``) clamping the p99-derived
        #: delay, so one pathological stall sample cannot defer hedging
        #: forever.  0 = no ceiling.
        self.fetch_hedge_max_ms = max(0, fetch_hedge_max_ms)
        #: timed-out fetches whose result buffer may still be a recv-thread
        #: scatter target — kept alive until their request completes, then
        #: closed by _sweep_abandoned (single reader thread; no lock)
        self._abandoned: List[Tuple[MemoryBlock, Request]] = []
        #: popularity-aware load spreading: ``holders_of(primary, shuffle_id)``
        #: returns the CURRENT holder set the primary advertises for a hot
        #: shuffle (transport.hot_holders — widened replica sets learned via
        #: HOT_SET_PULL, []/None when cold).  With >1 holder, this reader
        #: deterministically rotates its fetches across them instead of
        #: piling onto the primary.  None = the historical primary-only path.
        self.holders_of = holders_of
        #: where each in-flight block of the current window was ACTUALLY sent
        #: (spread target, not necessarily the primary) — hedges must pick a
        #: different holder than this (single reader thread; no lock)
        self._window_targets: Dict[ShuffleBlockId, ExecutorId] = {}
        self.metrics = ShuffleReadMetrics()

    # -- raw block iterator ------------------------------------------------

    def _block_ids(self) -> List[ShuffleBlockId]:
        return [
            ShuffleBlockId(self.shuffle_id, m, r)
            for r in range(self.start_partition, self.end_partition)
            for m in range(self.num_mappers)
            if self.block_sizes(m, r) > 0
        ]

    def fetch_blocks(self) -> Iterator[BlockFetchResult]:
        """Windowed fetch of all non-empty blocks; yields as windows complete.

        Window size caps one request like ``maxBlocksPerRequest``
        (UcxShuffleConf.scala:88-93); the spin between windows is charged to
        fetch_wait (UcxShuffleReader.scala:118-123).  With ``credit_bytes``
        set, later windows are issued AHEAD of consumption while their bytes
        fit the budget (credit-based pipelining: the wire fills the next
        windows' buffers while this thread deserializes the current one);
        yield order is window order either way, and ``credit_bytes == 0`` is
        the historical strictly-serial loop."""
        bids = self._block_ids()
        windows = [
            bids[w : w + self.max_blocks_per_request]
            for w in range(0, len(bids), self.max_blocks_per_request)
        ]
        if self.credit_bytes > 0 and len(windows) > 1:
            yield from self._fetch_windows_pipelined(windows)
            return
        for window in windows:
            # open the window span BEFORE issuing: with obs.traceContext on,
            # the fetch request carries (trace_id, span_id) over the wire and
            # every server's serve span — primary or replica — parents here
            wctx = self._start_window_span(len(window))
            try:
                with TRACER.activate(wctx):
                    requests = self._issue_window(window)
                    self._await_window(requests, len(window))
                yield from self._yield_window(requests, wctx)
            finally:
                self._end_window_span(wctx)
        self._sweep_abandoned()
        self._flush_read_counters()

    def _fetch_windows_pipelined(self, windows) -> Iterator[BlockFetchResult]:
        from collections import deque

        from sparkucx_tpu.transport.pipeline import CreditGate

        gate = CreditGate(self.credit_bytes)
        costs = [
            sum(self.block_sizes(b.map_id, b.reduce_id) for b in w) for w in windows
        ]
        issued: deque = deque()  # (window, wctx, requests, cost) awaiting completion
        nxt = 0
        while nxt < len(windows) or issued:
            while nxt < len(windows):
                cost = costs[nxt]
                if not issued:
                    gate.acquire(cost)  # head window always admits (oversized-alone)
                elif not gate.try_acquire(cost):
                    break  # budget full: stop issuing ahead
                # per-window span opened at ISSUE time: windows overlap, so
                # each carries its own explicit ctx rather than the thread
                # stack (start_span/end_span straddle the pipeline)
                wctx = self._start_window_span(len(windows[nxt]))
                with TRACER.activate(wctx):
                    reqs = self._issue_window(windows[nxt])
                issued.append((windows[nxt], wctx, reqs, cost))
                nxt += 1
            window, wctx, requests, cost = issued.popleft()
            try:
                with TRACER.activate(wctx):
                    self._await_window(requests, len(window))
                yield from self._yield_window(requests, wctx)
            finally:
                self._end_window_span(wctx)
                # credits return when the window is consumed (or the caller
                # abandons the iterator / a fetch raises or times out) — the
                # gate drains to zero either way, so one dead peer's windows
                # can never wedge the pipeline's budget
                gate.release(cost)
        self._sweep_abandoned()
        self._flush_read_counters()

    def _spread_target(self, bid: ShuffleBlockId) -> ExecutorId:
        """Where to send the fetch for ``bid``: the primary, unless the
        primary advertises a widened holder set for this (hot) shuffle — then
        a deterministic-per-reader rotation over the sorted holders, so N
        concurrent reducers spread a fan-in across every holder instead of
        piling onto one server, while any single reader stays deterministic
        (retries and the bit-equality contract rely on that)."""
        primary = self.sender_of(bid.map_id)
        if self.holders_of is None:
            return primary
        try:
            holders = sorted(set(self.holders_of(primary, bid.shuffle_id) or ()))
        except (TransportError, OSError):
            return primary  # advertisement pull failed: serve from primary
        # never rotate onto ourselves: a co-located copy is the local store
        # path's business, and the wire transport has no loopback connection
        # to its own executor (falling out of _issue_window unguarded)
        holders = [e for e in holders if e != self.executor_id]
        if len(holders) < 2 or primary not in holders:
            return primary
        return holders[
            (self.executor_id + bid.map_id + bid.reduce_id) % len(holders)
        ]

    def _issue_window(
        self, window: List[ShuffleBlockId]
    ) -> List[Tuple[ShuffleBlockId, MemoryBlock, Request]]:
        sizes = [self.block_sizes(bid.map_id, bid.reduce_id) for bid in window]
        if self.pool is not None:
            buffers = self.pool.get_many(sizes)
        else:
            buffers = [MemoryBlock(np.zeros(s, dtype=np.uint8), size=s) for s in sizes]
        groups: dict = {}
        for bid, buf in zip(window, buffers):
            target = self._spread_target(bid)
            self._window_targets[bid] = target
            groups.setdefault(target, []).append((bid, buf))
        requests: List[Tuple[ShuffleBlockId, MemoryBlock, Request]] = []
        for sender, items in groups.items():
            reqs = self.transport.fetch_blocks_by_block_ids(
                sender,
                [bid for bid, _ in items],
                [buf for _, buf in items],
                [None] * len(items),
            )
            requests.extend((bid, buf, req) for (bid, buf), req in zip(items, reqs))
        return requests

    def _start_window_span(self, num_blocks: int):
        """Open the per-window ``read.window`` span (explicit start/end: the
        pipelined path overlaps windows, so the span can't live on the
        thread-local stack).  Ended by ``_end_window_span`` in the read
        loop's ``finally``.  None when tracing is off."""
        if not TRACER.active:
            return None
        with TRACER.executor_scope(self.executor_id):
            return TRACER.start_span(
                "read.window", shuffle_id=self.shuffle_id, blocks=num_blocks
            )

    def _end_window_span(self, wctx) -> None:
        if wctx is not None:
            with TRACER.executor_scope(self.executor_id):
                TRACER.end_span(wctx)

    def _flush_read_counters(self) -> None:
        """Surface the reader's failover telemetry through the transport's
        StatsAggregator, where the metrics registry's ``ops`` provider picks
        it up (``sparkucx_tpu_ops_*_total{kind="read"}``)."""
        agg = getattr(self.transport, "stats_agg", None)
        if agg is None:
            return
        m = self.metrics
        if (
            m.failovers
            or m.blocks_retried
            or m.fetch_timeouts
            or m.hedges_issued
        ):
            agg.record_counters(
                "read",
                failovers=m.failovers,
                blocks_retried=m.blocks_retried,
                fetch_timeouts=m.fetch_timeouts,
                hedges_issued=m.hedges_issued,
                hedge_wins=m.hedge_wins,
                hedge_losses=m.hedge_losses,
            )

    def _hedge_delay_ns(self) -> int:
        """Hedge delay for the current window: max(observed rx stall p99 over
        all wire lanes, the ``fetch.hedgeMs`` floor), clamped to the
        ``fetch.hedgeMaxMs`` ceiling.  0 = hedging off.  The p99 seeds from
        ``wire_lane_stats`` so early windows (no samples yet) hedge at the
        floor and later windows adapt to what this link actually delivers."""
        if self.fetch_hedge_ms <= 0:
            return 0
        floor = self.fetch_hedge_ms * 1_000_000
        delay = floor
        lanes = getattr(self.transport, "wire_lane_stats", None)
        if lanes is not None:
            try:
                for lane in lanes():
                    delay = max(delay, int(lane.get("rx_stall_p99_ns", 0)))
            except Exception:
                delay = floor
        if self.fetch_hedge_max_ms > 0:
            delay = min(delay, max(self.fetch_hedge_max_ms * 1_000_000, floor))
        return delay

    @staticmethod
    def _window_settled(requests, hedges) -> bool:
        """A window is settled once every block's primary request OR its
        hedge has completed — a stalled primary whose hedge already won must
        not keep the window spinning toward the deadline."""
        for i, (_, _, req) in enumerate(requests):
            if req.completed():
                continue
            h = hedges.get(i)
            if h is not None and h[1].completed():
                continue
            return False
        return True

    def _issue_hedges(self, requests, hedges) -> None:
        """One duplicate fetch per straggling block, to a different holder.

        Candidates are the advertised hot-set holders (``holders_of``) plus
        the replication-ring successors (``replica_of``), minus the executor
        the straggling fetch was ACTUALLY sent to — racing the same stalled
        server is exactly the failure hedging exists to break — and minus
        (when the transport scores peers) any executor whose circuit breaker
        rejects the probe.  With several admissible holders the pick rotates
        deterministically per (reader, block), spreading hedge load instead
        of always hammering the first ring successor.  Hedge buffers are
        allocated OUTSIDE the credit gate on purpose: hedges exist to break
        stalls, and gating them on credits held by the very window that is
        stalled would deadlock; the overdraft is bounded by one buffer per
        straggling block, and losers drain through the ``_abandoned``
        quarantine."""
        if self.replica_of is None and self.holders_of is None:
            return
        allows = getattr(self.transport, "breaker_allows", None)
        for i, (bid, _, req) in enumerate(requests):
            if req.completed() or i in hedges:
                continue
            primary = self.sender_of(bid.map_id)
            actual = self._window_targets.get(bid, primary)
            candidates: List[ExecutorId] = []
            if self.holders_of is not None:
                try:
                    candidates += sorted(
                        set(self.holders_of(primary, bid.shuffle_id) or ())
                    )
                except (TransportError, OSError):
                    pass
            if primary not in candidates:
                candidates.append(primary)
            if self.replica_of is not None:
                candidates += [
                    e for e in self.replica_of(primary) if e not in candidates
                ]
            admissible = [
                e
                for e in candidates
                if e != actual
                and e != self.executor_id
                and (allows is None or allows(e))
            ]
            if not admissible:
                continue
            target = admissible[
                (self.executor_id + bid.map_id + bid.reduce_id) % len(admissible)
            ]
            size = self.block_sizes(bid.map_id, bid.reduce_id)
            hbuf = None
            try:
                hbuf = self._alloc_buf(size)
                hreq = self.transport.fetch_block(
                    target, bid.shuffle_id, bid.map_id, bid.reduce_id, hbuf
                )
            except (TransportError, OSError):
                # dead replica or allocation under memory pressure: hedging
                # is best-effort — the primary path still owns correctness
                if hbuf is not None:
                    hbuf.close()
                continue
            hedges[i] = (hbuf, hreq, target)
            self.metrics.hedges_issued += 1
            instant(
                "fetch.hedge",
                shuffle_id=bid.shuffle_id, map_id=bid.map_id,
                reduce_id=bid.reduce_id, executor=target,
            )

    def _resolve_hedges(self, requests, hedges) -> None:
        """First completion wins; the loser's buffer is quarantined (it may
        still be a recv-scatter target) and swept once its request settles.
        Ties — both completed successfully — go to the primary: the bytes are
        bit-identical by the deterministic-refetch contract, and the hedge
        buffer is the one safe to discard either way."""
        record = getattr(self.transport, "record_peer_failure", None)
        for i, (hbuf, hreq, target) in hedges.items():
            bid, buf, req = requests[i]
            primary_ok = (
                req.completed()
                and req.wait(0).status == OperationStatus.SUCCESS
            )
            hedge_won = False
            if not primary_ok and hreq.completed():
                hresult = hreq.wait(0)
                if hresult.status == OperationStatus.SUCCESS:
                    size = self.block_sizes(bid.map_id, bid.reduce_id)
                    if int(hresult.stats.recv_size) != size:
                        hbuf.close()
                        raise TransportError(
                            f"hedged fetch of {bid} from executor {target} "
                            f"returned {hresult.stats.recv_size} B, expected "
                            f"{size} B — replica diverges from primary"
                        )
                    hedge_won = True
            if hedge_won:
                # replica bytes win: quarantine the straggling primary fetch
                # and charge the stall to the primary's health score — a
                # consistently-hedged peer trips its breaker and later
                # fetches route straight to the ring
                self._abandoned.append((buf, req))
                requests[i] = (bid, hbuf, hreq)
                self.metrics.hedge_wins += 1
                if record is not None:
                    record(
                        self._window_targets.get(bid, self.sender_of(bid.map_id)),
                        f"hedged fetch of {bid} lost to replica {target}",
                    )
                instant(
                    "fetch.hedge_win",
                    shuffle_id=bid.shuffle_id, map_id=bid.map_id,
                    reduce_id=bid.reduce_id, executor=target,
                )
            else:
                self._abandoned.append((hbuf, hreq))
                self.metrics.hedge_losses += 1
        hedges.clear()

    def _await_window(self, requests, num_blocks: int) -> None:
        t0 = time.monotonic_ns()
        deadline_ns = self.fetch_deadline_ms * 1_000_000
        hedge_ns = self._hedge_delay_ns()
        hedges: dict = {}  # request index -> (hedge_buf, hedge_req, executor)
        hedged = False
        # wakeup park between polls when the transport supports it
        # (use_wakeup; GlobalWorkerRpcThread.scala:46-58) — a local fetch
        # completes on the first poll so the wait never fires there
        park = getattr(self.transport, "wait_for_activity", None)
        while not self._window_settled(requests, hedges):
            now = time.monotonic_ns()
            if deadline_ns and now - t0 > deadline_ns:
                # hung peer: stop spinning, let _yield_window fail the
                # incomplete fetches over to replicas — this bounds the
                # fetch_wait charge per window to the deadline
                self.metrics.fetch_timeouts += 1
                break
            if hedge_ns and not hedged and now - t0 > hedge_ns:
                hedged = True
                self._issue_hedges(requests, hedges)
            self.transport.progress()
            if park is not None and not self._window_settled(requests, hedges):
                park(0.002)
        self.metrics.fetch_wait_ns += time.monotonic_ns() - t0
        if hedges:
            self._resolve_hedges(requests, hedges)

    def _yield_window(self, requests, wctx=None) -> Iterator[BlockFetchResult]:
        prev: Optional[BlockFetchResult] = None
        try:
            self._sweep_abandoned()
            for bid, buf, req in requests:
                if not req.completed():
                    # window hit its deadline with this fetch outstanding; the
                    # recv thread may still scatter into buf, so quarantine it
                    # (closed by a later sweep once the request settles) and
                    # fail over with a fresh buffer
                    self._abandoned.append((buf, req))
                    with TRACER.activate(wctx):
                        result, buf = self._retry_fetch(bid, None, None)
                else:
                    result = req.wait(0)
                    if result.status != OperationStatus.SUCCESS:
                        # replica failover under the window span: the replica
                        # server's serve span parents here too, so the merged
                        # trace shows primary AND replica children
                        with TRACER.activate(wctx):
                            result, buf = self._retry_fetch(bid, buf, result)
                # Zero-copy hand-off: a read-only view of the recv bytes.
                # The old `bytes(...)` here copied every fetched block a
                # second time; now the copy happens only in detach(), and
                # only for pooled buffers nobody released in time.
                view = buf.host_view()[: result.stats.recv_size]
                view.flags.writeable = False
                self.metrics.remote_bytes_read += int(result.stats.recv_size)
                self.metrics.remote_blocks_fetched += 1
                prev = BlockFetchResult(
                    bid,
                    memoryview(view),
                    buf,
                    pooled=self.pool is not None,
                    sanitizer=self.pool.sanitizer if self.pool is not None else None,
                )
                yield prev
                prev.detach()
        finally:
            if prev is not None:
                prev.detach()

    def _alloc_buf(self, size: int) -> MemoryBlock:
        if self.pool is not None:
            return self.pool.get_many([size])[0]
        return MemoryBlock(np.zeros(size, dtype=np.uint8), size=size)

    def _sweep_abandoned(self) -> None:
        """Close quarantined buffers whose requests have since settled; a
        buffer whose request is still live may be a recv-scatter target and
        must stay alive (bounded: one per timed-out fetch attempt)."""
        still: List[Tuple[MemoryBlock, Request]] = []
        for buf, req in self._abandoned:
            if req.completed():
                buf.close()
            else:
                still.append((buf, req))
        self._abandoned = still

    def _retry_fetch(self, bid: ShuffleBlockId, buf: Optional[MemoryBlock], failed):
        """Per-block pull-path retry + replica failover — the straggler/failure
        escape hatch next to the batch path.  The reference logs failed sends
        and gives up (SURVEY.md section 5.3: "No retry, no re-fetch fallback");
        here a failed/timed-out batch fetch falls back to
        ``transport.fetch_block`` (the per-block AM ids 3/4 analogue), up to
        ``fetch_retries`` attempts against the primary and then the same
        against each replica executor (``replica_of``, the replication-ring
        successors), with a jittered doubling backoff between attempts.  A
        replica refetch must be deterministic — same bytes the primary staged
        — so its size is asserted against the committed block length.

        ``buf is None`` means the original buffer was quarantined (its request
        never completed); each attempt then allocates a fresh buffer, and a
        timed-out attempt quarantines its buffer too.  Returns
        ``(result, buffer_holding_the_bytes)``.

        Fail-fast faults (``_FAIL_FAST_ERRORS``) are NOT retried: tenant
        admission rejections (UnknownTenantError / TenantQuotaExceededError)
        hit the same registry budgets on every replica, and
        ``ExecutorLostError`` means the membership plane already declared
        the peer dead — failing over would just re-pay the backoff to hit
        the same wall.  They propagate immediately.
        ``ResourceExhaustedError`` (memory-pressure shed, the third arm of
        the failure taxonomy) IS retried: it inherits the jittered doubling
        backoff, which is exactly the back-off-and-retry contract the typed
        error promises — a later attempt lands after the server's watermark
        sweep freed room.

        When the transport scores peers (``breaker_allows``), candidates
        whose circuit breaker is open are skipped, so a gray-failing primary
        routes straight to the replica ring without burning a full deadline
        per attempt; if EVERY candidate's breaker rejects, the full list is
        kept (an open breaker must delay, never strand, a block)."""
        if failed is not None and isinstance(failed.error, _FAIL_FAST_ERRORS):
            if buf is not None:
                buf.close()
            raise failed.error
        last_error = failed.error if failed is not None else "fetch deadline exceeded"
        size = self.block_sizes(bid.map_id, bid.reduce_id)
        primary = self.sender_of(bid.map_id)
        candidates: List[ExecutorId] = [primary]
        if self.holders_of is not None:
            # hot-set holders are first-class failover candidates: a widened
            # replica set exists precisely because this block draws fire
            try:
                candidates += [
                    e
                    for e in sorted(set(self.holders_of(primary, bid.shuffle_id) or ()))
                    if e not in candidates
                ]
            except (TransportError, OSError):
                pass
        if self.replica_of is not None:
            candidates += [
                e for e in self.replica_of(primary)
                if e != primary and e not in candidates
            ]
        allows = getattr(self.transport, "breaker_allows", None)
        if allows is not None and len(candidates) > 1:
            admitted = [e for e in candidates if allows(e)]
            if admitted:
                candidates = admitted
        deadline_ns = self.fetch_deadline_ms * 1_000_000
        # same wakeup park as the batch window loop above — the retry path
        # exists exactly for slow/straggling peers, where busy-spinning
        # progress() would burn the GIL against the recv thread
        park = getattr(self.transport, "wait_for_activity", None)
        record = getattr(self.transport, "record_peer_failure", None)
        attempt = 0
        for executor in candidates:
            for _ in range(self.fetch_retries):
                if attempt > 0 and self.fetch_backoff_ms:
                    base = (self.fetch_backoff_ms / 1000.0) * (2 ** min(attempt - 1, 6))
                    time.sleep(random.uniform(base / 2.0, base))
                attempt += 1
                if buf is None:
                    buf = self._alloc_buf(size)
                try:
                    req = self.transport.fetch_block(
                        executor, bid.shuffle_id, bid.map_id, bid.reduce_id, buf
                    )
                except (TransportError, OSError) as e:
                    if isinstance(e, _FAIL_FAST_ERRORS):
                        buf.close()
                        raise
                    last_error = e  # dead peer at connect time: next candidate
                    continue
                t0 = time.monotonic_ns()
                timed_out = False
                while not req.completed():
                    if deadline_ns and time.monotonic_ns() - t0 > deadline_ns:
                        timed_out = True
                        break
                    self.transport.progress()
                    if park is not None and not req.completed():
                        park(0.002)
                self.metrics.fetch_wait_ns += time.monotonic_ns() - t0
                if timed_out:
                    self.metrics.fetch_timeouts += 1
                    self._abandoned.append((buf, req))
                    buf = None  # never reuse a possibly-still-scattering buffer
                    if record is not None:
                        # a timeout the transport never saw as a frame error:
                        # charge it to the peer's health score here so hung
                        # (not dead) peers still trip their breaker
                        record(
                            executor,
                            f"fetch of {bid} timed out after "
                            f"{self.fetch_deadline_ms} ms",
                        )
                    last_error = TransportError(
                        f"fetch of {bid} from executor {executor} timed out "
                        f"after {self.fetch_deadline_ms} ms"
                    )
                    continue
                result = req.wait(0)
                if result.status == OperationStatus.SUCCESS:
                    if executor != primary:
                        # deterministic-refetch contract: the replica serves
                        # the exact bytes the primary staged, so the committed
                        # length must match to the byte
                        if int(result.stats.recv_size) != size:
                            buf.close()
                            raise TransportError(
                                f"replica refetch of {bid} from executor "
                                f"{executor} returned {result.stats.recv_size} B, "
                                f"expected {size} B — replica diverges from primary"
                            )
                        self.metrics.failovers += 1
                    self.metrics.blocks_retried += 1
                    instant(
                        "fetch.retry",
                        shuffle_id=bid.shuffle_id, map_id=bid.map_id,
                        reduce_id=bid.reduce_id, executor=executor,
                        failover=executor != primary,
                    )
                    return result, buf
                last_error = result.error
                if isinstance(last_error, _FAIL_FAST_ERRORS):
                    buf.close()
                    raise last_error
        if buf is not None:
            buf.close()
        raise TransportError(
            f"fetch of {bid} failed after {attempt} attempt"
            f"{'' if attempt == 1 else 's'} across executors {candidates}: {last_error}"
        )

    # -- record pipeline ---------------------------------------------------

    def read(self) -> Iterator[Any]:
        """deserialize -> combine -> sort (UcxShuffleReader.scala:137-199).

        Combine and sort run through the spillable ``ExternalCombiner``
        (shuffle/external.py) under ``memory_budget`` — the ExternalSorter
        role the reference's pipeline delegates to Spark — so a reduce
        partition larger than memory streams through sorted disk runs instead
        of OOMing."""
        def stream() -> Iterator[Any]:
            # Release each block as soon as its deserializer is exhausted:
            # the decoder reads straight out of the fetch buffer (zero-copy)
            # and the pooled buffer recycles without the detach() copy.
            for blk in self.fetch_blocks():
                try:
                    yield from self.deserializer(blk.data)
                finally:
                    blk.release()

        records: Iterator[Any] = stream()

        def counted(it):
            for rec in it:
                self.metrics.records_read += 1
                yield rec

        records = counted(records)
        if self.aggregator is None and not self.key_ordering:
            return records  # pure streaming, nothing materializes

        from sparkucx_tpu.shuffle.external import ExternalCombiner

        combiner = ExternalCombiner(
            aggregator=self.aggregator,
            key_ordering=self.key_ordering,
            memory_budget=self.memory_budget,
            spill_dir=self.spill_dir,
            merge_combiners=self.merge_combiners,
        )
        try:
            combiner.insert_all(records)
        except BaseException:
            combiner.close()  # reclaim spilled runs; mkstemp files don't self-delete
            raise
        self.metrics.spills = combiner.spill_count

        def drain(c):
            try:
                yield from c
            finally:
                c.close()

        return drain(combiner)
