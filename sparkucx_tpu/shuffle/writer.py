"""Map-side output writer (L4) — the Spark ``ShuffleMapOutputWriter`` SPI shape.

Counterpart of ``NvkvShuffleMapOutputWriter`` (+ inner ``NvkvShufflePartitionWriter``
/ ``PartitionWriterStream``, NvkvShuffleMapOutputWriter.scala, 274 LoC): one writer
per map task, partitions opened in increasing order (:108), stream writes delegated
to the staged store at a running offset (:228-234), ``close`` records
(offset, length) + padding (:236-246), and ``commit_all_partitions`` packs the
MapperInfo commit blob and ships it through the transport (:116-148, AM id 2).

Differences by design: space is accounted dynamically by the store (no static
``shuffleId*shuffleBlockSize`` carve-up, :94-103) and the commit also returns the
partition-lengths array Spark's scheduler expects (``MapOutputCommitMessage``).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from sparkucx_tpu.core.operation import TransportError
from sparkucx_tpu.core.transport import ShuffleTransport
from sparkucx_tpu.store.hbm_store import HbmBlockStore, MapWriter


class PartitionWriterStream:
    """File-like stream for one reduce partition
    (``PartitionWriterStream``, NvkvShuffleMapOutputWriter.scala:151-226)."""

    def __init__(self, owner: "TpuShuffleMapOutputWriter", reduce_id: int) -> None:
        self._owner = owner
        self.reduce_id = reduce_id
        self.count = 0
        self._closed = False

    def write(self, data: bytes) -> int:
        if self._closed:
            raise TransportError("write to closed partition stream")
        self._owner.map_writer.write(data)
        self.count += len(data)
        return len(data)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._owner.map_writer.close_partition()
        self._owner.record_partition_length(self.reduce_id, self.count)

    def __enter__(self) -> "PartitionWriterStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class TpuShufflePartitionWriter:
    """Per-partition writer handle (``NvkvShufflePartitionWriter``,
    NvkvShuffleMapOutputWriter.scala:150-175)."""

    def __init__(self, owner: "TpuShuffleMapOutputWriter", reduce_id: int) -> None:
        self._owner = owner
        self.reduce_id = reduce_id
        self._stream: Optional[PartitionWriterStream] = None

    def open_stream(self) -> PartitionWriterStream:
        if self._stream is None:
            self._owner.map_writer.open_partition(self.reduce_id)
            self._stream = PartitionWriterStream(self._owner, self.reduce_id)
        return self._stream

    def get_num_bytes_written(self) -> int:
        return self._stream.count if self._stream is not None else 0


class DeviceMapWriter:
    """Device-resident per-map writer (conf.device_staging): partitions arrive
    as ``(rows, lane)`` int32 device arrays and never visit host memory — the
    block-scatter kernel places the whole round into HBM staging at seal
    (store/hbm_store.py ``MapWriter.write_partition_device``).  Same sequential
    protocol and first-commit-wins retry semantics as the host ``MapWriter``;
    this wrapper is the writer-layer surface that enforces the conf gate."""

    def __init__(self, store: HbmBlockStore, shuffle_id: int, map_id: int) -> None:
        if not store.conf.device_staging:
            raise TransportError(
                "device staging disabled — set spark.shuffle.tpu.deviceStaging=true"
            )
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.map_writer: MapWriter = store.map_writer(shuffle_id, map_id)

    def write_partition(self, reduce_id: int, rows, length: Optional[int] = None) -> None:
        self.map_writer.write_partition_device(reduce_id, rows, length=length)

    def commit(self):
        return self.map_writer.commit()


class TpuShuffleMapOutputWriter:
    """One map task's output writer.  Sequential partition protocol enforced by
    the underlying store writer (NvkvShuffleMapOutputWriter.scala:108)."""

    def __init__(
        self,
        store: HbmBlockStore,
        transport: ShuffleTransport,
        shuffle_id: int,
        map_id: int,
        num_partitions: int,
    ) -> None:
        self.shuffle_id = shuffle_id
        self.map_id = map_id
        self.num_partitions = num_partitions
        self._transport = transport
        self._conf = store.conf
        #: public: the friend writer/stream classes above drive this handle
        self.map_writer: MapWriter = store.map_writer(shuffle_id, map_id)
        self._partition_lengths = np.zeros(num_partitions, dtype=np.int64)
        self._committed = False
        self._last_partition = -1

    def get_partition_writer(self, reduce_id: int) -> TpuShufflePartitionWriter:
        if self._committed:
            raise TransportError("writer already committed")
        if reduce_id <= self._last_partition:
            raise TransportError(
                f"partitions must be requested in increasing order "
                f"(got {reduce_id} after {self._last_partition})"
            )
        if not (0 <= reduce_id < self.num_partitions):
            raise ValueError(f"reduce_id {reduce_id} out of range")
        self._last_partition = reduce_id
        return TpuShufflePartitionWriter(self, reduce_id)

    def write_partition_device(self, reduce_id: int, rows, length: Optional[int] = None) -> None:
        """Device-path partition write: ``rows`` is a ``(r, lane)`` int32
        device array staged without a host round trip (requires
        spark.shuffle.tpu.deviceStaging=true).  Follows the same increasing
        reduce-order protocol as ``get_partition_writer`` and records the true
        byte length for the commit message."""
        if not self._conf.device_staging:
            raise TransportError(
                "device staging disabled — set spark.shuffle.tpu.deviceStaging=true"
            )
        if self._committed:
            raise TransportError("writer already committed")
        if reduce_id <= self._last_partition:
            raise TransportError(
                f"partitions must be requested in increasing order "
                f"(got {reduce_id} after {self._last_partition})"
            )
        if not (0 <= reduce_id < self.num_partitions):
            raise ValueError(f"reduce_id {reduce_id} out of range")
        self.map_writer.write_partition_device(reduce_id, rows, length=length)
        self._last_partition = reduce_id
        self._partition_lengths[reduce_id] = (
            length if length is not None else int(rows.shape[0]) * (rows.shape[1] * 4)
        )

    def record_partition_length(self, reduce_id: int, count: int) -> None:
        """Called by PartitionWriterStream.close() with the partition's byte
        count (the lengths array is Spark's MapOutputCommitMessage)."""
        self._partition_lengths[reduce_id] = count

    def commit_all_partitions(self) -> np.ndarray:
        """Pack + ship the MapperInfo commit (NvkvShuffleMapOutputWriter.scala:116-148)
        and return per-partition lengths (Spark's MapOutputCommitMessage)."""
        if self._committed:
            raise TransportError("writer already committed")
        info = self.map_writer.commit()
        self._transport.commit_block(info.pack())
        self._committed = True
        return self._partition_lengths.copy()

    def abort(self, error: Optional[BaseException] = None) -> None:
        """Drop without committing (task failure/retry path)."""
        self._committed = True
