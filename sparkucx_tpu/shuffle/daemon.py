"""Shuffle daemon — the host-engine boundary (L7 wire side).

The reference preserves Spark compatibility by splitting into a JVM plugin and an
out-of-repo daemon: the plugin (``spark.shuffle.manager`` =
``UcxShuffleManager``) speaks AM ids 0-4 to a DPU-side daemon on port 1338
(CommonUcxShuffleManager.scala:84-89, Definitions.scala:22-29).  This module is
that daemon, TPU-side: a standalone process hosting a ``TpuShuffleManager`` and
serving a framed protocol any host engine can speak — the JVM shim under
``jvm/`` (the ``spark.shuffle.manager`` entry point), the benchmark CLI, or
tests.

Protocol: the data-plane messages are exactly AM ids 0-4 (handshake, commit,
fetch — see core/definitions.py and transport/peer.py's BlockServer which serves
them); shuffle *lifecycle* adds daemon ops >= 16 (the part Spark does through the
ShuffleManager SPI rather than the wire, so the reference has no AM ids for it):

==================  ==  =======================================================
CreateShuffle       16  header: json {shuffle_id, num_mappers, num_reducers}
OpenMapWriter       17  header: json {shuffle_id, map_id} -> writer handle
WritePartition      18  header: json {writer, reduce_id}; body: bytes (repeat ok)
CommitMap           19  header: json {writer} -> partition lengths
RunExchange         20  header: json {shuffle_id}
FetchBlock           3  AM FetchBlockReq (batched form, peer.py framing)
RemoveShuffle       21  header: json {shuffle_id}
Stats               22  header: json {shuffle_id}
Shutdown            23  —
==================  ==  =======================================================

Every control op gets an ``Ack`` (id 24) with ``{ok, error?, ...result}``.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.definitions import (
    FRAME_HEADER_SIZE,
    MAX_FRAME_BYTES,
    AmId,
    pack_frame,
    pack_frame_prefix,
)
from sparkucx_tpu.service.reactor import Reactor
from sparkucx_tpu.shuffle.manager import TpuShuffleManager
from sparkucx_tpu.transport.peer import (
    BlockServer,
    apply_wire_sockopts,
    pack_batch_fetch_req,
    recv_exact,
    recv_frame,
    unpack_batch_fetch_req,
)
import struct

_TAG = struct.Struct("<Q")
_COUNT = struct.Struct("<I")
_SIZE = struct.Struct("<q")


class DaemonOp:
    CREATE_SHUFFLE = 16
    OPEN_MAP_WRITER = 17
    WRITE_PARTITION = 18
    COMMIT_MAP = 19
    RUN_EXCHANGE = 20
    REMOVE_SHUFFLE = 21
    STATS = 22
    SHUTDOWN = 23
    ACK = 24
    # obs plane (PR 14): control-plane pulls of the daemon-side telemetry
    EXPORT_TRACE = 25
    METRICS = 26


def _frame(op: int, header: dict, body: bytes = b"") -> bytes:
    # reuse the AM frame layout with op ids beyond the AM enum
    payload = json.dumps(header).encode()
    return struct.pack("<IQQ", op, len(payload), len(body)) + payload + body


def _read_frame(sock) -> Optional[Tuple[int, dict, bytes]]:
    hdr = recv_exact(sock, FRAME_HEADER_SIZE)
    if hdr is None:
        return None
    op, hlen, blen = struct.unpack("<IQQ", hdr)
    if hlen + blen > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large ({hlen + blen} B)")
    header = recv_exact(sock, hlen) if hlen else b""
    body = recv_exact(sock, blen) if blen else b""
    if (hlen and header is None) or (blen and body is None):
        return None
    meta = json.loads(header) if header else {}
    return op, meta, body


class ShuffleDaemon:
    """Hosts a TpuShuffleManager behind the wire protocol."""

    def __init__(
        self,
        conf: Optional[TpuShuffleConf] = None,
        num_executors: Optional[int] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.conf = conf or TpuShuffleConf()
        self.manager = TpuShuffleManager(self.conf, num_executors=num_executors)
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(16)
        self.address: Tuple[str, int] = self._srv.getsockname()
        self._running = True
        # _serve runs per-connection threads; every handle-table touch goes
        # through _lock — a second connection's OPEN/COMMIT must never race a
        # stream rebinding mid-dispatch (analysis: lock-discipline pass).
        self._writers: Dict[int, object] = {}  #: guarded by self._lock
        self._streams: Dict[Tuple[int, int], object] = {}  #: guarded by self._lock
        self._next_writer = 0  #: guarded by self._lock
        self._lock = threading.Lock()
        # Serving plane: thread-per-connection by default; with
        # server.workers set (or tenants.enabled) the shared reactor holds
        # every idle client in one selector and serves frames from a bounded
        # pool (service/reactor.py) — same dispatch code either way.
        self._reactor: Optional[Reactor] = None
        self._thread: Optional[threading.Thread] = None
        if self.conf.server_workers > 0 or self.conf.tenants_enabled:
            self._reactor = Reactor(self.conf.server_workers, name="sparkucx-daemon")
            self._reactor.add_listener(self._srv, self._on_accept)
        else:
            self._thread = threading.Thread(target=self._accept_loop, daemon=True)
            self._thread.start()

    # ------------------------------------------------------------------

    @property
    def running(self) -> bool:
        """True until close() — the CLI main loop polls this."""
        return self._running

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._srv.accept()
                apply_wire_sockopts(conn, self.conf)
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _on_accept(self, conn: socket.socket) -> None:
        """Reactor accept path: restore blocking reads (the listener is
        non-blocking under the selector), then park the connection."""
        apply_wire_sockopts(conn, self.conf)
        conn.setblocking(True)
        self._reactor.add_connection(conn, self._serve_step)

    def _ack(self, conn, ok: bool, body: bytes = b"", **extra) -> None:
        conn.sendall(_frame(DaemonOp.ACK, {"ok": ok, **extra}, body))

    def _serve_step(self, conn: socket.socket) -> bool:
        """Read + dispatch exactly one frame; True keeps the connection.
        The unit of work for both serving planes — the per-connection threads
        loop over it, the reactor re-arms the connection after each True."""
        if not self._running:
            return False
        try:
            frame = _read_frame(conn)
            if frame is None:
                return False
            op, meta, body = frame
            try:
                self._dispatch(conn, op, meta, body)
            except Exception as e:
                self._ack(conn, False, error=f"{type(e).__name__}: {e}")
            return True
        except (OSError, ValueError):
            # dead socket or an unparseable/oversized frame: drop THIS
            # connection, keep serving others (the endpoint-eviction policy,
            # UcxWorkerWrapper.scala:248-253)
            return False

    def _serve(self, conn: socket.socket) -> None:
        try:
            while self._serve_step(conn):
                pass
        finally:
            conn.close()

    def _dispatch(self, conn, op: int, meta: dict, body: bytes) -> None:
        mgr = self.manager
        if op == DaemonOp.CREATE_SHUFFLE:
            mgr.register_shuffle(int(meta["shuffle_id"]), int(meta["num_mappers"]), int(meta["num_reducers"]))
            self._ack(conn, True)
        elif op == DaemonOp.OPEN_MAP_WRITER:
            writer = mgr.get_writer(int(meta["shuffle_id"]), int(meta["map_id"]))
            with self._lock:
                handle = self._next_writer
                self._next_writer += 1
                self._writers[handle] = writer
            self._ack(conn, True, writer=handle)
        elif op == DaemonOp.WRITE_PARTITION:
            handle, reduce_id = int(meta["writer"]), int(meta["reduce_id"])
            key = (handle, reduce_id)
            stale = []
            with self._lock:
                writer = self._writers[handle]
                stream = self._streams.get(key)
                if stream is None:
                    # close any open stream of this writer (sequential protocol);
                    # pop under the lock, close outside it (close flushes)
                    for k in [k for k in self._streams if k[0] == handle]:
                        stale.append(self._streams.pop(k))
            for s in stale:
                s.close()
            if stream is None:
                stream = writer.get_partition_writer(reduce_id).open_stream()
                with self._lock:
                    self._streams[key] = stream
            stream.write(body)
            self._ack(conn, True, written=len(body))
        elif op == DaemonOp.COMMIT_MAP:
            handle = int(meta["writer"])
            with self._lock:
                stale = [
                    self._streams.pop(k)
                    for k in [k for k in self._streams if k[0] == handle]
                ]
                writer = self._writers.pop(handle)
            for s in stale:
                s.close()
            lengths = writer.commit_all_partitions()
            self._ack(conn, True, body=np.asarray(lengths, dtype="<i8").tobytes())
        elif op == DaemonOp.RUN_EXCHANGE:
            mgr.run_exchange(int(meta["shuffle_id"]))
            self._ack(conn, True)
        elif op == DaemonOp.REMOVE_SHUFFLE:
            mgr.unregister_shuffle(int(meta["shuffle_id"]))
            self._ack(conn, True)
        elif op == DaemonOp.STATS:
            sid = int(meta["shuffle_id"])
            meta_obj = mgr.cluster.meta(sid)
            sizes = {
                f"{m}": [ln for (_, ln) in info.partitions]
                for m, info in meta_obj.mapper_infos.items()
            }
            self._ack(conn, True, num_mappers=meta_obj.num_mappers,
                      num_reducers=meta_obj.num_reducers, exchanged=meta_obj.exchanged,
                      block_lengths=sizes)
        elif op == DaemonOp.EXPORT_TRACE:
            # merge the daemon-side executors' trace buffers to a file the
            # CLIENT named — the daemon owns the cluster, so the trace lives
            # on its side of the control socket
            count = mgr.cluster.export_trace(str(meta["path"]))
            self._ack(conn, True, events=count)
        elif op == DaemonOp.METRICS:
            self._ack(conn, True, body=mgr.cluster.metrics_text().encode())
        elif op == int(AmId.FETCH_BLOCK_REQ):
            # data-plane fetch: batched AM form (binary batch header travels in
            # the body so the JSON control framing stays uniform)
            tag, bids = unpack_batch_fetch_req(body)
            self._serve_fetch(conn, tag, bids)
        elif op == DaemonOp.SHUTDOWN:
            self._ack(conn, True)
            self.close()
        else:
            self._ack(conn, False, error=f"unknown op {op}")

    def _serve_fetch(self, conn, tag, bids) -> None:
        # Resolve each block to a zero-copy view and stream the reply as a
        # vectored sendmsg over the views — the wire bytes are identical to
        # the historical [sizes | data...] frame, but no monolithic reply
        # body is ever assembled (and no per-block bytes() copies are paid).
        parts, sizes = [], []
        for bid in bids:
            try:
                meta_obj = self.manager.cluster.meta(bid.shuffle_id)
                consumer = meta_obj.owner_of_reduce(bid.reduce_id)
                view, length = self.manager.cluster.locate_received_block(
                    consumer, bid.shuffle_id, bid.map_id, bid.reduce_id
                )
                seg = np.ascontiguousarray(view[:length]).reshape(-1).view(np.uint8)
                if length:
                    parts.append(memoryview(seg))
                sizes.append(int(length))
            except Exception:
                sizes.append(-1)
        blob = b"".join(_SIZE.pack(s) for s in sizes)
        reply_hdr = _TAG.pack(tag) + _COUNT.pack(len(bids)) + blob
        total = sum(p.nbytes for p in parts)
        prefix = pack_frame_prefix(AmId.FETCH_BLOCK_REQ_ACK, reply_hdr, total)
        if hasattr(conn, "sendmsg"):
            BlockServer._sendmsg_all(conn, [prefix] + parts)
        else:
            conn.sendall(b"".join([prefix] + [bytes(p) for p in parts]))

    def close(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass
        if self._reactor is not None:
            self._reactor.close()
        self.manager.stop()


class DaemonClient:
    """What the JVM shim (jvm/TpuShuffleManager.java) speaks — also usable from
    Python for tests and tooling."""

    def __init__(self, address: Tuple[str, int], conf: Optional[TpuShuffleConf] = None) -> None:
        self._sock = socket.create_connection(address, timeout=30)
        apply_wire_sockopts(self._sock, conf)
        self._lock = threading.Lock()

    def _call(self, op: int, header: dict, body: bytes = b"") -> Tuple[dict, bytes]:
        with self._lock:
            self._sock.sendall(_frame(op, header, body))
            frame = _read_frame(self._sock)
        if frame is None:
            raise ConnectionError("daemon closed connection")
        _, meta, ack_body = frame
        if not meta.get("ok"):
            raise RuntimeError(meta.get("error", "daemon error"))
        return meta, ack_body

    def create_shuffle(self, shuffle_id: int, num_mappers: int, num_reducers: int) -> None:
        self._call(DaemonOp.CREATE_SHUFFLE, {
            "shuffle_id": shuffle_id, "num_mappers": num_mappers, "num_reducers": num_reducers,
        })

    def open_map_writer(self, shuffle_id: int, map_id: int) -> int:
        meta, _ = self._call(DaemonOp.OPEN_MAP_WRITER, {"shuffle_id": shuffle_id, "map_id": map_id})
        return int(meta["writer"])

    def write_partition(self, writer: int, reduce_id: int, data: bytes) -> None:
        self._call(DaemonOp.WRITE_PARTITION, {"writer": writer, "reduce_id": reduce_id}, data)

    def commit_map(self, writer: int) -> np.ndarray:
        _, body = self._call(DaemonOp.COMMIT_MAP, {"writer": writer})
        return np.frombuffer(body, dtype="<i8")

    def run_exchange(self, shuffle_id: int) -> None:
        self._call(DaemonOp.RUN_EXCHANGE, {"shuffle_id": shuffle_id})

    def fetch_blocks(self, block_ids) -> list:
        """Batched data-plane fetch (AM ids 3/4). Returns list of bytes|None."""
        with self._lock:
            self._sock.sendall(
                struct.pack("<IQQ", int(AmId.FETCH_BLOCK_REQ), 0, len(pack_batch_fetch_req(0, block_ids)))
                + pack_batch_fetch_req(0, block_ids)
            )
            frame = recv_frame(self._sock)
        if frame is None:
            raise ConnectionError("daemon closed connection")
        _, header, body = frame
        (count,) = _COUNT.unpack_from(header, _TAG.size)
        sizes = [
            _SIZE.unpack_from(header, _TAG.size + _COUNT.size + i * _SIZE.size)[0]
            for i in range(count)
        ]
        out, pos = [], 0
        for s in sizes:
            if s < 0:
                out.append(None)
            else:
                out.append(body[pos : pos + s])
                pos += s
        return out

    def remove_shuffle(self, shuffle_id: int) -> None:
        self._call(DaemonOp.REMOVE_SHUFFLE, {"shuffle_id": shuffle_id})

    def stats(self, shuffle_id: int) -> dict:
        meta, _ = self._call(DaemonOp.STATS, {"shuffle_id": shuffle_id})
        return meta

    def export_trace(self, path: str) -> int:
        """Ask the daemon to write its merged Perfetto trace to ``path``
        (a path on the DAEMON's filesystem); returns the event count."""
        meta, _ = self._call(DaemonOp.EXPORT_TRACE, {"path": path})
        return int(meta.get("events", 0))

    def metrics_text(self) -> str:
        """The daemon cluster's Prometheus exposition."""
        _, body = self._call(DaemonOp.METRICS, {})
        return body.decode(errors="replace")

    def shutdown(self) -> None:
        try:
            self._call(DaemonOp.SHUTDOWN, {})
        except (ConnectionError, OSError):
            pass

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def main(argv=None) -> None:
    import argparse

    from sparkucx_tpu.parallel.mesh import apply_platform_env

    apply_platform_env()
    p = argparse.ArgumentParser(prog="sparkucx-tpu-daemon")
    p.add_argument("--port", type=int, default=1338)  # the reference's DPU port
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--executors", type=int, default=1)
    args = p.parse_args(argv)
    daemon = ShuffleDaemon(num_executors=args.executors, host=args.host, port=args.port)
    print(f"shuffle daemon on {daemon.address[0]}:{daemon.address[1]}", flush=True)
    try:
        while daemon.running:
            import time

            time.sleep(0.5)
    except KeyboardInterrupt:
        daemon.close()


if __name__ == "__main__":
    main()
