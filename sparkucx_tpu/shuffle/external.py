"""Bounded-memory combine/sort for the reduce side — the ExternalSorter role.

The reference's read pipeline hands aggregation and ordering to Spark's
spilling ExternalSorter (``UcxShuffleReader.scala:137-199``: ``aggregator
.combineValuesByKey`` then ``ExternalSorter.insertAll``), which caps memory and
spills sorted runs to disk.  The previous in-repo pipeline used an unbounded
dict + ``sorted()`` over a full list, so a large reduce partition OOMed — this
module closes that gap:

* records insert into an in-memory map (combine) or list (no combine) under an
  approximate byte budget;
* crossing the budget spills the current contents to a temp file as ONE run,
  sorted by the merge key (the actual key when ordering is requested —
  orderable by definition then — else ``hash(key)``, which any dict key
  supports);
* iteration k-way-merges the runs + the in-memory tail with ``heapq.merge``
  and, when combining, groups merge-key-equal records and aggregates per
  actual key (same-hash-different-key collisions stay correct: groups are
  tiny and combined through a dict).

Like the rest of the staging tiers, spill files are ``spill_dir``-configurable
(conf.spill_dir — shared with the store's disk round tier).
"""

from __future__ import annotations

import heapq
import itertools
import os
import pickle
import sys
import tempfile
from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

#: rough per-record bookkeeping overhead (dict entry / list slot, pointers)
_RECORD_OVERHEAD = 64

#: max spilled runs merged in one pass; beyond this, runs are hierarchically
#: compacted first so the merge never holds an unbounded number of open files
#: (Spark's ExternalSorter caps fan-in the same way)
DEFAULT_MERGE_FAN_IN = 64

#: deep-estimate recursion depth — matches SizeEstimator's bounded object-graph
#: walk (Spark bounds by visit count; a depth bound plays the same role for the
#: tree-shaped values shuffle records actually carry)
_ESTIMATE_MAX_DEPTH = 4

#: elements sampled per container level; beyond this the mean of the sample is
#: extrapolated over len() — Spark's SizeEstimator samples large arrays the
#: same way (ARRAY_SAMPLE_SIZE) so a million-element value costs O(sample)
_ESTIMATE_SAMPLE = 16


def _estimate(obj: Any, depth: int = _ESTIMATE_MAX_DEPTH) -> int:
    """Approximate deep retained size of ``obj`` in bytes.

    The role Spark's ``SizeEstimator`` plays for ExternalSorter's
    ``maybeSpill`` budget (UcxShuffleReader.scala:137-199 hands records to
    exactly that machinery): a shallow ``sys.getsizeof`` counts a list of 10k
    ints as ~56 B of pointer header, so nested-value workloads would blow
    through ``memory_budget`` without ever spilling.  Containers recurse to a
    bounded depth and sample ``_ESTIMATE_SAMPLE`` elements, extrapolating the
    sample mean over ``len()``, so cost per record stays O(sample * depth)
    regardless of value size: sequences are indexed at evenly spaced
    positions; dict/set (not indexable) take the first ``sample`` entries — a
    biased but O(sample) draw.  Scalars, numpy arrays, and
    ``__slots__``/``__dict__`` objects are sized directly."""
    try:
        size = sys.getsizeof(obj)
    except TypeError:  # objects with broken __sizeof__
        size = 64
    # exact-size leaves (getsizeof already counts their payload)
    if isinstance(obj, (str, bytes, bytearray, memoryview, int, float, bool, complex)) or obj is None:
        return size
    if isinstance(obj, np.ndarray):
        # getsizeof misses the buffer of array *views*; nbytes covers payload
        return size if obj.base is None else size + obj.nbytes
    if depth <= 0:
        return size
    if isinstance(obj, dict):
        n = len(obj)
        if n == 0:
            return size
        sampled = list(itertools.islice(obj.items(), _ESTIMATE_SAMPLE))
        per = sum(_estimate(k, depth - 1) + _estimate(v, depth - 1) for k, v in sampled)
        return size + per * n // len(sampled)
    if isinstance(obj, (list, tuple)):
        n = len(obj)
        if n == 0:
            return size
        k = min(n, _ESTIMATE_SAMPLE)
        per = sum(_estimate(obj[(i * n) // k], depth - 1) for i in range(k))
        return size + per * n // k
    if isinstance(obj, (set, frozenset)):
        n = len(obj)
        if n == 0:
            return size
        sampled = list(itertools.islice(obj, _ESTIMATE_SAMPLE))
        per = sum(_estimate(e, depth - 1) for e in sampled)
        return size + per * n // len(sampled)
    # plain objects: their attribute dict / slots
    d = getattr(obj, "__dict__", None)
    if d:
        return size + _estimate(d, depth - 1)
    slots = getattr(type(obj), "__slots__", None)
    if slots:
        return size + sum(
            _estimate(getattr(obj, s, None), depth - 1)
            for s in ([slots] if isinstance(slots, str) else slots)
        )
    return size


class _Run:
    """One spilled sorted run: a pickle stream of (merge_key, key, value).

    Pickle is safe HERE and only here: spill files are written and read back
    by the same process under a mkstemp path — they never carry peer bytes.
    The socket-facing record codec is the typed one (utils/codec.py)."""

    def __init__(self, items: Iterable[Tuple[Any, Any, Any]], spill_dir: Optional[str]):
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        fd, self.path = tempfile.mkstemp(prefix="sparkucx_tpu_reduce_", dir=spill_dir)
        with os.fdopen(fd, "wb") as f:
            for item in items:
                pickle.dump(item, f, protocol=pickle.HIGHEST_PROTOCOL)

    def __iter__(self) -> Iterator[Tuple[Any, Any, Any]]:
        with open(self.path, "rb") as f:
            while True:
                try:
                    yield pickle.load(f)
                except EOFError:
                    return

    def close(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ExternalCombiner:
    """Spillable combine/sort with an approximate in-memory byte budget.

    ``aggregator(acc, v)`` folds a VALUE into an accumulator (Spark's
    mergeValue); ``merge_combiners(acc1, acc2)`` merges two per-run
    accumulators of the same key after a spill (Spark's mergeCombiners,
    ExternalSorter's exact distinction) and defaults to ``aggregator`` — only
    correct when accumulator and value have the same type (sum-like folds);
    collect-style aggregators MUST pass an explicit ``merge_combiners``.
    ``key_ordering`` yields output sorted by key.  Mirrors what Spark's
    ExternalSorter provides the reference's reader
    (UcxShuffleReader.scala:137-199).
    """

    def __init__(
        self,
        aggregator: Optional[Callable[[Any, Any], Any]] = None,
        key_ordering: bool = False,
        memory_budget: int = 64 << 20,
        spill_dir: Optional[str] = None,
        merge_combiners: Optional[Callable[[Any, Any], Any]] = None,
        merge_fan_in: int = DEFAULT_MERGE_FAN_IN,
    ) -> None:
        self.aggregator = aggregator
        self.merge_combiners = merge_combiners if merge_combiners is not None else aggregator
        self.key_ordering = key_ordering
        self.memory_budget = max(1, memory_budget)
        self.spill_dir = spill_dir
        self.merge_fan_in = max(2, merge_fan_in)
        self.spill_count = 0
        self._map: dict = {}
        self._list: List[Tuple[Any, Any]] = []
        self._approx = 0
        self._runs: List[_Run] = []

    # -- insert ------------------------------------------------------------

    def insert(self, key: Any, value: Any) -> None:
        if self.aggregator is not None:
            if key in self._map:
                old = self._map[key]
                # growing accumulators (collect-style folds) must count against
                # the budget too, or they bypass the spill entirely; size the
                # old accumulator BEFORE the fold — an in-place aggregator
                # returns the same (already grown) object
                old_size = _estimate(old)
                new = self.aggregator(old, value)
                self._map[key] = new
                self._approx += _estimate(new) - old_size
            else:
                self._map[key] = value
                self._approx += _estimate(key) + _estimate(value) + _RECORD_OVERHEAD
        else:
            self._list.append((key, value))
            self._approx += _estimate(key) + _estimate(value) + _RECORD_OVERHEAD
        if self._approx > self.memory_budget:
            self._spill()

    def insert_all(self, records: Iterable[Tuple[Any, Any]]) -> None:
        for k, v in records:
            self.insert(k, v)

    # -- spill -------------------------------------------------------------

    def _merge_key(self, key: Any) -> Any:
        return key if self.key_ordering else hash(key)

    def _memory_items(self) -> List[Tuple[Any, Any, Any]]:
        pairs = self._map.items() if self.aggregator is not None else self._list
        return [(self._merge_key(k), k, v) for k, v in pairs]

    def _spill(self) -> None:
        items = self._memory_items()
        items.sort(key=lambda t: t[0])
        self._runs.append(_Run(items, self.spill_dir))
        self.spill_count += 1
        self._map = {}
        self._list = []
        self._approx = 0

    # -- output ------------------------------------------------------------

    def __iter__(self) -> Iterator[Tuple[Any, Any]]:
        if not self._runs:
            # pure in-memory path: identical behavior to the pre-spill pipeline
            pairs = (
                iter(self._map.items()) if self.aggregator is not None else iter(self._list)
            )
            if self.key_ordering:
                pairs = iter(sorted(pairs, key=lambda kv: kv[0]))
            return pairs
        return self._merged()

    def _compact_runs(self) -> None:
        """Hierarchically merge runs until at most ``merge_fan_in`` remain, so
        the final merge never holds an unbounded number of open files.  Plain
        order-preserving concatenation of sorted streams — aggregator combine
        happens only at final iteration, so duplicates pass through intact."""
        while len(self._runs) > self.merge_fan_in:
            batch, self._runs = self._runs[: self.merge_fan_in], self._runs[self.merge_fan_in :]
            merged = heapq.merge(*(iter(r) for r in batch), key=lambda t: t[0])
            self._runs.append(_Run(merged, self.spill_dir))
            for r in batch:
                r.close()

    def _merged(self) -> Iterator[Tuple[Any, Any]]:
        self._compact_runs()
        tail = self._memory_items()
        tail.sort(key=lambda t: t[0])
        streams = [iter(r) for r in self._runs] + [iter(tail)]
        merged = heapq.merge(*streams, key=lambda t: t[0])
        if self.aggregator is None:
            for _mk, k, v in merged:
                yield (k, v)
        else:
            # combine within each merge-key group; a group holds one key in the
            # common case, a handful on hash collision — bounded either way.
            # Entries are per-run ACCUMULATORS, so they merge with
            # merge_combiners, not the value-folding aggregator.
            for _mk, group in itertools.groupby(merged, key=lambda t: t[0]):
                acc: dict = {}
                order: list = []
                for _, k, v in group:
                    if k in acc:
                        acc[k] = self.merge_combiners(acc[k], v)
                    else:
                        acc[k] = v
                        order.append(k)
                for k in order:
                    yield (k, acc[k])

    def close(self) -> None:
        for r in self._runs:
            r.close()
        self._runs = []
        self._map = {}
        self._list = []
        self._approx = 0
