"""The unified plan executor — ONE engine interprets every ``ExchangePlan``.

Before this module, the exchange engine existed four times: the single-shot
and quota-capped loops in ``transport/tpu.py``, and their SPMD twins in
``transport/spmd.py`` — each duplicating the sub-round walk, the drain-side
chunk accounting, the occupancy telemetry, and (in the transports' builder
methods) the stock/pallas/hierarchical/quantized variant dispatch.  Every
capability multiplied that matrix.  Now the matrix is a *plan*
(``ops/skew.ExchangePlan``): a planner (``ops/planner.py``) chooses the
schedule, and :func:`execute_plan` interprets it, for both deployments.

The split of responsibilities is deliberate:

* This module owns everything *plan-shaped*: the sub-round submission order
  (including the staging-footprint permutation, re-emitting results in
  natural round order), the per-round chunk accumulation on the single drain
  worker, final-chunk completion, the ``RoundPipeline`` wiring, and the
  occupancy/bytes telemetry contract (intermediate chunks record zero rows;
  a round's final chunk records the round's staging occupancy — exactly the
  stat stream the retired engines produced).
* The transports own everything *deployment-shaped*, passed in as closures:
  how a sub-round's payload is assembled and dispatched (global-array
  assembly vs per-process shards), how a chunk is materialized host-side,
  and how a finished round's chunks splice into the receive state
  (host_recv_mode arms, memmap spill, device retention, elastic probes).
  Closures keep each transport's private state in its own module — the
  whole-program private-access pass stays clean by construction.

``single_shot`` plans (one chunk per round) run the historical quota-off
engine through the same loop: the chunk IS the round, ``finish_round`` sees
exactly one part, and the no-copy donation / elastic-recovery behavior lives
in the transport's closures.  Bit-equality of both styles against the
retired engines is pinned in tests/test_planner.py.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from sparkucx_tpu.ops.exchange import ExchangeSpec, build_exchange
from sparkucx_tpu.ops.skew import ExchangePlan
from sparkucx_tpu.transport.pipeline import RoundPipeline
from sparkucx_tpu.utils.stats import StatsAggregator

#: every receive mode any deployment understands, in doc order
HOST_RECV_MODES: Tuple[str, ...] = ("array", "memmap", "device")


def validate_host_recv_mode(
    mode: str,
    *,
    allowed: Sequence[str] = HOST_RECV_MODES,
    where: str = "this transport",
) -> str:
    """THE ``host_recv_mode`` gate — called before any staging allocation.

    Two distinct failures, same everywhere (the check used to be copy-pasted
    per transport): an *unknown* mode is a typo (``ValueError`` naming the
    full vocabulary), while a known mode a deployment cannot serve (the SPMD
    executor releases its HBM shard after the collective, so ``'device'``
    has nothing to serve fetches from) names the deployment and what it does
    support."""
    if mode not in HOST_RECV_MODES:
        raise ValueError(f"unknown host_recv_mode {mode!r} (array|memmap|device)")
    if mode not in allowed:
        raise ValueError(
            f"host_recv_mode {mode!r} is not supported by {where} "
            f"({'|'.join(allowed)})"
        )
    return mode


def build_plan_exchange(
    mesh,
    *,
    num_executors: int,
    send_rows: int,
    lane: int,
    axis_name: str,
    impl: str,
    num_slices: int = 1,
    quantize=None,
    combine=None,
):
    """THE lowering dispatch: one compiled exchange for a plan's geometry.

    Subsumes the builder ladders that lived (twice, copy-pasted) in the
    transports and the quantized-variant routing in ``ops/ici_exchange.py``:
    ``impl`` is the *resolved* tier (``resolve_exchange_impl`` over the
    plan's ``lowering`` field), ``num_slices > 1`` selects the two-phase
    ICI+DCN route, a ``QuantizeSpec`` routes to the lossy aggregation
    exchange, and a ``CombineSpec`` (``plan.combine == 'dense'``) routes to
    the receive-side fused-combine exchange — the one route whose output is
    the O(groups) accumulator instead of O(rows) received rows (its
    ``QuantizeSpec`` rides inside the ``CombineSpec``, so the two tiers
    compose without a second dispatch arm).  Callers keep their own compile
    caches (and their cache keys — the bucketing discipline the cache-hygiene
    pass audits); this function is the single place a key miss turns into a
    lowering."""
    spec = ExchangeSpec(
        num_executors=num_executors,
        send_rows=send_rows,
        recv_rows=send_rows,  # worst case: all regions full
        lane=lane,
        axis_name=axis_name,
        impl="auto",
    )
    if combine is not None:
        from sparkucx_tpu.ops.ici_exchange import (
            DEFAULT_CHUNKS_PER_DEST,
            build_combine_exchange,
        )

        # the fused combine is inherently the scheduled ring (the fold rides
        # the superstep epilogue); flat meshes only, like the quantized tier
        return build_combine_exchange(
            mesh, spec, combine, chunks_per_dest=DEFAULT_CHUNKS_PER_DEST
        )
    if quantize is not None:
        from sparkucx_tpu.ops.ici_exchange import build_quantized_exchange

        # The quantized exchange is inherently the scheduled ring; ``impl``
        # (stock|pallas) does not map onto its ICI lowering vocabulary
        # (auto|dma|xla|interpret) — let it resolve per platform.
        return build_quantized_exchange(mesh, spec, quantize)
    if num_slices > 1:
        # multi-slice: two-phase ICI+DCN route over the same devices,
        # slice-major (ops/hierarchy.py)
        from sparkucx_tpu.ops.hierarchy import (
            build_hierarchical_exchange,
            make_hierarchical_mesh,
        )

        hmesh = make_hierarchical_mesh(
            num_slices,
            num_executors // num_slices,
            devices=list(mesh.devices.reshape(-1)),
        )
        if impl == "pallas":
            from sparkucx_tpu.ops.ici_exchange import (
                DEFAULT_CHUNKS_PER_DEST,
                build_ici_exchange,
            )

            return build_ici_exchange(
                hmesh, spec.resolve_impl(), chunks_per_dest=DEFAULT_CHUNKS_PER_DEST
            )
        return build_hierarchical_exchange(hmesh, spec.resolve_impl())
    if impl == "pallas":
        # FAST-scheduled ring exchange (ops/ici_exchange.py): bit-identical
        # results, remote-DMA kernel on TPU, scheduled permutes elsewhere
        from sparkucx_tpu.ops.ici_exchange import (
            DEFAULT_CHUNKS_PER_DEST,
            build_ici_exchange,
        )

        return build_ici_exchange(mesh, spec, chunks_per_dest=DEFAULT_CHUNKS_PER_DEST)
    return build_exchange(mesh, spec)


def execute_plan(
    plan: ExchangePlan,
    *,
    submit: Callable[[int, int, int], Any],
    drain_chunk: Callable[[int, int, int, Any], Any],
    finish_round: Callable[[int, int, List[Any]], Any],
    result_bytes: Callable[[Any], int],
    occupancy: Callable[[Any], Tuple[int, int]],
    stats: Optional[StatsAggregator] = None,
    name: str = "exchange.pipeline",
    interrupt: Optional[Callable[[], Optional[BaseException]]] = None,
) -> List[Any]:
    """Interpret one plan: submit every sub-round through the depth-bounded
    ``RoundPipeline``, accumulate each staging round's drained chunks, and
    return one ``finish_round`` result per staging round in NATURAL round
    order (whatever ``plan.round_order`` the optimizer chose — the
    permutation is a submission-side schedule, never an observable layout).

    * ``submit(rnd, chunk, nchunks)`` — assemble + dispatch one sub-round's
      collective, return the drain ticket.  Runs on the caller's thread in
      plan order; poll your abort conditions here (or pass ``interrupt``).
    * ``drain_chunk(rnd, chunk, nchunks, ticket)`` — materialize one
      sub-round host-side; the returned part is queued for its round.
    * ``finish_round(rnd, nchunks, parts)`` — splice a round's parts (chunk
      order) into the round result the transport's receive state keeps.

    Telemetry contract (the retired engines', verbatim): every sub-round is
    one ``<name>.submit``/``<name>.drain`` op pair; a drain that completes a
    round records ``occupancy(result)`` rows and ``result_bytes(result)``,
    an intermediate chunk records zeros.  Single-shot plans therefore record
    per-round occupancy exactly like the historical engine — every chunk is
    final."""
    subs = plan.ordered_subrounds()
    # a round's drained parts so far, chunk order: appended and consumed ONLY
    # by the pipeline's single in-order drain worker, so no lock is needed
    # (closure-local, single-thread access by construction)
    pending: Dict[int, List[Any]] = {}

    def _submit(i: int):
        rnd, chunk, nchunks = subs[i]
        return submit(rnd, chunk, nchunks)

    def _drain(i: int, ticket):
        rnd, chunk, nchunks = subs[i]
        parts = pending.setdefault(rnd, [])
        parts.append(drain_chunk(rnd, chunk, nchunks, ticket))
        if len(parts) < nchunks:
            return None
        del pending[rnd]
        return rnd, finish_round(rnd, nchunks, parts)

    pipe = RoundPipeline(
        max(1, int(plan.pipeline_depth)),
        _submit,
        _drain,
        name=name,
        stats=stats,
        result_bytes=lambda r: 0 if r is None else int(result_bytes(r[1])),
        result_rows=lambda r: (0, 0) if r is None else occupancy(r[1]),
        interrupt=interrupt,
    )
    done = [r for r in pipe.run(len(subs)) if r is not None]
    done.sort(key=lambda t: t[0])
    return [result for _, result in done]
