"""Pipelined multi-round superstep engine — overlap H2D, collective, and D2H.

A spilled shuffle runs one collective per staging round.  The serial engine
(the historical behavior) executed rounds strictly back-to-back::

    assemble(k) -> device_put(k) -> collective(k) -> block_until_ready -> drain(k)

so the ICI links idled while round k's shards crossed PCIe back to the host and
round k+1's payload was still being assembled.  This module replaces the
per-round hard sync with *completion tracking per in-flight round*: while round
k's collective runs on device, round k+1 is assembled and staged H2D (JAX async
dispatch), and round k-1's received shards drain D2H on a background worker —
their ``copy_to_host_async`` was already issued at submit time, so the worker's
``np.asarray`` mostly just observes completion.

The engine is deliberately transport-agnostic: callers hand it two callbacks,

* ``submit(round) -> ticket`` — assemble the round's payload, dispatch H2D and
  the collective, kick off the async D2H, and return whatever the drain needs
  (device arrays, typically).  Runs on the caller's thread, in round order.
* ``drain(round, ticket) -> result`` — complete the round host-side (materialize
  arrays, write spill memmaps, retain device shards).  Runs on the drain worker
  for ``depth > 1``; inline for ``depth == 1``.

``run(num_rounds)`` returns the drain results in round order.  ``depth`` bounds
the in-flight window: at most ``depth`` rounds are submitted whose drains have
not completed, so peak memory is ~``depth`` receive buffers (device) plus the
transient host copies — the "ring of staging buffers".  ``depth == 1`` is the
bit-for-bit serial engine: submit then drain inline, one round at a time.

Failure contract: exceptions from either callback propagate out of ``run()``
(submit errors first, then the earliest-round drain error), so callers see the
same ``TransportError`` surface as the serial engine — a disk-cap overflow in a
round's spill still raises from ``run_exchange``, it is just discovered up to
``depth - 1`` rounds later.

Observability: every stage is wrapped in a ``utils.trace`` span
(``<name>.submit`` / ``<name>.drain``, tagged with the round and depth) and,
when a ``StatsAggregator`` is given, recorded as an operation of the same kind
— ``stats.summary("<name>.drain").total_ns`` over the run's wall time is the
drain lane's occupancy.

Thread-safety: the lock-discipline analyzer (sparkucx_tpu/analysis) audits this
module and found it clean by construction — every field is assigned once in
``__init__`` and cross-thread state flows only through ``Future`` results and
the internally-locked ``StatsAggregator``, so there is nothing to annotate with
``#: guarded by``.  Keep it that way: adding mutable shared state here should
come with a guard annotation the analyzer can check.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, List, Optional, Tuple

from sparkucx_tpu.core.operation import OperationStats
from sparkucx_tpu.utils.stats import StatsAggregator
from sparkucx_tpu.utils.trace import span


class CreditGate:
    """Byte-budget flow control shared by the fetch reader and the pipeline.

    ``acquire(n)`` blocks until ``used + n <= budget`` — except that a request
    larger than the whole budget is admitted *alone* (when nothing else is in
    flight), so one oversized round can never deadlock the gate.  ``release``
    returns credits and wakes waiters.  The gate never lets concurrent
    admissions exceed the budget (modulo the documented oversized-alone case)
    and drains back to zero when all holders release — tests/test_wire.py pins
    both properties.
    """

    def __init__(self, budget: int) -> None:
        if budget <= 0:
            raise ValueError(f"credit budget must be positive, got {budget}")
        self.budget = budget
        self._lock = threading.Condition()
        self._used = 0  #: guarded by self._lock
        self._stall_ns = 0  #: guarded by self._lock (time spent waiting for credit)

    def acquire(self, nbytes: int, timeout: Optional[float] = None) -> bool:
        nbytes = max(0, int(nbytes))
        t0 = time.monotonic_ns()
        with self._lock:
            ok = self._lock.wait_for(
                lambda: self._used + nbytes <= self.budget or self._used == 0,
                timeout=timeout,
            )
            if not ok:
                return False
            self._used += nbytes
            self._stall_ns += time.monotonic_ns() - t0
        return True

    def try_acquire(self, nbytes: int) -> bool:
        nbytes = max(0, int(nbytes))
        with self._lock:
            if self._used + nbytes <= self.budget or self._used == 0:
                self._used += nbytes
                return True
            return False

    def release(self, nbytes: int) -> None:
        nbytes = max(0, int(nbytes))
        with self._lock:
            self._used = max(0, self._used - nbytes)
            self._lock.notify_all()

    @property
    def used(self) -> int:
        with self._lock:
            return self._used

    @property
    def stall_ns(self) -> int:
        with self._lock:
            return self._stall_ns


class RoundPipeline:
    """Run ``num_rounds`` submit/drain pairs with up to ``depth`` in flight."""

    def __init__(
        self,
        depth: int,
        submit: Callable[[int], Any],
        drain: Callable[[int, Any], Any],
        *,
        name: str = "pipeline",
        stats: Optional[StatsAggregator] = None,
        result_bytes: Optional[Callable[[Any], int]] = None,
        result_rows: Optional[Callable[[Any], Tuple[int, int]]] = None,
        credits: Optional[CreditGate] = None,
        round_bytes: Optional[Callable[[int], int]] = None,
        interrupt: Optional[Callable[[], Optional[BaseException]]] = None,
    ) -> None:
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        if credits is not None and round_bytes is None:
            raise ValueError("credits requires round_bytes to cost each round")
        self.depth = depth
        self._submit_cb = submit
        self._drain_cb = drain
        self.name = name
        self.stats = stats
        self._result_bytes = result_bytes
        # result_rows(result) -> (used_rows, padded_rows): staging occupancy
        # of the round, surfaced as the drain span's padding telemetry
        self._result_rows = result_rows
        # Optional byte-budget gate shared with the wire path: round k's
        # submit blocks until its round_bytes(k) fit the budget alongside the
        # rounds already in flight; the credits return when the round drains
        # (or its stage raises).  Composes with the depth window — depth
        # bounds rounds, credits bound bytes, whichever is tighter wins.
        self._credits = credits
        self._round_bytes = round_bytes
        # Optional abort probe, polled before every submit (both engines): a
        # non-None return aborts the run by raising it there, so the pipeline
        # stops launching rounds whose plan went stale (elastic recovery uses
        # this to stop on a membership-epoch change).  Rounds already
        # submitted still drain — their credits/resources settle normally.
        self._interrupt = interrupt

    # -- instrumented stage wrappers --------------------------------------

    def _submit(self, rnd: int) -> Any:
        if self._interrupt is not None:
            exc = self._interrupt()
            if exc is not None:
                raise exc
        if self._credits is not None:
            self._credits.acquire(self._round_bytes(rnd))
        op = OperationStats()
        try:
            with span(f"{self.name}.submit", round=rnd, depth=self.depth):
                ticket = self._submit_cb(rnd)
        except BaseException:
            if self._credits is not None:  # round never reaches drain
                self._credits.release(self._round_bytes(rnd))
            raise
        op.mark_done()
        if self.stats is not None:
            self.stats.record(f"{self.name}.submit", op)
        return ticket

    def _drain(self, rnd: int, ticket: Any) -> Any:
        op = OperationStats()
        try:
            with span(f"{self.name}.drain", round=rnd, depth=self.depth):
                result = self._drain_cb(rnd, ticket)
        finally:
            if self._credits is not None:
                self._credits.release(self._round_bytes(rnd))
        op.mark_done(
            recv_size=self._result_bytes(result) if self._result_bytes else 0
        )
        if self.stats is not None:
            used, padded = (
                self._result_rows(result) if self._result_rows else (0, 0)
            )
            self.stats.record(
                f"{self.name}.drain", op, used_rows=used, padded_rows=padded
            )
        return result

    # -- the engine --------------------------------------------------------

    def run(self, num_rounds: int) -> List[Any]:
        if num_rounds < 0:
            raise ValueError(f"num_rounds must be >= 0, got {num_rounds}")
        depth = min(self.depth, max(num_rounds, 1))
        if depth <= 1:
            # Serial engine: identical op order to the historical loop (and
            # the reference both pipeline depths must be bit-identical to).
            return [self._drain(rnd, self._submit(rnd)) for rnd in range(num_rounds)]
        return self._run_pipelined(num_rounds, depth)

    def _run_pipelined(self, num_rounds: int, depth: int) -> List[Any]:
        results: List[Any] = [None] * num_rounds
        inflight: deque = deque()  # (round, ticket) submitted, drain not queued
        futures: List = []         # (round, Future) in round order
        submit_exc: Optional[BaseException] = None
        pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix=f"{self.name}-drain")
        try:
            for rnd in range(num_rounds):
                # Backpressure: round k submits only once round k-depth has
                # fully drained, so host+device memory stays bounded by the
                # ring of `depth` rounds, not by the round count.  (During the
                # loop futures[i] is exactly round i — rounds are handed to the
                # worker in order.  result() is cached, so re-collecting below
                # is free; a drain error here aborts further submission.)
                if rnd >= depth:
                    futures[rnd - depth][1].result()
                inflight.append((rnd, self._submit(rnd)))
                if len(inflight) >= depth:
                    r0, t0 = inflight.popleft()
                    futures.append((r0, pool.submit(self._drain, r0, t0)))
        except BaseException as e:  # noqa: BLE001 — re-raised below
            submit_exc = e
        if submit_exc is None:
            while inflight:
                r0, t0 = inflight.popleft()
                futures.append((r0, pool.submit(self._drain, r0, t0)))
        pool.shutdown(wait=True)
        exc = submit_exc
        for r0, fut in futures:
            try:
                results[r0] = fut.result()
            except BaseException as e:  # noqa: BLE001
                if exc is None:
                    exc = e  # earliest round's failure wins, like the serial loop
        if exc is not None:
            raise exc
        return results
