"""TpuShuffleTransport — the real TPU data plane (L3b).

The counterpart of ``UcxShuffleTransport`` + ``UcxWorkerWrapper`` (790 LoC of
endpoint/AM machinery, UcxShuffleTransport.scala / UcxWorkerWrapper.scala), rebuilt
around the XLA collective model instead of RDMA active messages:

* The reference *pulls*: each reduce task sends ``FetchBlockReq`` per block and the
  DPU daemon replies with bytes (UcxShuffleClient.scala:17-47).  XLA collectives
  are bulk-synchronous, so this transport *batches*: all executors stage map output
  into their HBM store, then ONE ``shuffle superstep`` — the ragged all_to_all in
  ops/exchange.py — moves every block to its consuming executor at ICI line rate.
  ``fetch_blocks_by_block_ids`` afterwards is a local slice of the received shard:
  the fetch a reducer used to wait on over the wire becomes a zero-copy lookup.
  (This is the batching layer SURVEY.md section 7 calls out as the push/pull
  bridge.)
* A *pull fallback* remains for stragglers/retries: ``fetch_block`` reads a peer's
  staged store directly (the reference's per-block AM path, ids 3/4) — in
  single-controller mode an in-process read, in multi-process mode the peer socket
  server (transport/peer.py).
* ``progress()`` maps the reference's explicit UCX polling contract
  (ShuffleTransport.scala:158-165) onto JAX async dispatch: it polls outstanding
  XLA executions (``jax.Array.is_ready``) and fires callbacks, never blocking.
* Per-op stats are kept with the same content as ``UcxStats``
  (UcxShuffleTransport.scala:36-53): submit->completion ns and received bytes.

Single-controller topology: one ``TpuShuffleCluster`` owns the executor mesh and N
``TpuShuffleTransport`` facets (one per executor), mirroring how the reference runs
one ``UcxShuffleTransport`` per Spark executor bootstrapped by the driver
(CommonUcxShuffleManager.scala:67-99).  Multi-process SPMD wires the same facets
over ``jax.distributed`` + the control plane in parallel/bootstrap.py.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import Block, BlockId, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.definitions import MapperInfo
from sparkucx_tpu.core.operation import (
    BlockNotFoundError,
    ExecutorLostError,
    OperationCallback,
    OperationResult,
    OperationStats,
    OperationStatus,
    Request,
    TransportError,
)
from sparkucx_tpu.core.transport import ExecutorId, ShuffleTransport
from sparkucx_tpu.parallel.membership import ClusterMembership
from sparkucx_tpu.parallel.mesh import surviving_submesh
from sparkucx_tpu.ops.exchange import (
    bucket_send_rows,
    make_mesh,
    rebucket_slots,
)
from sparkucx_tpu.ops.planner import PlanContext, PlanSignals, make_planner
from sparkucx_tpu.ops.skew import (
    chunk_size_rows,
    pad_rows_pow2,
    piece_slices,
    reassemble_round,
    slice_subround,
)
from sparkucx_tpu.shuffle.resolver import degraded_plan, ring_neighbors
from sparkucx_tpu.store.hbm_store import HbmBlockStore, default_peer_ranges
from sparkucx_tpu.testing import faults
from sparkucx_tpu.transport.executor import (
    build_plan_exchange,
    execute_plan,
    validate_host_recv_mode,
)
from sparkucx_tpu.obs.metrics import (
    MetricsRegistry,
    counter_dict_provider,
    stats_aggregator_provider,
    tracer_provider,
)
from sparkucx_tpu.obs.recorder import FlightRecorder
from sparkucx_tpu.utils.stats import StatsAggregator
from sparkucx_tpu.utils.trace import TRACER, instant, merge_events, span


@dataclass
class _ShuffleMeta:
    """Cluster-wide shuffle metadata — the role of the DPU daemon's committed
    offset tables plus Spark's MapOutputTracker (which the reference leans on at
    UcxShuffleReader.scala:75-76)."""

    shuffle_id: int
    num_mappers: int
    num_reducers: int
    map_owner: List[ExecutorId]                      # map task -> executor
    peer_ranges: List[Tuple[int, int]]               # reducer ownership
    mapper_infos: Dict[int, MapperInfo] = field(default_factory=dict)
    #: per-peer staging region size in bytes, stashed at create_shuffle so
    #: block-offset math (_locate_rows) and the elastic restage path never
    #: have to reach into an executor's store — which may be dead.
    region_bytes: int = 0
    # post-exchange receive state, one entry per staging round (multi-round
    # spill; a single round in the common case), each per executor.  Entries
    # are plain arrays (host_recv_mode='array'), np.memmap views ('memmap'),
    # or absent entirely ('device' — fetches slice HBM on demand):
    recv_shards: Optional[List[List[np.ndarray]]] = None  # [round][executor] uint8
    recv_sizes: Optional[List[np.ndarray]] = None         # [round] (n, n) rows j<-i
    #: memmap backing (path, bytes) to unlink on remove_shuffle ('memmap'
    #: mode); sizes are tracked so the disk budget is refunded exactly.
    #: Appended from the pipeline DRAIN worker while the main thread may be
    #: tearing the shuffle down — mutate only under the cluster's lock.
    recv_spill_paths: List[Tuple[str, int]] = field(default_factory=list)  #: guarded by self._lock
    # HBM-resident copies of the received shards (conf.keep_device_recv) —
    # the source the device-side block gather serves from:
    recv_device: Optional[List[List[object]]] = None      # [round][executor] jax.Array
    exchanged: bool = False

    def owner_of_reduce(self, reduce_id: int) -> ExecutorId:
        for p, (s, e) in enumerate(self.peer_ranges):
            if s <= reduce_id < e:
                return p
        raise ValueError(f"reduce_id {reduce_id} unowned")


class _MeshChanged(Exception):
    """Internal abort signal: cluster membership changed under an in-flight
    exchange.  Never escapes ``run_exchange`` — it either converts into a
    degraded re-plan (elastic.enabled + replicas available) or into a typed
    ``ExecutorLostError``."""

    def __init__(self, epoch0: int, snapshot: dict) -> None:
        self.epoch0 = epoch0
        self.snapshot = snapshot
        super().__init__(f"membership epoch {epoch0} -> {snapshot['epoch']}")


class TpuShuffleCluster:
    """Owns the executor mesh, the compiled exchange, and shuffle metadata."""

    def __init__(
        self,
        conf: Optional[TpuShuffleConf] = None,
        num_executors: Optional[int] = None,
        mesh=None,
    ) -> None:
        self.conf = conf or TpuShuffleConf()
        n = num_executors or self.conf.num_executors
        self.mesh = mesh if mesh is not None else make_mesh(n, self.conf.mesh_axis_name)
        self.num_executors = int(self.mesh.devices.size)
        devices = list(self.mesh.devices.reshape(-1))
        self.transports: List[TpuShuffleTransport] = [
            TpuShuffleTransport(self, eid, device=devices[eid]) for eid in range(self.num_executors)
        ]
        #: the exchange planner (ops/planner.py): conf.planner_mode selects
        #: the legacy-1:1 static mapping or the telemetry-fed adaptive one
        self.planner = make_planner(self.conf)
        self._meta: Dict[int, _ShuffleMeta] = {}  #: guarded by self._lock
        self._exchange_cache: Dict[Tuple[int, int, str], Callable] = {}  #: guarded by self._lock
        self._lock = threading.RLock()
        #: aggregate per-stage pipeline/exchange timings (occupancy view)
        self.stats = StatsAggregator()
        #: bytes of received-shard spill currently on disk (host_recv_mode=
        #: 'memmap'), charged against conf.spill_disk_cap_bytes like the
        #: store's staging spill; the drain worker charges, teardown refunds
        self._recv_spill_bytes = 0  #: guarded by self._lock
        #: Liveness/epoch layer.  Always constructed (it is just bookkeeping);
        #: with elastic.enabled=false nothing ever reports a death through it,
        #: the epoch stays 0, and every code path below is byte-identical to
        #: the pre-elastic behavior.
        self.membership = ClusterMembership(
            range(self.num_executors), self.conf.membership_suspect_after_ms
        )
        #: degraded-mode recovery telemetry (perf/benchmark.py `elastic` mode
        #: and the chaos tests read this)
        self.elastic_stats = {
            "recoveries": 0,
            "last_recovery_ms": 0.0,
            "last_epoch": 0,
            "degraded_mesh": None,
        }  #: guarded by self._lock
        #: Obs plane (PR 14): cluster-level registry + flight recorder.  The
        #: registry absorbs the collective plane's surfaces (exchange timings,
        #: elastic recovery counters, the trace ring's health); per-executor
        #: wire surfaces live in each PeerTransport's own registry.  The
        #: recorder does NOT install the global TransportError hook — clusters
        #: have no close() to unhook from, and PeerTransports already cover
        #: the wire error path — it captures on the cluster's own fault paths
        #: (elastic recovery, chaos kills) explicitly.
        self.metrics = MetricsRegistry()
        self.metrics.register("ops", stats_aggregator_provider(self.stats))
        self.metrics.register(
            "elastic", counter_dict_provider("elastic", self._elastic_snapshot)
        )
        self.metrics.register("obs", tracer_provider(TRACER))
        self.recorder = FlightRecorder(
            TRACER,
            postmortem_dir=self.conf.obs_postmortem_dir or None,
            ring_capacity=self.conf.obs_ring_capacity,
        )
        self.recorder.attach_registry(self.metrics)
        self.recorder.attach_membership(self.membership.snapshot)

    # -- membership / lookup ----------------------------------------------

    def transport(self, executor_id: ExecutorId) -> "TpuShuffleTransport":
        return self.transports[executor_id]

    def meta(self, shuffle_id: int) -> _ShuffleMeta:
        with self._lock:
            m = self._meta.get(shuffle_id)
        if m is None:
            raise TransportError(f"unknown shuffle {shuffle_id}")
        return m

    # -- obs plane ---------------------------------------------------------

    def _elastic_snapshot(self) -> Dict[str, float]:
        """Numeric view of the elastic telemetry for the metrics registry
        (the degraded-mesh tuple is for tests, not exposition)."""
        with self._lock:
            s = {k: v for k, v in self.elastic_stats.items() if isinstance(v, (int, float))}
        s["epoch"] = self.membership.epoch
        s["alive"] = len(self.membership.alive())
        s["dead"] = len(self.membership.dead())
        return s

    def export_trace(self, path: str, extra_buffers: Optional[List[List[dict]]] = None) -> int:
        """Merge every executor's trace events into ONE Perfetto file with
        pid = executor id; returns the event count.  Single-controller
        executors share the process-wide TRACER (tracks split by the
        ``executor_scope`` eid tag); multi-process meshes gather peer buffers
        over TRACE_PULL (``PeerTransport.pull_trace``) and pass the ``events``
        lists in via ``extra_buffers``."""
        import json as _json

        buffers = [TRACER.events]
        buffers.extend(extra_buffers or [])
        merged = merge_events(buffers)
        with open(path, "w") as f:
            _json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
        return len(merged)

    def metrics_text(self) -> str:
        """The cluster registry's Prometheus exposition (collective-plane
        surfaces; per-executor wire surfaces are each peer's METRICS_PULL)."""
        return self.metrics.prometheus_text()

    # -- shuffle lifecycle -------------------------------------------------

    def create_shuffle(
        self,
        shuffle_id: int,
        num_mappers: int,
        num_reducers: int,
        map_owner: Optional[Sequence[ExecutorId]] = None,
        capacity: Optional[int] = None,
    ) -> _ShuffleMeta:
        """Declare a shuffle cluster-wide: reducer ownership is contiguous ranges
        over executors; map tasks are assigned round-robin unless given.
        ``capacity`` overrides ``conf.staging_capacity_per_executor`` for this
        shuffle only — right-sizing small shuffles; capacity bucketing in
        ``_exchange_fn`` keeps nearby sizes on one compiled exchange."""
        n = self.num_executors
        owners = list(map_owner) if map_owner is not None else [m % n for m in range(num_mappers)]
        if len(owners) != num_mappers:
            raise ValueError("map_owner length != num_mappers")
        ranges = default_peer_ranges(num_reducers, n)
        meta = _ShuffleMeta(shuffle_id, num_mappers, num_reducers, owners, ranges)
        with self._lock:
            if shuffle_id in self._meta:
                raise TransportError(f"shuffle {shuffle_id} already exists")
            self._meta[shuffle_id] = meta
        for t in self.transports:
            t.store.create_shuffle(
                shuffle_id, num_mappers, num_reducers, peer_ranges=ranges, capacity=capacity
            )
        meta.region_bytes = self.transports[0].store.region_bytes(shuffle_id)
        return meta

    def remove_shuffle(self, shuffle_id: int) -> None:
        with self._lock:
            meta = self._meta.pop(shuffle_id, None)
        if meta is not None:
            import os

            meta.recv_shards = None  # drop memmap views before unlinking
            for path, size in meta.recv_spill_paths:
                try:
                    os.unlink(path)
                    freed = True
                except FileNotFoundError:
                    freed = True  # already gone: the bytes are not on disk
                except OSError:
                    freed = False  # still on disk: keep it charged
                if freed:
                    with self._lock:
                        self._recv_spill_bytes -= size
        for t in self.transports:
            t.store.remove_shuffle(shuffle_id)

    def drop_meta(self, shuffle_id: int) -> None:
        """Forget cluster-level metadata only — for callers whose resolvers
        already removed the per-store state (the unregisterShuffle split,
        CommonUcxShuffleManager.scala:103-106)."""
        with self._lock:
            self._meta.pop(shuffle_id, None)

    def commit_mapper(self, info: MapperInfo) -> None:
        """AM id 2 sink — the cluster is the 'daemon' holding the commit table."""
        meta = self.meta(info.shuffle_id)
        with self._lock:
            meta.mapper_infos[info.map_id] = info

    # -- the superstep -----------------------------------------------------

    @property
    def row_bytes(self) -> int:
        return self.conf.block_alignment

    def _exchange_fn(self, send_rows: int, lowering: Optional[str] = None):
        # Capacity bucketing: round the per-peer slot up to the next power of
        # two so shuffles of varying staging size share one compiled
        # executable per bucket (the caller relocates payloads into the
        # bucketed slot layout; padding rows carry zero sizes and never cross
        # the wire under the ragged lowering).  ``lowering`` is the plan's
        # collective tier (defaults to the conf knob); a key miss lowers
        # through the shared build_plan_exchange dispatch.
        send_rows = bucket_send_rows(send_rows, self.num_executors)
        from sparkucx_tpu.ops.ici_exchange import resolve_exchange_impl

        impl = resolve_exchange_impl(
            lowering or self.conf.exchange_impl,
            self.mesh.devices.reshape(-1)[0].platform,
            self.num_executors,
        )
        key = (
            self.num_executors, send_rows, self.row_bytes,
            self.conf.num_slices, impl,
        )
        with self._lock:
            fn = self._exchange_cache.get(key)
            if fn is None:
                fn = build_plan_exchange(
                    self.mesh,
                    num_executors=self.num_executors,
                    send_rows=send_rows,
                    lane=self.row_bytes // 4,
                    axis_name=self.conf.mesh_axis_name,
                    impl=impl,
                    num_slices=self.conf.num_slices,
                )
                self._exchange_cache[key] = fn
        return fn

    def run_exchange(self, shuffle_id: int) -> None:
        """Seal every executor's staging for this shuffle and run ONE collective
        superstep.  After this, every block is resident on its consuming
        executor and fetches are local."""
        with span("exchange.superstep", shuffle_id=shuffle_id):
            self._run_exchange(shuffle_id)

    def _run_exchange(self, shuffle_id: int) -> None:
        meta = self.meta(shuffle_id)
        if meta.exchanged:
            raise TransportError(f"shuffle {shuffle_id} already exchanged")
        committed = len(meta.mapper_infos)
        if committed != meta.num_mappers:
            raise TransportError(
                f"exchange before all maps committed ({committed}/{meta.num_mappers})"
            )

        # ONE host_recv_mode gate (transport/executor.py) — an unknown mode
        # is rejected here, before any staging allocation, with the same
        # vocabulary and error text as the SPMD executor's gate.
        mode = validate_host_recv_mode(self.conf.host_recv_mode)
        if mode == "device" and not self.conf.keep_device_recv:
            raise TransportError(
                "host_recv_mode='device' serves fetches from the HBM shards — "
                "it requires conf.keep_device_recv=true"
            )

        with span("exchange.seal", shuffle_id=shuffle_id):
            sealed = [t.store.seal(shuffle_id) for t in self.transports]
        num_rounds = max(len(s) for s in sealed)
        first_payload = sealed[0][0][0]
        send_rows, lane = int(first_payload.shape[0]), int(first_payload.shape[1])
        # Every executor's every round must share one (rows, lane) shape — the
        # assembly below slices the global array at bucketed-row strides, so a
        # divergent store geometry would mis-slice silently, not fail.
        for eid, s in enumerate(sealed):
            for rnd, (payload, _) in enumerate(s):
                shape = (int(payload.shape[0]), int(payload.shape[1]))
                if shape != (send_rows, lane):
                    raise TransportError(
                        f"executor {eid} sealed round {rnd} with shape {shape}, "
                        f"expected {(send_rows, lane)} — mismatched staging "
                        "geometry (stagingCapacity/blockAlignment) across executors"
                    )
        import jax.numpy as jnp

        n = self.num_executors
        staging_slot = send_rows // n
        # Plan context from the sealed size matrices (metadata-before-data:
        # the planner never sees payload bytes), plus the local telemetry
        # snapshot for the serve-plane decisions and the plan span.
        round_maxes = tuple(
            max(
                (int(np.max(s[rnd][1], initial=0)) for s in sealed if rnd < len(s)),
                default=0,
            )
            for rnd in range(num_rounds)
        )
        used_total = sum(int(np.sum(sr[1])) for s in sealed for sr in s)
        signals = PlanSignals.from_registry(self.metrics)
        ctx = PlanContext(
            num_executors=n,
            staging_slot_rows=staging_slot,
            round_max_rows=round_maxes,
            used_rows_total=used_total,
            row_bytes=self.row_bytes,
            platform=self.mesh.devices.reshape(-1)[0].platform,
            # raw block shuffles carry no aggregation geometry: agg_partial
            # stays False, so the planner's combine tier resolves to 'off'
            # (the fused fold only applies to partial-aggregate exchanges —
            # ops/relational.py fills these fields on that path)
            signals=signals,
        )
        plan = self.planner.plan(ctx)
        instant(
            "exchange.plan",
            shuffle_id=shuffle_id,
            planner=type(self.planner).__name__,
            **plan.describe(),
            **{f"signal_{k}": v for k, v in signals.describe().items()},
        )

        q = plan.slot_rows
        bucketed = q * n  # staged rows per executor (n slots x the plan slot)
        fn = self._exchange_fn(bucketed, plan.lowering)

        # Elastic prep: snapshot the membership epoch the plan was built
        # against, and (when replication is on) copy each executor's sealed
        # rounds to its ring successors so a mid-superstep death is
        # recoverable.  Degraded recovery covers single-shot plans only (the
        # historical quota-off engine); chunked plans fail fast with a typed
        # error, exactly like the retired quota engine.
        epoch0 = self.membership.epoch
        if plan.single_shot and self.conf.elastic and self.conf.replication_factor >= 1:
            with span("exchange.replicate", shuffle_id=shuffle_id):
                self._replicate_sealed(shuffle_id)

        def _mesh_changed() -> Optional[_MeshChanged]:
            if self.membership.epoch != epoch0:
                return _MeshChanged(epoch0, self.membership.snapshot())
            return None

        ax = self.conf.mesh_axis_name
        data_sharding = NamedSharding(self.mesh, P(ax, None))
        devices = list(self.mesh.devices.reshape(-1))
        keep_device = self.conf.keep_device_recv

        def _submit(rnd, chunk, nchunks):
            """One sub-round's assemble + H2D + collective dispatch + async
            D2H kick-off.  Everything here is JAX async dispatch: this
            sub-round's collective is still in flight when the next one
            assembles."""
            faults.check("exchange.submit", shuffle_id=shuffle_id, round=rnd)
            if self.membership.epoch != epoch0:
                if plan.single_shot:
                    raise _MeshChanged(epoch0, self.membership.snapshot())
                snap = self.membership.snapshot()
                dead = sorted(snap["dead"])
                raise ExecutorLostError(
                    dead[0] if dead else -1,
                    snap["epoch"],
                    "executor lost mid-exchange; degraded recovery does not "
                    "cover the quota-capped engine (slot_quota_rows > 0) — "
                    f"dead: {dead}",
                )
            payloads, size_rows = [], []
            for s in sealed:
                if rnd < len(s):
                    payloads.append(s[rnd][0])
                    size_rows.append(s[rnd][1])
                else:  # executor had fewer spill rounds: empty contribution
                    payloads.append(None)
                    size_rows.append(np.zeros(n, dtype=np.int32))
            sub_sizes = np.stack([chunk_size_rows(sr, chunk, q) for sr in size_rows])
            if all(isinstance(p, jax.Array) for p in payloads):
                # Shards were sealed straight onto their executors' devices —
                # assemble the global array without any host round-trip.
                if plan.single_shot and q == staging_slot:
                    # bucket == staging slot: donate the sealed payloads as-is
                    # (the historical single-shot no-copy fast path)
                    pieces = payloads
                else:
                    # slot relocation / chunk-window slice on each device
                    pieces = [slice_subround(p, n, chunk, q, xp=jnp) for p in payloads]
                data = jax.make_array_from_single_device_arrays(
                    (n * bucketed, lane), data_sharding, pieces
                )
            else:
                host = np.zeros((n * bucketed, lane), dtype=np.int32)
                for i, p in enumerate(payloads):
                    if p is not None:
                        # mixed host/device rounds pay one D2H here, same as
                        # the historical assemble (allowlisted host-sync cost)
                        arr = np.asarray(p) if isinstance(p, jax.Array) else p
                        host[i * bucketed : (i + 1) * bucketed] = slice_subround(
                            arr, n, chunk, q
                        )
                data = jax.device_put(host, data_sharding)
            size_mat = jax.device_put(
                sub_sizes.astype(np.int32), NamedSharding(self.mesh, P(ax, None))
            )
            with span(
                "exchange.collective",
                shuffle_id=shuffle_id, round=rnd, chunk=chunk, rows=bucketed,
            ):
                recv, recv_sizes = fn(data, size_mat)
            # Pin the per-device shard objects HERE (addressable_shards builds
            # fresh wrappers per call — reusing these keeps the async-copy
            # cache) and start their D2H now, while later sub-rounds keep the
            # device busy; the drain's np.asarray then observes completion
            # instead of initiating the copy.
            shard_by_device = {s.device: s.data for s in recv.addressable_shards}
            if mode != "device":
                for a in shard_by_device.values():
                    a.copy_to_host_async()
            recv_sizes.copy_to_host_async()
            return recv, recv_sizes, shard_by_device

        def _drain_chunk(rnd, chunk, nchunks, ticket):
            """Complete one sub-round host-side (drain-worker thread at
            depth > 1).  Single-shot rounds materialize their whole receive
            state here — including the streamed memmap spill — so host RSS
            keeps the historical one-in-flight-window bound."""
            recv, recv_sizes, shard_by_device = ticket
            sizes_host = np.asarray(recv_sizes)
            if mode == "device":
                # No host copy at all: fetches slice the retained HBM shard
                # and D2H only the requested block (locate_received_block).
                jax.block_until_ready(recv)
                host_parts = None
            elif plan.single_shot and mode == "memmap":
                # One D2H per shard, streamed straight into a disk-backed
                # mapping; the round's RAM is released once pages flush, so
                # host RSS stays bounded by ~one in-flight window however many
                # rounds the shuffle spills.
                with span("exchange.d2h_memmap", shuffle_id=shuffle_id, round=rnd):
                    host_parts = self._memmap_round(
                        meta,
                        rnd,
                        (
                            np.asarray(shard_by_device[devices[j]]).reshape(-1).view(np.uint8)
                            for j in range(n)
                        ),
                    )
            else:
                # One D2H per executor shard; fetches (or the round splice)
                # then slice host memory.
                with span("exchange.d2h", shuffle_id=shuffle_id, round=rnd, chunk=chunk):
                    host_parts = [
                        np.asarray(shard_by_device[devices[j]]).reshape(-1).view(np.uint8)
                        for j in range(n)
                    ]
            dev_parts = (
                [shard_by_device[devices[j]] for j in range(n)] if keep_device else None
            )
            return sizes_host, host_parts, dev_parts

        def _finish_round(rnd, nchunks, parts):
            """Emit one staging round's receive state: a single-shot round
            passes its only chunk through (whole padded shards, the
            historical layout); a chunked round splices its sub-round shards
            back into the exact single-shot layout (bit-equality pinned in
            tests/test_skew.py and tests/test_planner.py)."""
            if plan.single_shot:
                sizes_host, shards, dev_shards = parts[0]
                used = int(sizes_host.sum())
                return shards, sizes_host, dev_shards, (used, n * bucketed - used)
            sub_size_mats = [p[0] for p in parts]
            logical = np.sum(sub_size_mats, axis=0).astype(np.int32)
            shards = dev_shards = None
            if mode != "device":
                assembled = [
                    reassemble_round(
                        [p[1][j] for p in parts],
                        [m[j] for m in sub_size_mats],
                        self.row_bytes,
                    )
                    for j in range(n)
                ]
                if mode == "memmap":
                    with span("exchange.d2h_memmap", shuffle_id=shuffle_id, round=rnd):
                        shards = self._memmap_round(meta, rnd, assembled)
                else:
                    shards = assembled
            if keep_device:
                dev_shards = []
                for j in range(n):
                    splice = piece_slices([m[j] for m in sub_size_mats])
                    pieces = [
                        parts[c][2][j][start : start + rows] for c, start, rows in splice
                    ]
                    if pieces:
                        # pow2-pad so the block gather's jit cache stays
                        # bounded despite data-dependent reassembled rows
                        dshard = pad_rows_pow2(jnp.concatenate(pieces), xp=jnp)
                    else:
                        dshard = jnp.zeros((1, lane), dtype=parts[0][2][j].dtype)
                    dev_shards.append(dshard)
            used = int(logical.sum())
            return shards, logical, dev_shards, (used, nchunks * n * bucketed - used)

        try:
            results = execute_plan(
                plan,
                submit=_submit,
                drain_chunk=_drain_chunk,
                finish_round=_finish_round,
                result_bytes=lambda r: int(r[1].sum()) * self.row_bytes,
                # staging occupancy per round: used rows vs. the slot padding
                # the planner's quota/chunking decisions exist to shrink
                occupancy=lambda r: r[3],
                stats=self.stats,
                interrupt=_mesh_changed if plan.single_shot else None,
            )
        except _MeshChanged:
            # An executor died under this exchange: abort the stale full-mesh
            # plan and re-run degraded on the surviving pow2 bucket (or raise
            # a typed ExecutorLostError when recovery is impossible).
            with span("exchange.recover", shuffle_id=shuffle_id):
                self._recover_and_rerun(meta, sealed, mode)
            return

        meta.recv_shards, meta.recv_sizes = [], []
        for shards, sizes_host, dev_shards, _occ in results:
            if shards is not None:
                meta.recv_shards.append(shards)
            meta.recv_sizes.append(sizes_host)
            active = int(np.count_nonzero(sizes_host))
            self.stats.record_rows("exchange.lanes", active, sizes_host.size - active)
            if dev_shards is not None:
                if meta.recv_device is None:
                    meta.recv_device = []
                meta.recv_device.append(dev_shards)
        if mode == "device":
            meta.recv_shards = None  # explicit no-host-copy marker
        meta.exchanged = True

    # -- elastic membership / degraded-mode recovery -----------------------

    def _replicate_sealed(self, shuffle_id: int) -> None:
        """Copy every executor's sealed rounds to its ring successors
        (single-controller twin of PeerTransport._replicate_push): a direct
        store-to-store ``put_replica`` with the same entry table and landing
        zone as the wire path, so ``_recover_and_rerun`` restages from the
        same placement either way."""
        n = self.num_executors
        factor = self.conf.replication_factor
        for t in self.transports:
            if not self.membership.is_alive(t.executor_id):
                continue
            rounds = t.store.replica_source(shuffle_id)
            for succ in ring_neighbors(t.executor_id, range(n), factor):
                if not self.membership.is_alive(succ):
                    continue
                for rnd, entries, body in rounds:
                    self.transports[succ].store.put_replica(
                        shuffle_id, t.executor_id, rnd, entries, body
                    )

    def _recover_and_rerun(self, meta, sealed, mode: str) -> None:
        """Degraded-mode recovery: quarantine the aborted exchange's partial
        state, restage every dead executor's rounds from ring-successor
        replicas, shrink to the surviving pow2 bucket, and re-run the whole
        shuffle as ``waves x waves`` sub-exchanges on the shrunk mesh.

        Determinism: each sub-exchange (i, j) moves wave i's senders' regions
        for wave j's consumers, and a consumer's final shard concatenates its
        sub-shards in ascending wave order — exactly the sender-major packed
        layout the full-mesh exchange produces, so the recovered bytes are
        bit-identical to an undisturbed run (pinned in tests/test_elastic.py).
        """
        shuffle_id = meta.shuffle_id
        op = OperationStats()
        t0 = time.monotonic()
        snap = self.membership.snapshot()
        dead, alive, epoch = snap["dead"], snap["alive"], snap["epoch"]
        first_dead = sorted(dead)[0] if dead else -1

        # Quarantine: drop any partially-drained receive state and refund its
        # disk budget — the aborted plan's outputs must never leak into the
        # recovered shuffle.
        meta.recv_shards = None
        meta.recv_sizes = None
        meta.recv_device = None
        with self._lock:
            doomed, meta.recv_spill_paths = meta.recv_spill_paths, []
        if doomed:
            import os

            for path, size in doomed:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                with self._lock:
                    self._recv_spill_bytes -= size

        def unsupported(why: str) -> ExecutorLostError:
            return ExecutorLostError(
                first_dead, epoch, f"{why}; dead executors: {dict(dead)}"
            )

        if not self.conf.elastic:
            raise unsupported(
                "elastic recovery disabled (spark.shuffle.tpu.elastic.enabled=false)"
            )
        if dead and self.conf.replication_factor < 1:
            raise unsupported("no replicas to restage from (replication.factor=0)")
        if self.conf.num_slices > 1:
            raise unsupported(
                "degraded recovery does not cover multi-slice meshes (num_slices > 1)"
            )
        if mode == "device" or self.conf.keep_device_recv:
            raise unsupported(
                "degraded recovery does not cover device-resident receive "
                "(host_recv_mode='device' / keep_device_recv)"
            )

        n = self.num_executors
        num_rounds = max(len(s) for s in sealed)
        m, phys, waves = degraded_plan(n, alive)
        alive_set = set(alive)
        slot_rows = meta.region_bytes // self.row_bytes
        send_rows = n * slot_rows
        lane = self.row_bytes // 4

        # Restage each dead executor's rounds bit-identically from replicas:
        # zeros staging (padding rows are zero by construction), replica block
        # bodies at their MapperInfo absolute offsets, per-region used-row
        # counts rebuilt from the padded lengths (allocation was contiguous,
        # so the padded sum IS the region's used prefix).
        restaged: Dict[int, List[Tuple[np.ndarray, np.ndarray]]] = {}
        for d in sorted(dead):
            dead_rounds = len(sealed[d])
            sealed[d] = None  # its memory died with it — recover honestly
            cands = ring_neighbors(d, range(n), self.conf.replication_factor)
            live_cands = [c for c in cands if c in alive_set]
            rounds_out: List[Tuple[np.ndarray, np.ndarray]] = []
            for rnd in range(dead_rounds):
                payload = np.zeros((send_rows, lane), dtype=np.int32)
                flat = payload.reshape(-1).view(np.uint8)
                sizes = np.zeros(n, dtype=np.int64)
                for map_id, info in meta.mapper_infos.items():
                    if meta.map_owner[map_id] != d:
                        continue
                    for r, (off, ln) in enumerate(info.partitions):
                        if not ln or info.round_of(r) != rnd:
                            continue
                        body = None
                        for c in live_cands:
                            body = self.transports[c].store.replica_block(
                                shuffle_id, d, map_id, r
                            )
                            if body is not None:
                                break
                        if body is None:
                            raise BlockNotFoundError(
                                shuffle_id, map_id, r,
                                f"primary executor {d} is dead and no replica "
                                f"found on candidates {cands} (alive: "
                                f"{live_cands}) — shuffle {shuffle_id} is "
                                "unrecoverable",
                            )
                        flat[off : off + ln] = np.frombuffer(bytes(body), dtype=np.uint8)
                        sizes[off // meta.region_bytes] += -(-ln // self.row_bytes)
                rounds_out.append((payload, sizes.astype(np.int32)))
            restaged[d] = rounds_out

        def round_payload(l, rnd):
            src = sealed[l] if sealed[l] is not None else restaged.get(l, [])
            if rnd < len(src):
                return src[rnd]
            return None, np.zeros(n, dtype=np.int32)

        fn, submesh = self._degraded_exchange_fn(m, phys, m * slot_rows, epoch)
        bucketed = bucket_send_rows(m * slot_rows, m)
        ax = self.conf.mesh_axis_name
        sub_sharding = NamedSharding(submesh, P(ax, None))
        sub_devices = list(submesh.devices.reshape(-1))

        meta.recv_shards, meta.recv_sizes = [], []
        for rnd in range(num_rounds):
            payloads, size_rows = [], []
            for l in range(n):
                p, s = round_payload(l, rnd)
                payloads.append(p)
                size_rows.append(s)
            full_sizes = np.stack(size_rows).astype(np.int64)  # [sender, dest]
            consumer_parts: List[List[np.ndarray]] = [[] for _ in range(n)]
            for i in range(waves):
                for j in range(waves):
                    host = np.zeros((m * bucketed, lane), dtype=np.int32)
                    sub_sizes = np.zeros((m, m), dtype=np.int32)
                    lo = j * m * slot_rows
                    hi = min((j + 1) * m, n) * slot_rows
                    for p in range(m):
                        l = i * m + p
                        if l >= n:
                            continue
                        for q in range(m):
                            c = j * m + q
                            if c < n:
                                sub_sizes[p, q] = full_sizes[l, c]
                        if payloads[l] is None:
                            continue
                        src = np.asarray(payloads[l])
                        block = np.zeros((m * slot_rows, lane), dtype=np.int32)
                        block[: hi - lo] = src[lo:hi]
                        host[p * bucketed : (p + 1) * bucketed] = rebucket_slots(
                            block, m, bucketed
                        )
                    if not int(sub_sizes.sum()):
                        continue  # empty sub-exchange: contributes zero rows
                    data = jax.device_put(host, sub_sharding)
                    size_mat = jax.device_put(sub_sizes, sub_sharding)
                    with span(
                        "exchange.collective.degraded",
                        shuffle_id=shuffle_id, round=rnd, wave=(i, j), rows=bucketed,
                    ):
                        recv, recv_sizes = fn(data, size_mat)
                    shard_by_device = {s.device: s.data for s in recv.addressable_shards}
                    sizes_host = np.asarray(recv_sizes)  # [consumer, sender]
                    for q in range(m):
                        c = j * m + q
                        if c >= n:
                            continue
                        used = int(sizes_host[q].sum())
                        if used:
                            consumer_parts[c].append(
                                np.asarray(shard_by_device[sub_devices[q]])[:used]
                                .reshape(-1)
                                .view(np.uint8)
                            )
            assembled = [
                np.concatenate(parts) if parts else np.empty(0, dtype=np.uint8)
                for parts in consumer_parts
            ]
            if mode == "memmap":
                with span("exchange.d2h_memmap", shuffle_id=shuffle_id, round=rnd):
                    shards = self._memmap_round(meta, rnd, iter(assembled))
            else:
                shards = assembled
            recv_mat = full_sizes.T.astype(np.int32).copy()
            meta.recv_shards.append(shards)
            meta.recv_sizes.append(recv_mat)
            active = int(np.count_nonzero(recv_mat))
            self.stats.record_rows("exchange.lanes", active, recv_mat.size - active)
        meta.exchanged = True
        recovery_ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            self.elastic_stats["recoveries"] += 1
            self.elastic_stats["last_recovery_ms"] = recovery_ms
            self.elastic_stats["last_epoch"] = epoch
            self.elastic_stats["degraded_mesh"] = (m, tuple(phys))
        op.mark_done()
        self.stats.record("exchange.recovery", op)
        instant(
            "exchange.recovered",
            shuffle_id=shuffle_id, epoch=epoch, mesh=m, waves=waves,
            recovery_ms=round(recovery_ms, 3),
        )
        # full postmortem bundle (metrics + membership): safe here — the
        # recovery is done and no subsystem lock is held on this thread
        self.recorder.capture(
            "elastic_recovery",
            shuffle_id=shuffle_id,
            epoch=epoch,
            mesh=m,
            recovery_ms=round(recovery_ms, 3),
        )

    def _degraded_exchange_fn(self, m: int, phys, sub_rows: int, epoch: int):
        """Compile (or reuse) the shrunk-mesh exchange for a degraded epoch.
        The cache key carries the membership epoch and surviving device set on
        top of the usual pow2 bucket, so a later failure pattern with the same
        geometry still recompiles against its own mesh."""
        send_rows = bucket_send_rows(sub_rows, m)
        from sparkucx_tpu.ops.ici_exchange import resolve_exchange_impl

        submesh = surviving_submesh(self.mesh, phys, self.conf.mesh_axis_name)
        impl = resolve_exchange_impl(
            self.conf.exchange_impl, submesh.devices.reshape(-1)[0].platform, m
        )
        key = ("degraded", epoch, m, tuple(phys), send_rows, self.row_bytes, impl)
        with self._lock:
            fn = self._exchange_cache.get(key)
            if fn is None:
                fn = build_plan_exchange(
                    submesh,
                    num_executors=m,
                    send_rows=send_rows,
                    lane=self.row_bytes // 4,
                    axis_name=self.conf.mesh_axis_name,
                    impl=impl,
                )
                self._exchange_cache[key] = fn
        return fn, submesh

    def note_executor_lost(self, executor_id: ExecutorId, reason: str) -> bool:
        """Report a death observed outside the chaos harness (wire errors,
        timeouts); returns True when this observation newly killed the
        executor (epoch bumped)."""
        return self.membership.mark_dead(executor_id, reason)

    def rejoin_executor(self, executor_id: ExecutorId) -> bool:
        """Regrow: mark a previously-dead executor alive again.  The full mesh
        is restored for the NEXT shuffle epoch — in-flight degraded state is
        untouched, and because full-mesh compile-cache keys carry no epoch,
        regrowing recompiles nothing."""
        return self.membership.mark_alive(executor_id)

    def _memmap_round(self, meta, rnd: int, host_views):
        """Spill one round's received shards to a disk-backed mapping and
        return uint8 ``np.memmap`` views (host_recv_mode='memmap').

        ``host_views`` yields one flat uint8 array per executor; passing a
        generator keeps host RSS at ~one transient shard — each view is
        materialized, written, and dropped before the next is produced."""
        import os
        import tempfile

        spill_dir = self.conf.spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        views = []
        for j, host in enumerate(host_views):
            cap = self.conf.spill_disk_cap_bytes
            nbytes = int(host.nbytes)
            if nbytes == 0:
                # nothing received (a quota-path tight shard can be empty);
                # np.memmap cannot map a zero-byte file, and there is nothing
                # to spill — keep the empty array itself
                views.append(host)
                continue
            # reserve-then-write keeps check+charge atomic under the lock;
            # any write failure refunds the reservation and removes the
            # half-written file so the budget cannot leak
            with self._lock:
                if cap and self._recv_spill_bytes + nbytes > cap:
                    raise TransportError(
                        f"received-shard spill would exceed spill_disk_cap_bytes "
                        f"({self._recv_spill_bytes + nbytes} > {cap}); raise the "
                        f"cap or use host_recv_mode='device'"
                    )
                self._recv_spill_bytes += nbytes
            fd, path = tempfile.mkstemp(
                prefix=f"sparkucx_tpu_recv_s{meta.shuffle_id}_r{rnd}_e{j}_",
                dir=spill_dir,
            )
            os.close(fd)
            shape = host.shape
            try:
                mm = np.memmap(path, dtype=np.uint8, mode="w+", shape=shape)
                mm[:] = host
                mm.flush()
            except BaseException:
                with self._lock:
                    self._recv_spill_bytes -= nbytes
                try:
                    os.unlink(path)
                except OSError:
                    pass
                raise
            # Drop the write mapping and reopen read-only: the dirty pages are
            # unmapped (host RSS actually falls back to ~one transient shard),
            # and fetches fault in only the pages they touch.
            del mm, host
            # the drain worker appends while remove_shuffle may iterate on the
            # main thread — same lock as the budget it charges against
            with self._lock:
                meta.recv_spill_paths.append((path, nbytes))
            views.append(np.memmap(path, dtype=np.uint8, mode="r", shape=shape))
        return views

    # -- post-exchange block lookup ---------------------------------------

    def locate_received_block(
        self, consumer: ExecutorId, shuffle_id: int, map_id: int, reduce_id: int
    ) -> Tuple[np.ndarray, int]:
        """Locate block (map_id, reduce_id) inside ``consumer``'s received shard.

        Returns (uint8 view of the block payload, length).  Offset math:
        sender's chunk starts at sum of earlier senders' recv sizes; within the
        chunk the block sits at its region-relative offset (MapperInfo offsets
        are absolute in the sender's staging buffer; regions are slot-aligned).
        """
        meta = self.meta(shuffle_id)
        if not meta.exchanged:
            raise TransportError(f"shuffle {shuffle_id} not exchanged yet")
        rnd, src_row, rows = self._locate_rows(meta, consumer, map_id, reduce_id)
        if rows == 0:
            return np.empty(0, dtype=np.uint8), 0
        length = meta.mapper_infos[map_id].partitions[reduce_id][1]
        if meta.recv_shards is None:
            # host_recv_mode='device': no host copy exists — slice the block's
            # rows out of the HBM-resident shard and D2H just those bytes.
            shard = meta.recv_device[rnd][consumer]
            block_rows = np.asarray(shard[src_row : src_row + rows])
            return block_rows.reshape(-1).view(np.uint8)[:length], length
        shard = meta.recv_shards[rnd][consumer]
        start = src_row * self.row_bytes
        return shard[start : start + length], length

    def _locate_rows(
        self, meta: _ShuffleMeta, consumer: ExecutorId, map_id: int, reduce_id: int
    ) -> Tuple[int, int, int]:
        """Row-granular location of a block inside ``consumer``'s received shard:
        (round, src_row, row_count).  Same offset math as
        ``locate_received_block`` in rows of ``row_bytes``."""
        if meta.owner_of_reduce(reduce_id) != consumer:
            raise TransportError(
                f"reducer {reduce_id} is owned by executor "
                f"{meta.owner_of_reduce(reduce_id)}, not {consumer}"
            )
        info = meta.mapper_infos.get(map_id)
        if info is None:
            raise TransportError(f"map {map_id} never committed")
        abs_offset, length = info.partitions[reduce_id]
        if length == 0:
            return 0, 0, 0
        rnd = info.round_of(reduce_id)
        sender = meta.map_owner[map_id]
        region_bytes = meta.region_bytes
        region_rel = abs_offset - consumer * region_bytes
        if not (0 <= region_rel < region_bytes):
            raise TransportError(
                f"block ({meta.shuffle_id},{map_id},{reduce_id}) offset {abs_offset} "
                f"not in consumer {consumer}'s region"
            )
        row = self.row_bytes
        chunk_start = int(meta.recv_sizes[rnd][consumer, :sender].sum())
        return rnd, chunk_start + region_rel // row, -(-length // row)

    def _gather_fn(self, impl: Optional[str], num_blocks: int, out_rows: int):
        """Cache compiled gathers; shapes are bucketed to powers of two (blocks
        padded with zero-count entries, which the kernels skip) so repeated
        fetches of varying batch sizes reuse a handful of compilations."""
        from sparkucx_tpu.ops.pallas_kernels import build_block_gather

        if impl is None or impl == "auto":
            impl = self.conf.gather_impl
        if impl == "auto":
            impl = None  # build_block_gather picks by platform
        b = 1 << max(num_blocks - 1, 0).bit_length()
        r = 1 << max(out_rows - 1, 0).bit_length()
        key = ("gather", impl, b, r)
        with self._lock:
            fn = self._exchange_cache.get(key)
            if fn is None:
                fn = build_block_gather(b, r, impl=impl)
                self._exchange_cache[key] = fn
        return fn, b, r

    def fetch_blocks_to_device(
        self,
        consumer: ExecutorId,
        shuffle_id: int,
        block_ids: Sequence[ShuffleBlockId],
        impl: Optional[str] = None,
    ) -> Tuple[object, np.ndarray]:
        """Device-side batch fetch: pack the requested blocks into ONE
        HBM-resident buffer on ``consumer``'s device — the bytes never visit the
        host.  The TPU analogue of the reference's reply packing (parallel
        reads into one pooled bounce buffer, single AM reply —
        UcxWorkerWrapper.scala:397-448), with the DMA engine playing the IO
        thread pool (ops/pallas_kernels.py).

        Returns ``(packed, entries)``: ``packed`` is a (rows, lane) int32
        ``jax.Array`` (rows past the packed total are unspecified); ``entries``
        is (B, 2) int64 — per requested block, its starting ROW in ``packed``
        and its true byte length.  Requires ``conf.keep_device_recv``.
        """
        meta = self.meta(shuffle_id)
        if not meta.exchanged:
            raise TransportError(f"shuffle {shuffle_id} not exchanged yet")
        if meta.recv_device is None:
            raise TransportError("device shards not retained (conf.keep_device_recv=false)")

        with span("fetch.device_gather", shuffle_id=shuffle_id, blocks=len(block_ids)):
            return self._fetch_blocks_to_device(meta, consumer, shuffle_id, block_ids, impl)

    def _fetch_blocks_to_device(self, meta, consumer, shuffle_id, block_ids, impl):
        import jax.numpy as jnp

        located = []  # (round, src_row, rows) per request
        for bid in block_ids:
            if bid.shuffle_id != shuffle_id:
                raise TransportError(f"block {bid} not from shuffle {shuffle_id}")
            located.append(self._locate_rows(meta, consumer, bid.map_id, bid.reduce_id))

        entries = np.zeros((len(located), 2), dtype=np.int64)
        lane = self.row_bytes // 4
        segments = []
        base = 0
        for rnd in sorted({r for r, _, c in located if c}):
            idxs = [i for i, (r, _, c) in enumerate(located) if r == rnd and c]
            starts = np.asarray([located[i][1] for i in idxs], dtype=np.int32)
            counts = np.asarray([located[i][2] for i in idxs], dtype=np.int32)
            outs = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
            total = int(counts.sum())
            for i, o in zip(idxs, outs):
                bid = block_ids[i]
                entries[i] = (base + int(o), meta.mapper_infos[bid.map_id].partitions[bid.reduce_id][1])
            fn, b_pad, _ = self._gather_fn(impl, len(idxs), total)
            pad = b_pad - len(idxs)
            if pad:
                starts = np.pad(starts, (0, pad))
                counts = np.pad(counts, (0, pad))
                # Padding entries land at the packed end (outs=total, count=0):
                # the xla lowering's searchsorted needs outs+counts non-
                # decreasing; the Pallas lowerings skip zero-count blocks.
                outs = np.pad(outs, (0, pad), constant_values=total)
            src = meta.recv_device[rnd][consumer]
            dev = src.device
            # One (3, B) H2D upload for the whole gather plan instead of three
            # tiny per-array transfers; split back on device (views, no copy).
            plan = jax.device_put(np.stack([starts, counts, outs]), dev)
            packed = fn(plan[0], plan[1], plan[2], src)
            segments.append(packed[:total])
            base += total
        if not segments:
            return jnp.zeros((0, lane), dtype=jnp.int32), entries
        packed_all = segments[0] if len(segments) == 1 else jnp.concatenate(segments, axis=0)
        return packed_all, entries


class TpuShuffleTransport(ShuffleTransport):
    """Per-executor facet of the cluster — implements the transport trait."""

    def __init__(self, cluster: TpuShuffleCluster, executor_id: ExecutorId, device=None) -> None:
        self.cluster = cluster
        self.executor_id = executor_id
        self.device = device
        self.store = HbmBlockStore(cluster.conf, device=device, executor_id=executor_id)
        self._registry: Dict[BlockId, Block] = {}
        self._registry_lock = threading.Lock()
        self._outstanding: List[Request] = []
        self._outstanding_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> bytes:
        return f"tpu:{self.executor_id}".encode()

    def close(self) -> None:
        with self._outstanding_lock:
            for req in self._outstanding:
                if not req.completed():
                    req.cancel()
            self._outstanding.clear()
        self.store.close()

    @property
    def recorder(self) -> FlightRecorder:
        """The cluster's flight recorder — exposed per-facet so the chaos
        harness (testing.faults.kill_executor) finds it on any transport."""
        return self.cluster.recorder

    def chaos_kill(self) -> None:
        """Chaos-harness death hook (testing.faults.kill_executor): close the
        store — its staging, spills, and replicas become unreachable, like a
        dead process's memory — and report the loss to cluster membership, the
        collective-plane analogue of a peer observing ECONNRESET."""
        self.store.close()
        self.cluster.membership.mark_dead(self.executor_id, "chaos kill_executor")

    def add_executor(self, executor_id: ExecutorId, address: bytes) -> None:
        # Single-controller mode: membership is the cluster's mesh; nothing to do.
        pass

    def remove_executor(self, executor_id: ExecutorId) -> None:
        pass

    # -- server side (upstream peer-serving registry, §3.5 parity) ---------

    def register(self, block_id: BlockId, block: Block) -> None:
        with self._registry_lock:
            self._registry[block_id] = block

    def mutate(self, block_id: BlockId, block: Block, callback: Optional[OperationCallback]) -> None:
        with self._registry_lock:
            old = self._registry.get(block_id)
            if old is not None:
                with old.lock:
                    self._registry[block_id] = block
            else:
                self._registry[block_id] = block
        if callback is not None:
            callback(OperationResult(OperationStatus.SUCCESS))

    def unregister(self, block_id: BlockId) -> None:
        with self._registry_lock:
            block = self._registry.pop(block_id, None)
        if block is not None:
            block.close()  # release serving resources (cached mmaps) eagerly

    def unregister_shuffle(self, shuffle_id: int) -> None:
        with self._registry_lock:
            doomed = [
                b for b in self._registry
                if isinstance(b, ShuffleBlockId) and b.shuffle_id == shuffle_id
            ]
            blocks = [self._registry.pop(b) for b in doomed]
        for block in blocks:
            block.close()

    def registered_block(self, block_id: BlockId) -> Optional[Block]:
        with self._registry_lock:
            return self._registry.get(block_id)

    # -- client side -------------------------------------------------------

    def fetch_blocks_by_block_ids(
        self,
        executor_id: ExecutorId,
        block_ids: Sequence[BlockId],
        result_buffers: Sequence[MemoryBlock],
        callbacks: Sequence[Optional[OperationCallback]],
    ) -> List[Request]:
        """Post-exchange batch fetch: each block is a local slice of this
        executor's received shard (``executor_id`` names the *sender*, kept for
        trait parity; the data already arrived via the collective)."""
        if not (len(block_ids) == len(result_buffers) == len(callbacks)):
            raise ValueError("length mismatch")
        requests = []
        for bid, buf, cb in zip(block_ids, result_buffers, callbacks):
            req = Request(OperationStats())
            try:
                if not isinstance(bid, ShuffleBlockId):
                    raise TransportError(f"TpuShuffleTransport fetches ShuffleBlockIds, got {bid!r}")
                view, length = self.cluster.locate_received_block(
                    self.executor_id, bid.shuffle_id, bid.map_id, bid.reduce_id
                )
                dest = buf.host_view()
                if length > dest.size:
                    raise TransportError(
                        f"block {bid} ({length} B) exceeds result buffer ({dest.size} B)"
                    )
                dest[:length] = view
                buf.size = length
                req.stats.mark_done(recv_size=length)
                result = OperationResult(OperationStatus.SUCCESS, stats=req.stats, data=buf)
            except Exception as e:
                req.stats.mark_done()
                err = e if isinstance(e, TransportError) else TransportError(str(e))
                result = OperationResult(OperationStatus.FAILURE, error=err, stats=req.stats)
            req.complete(result)
            if cb is not None:
                cb(result)
            requests.append(req)
        return requests

    def fetch_blocks_device(
        self, block_ids: Sequence[ShuffleBlockId], impl: Optional[str] = None
    ) -> Tuple[object, np.ndarray]:
        """Device-resident batch fetch: pack these blocks into one HBM buffer on
        this executor's device (see ``TpuShuffleCluster.fetch_blocks_to_device``).
        All blocks must be from one shuffle."""
        if not block_ids:
            raise ValueError("no block ids")
        sid = block_ids[0].shuffle_id
        return self.cluster.fetch_blocks_to_device(self.executor_id, sid, block_ids, impl=impl)

    def progress(self) -> None:
        """Poll outstanding async work (non-blocking).  Post-exchange fetches
        complete synchronously (local memory), so this mostly drives the
        pull-fallback path and keeps the trait's polling contract alive."""
        with self._outstanding_lock:
            self._outstanding = [r for r in self._outstanding if not r.completed()]

    # -- staged-store extensions ------------------------------------------

    def init_executor(self, num_mappers: int, num_reducers: int) -> None:
        # Store sizing happens in cluster.create_shuffle; the reference's NVKV
        # handshake (UcxWorkerWrapper.scala:286-322) has no wire step here.
        pass

    def commit_block(self, mapper_info_blob: bytes, callback: Optional[OperationCallback] = None) -> None:
        info = MapperInfo.unpack(mapper_info_blob)
        self.cluster.commit_mapper(info)
        if callback is not None:
            callback(OperationResult(OperationStatus.SUCCESS))

    def fetch_block(
        self,
        executor_id: ExecutorId,
        shuffle_id: int,
        map_id: int,
        reduce_id: int,
        result_buffer: MemoryBlock,
        callback: Optional[OperationCallback] = None,
    ) -> Request:
        """Pull fallback: direct read of a peer's staged store (per-block AM path
        ids 3/4 — the straggler/retry escape hatch next to the collective)."""
        req = Request(OperationStats())

        def poll() -> bool:
            try:
                payload = self.cluster.transports[executor_id].store.read_block(
                    shuffle_id, map_id, reduce_id
                )
                dest = result_buffer.host_view()
                if len(payload) > dest.size:
                    raise TransportError(
                        f"staged block ({len(payload)} B) exceeds result buffer ({dest.size} B)"
                    )
                dest[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
                result_buffer.size = len(payload)
                req.stats.mark_done(recv_size=len(payload))
                result = OperationResult(OperationStatus.SUCCESS, stats=req.stats, data=result_buffer)
            except Exception as e:
                req.stats.mark_done()
                err = e if isinstance(e, TransportError) else TransportError(str(e))
                result = OperationResult(OperationStatus.FAILURE, error=err, stats=req.stats)
            req.complete(result)
            if callback is not None:
                callback(result)
            return True

        req.attach_poll(poll)
        with self._outstanding_lock:
            self._outstanding.append(req)
        return req
