"""Peer block server + socket transport — the multi-process serving path (L3).

Two reference capabilities live here, both speaking the AM protocol of
``Definitions.scala:22-29`` over TCP frames (core/definitions.py):

1. **The executor<->executor serving path** (upstream SparkUCX, partly commented
   out in the fork — UcxShuffleTransport.handleFetchBlockRequest :305-323,
   UcxWorkerWrapper.scala:397-448, GlobalWorkerRpcThread.scala:22-44): a server
   thread answers batched ``FetchBlockReq`` by reading registered blocks /
   staged-store blocks in parallel and replying with ONE ack frame laid out
   ``[sizes | data...]`` exactly like the reference's single bounce-buffer reply.
2. **The store daemon role** (the out-of-repo DPU daemon on port 1338,
   CommonUcxShuffleManager.scala:84-89): ``InitExecutorReq`` handshakes an
   executor's store context, ``MapperInfo`` installs commit metadata — so a
   ``BlockServer`` *is* the daemon the reference only talks to.

``PeerTransport`` implements the full ``ShuffleTransport`` trait over this wire:
completions arrive on a receiver thread but requests only *complete* under
``progress()`` (results park in a queue), preserving the reference's explicit-poll
contract (ShuffleTransport.scala:158-165).
"""

from __future__ import annotations

import json
import os
import queue
import socket
import struct
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import Block, BlockId, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.definitions import (
    CHUNK_CODEC_EXT_SIZE,
    CHUNK_HEADER_SIZE,
    FRAME_HEADER_SIZE,
    MAX_FRAME_BYTES,
    REPLICA_ENTRY_SIZE,
    REPLICA_HEADER_SIZE,
    REPLICA_TRACE_EXT_SIZE,
    TRACE_EXT_SIZE,
    AmId,
    MapperInfo,
    pack_chunk_codec_ext,
    pack_chunk_hdr,
    pack_frame,
    pack_frame_prefix,
    pack_hot_set,
    pack_member_event,
    pack_replica_ack,
    pack_replica_put,
    pack_replica_trace_ext,
    pack_trace_ext,
    pack_wire_hello,
    unpack_chunk_codec_ext,
    unpack_chunk_hdr,
    unpack_frame_header,
    unpack_hot_set,
    unpack_member_event,
    unpack_replica_ack,
    unpack_replica_put,
    unpack_replica_trace_ext,
    unpack_trace_ext,
    unpack_wire_hello,
)
from sparkucx_tpu.core.operation import (
    BlockCorruptError,
    OperationCallback,
    OperationResult,
    OperationStats,
    OperationStatus,
    Request,
    ResourceExhaustedError,
    TenantQuotaExceededError,
    TransportError,
    UnknownTenantError,
)
from sparkucx_tpu.service.reactor import Reactor
from sparkucx_tpu.core.transport import ExecutorId, ShuffleTransport
# tier-(a) wire compression policy + page formats; ops.compress keeps its jax
# imports function-local, so this pulls no accelerator stack into the transport
from sparkucx_tpu.ops.compress import CompressSpec, encode_chunk
from sparkucx_tpu.store.hbm_store import BlockPopularity, HbmBlockStore
from sparkucx_tpu.testing import faults
from sparkucx_tpu.obs.metrics import (
    MetricsRegistry,
    close_http_server,
    counter_dict_provider,
    start_http_server,
    stats_aggregator_provider,
    tracer_provider,
    wire_lane_provider,
)
from sparkucx_tpu.obs.recorder import FlightRecorder
from sparkucx_tpu.utils.checksum import crc32c
from sparkucx_tpu.utils.pagecodec import CODEC_RAW, CodecError, decode_page
from sparkucx_tpu.utils.logging import get_logger
from sparkucx_tpu.utils.stats import StatsAggregator
from sparkucx_tpu.utils.trace import TRACER, instant

logger = get_logger("transport.peer")

_TAG = struct.Struct("<Q")
_COUNT = struct.Struct("<I")
_TRIPLE = struct.Struct("<iii")
_SIZE = struct.Struct("<q")
#: Tenant header extension of FETCH_BLOCK_REQ: <u32 len><utf-8 app_id> after
#: the block triples.  Absent by default (single-tenant frames stay
#: byte-identical to the golden captures); unpack_batch_fetch_req reads
#: exactly ``count`` triples, so old servers ignore the extension.
_APP = struct.Struct("<I")
#: Negative size codes in fetch-reply size lists.  -1 is the historical
#: block-not-found (retryable through replica failover); -2/-3 are the
#: tenant admission rejections, surfaced client-side as the typed
#: UnknownTenantError / TenantQuotaExceededError which readers treat as
#: NOT retryable (every replica enforces the same registry).  -4 is the
#: gray-failure arm: the serving store hit its hard watermark
#: (``store.hardWatermark``) mid-serve — surfaced as ResourceExhaustedError,
#: which readers treat as RETRYABLE WITH BACKOFF (pressure is per-executor
#: and transient; the soft-watermark sweep clears it).
SIZE_NOT_FOUND = -1
SIZE_UNKNOWN_TENANT = -2
SIZE_QUOTA_EXCEEDED = -3
SIZE_RESOURCE_EXHAUSTED = -4
#: CRC32C trailer appended to chunk / ReplicaPut headers when
#: ``spark.shuffle.tpu.wire.checksum`` is on.  Receivers detect it by header
#: length — the knob never changes frame layout when off (golden frames).
_CRC = struct.Struct("<I")
_MAX_FRAME = MAX_FRAME_BYTES  # shared frame ceiling (core/definitions.py)
#: The encoded-chunk pool's byte cap lives on the conf
#: (``spark.shuffle.tpu.compress.cacheBytes``, 0 disables the pool).  Encoded
#: pages are typically a fraction of their raw chunks, so the 128 MiB default
#: covers on the order of a GiB of hot raw blocks; past the cap the pool
#: LRU-evicts — a cap, not a correctness boundary (a miss just re-encodes).


def apply_wire_sockopts(
    sock: socket.socket,
    conf: Optional[TpuShuffleConf] = None,
    *,
    sndbuf: int = 0,
    rcvbuf: int = 0,
) -> None:
    """TCP_NODELAY + kernel buffer sizing for every wire socket (both ends).

    Small control frames (acks, ``MapperInfo``) must not eat Nagle delays, so
    NODELAY is unconditional.  ``conf.wire_sock_buf_bytes``
    (``spark.shuffle.tpu.wire.sockBufBytes``), when set, overrides BOTH
    directions' kernel buffers; otherwise the caller's per-direction defaults
    apply (0 = leave the platform default alone)."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    override = conf.wire_sock_buf_bytes if conf is not None else 0
    for opt, val in (
        (socket.SO_SNDBUF, override or sndbuf),
        (socket.SO_RCVBUF, override or rcvbuf),
    ):
        if val:
            try:
                sock.setsockopt(socket.SOL_SOCKET, opt, val)
            except OSError:
                pass


def _peername(sock: socket.socket) -> str:
    """Best-effort ``host:port`` of the remote end, for error messages."""
    try:
        name = sock.getpeername()
        return f"{name[0]}:{name[1]}"
    except (OSError, AttributeError, IndexError, TypeError):
        return "?"


def recv_exact(
    sock: socket.socket, n: int, *, idle_ok: bool = False, peer: str = ""
) -> Optional[bytearray]:
    """Receive exactly ``n`` bytes into ONE preallocated buffer.

    ``recv_into`` a sliding memoryview of a single bytearray: the historical
    implementation collected per-``recv`` bytes chunks and paid a second full
    copy joining them.  Returns ``None`` on EOF.  A bytearray is accepted
    everywhere the old bytes was (struct unpacking, json, ``np.frombuffer``,
    ``bytes + bytearray`` concatenation).

    When the socket carries a timeout (``conf.wire_timeout_ms``), a read that
    times out with part of the buffer already received means the peer hung
    mid-frame: raise an addressed OSError.  With ``idle_ok`` (the wait for the
    NEXT frame header), a timeout with zero bytes received is a quiet
    connection, not a fault — keep waiting."""
    out = bytearray(n)
    mv = memoryview(out)
    got = 0
    while got < n:
        try:
            r = sock.recv_into(mv[got:], n - got)
        except socket.timeout:
            if idle_ok and got == 0:
                continue
            raise OSError(
                f"peer {peer or _peername(sock)} hung mid-frame: read timed out "
                f"with {got}/{n} B received"
            ) from None
        if r == 0:
            return None
        got += r
    return out


def recv_frame(sock: socket.socket, peer: str = "") -> Optional[Tuple[AmId, bytes, bytes]]:
    hdr = recv_exact(sock, FRAME_HEADER_SIZE, idle_ok=True, peer=peer)
    if hdr is None:
        return None
    am_id, hlen, blen = unpack_frame_header(hdr)
    if hlen + blen > _MAX_FRAME:
        raise ValueError(f"frame too large from peer {peer or _peername(sock)}")
    header = recv_exact(sock, hlen, peer=peer) if hlen else b""
    body = recv_exact(sock, blen, peer=peer) if blen else b""
    if (hlen and header is None) or (blen and body is None):
        return None
    return am_id, header, body


def pack_batch_fetch_req(
    tag: int,
    block_ids: Sequence[ShuffleBlockId],
    app_id: Optional[str] = None,
    trace: Optional[Tuple[int, int]] = None,
) -> bytes:
    """Header: tag + count + (sid, mid, rid) triples — the batched variant of the
    reference's 12-byte fetch header (UcxWorkerWrapper.scala:96-126).

    With ``app_id`` (tenants.enabled) the requesting tenant rides as a
    self-describing extension after the triples (``_APP`` length + utf-8
    bytes); the triples then carry TENANT-LOCAL shuffle ids, which the server
    translates through its registry.  With ``trace`` (obs.traceContext) the
    issuing span's (trace_id, span_id) rides as a magic-prefixed 20-byte
    trailer AFTER the app extension (core/definitions.py ``_TRACE_EXT``).
    Both None (the default) emits the historical bytes exactly."""
    out = bytearray(_TAG.pack(tag) + _COUNT.pack(len(block_ids)))
    for b in block_ids:
        out += _TRIPLE.pack(b.shuffle_id, b.map_id, b.reduce_id)
    if app_id:
        raw = app_id.encode("utf-8")
        out += _APP.pack(len(raw)) + raw
    if trace is not None:
        out += pack_trace_ext(trace[0], trace[1])
    return bytes(out)


def split_fetch_req_trace(header: bytes) -> Tuple[Optional[Tuple[int, int]], bytes]:
    """Split a FETCH_BLOCK_REQ header into ``(trace_ctx, header-without-ext)``.

    The trace ext is the LAST 20 bytes when present.  Beyond the magic check,
    the remaining length must be structurally consistent — either the ext
    directly follows the triples, or an app extension accounts for EXACTLY
    the bytes in between — so an app_id whose utf-8 tail happens to contain
    the magic bytes can never be mis-split."""
    base = _TAG.size + _COUNT.size
    if len(header) < base + TRACE_EXT_SIZE:
        return None, header
    ctx = unpack_trace_ext(header)
    if ctx is None:
        return None, header
    (count,) = _COUNT.unpack_from(header, _TAG.size)
    pos = base + count * _TRIPLE.size
    rem = len(header) - pos
    if rem < TRACE_EXT_SIZE:
        return None, header
    if rem != TRACE_EXT_SIZE:
        if rem < _APP.size + TRACE_EXT_SIZE:
            return None, header
        (n,) = _APP.unpack_from(header, pos)
        if _APP.size + n + TRACE_EXT_SIZE != rem:
            return None, header
    return ctx, header[:-TRACE_EXT_SIZE]


def unpack_batch_fetch_req(header: bytes) -> Tuple[int, List[ShuffleBlockId]]:
    (tag,) = _TAG.unpack_from(header, 0)
    (count,) = _COUNT.unpack_from(header, _TAG.size)
    ids = []
    pos = _TAG.size + _COUNT.size
    for _ in range(count):
        s, m, r = _TRIPLE.unpack_from(header, pos)
        ids.append(ShuffleBlockId(s, m, r))
        pos += _TRIPLE.size
    return tag, ids


def unpack_fetch_req_app_id(header: bytes, count: int) -> Optional[str]:
    """The tenant extension of a FETCH_BLOCK_REQ header, or None when absent
    (single-tenant frame) or malformed (treated as absent — the request then
    resolves in the untranslated namespace, exactly like an old client)."""
    pos = _TAG.size + _COUNT.size + count * _TRIPLE.size
    if len(header) < pos + _APP.size:
        return None
    (n,) = _APP.unpack_from(header, pos)
    raw = bytes(header[pos + _APP.size : pos + _APP.size + n])
    if n == 0 or len(raw) != n:
        return None
    try:
        return raw.decode("utf-8")
    except UnicodeDecodeError:
        return None


class _ServerGroup:
    """Server-side stripe group: the K accepted lane sockets of one client
    ``_StripeGroup``, plus one sender thread per lane so chunk frames bound
    for different lanes hit the kernel concurrently (the GIL is released
    inside ``sendmsg``/``sendall``, so K senders really do overlap socket
    copies — a single serving thread would serialize them).

    Each lane's sender shares a per-connection send lock with the lane's
    ``_serve_conn`` thread, so control acks (InitExecutorAck) interleave with
    chunk frames only at frame granularity, never mid-frame.  Queues are
    bounded: a slow wire backpressures the resolve loop instead of buffering
    the whole reply in queued iovecs."""

    def __init__(self, group_id: int, nlanes: int, chunk_bytes: int) -> None:
        self.group_id = group_id
        self.nlanes = max(1, nlanes)
        self.chunk_bytes = max(4096, chunk_bytes)
        self._lock = threading.Lock()
        self._lanes: Dict[int, socket.socket] = {}  #: guarded by self._lock
        self._queues: Dict[int, "queue.Queue"] = {}  #: guarded by self._lock
        self._ready = threading.Event()  # set once all nlanes registered
        self.broken = False  # one dead lane poisons the group (benign flag,
        # single transition False->True, read without the lock by design)
        #: per-lane tx telemetry, each entry written only by its sender thread
        self.tx_bytes: Dict[int, int] = {}
        self.tx_frames: Dict[int, int] = {}

    def register(self, lane: int, conn: socket.socket, send_lock: threading.Lock) -> None:
        with self._lock:
            self._lanes[lane] = conn
            q: "queue.Queue" = queue.Queue(maxsize=64)
            self._queues[lane] = q
            self.tx_bytes[lane] = 0
            self.tx_frames[lane] = 0
            ready = len(self._lanes) == self.nlanes
        threading.Thread(
            target=self._send_loop, args=(lane, conn, q, send_lock), daemon=True
        ).start()
        if ready:
            self._ready.set()

    def ready(self, timeout: float = 5.0) -> bool:
        """True once every lane has said hello — striping before that would
        address lanes that do not exist yet.  A timed-out or broken group
        makes the caller fall back to the single-frame reply."""
        return self._ready.wait(timeout) and not self.broken

    def enqueue(self, lane: int, parts: list) -> None:
        with self._lock:
            q = self._queues.get(lane)
        while True:
            if q is None or self.broken:
                raise OSError("stripe group lane gone")
            try:  # bounded wait so a group broken mid-put cannot hang the server
                q.put(parts, timeout=0.25)
                return
            except queue.Full:
                continue

    def _send_loop(self, lane: int, conn: socket.socket, q: "queue.Queue", send_lock: threading.Lock) -> None:
        while not self.broken:
            try:
                parts = q.get(timeout=0.25)
            except queue.Empty:
                continue
            if parts is None:
                return
            try:
                with send_lock:
                    if hasattr(conn, "sendmsg"):
                        BlockServer._sendmsg_all(conn, parts)
                    else:
                        conn.sendall(b"".join(bytes(p) for p in parts))
            except OSError:
                self.close()
                return
            self.tx_bytes[lane] += sum(len(p) for p in parts)
            self.tx_frames[lane] += 1

    def drop_lane(self, lane: int) -> None:
        """A lane's serve thread saw EOF/error: the group can no longer
        stripe (chunks for that lane would be lost), so poison it."""
        self.close(keep_lane=lane)

    def close(self, keep_lane: Optional[int] = None) -> None:
        self.broken = True
        with self._lock:
            queues = list(self._queues.values())
            lanes = [c for ln, c in self._lanes.items() if ln != keep_lane]
            self._queues.clear()
            self._lanes.clear()
        for q in queues:
            try:
                q.put_nowait(None)  # early wakeup; senders also poll `broken`
            except queue.Full:
                pass
        for conn in lanes:
            # shutdown (not close) so each lane's _serve_conn thread observes
            # the death and runs its own cleanup exactly once
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass


class _ConnState:
    """Per-connection serve state, shared by the thread-per-connection loop
    and the reactor's frame-at-a-time serving: the stripe group this lane
    joined (via WIRE_HELLO), its lane id, and the send lock the lane's group
    sender thread shares with the serving code."""

    __slots__ = ("peer", "send_lock", "group", "lane", "use_sendmsg")

    def __init__(self, conn: socket.socket) -> None:
        self.peer = _peername(conn)
        self.send_lock = threading.Lock()
        self.group: Optional[_ServerGroup] = None
        self.lane = -1
        self.use_sendmsg = hasattr(conn, "sendmsg")


class BlockServer:
    """Serves registered blocks + staged-store blocks to peers.

    The reply layout for a batch is ``header=[tag, count, size*count]``,
    ``body=concat(payloads)`` — the reference's one-pooled-buffer reply
    (UcxWorkerWrapper.scala:397-448); sizes of -1 mark per-block failures.
    Reads are parallelized across ``num_io_threads`` like the reference's
    ForkJoin ``ioThreadPool`` (UcxWorkerWrapper.scala:69-71,416-422).
    """

    def __init__(
        self,
        conf: Optional[TpuShuffleConf] = None,
        store: Optional[HbmBlockStore] = None,
        registry_lookup: Optional[Callable[[BlockId], Optional[Block]]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        member_sink: Optional[Callable[[int, int, int, int], None]] = None,
        tenants=None,
        executor_id: Optional[int] = None,
        metrics: Optional[MetricsRegistry] = None,
        popularity: Optional[BlockPopularity] = None,
        hot_sink: Optional[Callable[[int, bool], None]] = None,
        hot_set_provider: Optional[Callable[[], Dict[int, List[int]]]] = None,
    ) -> None:
        self.conf = conf or TpuShuffleConf()
        self.store = store
        self.registry_lookup = registry_lookup
        #: popularity-aware serving tier (serve.hotThresholdFetchesPerSec):
        #: per-block fetch-rate tracker, the owner's reaction hook for
        #: promote/demote transitions (the transport widens/narrows the
        #: replica set there), and the advertisement source HOT_SET_PULL
        #: replies from.  All None by default — the off path never touches
        #: the tracker lock.
        self.popularity = popularity
        self.hot_sink = hot_sink
        self.hot_set_provider = hot_set_provider
        #: obs plane: which executor this server serves for (trace-event
        #: attribution in the shared-process loopback mesh) and the metrics
        #: registry METRICS_PULL answers from (None = empty exposition)
        self.executor_id = executor_id
        self.metrics = metrics
        #: TenantRegistry of the owning process (service/tenants.py), or None
        #: for the historical single-tenant server.  With a registry, FETCH
        #: requests carrying the tenant extension get their shuffle ids
        #: translated and their reply bytes drawn from per-tenant CreditGates.
        self.tenants = tenants
        #: membership-frame sink: called as (am_id, epoch, subject, observer)
        #: for every MemberSuspect/MemberRejoin frame a peer sends us
        self.member_sink = member_sink
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.address: Tuple[str, int] = self._srv.getsockname()
        self._running = True
        self._io = (
            ThreadPoolExecutor(max_workers=self.conf.num_io_threads)
            if self.conf.num_io_threads > 1
            else None
        )
        self._accepted: list = []
        self._accepted_lock = threading.Lock()
        # Stripe groups announced by WIRE_HELLO frames (striped wire path);
        # a group forms as its K lane connections each say hello.
        self._groups: Dict[int, _ServerGroup] = {}  #: guarded by self._groups_lock
        self._groups_lock = threading.Lock()
        #: tier-(a) wire compression policy (conf compress.codec); off =
        #: chunk frames byte-identical to the pinned golden captures
        self._compress = CompressSpec.from_conf(self.conf)
        #: serve-side compression telemetry: decoded (raw) vs wire bytes
        #: streamed through chunk frames, and how many pages actually encoded
        #: vs fell back to raw.  Aggregated per reply under _compress_lock.
        self.compress_stats: Dict[str, int] = {
            "raw_bytes": 0,
            "wire_bytes": 0,
            "encoded_chunks": 0,
            "raw_chunks": 0,
            "cache_hits": 0,
            "cache_misses": 0,
            "cache_evictions": 0,
        }  #: guarded by self._compress_lock
        self._compress_lock = threading.Lock()
        #: serve-side encoded-chunk pool: sealed blocks are immutable for the
        #: life of their shuffle id, so each (block, offset, len) chunk pays
        #: the encoder exactly once and every later fetch of the same chunk —
        #: other reducers, credit-window re-issues, retry/failover replays —
        #: serves the cached encoding (or the cached "unprofitable, ship raw"
        #: verdict, so incompressible blocks never re-attempt the encoder).
        #: Maps (bid, offset, len) -> (codec_id, encoded | None); insertion
        #: order doubles as recency order (hits re-insert at the MRU end), so
        #: eviction from the front is LRU.  Evicted once the encoded bytes
        #: held exceed ``compress.cacheBytes`` (0 = pool off, every chunk
        #: re-encodes).
        self._encoded_pool: Dict[tuple, tuple] = {}  #: guarded by self._compress_lock
        self._encoded_pool_bytes = 0  #: guarded by self._compress_lock
        self._encoded_pool_cap = self.conf.compress_cache_bytes
        # Serving plane: by default, numListenerThreads accept loops on one
        # listen socket (UcxShuffleConf.scala:73-78; the kernel load-balances
        # accepts) and a thread per accepted connection.  With server.workers
        # set (or tenants.enabled), the shared reactor holds every idle
        # connection in one selector and serves frames from a bounded pool —
        # the scalable plane for many-tenant fan-in.
        self._reactor: Optional[Reactor] = None
        self._threads: list = []
        if (
            self.conf.server_workers > 0
            or self.conf.tenants_enabled
            or self.conf.server_accept_backlog > 0
        ):
            # server.acceptBacklog implies the reactor plane: shedding needs
            # the one place that owns the resident-connection count
            self._reactor = Reactor(
                self.conf.server_workers,
                name=f"blocksrv-{self.address[1]}",
                accept_backlog=self.conf.server_accept_backlog,
            )
            self._reactor.add_listener(self._srv, self._on_accept)
        else:
            self._threads = [
                threading.Thread(target=self._accept_loop, daemon=True)
                for _ in range(max(1, self.conf.num_listener_threads))
            ]
            for t in self._threads:
                t.start()
        self.handshaken: Dict[int, bytes] = {}  # executor_id -> context blob

    def address_bytes(self) -> bytes:
        return f"{self.address[0]}:{self.address[1]}".encode()

    def compress_snapshot(self) -> Dict[str, int]:
        """Consistent copy of :attr:`compress_stats` (serve threads aggregate
        per striped reply under the same lock)."""
        with self._compress_lock:
            return dict(self.compress_stats)

    def drop_shuffle_chunks(self, shuffle_id: int) -> int:
        """Purge the shuffle's cached encodings from the encoded-chunk pool.

        The pool's safety argument is that sealed blocks are immutable for
        the life of their shuffle id — so when the id is unregistered (and a
        later shuffle, or a recomputed lineage-cache round, may legitimately
        reuse it) every cached encoding keyed by that id must go, or a serve
        thread could ship stale bytes for a fresh block.  Returns the number
        of chunks dropped."""
        with self._compress_lock:
            doomed = [
                k for k in self._encoded_pool
                if isinstance(k[0], ShuffleBlockId) and k[0].shuffle_id == shuffle_id
            ]
            for k in doomed:
                _, enc = self._encoded_pool.pop(k)
                if enc is not None:
                    self._encoded_pool_bytes -= len(enc)
            return len(doomed)

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._srv.accept()
                # deep send window default: one reply batch is tens of MiB
                apply_wire_sockopts(conn, self.conf, sndbuf=4 << 20)
                # mid-frame reads (and stuck sends) may not hang forever; idle
                # header waits are exempt inside recv_exact(idle_ok=True)
                if self.conf.wire_timeout_ms:
                    conn.settimeout(self.conf.wire_timeout_ms / 1000.0)
            except OSError:
                return
            with self._accepted_lock:
                self._accepted.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,), daemon=True).start()

    def _on_accept(self, conn: socket.socket) -> None:
        """Reactor accept path: same socket setup as ``_accept_loop``, but the
        connection parks in the shared selector instead of owning a thread."""
        apply_wire_sockopts(conn, self.conf, sndbuf=4 << 20)
        # accepted from a non-blocking listener: restore blocking reads (with
        # the usual mid-frame timeout) for the frame-at-a-time workers
        if self.conf.wire_timeout_ms:
            conn.settimeout(self.conf.wire_timeout_ms / 1000.0)
        else:
            conn.setblocking(True)
        with self._accepted_lock:
            self._accepted.append(conn)
        state = _ConnState(conn)
        self._reactor.add_connection(
            conn,
            lambda c, s=state: self._serve_frame(c, s),
            on_close=lambda c, s=state: self._drop_conn(c, s),
        )

    def _fire_hot_transitions(self, transitions) -> None:
        """Emit the promote/demote trace instants and hand each shuffle-level
        transition to the owning transport's hot sink (which widens or
        narrows the replica advertisement).  Sink errors are contained — a
        failed widen must never fail the fetch that triggered it."""
        for sid, hot in transitions:
            if hot:
                instant("serve.promote", shuffle_id=sid)
            else:
                instant("serve.demote", shuffle_id=sid)
            if self.hot_sink is not None:
                try:
                    self.hot_sink(sid, hot)
                except Exception:
                    logger.exception(
                        "hot-set %s of shuffle %d failed",
                        "promote" if hot else "demote", sid,
                    )

    def sweep_popularity(self) -> None:
        """Cool-down pass (rate-limited inside the tracker): demote blocks
        whose fetch rate decayed below the hysteresis edge, firing
        ``serve.demote`` for each shuffle whose last hot block cooled."""
        pop = self.popularity
        if pop is not None:
            self._fire_hot_transitions(pop.maybe_sweep())

    def _resolve_one(self, bid: ShuffleBlockId):
        """Resolve to a ``(buffer, offset, length)`` view or None.

        Registry blocks serve their stable ``memory_view`` zero-copy —
        memory-backed blocks hand back their payload array, file-backed ones
        a cached read-only mmap of the segment (materializing a fresh buffer
        per fetch — alloc + copy + page faults — was the measured wall of
        this path); only blocks with no mappable view (``memory_view() is
        None``) materialize under the block lock.  Store blocks serve a
        zero-copy view of host staging.  Either way the reply path sends the
        view without another copy.

        Popularity tier (serve.hotThresholdFetchesPerSec > 0): every resolve
        folds into the block's fetch-rate EWMA; a hot block is served from
        the store's decoded-block cache when pinned there (bypassing the
        eviction tiers — no restage, no LRU bump below), and admitted to it
        on the miss that follows promotion."""
        pop = self.popularity
        hot = False
        if pop is not None:
            hot, transitions = pop.observe(bid.shuffle_id, bid.map_id, bid.reduce_id)
            if transitions:
                self._fire_hot_transitions(transitions)
        if hot and self.store is not None:
            cached = self.store.serve_cache_get(
                bid.shuffle_id, bid.map_id, bid.reduce_id
            )
            if cached is not None:
                return cached
        resolved = self._resolve_one_tiers(bid)
        if hot and self.store is not None and isinstance(resolved, tuple):
            staging, off, ln = resolved
            if ln:
                flat = np.asarray(staging).reshape(-1).view(np.uint8)
                self.store.serve_cache_offer(
                    bid.shuffle_id, bid.map_id, bid.reduce_id,
                    bytes(flat[off : off + ln]),
                )
        return resolved

    def _resolve_one_tiers(self, bid: ShuffleBlockId):
        """The historical registry -> replica -> staging resolution."""
        if self.registry_lookup is not None:
            blk = self.registry_lookup(bid)
            if blk is not None:
                with blk.lock:
                    view = blk.memory_view()
                    if view is not None:
                        return view, 0, int(view.size)
                    mb = blk.get_memory_block()
                # hand back the materialized buffer as a view, not bytes — the
                # reply path then sends it without a second copy
                return mb.host_view(), 0, int(mb.size)
        if self.store is not None:
            # Replica tier BEFORE staging: apply_mapper_info installs entries
            # for maps this executor does NOT hold into the local block table
            # with sender-relative offsets, so block_staging_view on a
            # non-owner would happily serve garbage bytes for a remote map.
            # Replica keys are exactly those remote maps (ownership partitions
            # maps across executors), so they must win the lookup.
            view = self.store.replica_view(bid.shuffle_id, bid.map_id, bid.reduce_id)
            if view is not None:
                return view
            try:
                return self.store.block_staging_view(
                    bid.shuffle_id, bid.map_id, bid.reduce_id
                )
            except TenantQuotaExceededError:
                # restage-on-fetch needed HBM headroom the owning tenant no
                # longer has: a typed, addressed admission failure — NOT the
                # retryable block-not-found
                return SIZE_QUOTA_EXCEEDED
            except ResourceExhaustedError:
                # restage-on-fetch hit the store's hard watermark: this
                # executor is under memory pressure RIGHT NOW, but the
                # eviction sweep clears it — retryable with backoff
                return SIZE_RESOURCE_EXHAUSTED
            except TransportError:
                return None
        return None

    def _assemble_reply(self, entries) -> Tuple[bytes, "np.ndarray"]:
        """Build ``(sizes blob, one contiguous body)`` from resolved views —
        the reference's single pooled reply buffer (UcxWorkerWrapper.scala:397-448),
        gathered through the native threaded batch copy (ts_batch_copy, the
        ForkJoin ioThreadPool analogue).  Fallback for platforms without
        ``socket.sendmsg``; the primary reply path is the vectored
        ``_reply_parts`` + ``_sendmsg_all``, which skips this copy."""
        from sparkucx_tpu import native

        sizes, total = [], 0
        for e in entries:
            if e is None or isinstance(e, int):
                sizes.append(SIZE_NOT_FOUND if e is None else e)
            else:
                sizes.append(e[2])
                total += e[2]
        body = np.empty(total, dtype=np.uint8)
        by_staging: Dict[int, Tuple[np.ndarray, list]] = {}
        pos = 0
        for e in entries:
            if e is None or isinstance(e, int):
                continue
            staging, off, ln = e
            if ln:
                key = id(staging)
                if key not in by_staging:
                    by_staging[key] = (staging.reshape(-1).view(np.uint8), [])
                by_staging[key][1].append((pos, off, ln))
            pos += ln
        for src, segs in by_staging.values():
            native.batch_copy(body, src, segs, max_threads=self.conf.num_io_threads)
        blob = b"".join(_SIZE.pack(s) for s in sizes)
        return blob, body

    def _reply_parts(self, entries) -> Tuple[bytes, list, int]:
        """(sizes blob, zero-copy body views in order, total bytes) — the
        scatter-gather form of ``_assemble_reply``: store-backed views go to
        the wire as memoryviews of the staging buffer itself, no intermediate
        contiguous body is built (the kernel gathers via sendmsg iovecs —
        the single-pooled-buffer copy of UcxWorkerWrapper.scala:397-448
        replaced by vectored IO)."""
        sizes, parts, total = [], [], 0
        for e in entries:
            if e is None or isinstance(e, int):
                sizes.append(SIZE_NOT_FOUND if e is None else e)
                continue
            staging, off, ln = e
            if ln:
                parts.append(memoryview(staging.reshape(-1).view(np.uint8))[off : off + ln])
            sizes.append(ln)
            total += ln
        return b"".join(_SIZE.pack(s) for s in sizes), parts, total

    @staticmethod
    def _sendmsg_all(conn: socket.socket, parts: list) -> None:
        """sendall over an iovec list, handling partial sends and the
        IOV_MAX window (1024 on Linux)."""
        bufs = [memoryview(p) for p in parts if len(p)]
        i = 0
        while i < len(bufs):
            sent = conn.sendmsg(bufs[i : i + 1024])
            while sent > 0:
                if sent >= bufs[i].nbytes:
                    sent -= bufs[i].nbytes
                    i += 1
                else:
                    bufs[i] = bufs[i][sent:]
                    sent = 0

    def _serve_fetch_striped(self, group: _ServerGroup, tag: int, bids, entries) -> None:
        """Stream a fetch reply as striped chunk frames, size manifest last.

        Chunks are enqueued to the group's lane senders as each block
        resolves — store read overlaps wire send instead of assembling the
        whole reply first — and every chunk frame addresses its destination
        ``(tag, block, offset within block)``, so the lanes need no mutual
        ordering.  The manifest (a FetchBlockReqAck with ``body_len == 0``
        carrying the sizes) goes last on lane 0; the client completes the
        batch once the manifest AND every payload byte have arrived."""
        sizes: List[int] = []
        seq = 0
        chunk = group.chunk_bytes
        checksum = self.conf.wire_checksum
        cspec = self._compress
        pool_cap = self._encoded_pool_cap
        raw_total = wire_total = encoded_chunks = raw_chunks = 0
        cache_hits = cache_misses = cache_evictions = 0
        for i, e in enumerate(entries):
            if e is None or isinstance(e, int):
                sizes.append(SIZE_NOT_FOUND if e is None else e)
                continue
            staging, off, ln = e
            sizes.append(ln)
            if not ln:
                continue
            view = memoryview(staging.reshape(-1).view(np.uint8))[off : off + ln]
            pos = 0
            while pos < ln:
                n = min(chunk, ln - pos)
                hdr = pack_chunk_hdr(tag, i, seq, pos)
                wire = view[pos : pos + n]
                if cspec.enabled:
                    # codec ext on EVERY chunk of the reply (uniform header
                    # length); unprofitable pages ship codec_id=0 raw.  The
                    # chunk offset stays the RAW offset — the client resolves
                    # its scatter destination with decoded coordinates.
                    key = (bids[i], pos, n)
                    hit = None
                    if pool_cap > 0:
                        with self._compress_lock:
                            hit = self._encoded_pool.pop(key, None)
                            if hit is not None:
                                # LRU refresh: re-insert at the MRU end
                                # (insertion order IS recency order)
                                self._encoded_pool[key] = hit
                    if hit is not None:
                        cid, enc = hit
                        cache_hits += 1
                    else:
                        cache_misses += 1
                        # encode OUTSIDE the lock: a concurrent reply racing
                        # on the same chunk just produces the same bytes
                        cid, enc = encode_chunk(cspec, wire)
                        cost = len(enc) if enc is not None else 0
                        if pool_cap > 0:
                            with self._compress_lock:
                                while (
                                    self._encoded_pool_bytes + cost > pool_cap
                                    and self._encoded_pool
                                ):
                                    oldest = next(iter(self._encoded_pool))
                                    _, old = self._encoded_pool.pop(oldest)
                                    cache_evictions += 1
                                    if old is not None:
                                        self._encoded_pool_bytes -= len(old)
                                if key not in self._encoded_pool:
                                    self._encoded_pool[key] = (cid, enc)
                                    self._encoded_pool_bytes += cost
                    if enc is not None:
                        wire = enc
                        encoded_chunks += 1
                    else:
                        raw_chunks += 1
                    hdr += pack_chunk_codec_ext(cid, n)
                if checksum:
                    # 4 B CRC32C trailer, always LAST in the header; it
                    # covers the WIRE (encoded) payload so corruption is
                    # caught before the decoder ever parses the page.  The
                    # client detects both extensions by header length, so
                    # frames stay byte-identical with the knobs off.
                    hdr += _CRC.pack(crc32c(wire))
                prefix = pack_frame_prefix(AmId.FETCH_BLOCK_CHUNK, hdr, len(wire))
                # chaos hook AFTER the crc: an armed garble models payload
                # corrupted in flight, which the client-side crc must catch
                payload = faults.transform(
                    "peer.server.chunk", wire, tag=tag, block=i
                )
                group.enqueue(seq % group.nlanes, [prefix, memoryview(payload)])
                raw_total += n
                wire_total += len(wire)
                seq += 1
                pos += n
        if cspec.enabled:
            with self._compress_lock:
                self.compress_stats["raw_bytes"] += raw_total
                self.compress_stats["wire_bytes"] += wire_total
                self.compress_stats["encoded_chunks"] += encoded_chunks
                self.compress_stats["raw_chunks"] += raw_chunks
                self.compress_stats["cache_hits"] += cache_hits
                self.compress_stats["cache_misses"] += cache_misses
                self.compress_stats["cache_evictions"] += cache_evictions
        blob = b"".join(_SIZE.pack(s) for s in sizes)
        manifest = pack_frame(
            AmId.FETCH_BLOCK_REQ_ACK, _TAG.pack(tag) + _COUNT.pack(len(sizes)) + blob, b""
        )
        group.enqueue(0, [manifest])

    def _serve_conn(self, conn: socket.socket) -> None:
        state = _ConnState(conn)
        try:
            while self._running:
                frame = recv_frame(conn, peer=state.peer)
                if frame is None:
                    return
                self._dispatch_frame(conn, state, *frame)
        except (OSError, ValueError, struct.error):
            # malformed frame or dead socket: drop THIS connection, keep serving
            # (the reference's endpoint error handler evicts one endpoint,
            # UcxWorkerWrapper.scala:248-253)
            pass
        finally:
            self._drop_conn(conn, state)

    def _serve_frame(self, conn: socket.socket, state: _ConnState) -> bool:
        """Reactor worker entry: serve exactly ONE frame; True re-arms the
        connection in the selector.  The header read blocks only briefly —
        the selector fired because bytes are pending — and the dispatch is
        the same code the per-connection threads run."""
        if not self._running:
            return False
        try:
            frame = recv_frame(conn, peer=state.peer)
            if frame is None:
                return False
            self._dispatch_frame(conn, state, *frame)
            return True
        except (OSError, ValueError, struct.error):
            return False

    def _drop_conn(self, conn: socket.socket, state: _ConnState) -> None:
        """Connection teardown shared by both serving planes (idempotent)."""
        if state.group is not None:
            state.group.drop_lane(state.lane)
            with self._groups_lock:
                if self._groups.get(state.group.group_id) is state.group:
                    del self._groups[state.group.group_id]
            state.group = None
        try:
            conn.close()
        except OSError:
            pass
        with self._accepted_lock:
            try:
                self._accepted.remove(conn)
            except ValueError:
                pass

    def _serve_fetch_req(self, conn: socket.socket, state: _ConnState, header: bytes) -> None:
        # obs plane: a trailing trace ext re-parents this serve under the
        # requesting reducer's fetch span (merged-trace view); stripped before
        # any of the historical parsing below sees the header
        trace_ctx, header = split_fetch_req_trace(header)
        if trace_ctx is not None and TRACER.active:
            (count,) = _COUNT.unpack_from(header, _TAG.size)
            with TRACER.executor_scope(self.executor_id):
                with TRACER.activate(TRACER.remote_context(*trace_ctx)):
                    with TRACER.span("server.serve", blocks=count):
                        self._serve_fetch_req_inner(conn, state, header)
            return
        self._serve_fetch_req_inner(conn, state, header)

    def _serve_fetch_req_inner(
        self, conn: socket.socket, state: _ConnState, header: bytes
    ) -> None:
        # popularity cool-down piggybacks on serve traffic (rate-limited
        # inside the tracker); explicit sweeps remain available to owners
        self.sweep_popularity()
        tag, bids = unpack_batch_fetch_req(header)
        app_id = unpack_fetch_req_app_id(header, len(bids))
        gate = None
        code: Optional[int] = None
        if app_id is not None:
            # tenant-addressed request: translate its local shuffle ids (or
            # reject the whole batch with the typed unknown-tenant code — a
            # server with no registry cannot admit ANY tenant traffic)
            if self.tenants is None:
                code = SIZE_UNKNOWN_TENANT
            else:
                try:
                    bids = [
                        ShuffleBlockId(
                            self.tenants.translate(app_id, b.shuffle_id),
                            b.map_id,
                            b.reduce_id,
                        )
                        for b in bids
                    ]
                    gate = self.tenants.gate(app_id)
                except UnknownTenantError:
                    code = SIZE_UNKNOWN_TENANT
        if code is not None:
            entries = [code] * len(bids)
        elif self._io is not None:
            # executor.map is lazy-in-order: all resolves run concurrently,
            # iteration yields each block as soon as it (and its
            # predecessors) complete
            entries = self._io.map(self._resolve_one, bids)
        else:
            entries = map(self._resolve_one, bids)
        group = state.group
        if group is not None and group.ready():
            if gate is None:
                self._serve_fetch_striped(group, tag, bids, entries)
                return
            # per-tenant wire credits: the whole reply's bytes are held
            # against the tenant's gate while its chunks stream, so one
            # tenant's fan-in cannot monopolize every lane
            entries = list(entries)
            total = sum(e[2] for e in entries if isinstance(e, tuple))
            gate.acquire(total)
            try:
                self._serve_fetch_striped(group, tag, bids, entries)
            finally:
                gate.release(total)
            return
        entries = list(entries)
        if state.use_sendmsg:
            sizes, parts, total = self._reply_parts(entries)
            reply_hdr = _TAG.pack(tag) + _COUNT.pack(len(bids)) + sizes
            prefix = pack_frame_prefix(AmId.FETCH_BLOCK_REQ_ACK, reply_hdr, total)
            if gate is not None:
                gate.acquire(total)
            try:
                with state.send_lock:
                    self._sendmsg_all(conn, [prefix] + parts)
            finally:
                if gate is not None:
                    gate.release(total)
            return
        sizes, body = self._assemble_reply(entries)
        reply_hdr = _TAG.pack(tag) + _COUNT.pack(len(bids)) + sizes
        if gate is not None:
            gate.acquire(body.size)
        try:
            with state.send_lock:
                conn.sendall(
                    pack_frame_prefix(AmId.FETCH_BLOCK_REQ_ACK, reply_hdr, body.size)
                )
                if body.size:
                    conn.sendall(memoryview(body))
        finally:
            if gate is not None:
                gate.release(body.size)

    def _dispatch_frame(
        self, conn: socket.socket, state: _ConnState, am_id: AmId, header: bytes, body: bytes
    ) -> None:
        peer, send_lock = state.peer, state.send_lock
        faults.check("peer.server.frame", peer=peer, am_id=int(am_id), executor=self.executor_id)
        if am_id == AmId.FETCH_BLOCK_REQ:
            self._serve_fetch_req(conn, state, header)
        elif am_id == AmId.WIRE_HELLO:
            gid, lane, nlanes, chunk_bytes = unpack_wire_hello(header)
            with self._groups_lock:
                group = self._groups.get(gid)
                if group is None:
                    group = self._groups[gid] = _ServerGroup(gid, nlanes, chunk_bytes)
            state.group, state.lane = group, lane
            group.register(lane, conn, send_lock)
        elif am_id == AmId.MAPPER_INFO:
            info = MapperInfo.unpack(body)
            if self.store is not None:
                try:
                    self.store.apply_mapper_info(info)
                except TransportError:
                    pass  # shuffle not created on this server yet
        elif am_id == AmId.REPLICA_PUT:
            # header extensions after the entry table, detected by the
            # residue mod entry size: 0 plain, 4 crc, 8 codec, 12
            # codec+crc (core/definitions.py).  The crc trailer is
            # always LAST and covers the WIRE (possibly encoded) body —
            # except for the obs trace ext, which (when present) trails
            # even the crc and shifts every residue by 2: strip it first,
            # then the historical dispatch below runs unchanged.
            trace_ctx = None
            residue = (len(header) - REPLICA_HEADER_SIZE) % REPLICA_ENTRY_SIZE
            if residue % 4 == 2:
                trace_ctx = unpack_replica_trace_ext(header)
                if trace_ctx is not None:
                    header = header[:-REPLICA_TRACE_EXT_SIZE]
                    residue = (len(header) - REPLICA_HEADER_SIZE) % REPLICA_ENTRY_SIZE
            if residue in (4, 12):
                # wire.checksum trailer: verify before installing; a
                # corrupt replica gets NO ack, so the pusher's
                # replication_wait names this successor as stalled
                # instead of the store holding silently bad bytes
                (want,) = _CRC.unpack(bytes(header[-4:]))
                header = header[:-4]
                if crc32c(body) != want:
                    sid, src, rnd, _ = unpack_replica_put(header)
                    logger.warning(
                        "replica round (shuffle=%d, src=%d, round=%d) from "
                        "peer %s failed crc32c — discarded, not acked",
                        sid, src, rnd, peer,
                    )
                    return
            if residue in (8, 12):
                # compress.codec ext: the whole round body is one
                # encoded page; a decode failure is handled exactly
                # like a crc mismatch — discard, no ack
                codec_id, raw_len = unpack_chunk_codec_ext(
                    header, len(header) - CHUNK_CODEC_EXT_SIZE
                )
                header = header[:-CHUNK_CODEC_EXT_SIZE]
                if codec_id != CODEC_RAW or raw_len != len(body):
                    decoded = bytearray(raw_len)
                    try:
                        decode_page(codec_id, body, decoded)
                    except CodecError as e:
                        sid, src, rnd, _ = unpack_replica_put(header)
                        logger.warning(
                            "replica round (shuffle=%d, src=%d, round=%d) "
                            "from peer %s failed page decode (%s) — "
                            "discarded, not acked",
                            sid, src, rnd, peer, e,
                        )
                        return
                    body = decoded
            sid, src, rnd, entries = unpack_replica_put(header)
            faults.check(
                "replica.apply", shuffle_id=sid, src_executor=src, round_idx=rnd
            )
            if self.store is not None:
                try:
                    if trace_ctx is not None and TRACER.active:
                        # parent the apply under the pusher's replica.push span
                        with TRACER.executor_scope(self.executor_id):
                            with TRACER.activate(TRACER.remote_context(*trace_ctx)):
                                with TRACER.span(
                                    "server.replica_apply",
                                    shuffle_id=sid,
                                    src_executor=src,
                                    round=rnd,
                                ):
                                    self.store.put_replica(sid, src, rnd, entries, body)
                    else:
                        self.store.put_replica(sid, src, rnd, entries, body)
                except ResourceExhaustedError as e:
                    # store hard watermark: handled like a crc mismatch —
                    # discard, no ack — so the pusher's replication_wait
                    # names this successor stalled instead of the serving
                    # connection dying under memory pressure
                    logger.warning(
                        "replica round (shuffle=%d, src=%d, round=%d) from "
                        "peer %s shed under memory pressure (%s) — not acked",
                        sid, src, rnd, peer, e,
                    )
                    return
            with send_lock:
                conn.sendall(
                    pack_frame(AmId.REPLICA_ACK, pack_replica_ack(sid, src, rnd))
                )
        elif am_id in (AmId.MEMBER_SUSPECT, AmId.MEMBER_REJOIN):
            epoch, subject, observer = unpack_member_event(header)
            if self.member_sink is not None:
                self.member_sink(int(am_id), epoch, subject, observer)
        elif am_id == AmId.TRACE_PULL:
            # obs plane: hand the puller this executor's slice of the trace
            # ring (the loopback mesh shares one process-wide TRACER, so
            # events are attributed by their executor scope; merge_events
            # dedups overlap by uid).  Runs on a serving worker thread —
            # never the reactor loop lane (reactor-discipline).
            (tag,) = _TAG.unpack_from(header)
            events = TRACER.events
            if self.executor_id is not None:
                events = [e for e in events if e.get("eid") == self.executor_id]
            payload = json.dumps(
                {
                    "executor": self.executor_id,
                    "events": events,
                    "dropped": TRACER.dropped,
                }
            ).encode()
            with send_lock:
                conn.sendall(pack_frame(AmId.TRACE_PULL, _TAG.pack(tag), payload))
        elif am_id == AmId.METRICS_PULL:
            (tag,) = _TAG.unpack_from(header)
            text = self.metrics.prometheus_text() if self.metrics is not None else ""
            with send_lock:
                conn.sendall(pack_frame(AmId.METRICS_PULL, _TAG.pack(tag), text.encode()))
        elif am_id == AmId.HOT_SET_PULL:
            # popularity plane: hand the puller this executor's advertised
            # hot-set table — {shuffle: [holder ids]} for every shuffle whose
            # replica set is currently widened.  Readers rotate their fetches
            # across the holders.  Empty table when nothing is hot (or the
            # popularity tier is off) — a valid, cheap reply.
            (tag,) = _TAG.unpack_from(header)
            hot = self.hot_set_provider() if self.hot_set_provider is not None else {}
            with send_lock:
                conn.sendall(
                    pack_frame(AmId.HOT_SET_PULL, _TAG.pack(tag), pack_hot_set(hot))
                )
        elif am_id == AmId.INIT_EXECUTOR_REQ:
            (eid,) = _TAG.unpack_from(header)
            self.handshaken[eid] = body
            with send_lock:
                conn.sendall(pack_frame(AmId.INIT_EXECUTOR_ACK, header, b""))

    def close(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass
        with self._groups_lock:
            groups, self._groups = list(self._groups.values()), {}
        for g in groups:
            g.close()
        with self._accepted_lock:
            accepted, self._accepted = list(self._accepted), []
        for conn in accepted:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._reactor is not None:
            # after the conns are shut down, so no worker is blocked mid-frame
            self._reactor.close()
        if self._io is not None:
            self._io.shutdown(wait=False)


class _PeerConnection:
    """One client connection: sender + receiver thread parking acks for progress().

    The endpoint-cache entry of the reference (UcxWorkerWrapper.scala:64,233-276).
    Fetch-ack bodies are received **directly into the caller's result buffers**
    (``ack_buffers`` lookup) — the RNDV-into-registered-bounce-buffer receive
    (UcxWorkerWrapper.scala:142-185) rather than parking a parsed copy; the
    parked frame then carries an empty body and progress() only completes
    requests.  ``activity`` is set whenever a frame parks (the wakeup doorbell).
    """

    def __init__(
        self,
        address: Tuple[str, int],
        ack_buffers: Optional[Callable[[int], Optional[list]]] = None,
        ack_done: Optional[Callable[[int], None]] = None,
        activity: Optional[threading.Event] = None,
        conf: Optional[TpuShuffleConf] = None,
        lane: int = 0,
        chunk_sink: Optional[Callable[[int, int, int, int], Optional[memoryview]]] = None,
        chunk_done: Optional[Callable[[int, int, bool], Optional[bytes]]] = None,
        manifest_sink: Optional[Callable[[bytes], Optional[bytes]]] = None,
    ) -> None:
        #: host:port of the server end — every raised error names it
        self.peer = f"{address[0]}:{address[1]}"
        timeout_ms = conf.wire_timeout_ms if conf is not None else 30000
        self._timeout_s: Optional[float] = (timeout_ms / 1000.0) if timeout_ms else None
        try:
            self.sock = socket.create_connection(address, timeout=self._timeout_s or 30)
        except socket.timeout:
            raise OSError(f"connect to peer {self.peer} timed out after {timeout_ms} ms") from None
        # the connect timeout persists as the socket timeout: mid-frame reads
        # and stuck sends fail after wire_timeout_ms instead of hanging; the
        # idle wait for the next frame header is exempt (idle_ok below).
        # wire_timeout_ms = 0 clears it — the historical block-forever wire.
        self.sock.settimeout(self._timeout_s)
        # deep recv window default keeps the scatter recv fed between polls
        apply_wire_sockopts(self.sock, conf, rcvbuf=4 << 20)
        self.pending: Dict[int, Callable[[bytes, bytes], None]] = {}
        self.lock = threading.Lock()
        #: parked (am_id, header, body, scattered) frames; ``scattered`` marks
        #: acks whose payload already sits in the caller's result buffers
        self.inbox: Deque[Tuple[AmId, bytes, bytes, bool]] = deque()
        self.inbox_lock = threading.Lock()
        self.ack_buffers = ack_buffers
        self.ack_done = ack_done
        self.activity = activity
        #: striped-wire role (lane of a _StripeGroup): chunk_sink maps a chunk
        #: to its destination view, chunk_done/manifest_sink account receive
        #: progress and hand back the manifest header once the batch completes
        self.lane = lane
        self.chunk_sink = chunk_sink
        self.chunk_done = chunk_done
        self.manifest_sink = manifest_sink
        # per-lane telemetry — written only by this connection's recv thread,
        # read racily by wire_lane_stats() (monotonic counters, no lock needed)
        self.rx_bytes = 0
        self.rx_syscalls = 0
        self.rx_stall_ns = 0
        self.stall_samples: Deque[int] = deque(maxlen=4096)
        #: reusable landing buffer for ENCODED chunk payloads (compressed wire
        #: path): wire bytes land here, then decode into the chunk's final
        #: destination view — written only by this connection's recv thread,
        #: so the pool needs no lock (same contract as the rx_* counters)
        self._codec_scratch: Optional[bytearray] = None
        #: the exception that killed the recv loop (None for a clean EOF) —
        #: _fail_conn_inflight surfaces a typed error (BlockCorruptError)
        #: instead of the generic connection-lost one when it is set
        self.last_error: Optional[Exception] = None
        self.alive = True
        self.recv_thread = threading.Thread(target=self._recv_loop, daemon=True)
        self.recv_thread.start()

    # -- counted zero-copy receive primitives (recv thread only) -----------

    def _recv_exact(self, n: int, idle_ok: bool = False) -> Optional[bytearray]:
        out = bytearray(n)
        mv = memoryview(out)
        got = 0
        while got < n:
            try:
                r = self.sock.recv_into(mv[got:], n - got)
            except socket.timeout:
                # idle between frames is normal; hung MID-frame is a fault
                if idle_ok and got == 0:
                    if not self.alive:
                        return None
                    continue
                raise OSError(
                    f"peer {self.peer} (lane {self.lane}) hung mid-frame: read "
                    f"timed out with {got}/{n} B received"
                ) from None
            if r == 0:
                return None
            got += r
            self.rx_bytes += r
            self.rx_syscalls += 1
        return out

    def _recv_into(self, mv: memoryview, what: str = "") -> None:
        """recv_into a caller-owned destination until full — the zero-copy
        scatter receive (no staging allocation, no join copy).  ``what``
        carries block context (tag/block id) into any raised error."""
        while mv.nbytes:
            try:
                n = self.sock.recv_into(mv, mv.nbytes)
            except socket.timeout:
                raise OSError(
                    f"peer {self.peer} (lane {self.lane}) hung mid-body{what}: "
                    f"read timed out with {mv.nbytes} B still expected"
                ) from None
            if n == 0:
                raise OSError(f"peer {self.peer} (lane {self.lane}) closed mid-body{what}")
            self.rx_bytes += n
            self.rx_syscalls += 1
            mv = mv[n:]

    def _recv_ack_into_buffers(self, header: bytes, blen: int) -> bool:
        """Scatter a fetch-ack body straight into the batch's result buffers.
        Returns False when the buffers are unknown (caller falls back to a
        parked bytes body)."""
        if self.ack_buffers is None:
            return False
        (tag,) = _TAG.unpack_from(header, 0)
        (count,) = _COUNT.unpack_from(header, _TAG.size)
        sizes = [
            _SIZE.unpack_from(header, _TAG.size + _COUNT.size + i * _SIZE.size)[0]
            for i in range(count)
        ]
        # Trust the FRAME boundary, not the header: a skewed/buggy peer whose
        # size list disagrees with blen would otherwise make us read past the
        # frame into the next one.  Fall back to the parked-bytes path, which
        # fails loudly instead of completing with corrupt data.
        if sum(s for s in sizes if s > 0) != blen:
            return False
        bufs = self.ack_buffers(tag)
        if bufs is None or len(bufs) != count:
            return False
        for i in range(count):
            size = sizes[i]
            if size <= 0:
                continue
            view = bufs[i].host_view() if bufs[i] is not None else None
            if view is not None and size <= view.size:
                self._recv_into(memoryview(view)[:size], what=f" (fetch tag {tag}, block {i})")
            else:  # oversized/unknown: drain and let progress() report failure
                if self._recv_exact(size) is None:
                    raise OSError(
                        f"peer {self.peer} (lane {self.lane}) closed mid-body "
                        f"(fetch tag {tag}, block {i})"
                    )
        return True

    def _park(self, am_id: AmId, header: bytes, body: bytes, scattered: bool) -> None:
        # park — completion happens under progress() (explicit-poll contract)
        with self.inbox_lock:
            self.inbox.append((am_id, header, body, scattered))
        if self.activity is not None:
            self.activity.set()

    def _codec_buf(self, n: int) -> memoryview:
        """Recv-thread-only scratch for encoded chunk payloads (grown, never
        shrunk): one live landing buffer per lane, reused chunk to chunk."""
        if self._codec_scratch is None or len(self._codec_scratch) < n:
            self._codec_scratch = bytearray(max(n, 1 << 16))
        return memoryview(self._codec_scratch)[:n]

    def _recv_chunk(self, header: bytes, blen: int) -> None:
        """Receive one striped chunk straight into its destination buffer.

        The chunk is self-addressing — (tag, block, offset within block) —
        so this lane needs no coordination with its siblings.  If this chunk
        is the batch's last missing piece, park the manifest header here so
        progress() completes the batch on whichever lane finished last.

        Header extensions are detected by header length (24 plain, +8 codec
        ext, +4 crc trailer last — core/definitions.py).  An encoded chunk
        lands in this lane's scratch and decodes into the destination view;
        the crc covers the ENCODED bytes, so corruption is caught before the
        decoder parses anything, and a decode failure (CodecError) surfaces
        as ``BlockCorruptError`` exactly like a crc mismatch.  Either kills
        this lane — the batch then fails typed and the reducer-side failover
        (``_retry_fetch``) re-sources the block from a replica holder.
        Receive accounting is in DECODED bytes (``raw_len``), matching the
        manifest totals the stripe tracker sums."""
        tag, block, seq, offset = unpack_chunk_hdr(header)
        ext = len(header) - CHUNK_HEADER_SIZE
        want = None
        codec_id: Optional[int] = None
        raw_len = blen
        if ext == 4:
            (want,) = _CRC.unpack_from(header, CHUNK_HEADER_SIZE)
        elif ext in (CHUNK_CODEC_EXT_SIZE, CHUNK_CODEC_EXT_SIZE + 4):
            codec_id, raw_len = unpack_chunk_codec_ext(header, CHUNK_HEADER_SIZE)
            if ext == CHUNK_CODEC_EXT_SIZE + 4:
                (want,) = _CRC.unpack_from(header, CHUNK_HEADER_SIZE + CHUNK_CODEC_EXT_SIZE)
        mv = self.chunk_sink(tag, block, offset, raw_len) if raw_len else None
        ok = False
        try:
            what = f" (fetch tag {tag}, block {block}, chunk offset {offset})"
            if codec_id is None or (codec_id == CODEC_RAW and raw_len == blen):
                # plain chunk (or explicit raw fallback): payload IS the slice
                data = b""
                if mv is not None:
                    self._recv_into(mv, what=what)
                    data = mv
                elif blen:  # unknown tag / oversized target: drain off the wire
                    data = self._recv_exact(blen)
                    if data is None:
                        raise OSError(
                            f"peer {self.peer} (lane {self.lane}) closed mid-chunk "
                            f"(fetch tag {tag}, block {block})"
                        )
                if want is not None and blen and crc32c(data) != want:
                    raise BlockCorruptError(
                        -1, -1, block,
                        f"striped chunk (fetch tag {tag}, block {block}, offset "
                        f"{offset}) from peer {self.peer} lane {self.lane} failed "
                        "its crc32c check",
                    )
            else:
                # encoded page: wire bytes -> lane scratch, verify, decode
                # into the final destination (still one write into the
                # result buffer; the scatter offsets are raw coordinates)
                enc = self._codec_buf(blen)
                self._recv_into(enc, what=what)
                if want is not None and crc32c(enc) != want:
                    raise BlockCorruptError(
                        -1, -1, block,
                        f"striped chunk (fetch tag {tag}, block {block}, offset "
                        f"{offset}) from peer {self.peer} lane {self.lane} failed "
                        "its crc32c check",
                    )
                if mv is not None:
                    try:
                        decode_page(codec_id, enc, mv)
                    except CodecError as e:
                        raise BlockCorruptError(
                            -1, -1, block,
                            f"striped chunk (fetch tag {tag}, block {block}, "
                            f"offset {offset}) from peer {self.peer} lane "
                            f"{self.lane} failed page decode: {e}",
                        ) from None
            ok = True
        finally:
            # the done callback must run even when the socket dies mid-chunk:
            # it clears the tag's scattering mark so a later sweep can fail it
            done_hdr = self.chunk_done(tag, raw_len if ok else 0, mv is not None)
        if done_hdr is not None:
            self._park(AmId.FETCH_BLOCK_REQ_ACK, done_hdr, b"", True)

    def _recv_loop(self) -> None:
        try:
            while self.alive:
                faults.check("peer.client.recv", peer=self.peer, lane=self.lane)
                t0 = time.monotonic_ns()
                hdr = self._recv_exact(FRAME_HEADER_SIZE, idle_ok=True)
                stall = time.monotonic_ns() - t0
                self.rx_stall_ns += stall
                self.stall_samples.append(stall)
                if hdr is None:
                    break
                hdr = faults.transform("peer.client.frame", hdr, peer=self.peer, lane=self.lane)
                am_id, hlen, blen = unpack_frame_header(hdr)
                if hlen + blen > _MAX_FRAME:
                    raise ValueError(f"frame too large from peer {self.peer}")
                if am_id == AmId.SERVER_BUSY:
                    # load shed: the server refused this connection over its
                    # accept backlog and closes right after.  Die typed so
                    # in-flight batches fail RETRYABLE (backoff + retry)
                    # instead of with the generic connection-lost error.
                    self.last_error = ResourceExhaustedError(
                        detail=f"peer {self.peer} shed the connection "
                        "(accept backlog full)"
                    )
                    break
                header = self._recv_exact(hlen) if hlen else b""
                if hlen and header is None:
                    break
                if am_id == AmId.FETCH_BLOCK_CHUNK and self.chunk_done is not None:
                    self._recv_chunk(header, blen)
                    continue
                if (
                    am_id == AmId.FETCH_BLOCK_REQ_ACK
                    and blen == 0
                    and self.manifest_sink is not None
                ):
                    # striped reply manifest: sizes only, payload rides (or
                    # rode) chunk frames — completion may be here or on a
                    # sibling lane still scattering
                    done_hdr = self.manifest_sink(bytes(header))
                    if done_hdr is not None:
                        self._park(am_id, done_hdr, b"", True)
                    continue
                scattered = False
                if am_id == AmId.FETCH_BLOCK_REQ_ACK and self.ack_buffers is not None:
                    (tag,) = _TAG.unpack_from(header, 0)
                    try:
                        scattered = self._recv_ack_into_buffers(header, blen)
                    finally:
                        if self.ack_done is not None:
                            self.ack_done(tag)
                if not scattered:
                    body = self._recv_exact(blen) if blen else b""
                    if blen and body is None:
                        break
                else:
                    body = b""  # payload already scattered into result buffers
                self._park(am_id, header, body, scattered)
        except (OSError, ValueError, struct.error, TransportError) as e:
            self.last_error = e
        self.alive = False
        if self.activity is not None:
            self.activity.set()  # wake parked waiters so they observe the death
        try:  # release the fd as soon as the peer is gone
            self.sock.close()
        except OSError:
            pass

    def send(self, frame: bytes) -> None:
        with self.lock:
            self.sock.sendall(frame)

    def drain_one(self) -> Optional[Tuple[AmId, bytes, bytes, bool]]:
        with self.inbox_lock:
            return self.inbox.popleft() if self.inbox else None

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class _StripeGroup:
    """Client-side bundle of K lane connections acting as ONE logical peer
    connection — it lives in the transport's conn cache and duck-types
    ``_PeerConnection`` (alive / send / drain_one / inbox / close), so the
    progress() pump, eviction, zombie retirement, and failure sweeps all work
    on it unchanged.

    Requests and non-fetch AMs travel on lane 0; fetch replies return as a
    size manifest plus self-addressing chunks striped across every lane
    (core/definitions.py, AM ids 5-6).  ``alive`` is all-lanes-alive: a chunk
    lost with one lane makes the group's in-flight batches unrecoverable, so
    a single dead lane fails the whole bundle fast."""

    def __init__(self, group_id: int, lanes: List[_PeerConnection]) -> None:
        self.group_id = group_id
        self.lanes = lanes

    @property
    def peer(self) -> str:
        return self.lanes[0].peer if self.lanes else "?"

    @property
    def alive(self) -> bool:
        return all(lane.alive for lane in self.lanes)

    @property
    def inbox(self) -> bool:
        # truthiness only (zombie retirement): any lane still holding frames
        return any(lane.inbox for lane in self.lanes)

    @property
    def last_error(self) -> Optional[Exception]:
        # a typed lane death (e.g. BlockCorruptError) wins over plain EOFs
        for lane in self.lanes:
            if isinstance(lane.last_error, TransportError):
                return lane.last_error
        for lane in self.lanes:
            if lane.last_error is not None:
                return lane.last_error
        return None

    def send(self, frame: bytes) -> None:
        self.lanes[0].send(frame)

    def drain_one(self) -> Optional[Tuple[AmId, bytes, bytes, bool]]:
        for lane in self.lanes:
            frame = lane.drain_one()
            if frame is not None:
                return frame
        return None

    def close(self) -> None:
        for lane in self.lanes:
            lane.close()

    def lane_stats(self) -> List[Dict[str, int]]:
        return [
            {
                "lane": lane.lane,
                "rx_bytes": lane.rx_bytes,
                "rx_syscalls": lane.rx_syscalls,
                "rx_stall_ns": lane.rx_stall_ns,
                "rx_stall_p99_ns": _stall_p99_ns(lane),
            }
            for lane in self.lanes
        ]


def _stall_p99_ns(conn: "_PeerConnection") -> int:
    """p99 of the connection's recent frame-stall samples (time spent waiting
    for the next frame header).  Snapshot + sort of a bounded deque; the recv
    thread appends concurrently, which at worst skews one sample."""
    samples = sorted(conn.stall_samples)
    if not samples:
        return 0
    return samples[min(len(samples) - 1, int(0.99 * len(samples)))]


class _StripeRx:
    """Per-tag striped-receive accounting; every field is guarded by the
    transport's ``_tag_lock`` (mutated from multiple lane recv threads)."""

    __slots__ = ("manifest", "total", "received")

    def __init__(self) -> None:
        self.manifest: Optional[bytes] = None  # manifest header, once landed
        self.total: Optional[int] = None  # payload bytes promised by the sizes
        self.received = 0  # chunk payload bytes landed across all lanes


#: EWMA smoothing factor for per-peer fetch latency and error rate — heavy
#: enough that a handful of samples move the score, light enough that one
#: outlier does not trip anything by itself.
_HEALTH_ALPHA = 0.25

#: Circuit-breaker states (closed = healthy traffic flows; open = peer is
#: sick, new fetches skip it for the replica ring; half-open = cooldown
#: elapsed, exactly one probe request is in flight to test recovery).
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


class _PeerHealth:
    """Per-executor health score + circuit breaker; every field is guarded by
    the transport's ``_health_lock`` (a leaf lock: no calls out while held)."""

    __slots__ = (
        "latency_ewma_ns",
        "error_ewma",
        "consecutive_failures",
        "state",
        "opened_at_ns",
        "probe_inflight",
        "successes",
        "failures",
        "trips",
    )

    def __init__(self) -> None:
        self.latency_ewma_ns = 0.0  # EWMA of observed fetch completion latency
        self.error_ewma = 0.0  # EWMA of the error indicator (1=fail, 0=ok)
        self.consecutive_failures = 0
        self.state = BREAKER_CLOSED
        self.opened_at_ns = 0
        self.probe_inflight = False
        self.successes = 0
        self.failures = 0
        self.trips = 0


class PeerTransport(ShuffleTransport):
    """ShuffleTransport over TCP peers — the socket twin of the loopback
    transport, used by multi-process deployments and the Spark shim."""

    def __init__(
        self,
        conf: Optional[TpuShuffleConf] = None,
        executor_id: ExecutorId = 0,
        store: Optional[HbmBlockStore] = None,
    ) -> None:
        self.conf = conf or TpuShuffleConf()
        self.executor_id = executor_id
        self.store = store if store is not None else HbmBlockStore(self.conf, executor_id=executor_id)
        self._registry: Dict[BlockId, Block] = {}  #: guarded by self._registry_lock
        self._registry_lock = threading.Lock()
        self.server: Optional[BlockServer] = None
        # Connection cache keyed by (executor, slot): callers map onto
        # num_client_workers parallel connections per peer by thread identity —
        # the reference's thread->worker routing ``threadId % numWorkers``
        # (UcxShuffleTransport.scala:277-279, UcxShuffleConf.scala:80-86).
        self._conns: Dict[Tuple[ExecutorId, int], Union[_PeerConnection, _StripeGroup]] = {}  #: guarded by self._conn_lock
        self._conn_addrs: Dict[ExecutorId, Tuple[str, int]] = {}  #: guarded by self._conn_lock
        self._conn_lock = threading.Lock()
        self._slot_local = threading.local()
        self._slot_rr = 0  #: guarded by self._tag_lock
        self._connecting: Dict[Tuple[ExecutorId, int], threading.Event] = {}  #: guarded by self._conn_lock
        self._next_tag = 0  #: guarded by self._tag_lock
        self._tag_lock = threading.Lock()
        self._inflight: Dict[int, Tuple[List[Request], List[MemoryBlock], List[Optional[OperationCallback]], Optional[Union[_PeerConnection, _StripeGroup]]]] = {}  #: guarded by self._tag_lock
        # tag -> count of lane recv threads currently writing the tag's result
        # buffers (a counter, not a set: with striping, several lanes scatter
        # one tag concurrently and set-discard would drop siblings' marks)
        self._scattering: Dict[int, int] = {}  #: guarded by self._tag_lock
        #: striped-receive progress per in-flight tag (striped groups only)
        self._stripe_rx: Dict[int, _StripeRx] = {}  #: guarded by self._tag_lock
        self._zombies: List[_PeerConnection] = []  #: guarded by self._conn_lock (evicted, not yet drained)
        # -- neighbor replication (client side of REPLICA_PUT/REPLICA_ACK) --
        #: outstanding REPLICA_ACKs per shuffle this executor pushed
        self._replica_pending: Dict[int, int] = {}  #: guarded by self._tag_lock
        #: shuffles whose replica push is still queued or in flight
        self._replica_pushing: set = set()  #: guarded by self._tag_lock
        #: outstanding acks per shuffle broken down by successor executor —
        #: lets replication_wait name WHICH neighbor stalled, not just that one did
        self._replica_unacked: Dict[int, Dict[ExecutorId, int]] = {}  #: guarded by self._tag_lock
        #: replication jobs awaiting the replicator worker, oldest first —
        #: ``(shuffle_id, neighbors | None)`` tuples; None = the ring's
        #: ``replication.factor`` successors (seal-time push), an explicit
        #: list = a popularity widen job pushing to the extra holders only
        self._replica_queue: deque = deque()  #: guarded by self._tag_lock
        self._replica_worker: Optional[threading.Thread] = None  #: guarded by self._tag_lock
        self._replica_run = True  #: guarded by self._tag_lock (close() clears)
        self._replica_wake = threading.Event()
        #: replication telemetry: rounds/bytes pushed, acks seen, failed sends,
        #: rounds dropped by the backlog cap, and the live backlog gauge (bytes
        #: of replica payload admitted to the wire but not yet sent)
        self.replica_stats: Dict[str, int] = {
            "pushed_rounds": 0,
            "pushed_bytes": 0,
            "acks": 0,
            "failed": 0,
            "dropped_rounds": 0,
            "replica_backlog_bytes": 0,
        }  #: guarded by self._tag_lock
        #: Optional ClusterMembership installed by elastic owners (the SPMD
        #: driver / loopback harness); peer-observed wire failures and rejoin
        #: announcements feed it.  None = membership-unaware (the default).
        self.membership = None
        #: Popularity-aware serving tier (serve.hotThresholdFetchesPerSec):
        #: the per-block fetch-rate tracker the block server observes into
        #: (None = tier off, zero overhead), the advertised holder sets of
        #: currently-hot shuffles (served to readers via HOT_SET_PULL), and
        #: the reader-side TTL cache of peers' advertisements.
        self.popularity: Optional[BlockPopularity] = (
            BlockPopularity(self.conf.serve_hot_threshold_fetches_per_sec)
            if self.conf.serve_hot_threshold_fetches_per_sec > 0
            else None
        )
        self._hot_shuffles: Dict[int, List[ExecutorId]] = {}  #: guarded by self._tag_lock
        self._hot_holders_cache: Dict[ExecutorId, Tuple[float, Dict[int, List[int]]]] = {}  #: guarded by self._tag_lock
        #: Gray-failure plane: per-executor health scores + circuit breakers.
        #: Scoring (latency/error EWMAs) is always on — pure bookkeeping, no
        #: behavior change; the breaker only trips when
        #: ``breaker.failureThreshold`` > 0.  _health_lock is a LEAF lock:
        #: nothing is called while it is held.
        self._health: Dict[ExecutorId, _PeerHealth] = {}  #: guarded by self._health_lock
        self._health_lock = threading.Lock()
        #: Multi-tenant identity of this executor's fetches: with
        #: ``conf.tenants_enabled`` and an ``app_id`` set, every
        #: FETCH_BLOCK_REQ carries the tenant header extension and its triples
        #: use tenant-local shuffle ids (servers translate via their
        #: registry).  None (the default) emits the historical frames.
        self.app_id: Optional[str] = None
        self.stats_agg = StatsAggregator() if self.conf.collect_stats else None
        #: obs plane: this executor's unified metrics surface.  Subsystem
        #: providers are registered below; stores/services owned elsewhere
        #: (eviction manager, tenant registry, the cluster's elastic stats)
        #: register theirs through the same object.  METRICS_PULL serves it.
        self.metrics = MetricsRegistry(executor_id=executor_id)
        #: obs plane: TRACE_PULL/METRICS_PULL replies waiting on their tag
        self._pull_pending: Dict[int, dict] = {}  #: guarded by self._tag_lock
        self._metrics_http = None
        #: always-on flight recorder: ring stays warm, TransportError /
        #: elastic-recovery / chaos triggers capture postmortem bundles
        self.recorder = FlightRecorder(
            TRACER,
            executor_id=executor_id,
            postmortem_dir=self.conf.obs_postmortem_dir or None,
            ring_capacity=self.conf.obs_ring_capacity,
        )
        self.recorder.attach_registry(self.metrics)
        self.recorder.attach_membership(self._membership_snapshot)
        self.recorder.install()
        self._register_metrics_providers()
        #: Wakeup doorbell (conf.use_wakeup): recv threads set it when an ack
        #: parks, so fetch loops can sleep in wait_for_activity() instead of
        #: busy-spinning progress() against the receiver's GIL slices.
        self._activity = threading.Event()
        # asynchronous neighbor replication: seal() hands the sealed shuffle
        # to a background push thread (no frames at replication_factor = 0)
        self.store.on_seal = self._on_store_seal

    def _ack_buffers(self, tag: int) -> Optional[list]:
        """Recv-thread lookup: the batch's result buffers, WITHOUT popping the
        inflight entry (progress() still owns completion).  Marks the tag as
        scattering so a concurrent eviction cannot fail-and-release the buffers
        while the recv thread writes into them; ``_ack_buffers_done`` clears."""
        with self._tag_lock:
            entry = self._inflight.get(tag)
            if entry is None:
                return None
            self._scattering[tag] = self._scattering.get(tag, 0) + 1
            return list(entry[1])

    def _ack_buffers_done(self, tag: int) -> None:
        with self._tag_lock:
            self._unmark_scattering_locked(tag)

    def _unmark_scattering_locked(self, tag: int) -> None:
        """Caller holds self._tag_lock."""
        left = self._scattering.get(tag, 0) - 1
        if left > 0:
            self._scattering[tag] = left
        else:
            self._scattering.pop(tag, None)

    # -- striped-wire receive callbacks (called from lane recv threads) ----

    def _chunk_buffers(self, tag: int, block: int, offset: int, nbytes: int) -> Optional[memoryview]:
        """Resolve one chunk's destination: a view of the batch's result
        buffer at the chunk's final offset (the zero-copy scatter target).
        Marks the tag as scattering so eviction cannot fail-and-release the
        buffer mid-write; ``_chunk_done`` clears the mark and accounts."""
        with self._tag_lock:
            entry = self._inflight.get(tag)
            if entry is None or not 0 <= block < len(entry[1]):
                return None
            buf = entry[1][block]
            view = buf.host_view() if buf is not None else None
            if view is None or offset + nbytes > view.size:
                return None  # oversized block: drain; progress() reports failure
            self._scattering[tag] = self._scattering.get(tag, 0) + 1
            return memoryview(view)[offset : offset + nbytes]

    def _chunk_done(self, tag: int, nbytes: int, scattered: bool) -> Optional[bytes]:
        """Account one received chunk.  Returns the manifest header iff this
        chunk completed the batch (manifest seen AND all payload bytes in), so
        the calling lane parks the completion frame for progress()."""
        with self._tag_lock:
            if scattered:
                self._unmark_scattering_locked(tag)
            rx = self._stripe_rx.get(tag)
            if rx is None:
                return None
            rx.received += nbytes
            return self._stripe_complete_locked(tag)

    def _on_manifest(self, header: bytes) -> Optional[bytes]:
        """A striped reply's size manifest landed (FetchBlockReqAck with an
        empty body).  Returns the header iff the batch is now complete —
        either here or, for unknown tags, immediately (parked for the generic
        frame handler, which drops stale tags)."""
        if len(header) < _TAG.size + _COUNT.size:
            return header  # runt header: parked; _handle_frame ignores it
        (tag,) = _TAG.unpack_from(header, 0)
        (count,) = _COUNT.unpack_from(header, _TAG.size)
        if len(header) < _TAG.size + _COUNT.size + count * _SIZE.size:
            return header  # truncated sizes: let _handle_frame fail the batch
        total = 0
        for i in range(count):
            (s,) = _SIZE.unpack_from(header, _TAG.size + _COUNT.size + i * _SIZE.size)
            if s > 0:
                total += s
        with self._tag_lock:
            rx = self._stripe_rx.get(tag)
            if rx is None:
                return header  # unknown/failed tag: park; handler discards
            rx.manifest = bytes(header)
            rx.total = total
            return self._stripe_complete_locked(tag)

    def _stripe_complete_locked(self, tag: int) -> Optional[bytes]:
        """Caller holds self._tag_lock."""
        rx = self._stripe_rx.get(tag)
        if rx is None or rx.total is None or rx.received < rx.total:
            return None
        del self._stripe_rx[tag]
        return rx.manifest

    def wire_lane_stats(self) -> List[Dict[str, int]]:
        """Per-lane receive telemetry for striped connections: bytes,
        recv_into syscalls, and cumulative frame-stall time per lane.
        Single-lane connections report as lane 0 of their key."""
        with self._conn_lock:
            conns = list(self._conns.items())
        out: List[Dict[str, int]] = []
        for (eid, slot), conn in conns:
            if isinstance(conn, _StripeGroup):
                for s in conn.lane_stats():
                    out.append({"executor": eid, "slot": slot, **s})
            else:
                out.append(
                    {
                        "executor": eid,
                        "slot": slot,
                        "lane": 0,
                        "rx_bytes": conn.rx_bytes,
                        "rx_syscalls": conn.rx_syscalls,
                        "rx_stall_ns": conn.rx_stall_ns,
                        "rx_stall_p99_ns": _stall_p99_ns(conn),
                    }
                )
        return out

    def compress_stats(self) -> Dict[str, int]:
        """Serve-side wire-compression telemetry (tier a): decoded vs wire
        bytes this executor streamed through chunk frames, plus the page
        encode/raw-fallback split.  All zeros when ``compress.codec`` is off
        or no striped reply has been served yet."""
        if self.server is None:
            return {"raw_bytes": 0, "wire_bytes": 0, "encoded_chunks": 0, "raw_chunks": 0}
        return self.server.compress_snapshot()

    # -- obs plane ---------------------------------------------------------

    def _replica_stats_snapshot(self) -> Dict[str, int]:
        with self._tag_lock:
            return dict(self.replica_stats)

    def _membership_snapshot(self) -> Optional[dict]:
        """Flight-recorder leg: the executor's membership view, or None when
        membership-unaware (elastic off)."""
        m = self.membership
        if m is None:
            return None
        try:
            return m.snapshot()  # {"epoch", "alive", "dead"}
        except Exception:
            return None

    # -- gray-failure plane: peer health + circuit breakers ----------------

    def _health_of(self, executor_id: ExecutorId) -> _PeerHealth:
        """Caller holds self._health_lock."""
        h = self._health.get(executor_id)
        if h is None:
            h = self._health[executor_id] = _PeerHealth()
        return h

    def record_peer_success(self, executor_id: ExecutorId, latency_ns: int = 0) -> None:
        """A fetch against ``executor_id`` completed: fold the latency into
        the EWMA, clear the failure streak, and close a half-open breaker
        (the probe came back)."""
        with self._health_lock:
            h = self._health_of(executor_id)
            h.successes += 1
            h.consecutive_failures = 0
            h.error_ewma += _HEALTH_ALPHA * (0.0 - h.error_ewma)
            if latency_ns > 0:
                if h.latency_ewma_ns == 0.0:
                    h.latency_ewma_ns = float(latency_ns)
                else:
                    h.latency_ewma_ns += _HEALTH_ALPHA * (latency_ns - h.latency_ewma_ns)
            if h.state != BREAKER_CLOSED:
                h.state = BREAKER_CLOSED
                h.probe_inflight = False

    def record_peer_failure(self, executor_id: ExecutorId, reason: str = "") -> None:
        """A fetch against ``executor_id`` failed at the wire level (send
        failure, dead connection, timeout).  Trips the breaker open once the
        failure streak reaches ``breaker.failureThreshold`` (0 = never); a
        failed half-open probe re-opens with a fresh cooldown."""
        threshold = self.conf.breaker_failure_threshold
        with self._health_lock:
            h = self._health_of(executor_id)
            h.failures += 1
            h.consecutive_failures += 1
            h.error_ewma += _HEALTH_ALPHA * (1.0 - h.error_ewma)
            if threshold <= 0:
                return
            if h.state == BREAKER_HALF_OPEN or (
                h.state == BREAKER_CLOSED and h.consecutive_failures >= threshold
            ):
                if h.state != BREAKER_OPEN:
                    h.trips += 1
                h.state = BREAKER_OPEN
                h.opened_at_ns = time.monotonic_ns()
                h.probe_inflight = False
        if threshold > 0 and reason:
            logger.debug("peer %s health: %s", executor_id, reason)

    def breaker_allows(self, executor_id: ExecutorId) -> bool:
        """Gate a new fetch against ``executor_id``.  Closed (or breaker off)
        admits; open rejects until ``breaker.cooldownMs`` elapses, then flips
        half-open and admits EXACTLY ONE probe — further fetches are rejected
        until the probe resolves through record_peer_success/_failure."""
        if self.conf.breaker_failure_threshold <= 0:
            return True
        with self._health_lock:
            h = self._health.get(executor_id)
            if h is None or h.state == BREAKER_CLOSED:
                return True
            if h.state == BREAKER_OPEN:
                cooldown_ns = self.conf.breaker_cooldown_ms * 1_000_000
                if time.monotonic_ns() - h.opened_at_ns < cooldown_ns:
                    return False
                h.state = BREAKER_HALF_OPEN
                h.probe_inflight = True
                return True
            # half-open: one probe at a time
            if h.probe_inflight:
                return False
            h.probe_inflight = True
            return True

    def breaker_state(self, executor_id: ExecutorId) -> str:
        with self._health_lock:
            h = self._health.get(executor_id)
            return h.state if h is not None else BREAKER_CLOSED

    def health_snapshot(self) -> Dict[int, Dict[str, object]]:
        """Per-executor health view for postmortems (kill_executor captures
        this) and white-box tests."""
        with self._health_lock:
            return {
                eid: {
                    "state": h.state,
                    "latency_ewma_ns": int(h.latency_ewma_ns),
                    "error_ewma": round(h.error_ewma, 4),
                    "consecutive_failures": h.consecutive_failures,
                    "successes": h.successes,
                    "failures": h.failures,
                    "trips": h.trips,
                }
                for eid, h in self._health.items()
            }

    def _health_view(self) -> Dict[str, int]:
        """Metrics-registry leg (family ``health``): fleet-level roll-up of
        the per-peer scores — counts by breaker state plus cumulative
        success/failure/trip counters."""
        with self._health_lock:
            if not self._health:
                return {}
            out = {
                "peers": len(self._health),
                "open": 0,
                "half_open": 0,
                "successes": 0,
                "failures": 0,
                "trips": 0,
                "latency_ewma_ns_max": 0,
            }
            for h in self._health.values():
                if h.state == BREAKER_OPEN:
                    out["open"] += 1
                elif h.state == BREAKER_HALF_OPEN:
                    out["half_open"] += 1
                out["successes"] += h.successes
                out["failures"] += h.failures
                out["trips"] += h.trips
                out["latency_ewma_ns_max"] = max(
                    out["latency_ewma_ns_max"], int(h.latency_ewma_ns)
                )
            return out

    def _register_metrics_providers(self) -> None:
        """Wire this transport's scattered telemetry surfaces into the one
        registry: op summaries, per-lane wire counters, replication and
        store replica-tier accounting, serve-side compression, and the trace
        ring's own health.  Cluster-owned surfaces (elastic, eviction,
        tenants) register from their owners (transport/tpu.py)."""
        if self.stats_agg is not None:
            self.metrics.register("ops", stats_aggregator_provider(self.stats_agg))
        self.metrics.register("wire", wire_lane_provider(self.wire_lane_stats))
        self.metrics.register(
            "replica", counter_dict_provider("replica", self._replica_stats_snapshot)
        )
        self.metrics.register(
            "replica_tier", counter_dict_provider("replica", self.store.replica_stats)
        )
        self.metrics.register("compress", counter_dict_provider("compress", self.compress_stats))
        # dynamic closures: membership and the eviction manager attach AFTER
        # construction (elastic wiring, service plane) — resolve at scrape time
        self.metrics.register(
            "elastic", counter_dict_provider("elastic", self._elastic_view)
        )
        self.metrics.register(
            "eviction", counter_dict_provider("eviction", self._eviction_view)
        )
        self.metrics.register(
            "reactor", counter_dict_provider("reactor", self._reactor_view)
        )
        self.metrics.register(
            "health", counter_dict_provider("health", self._health_view)
        )
        self.metrics.register(
            "serve", counter_dict_provider("serve", self._serve_view)
        )
        self.metrics.register("obs", tracer_provider(TRACER))

    def _elastic_view(self) -> Dict[str, int]:
        m = self.membership
        if m is None:
            return {}
        snap = m.snapshot()
        return {
            "epoch": snap["epoch"],
            "alive": len(snap["alive"]),
            "dead": len(snap["dead"]),
        }

    def _eviction_view(self) -> Dict[str, int]:
        ev = getattr(self.store, "eviction", None)
        out = dict(ev.eviction_stats()) if ev is not None else {}
        # watermark-sweep telemetry rides the eviction family: sweeps ARE
        # out-of-band eviction epochs, just triggered by store.softWatermark
        wm = getattr(self.store, "watermark_stats", None)
        if wm is not None:
            out.update(wm())
        return out

    def _reactor_view(self) -> Dict[str, int]:
        srv = self.server
        reactor = getattr(srv, "_reactor", None) if srv is not None else None
        return reactor.stats() if reactor is not None else {}

    def _serve_view(self) -> Dict[str, int]:
        """``serve`` metrics family: popularity-tracker counters, serve-cache
        counters, and the live widened-advertisement gauge.  Empty when the
        tier is fully off."""
        out: Dict[str, int] = {}
        if self.popularity is not None:
            out.update(self.popularity.snapshot())
        cache = getattr(self.store, "serve_cache", None)
        if cache is not None:
            out.update(cache.snapshot())
        if self.popularity is not None:
            with self._tag_lock:
                out["advertised_hot_shuffles"] = len(self._hot_shuffles)
        return out

    def _pull(self, executor_id: ExecutorId, am_id: AmId, timeout: float = 5.0) -> bytes:
        """Blocking pull RPC on the peer plane (TRACE_PULL / METRICS_PULL):
        send the tagged request, pump progress() until the tagged reply parks
        and drains — the same explicit-poll contract every fetch follows."""
        with self._tag_lock:
            tag = self._next_tag
            self._next_tag += 1
            pending = self._pull_pending[tag] = {"done": threading.Event(), "body": b""}
        try:
            conn = self._connection(executor_id)
            conn.send(pack_frame(am_id, _TAG.pack(tag)))
            deadline = time.monotonic() + timeout
            while not pending["done"].is_set():
                if time.monotonic() > deadline:
                    raise TransportError(
                        f"{am_id.name} from executor {executor_id} timed out "
                        f"after {timeout:.1f}s"
                    )
                self.progress()
                self.wait_for_activity(0.005)
            return pending["body"]
        finally:
            with self._tag_lock:
                self._pull_pending.pop(tag, None)

    def pull_trace(self, executor_id: ExecutorId, timeout: float = 5.0) -> dict:
        """Fetch a peer executor's trace buffer: ``{"executor", "events",
        "dropped"}`` (TpuShuffleCluster.export_trace merges these)."""
        body = self._pull(executor_id, AmId.TRACE_PULL, timeout=timeout)
        try:
            return json.loads(body.decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise TransportError(f"malformed TRACE_PULL reply from executor {executor_id}: {e}")

    def pull_metrics(self, executor_id: ExecutorId, timeout: float = 5.0) -> str:
        """Fetch a peer executor's Prometheus text exposition."""
        return self._pull(executor_id, AmId.METRICS_PULL, timeout=timeout).decode(
            errors="replace"
        )

    def _hot_set_view(self) -> Dict[int, List[int]]:
        """Block-server provider: snapshot of this executor's advertised
        hot-set table for HOT_SET_PULL replies."""
        with self._tag_lock:
            return {sid: list(h) for sid, h in self._hot_shuffles.items()}

    def hot_holders(self, executor_id: ExecutorId, shuffle_id: int) -> List[ExecutorId]:
        """Current holder set the primary advertises for a hot shuffle, or
        ``[]`` when nothing is advertised (cold shuffle / tier off).  Served
        from a TTL cache (``spark.shuffle.tpu.serve.holdersTtlMs``; 0 =
        re-pull every fetch) so readers learn widened sets without a
        round-trip per fetch; pull failures are non-fatal (an empty table is
        cached, and the reader just keeps fetching from the primary)."""
        if self.conf.serve_hot_threshold_fetches_per_sec <= 0:
            return []
        now = time.monotonic()
        with self._tag_lock:
            cached = self._hot_holders_cache.get(executor_id)
        ttl_s = self.conf.serve_holders_ttl_ms / 1e3
        if cached is not None and now - cached[0] < ttl_s:
            return list(cached[1].get(shuffle_id, []))
        try:
            table = unpack_hot_set(
                self._pull(executor_id, AmId.HOT_SET_PULL, timeout=1.0)
            )
        except (TransportError, OSError, struct.error):
            table = {}
        with self._tag_lock:
            self._hot_holders_cache[executor_id] = (now, table)
        return list(table.get(shuffle_id, []))

    def wait_for_activity(self, timeout: float = 0.01) -> None:
        """Park until a recv thread posts an ack (or timeout) — the wakeup-mode
        progress contract (GlobalWorkerRpcThread.scala:46-58).  No-op when
        ``use_wakeup`` is off (pure busy-spin, like UCX without wakeup)."""
        if self.conf.use_wakeup:
            self._activity.wait(timeout)
            self._activity.clear()

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> bytes:
        host, port = self.conf.listener_address
        host = host if host != "0.0.0.0" else "127.0.0.1"
        self.server = BlockServer(
            self.conf, store=self.store, registry_lookup=self.registered_block,
            host=host, port=port, member_sink=self._on_member_event,
            tenants=getattr(self.store, "tenants", None),
            executor_id=self.executor_id, metrics=self.metrics,
            popularity=self.popularity, hot_sink=self._on_hot_transition,
            hot_set_provider=self._hot_set_view,
        )
        if self.conf.obs_metrics_port > 0:
            try:
                self._metrics_http = start_http_server(
                    self.metrics, self.conf.obs_metrics_port
                )
            except OSError:
                # loopback clusters build one transport per virtual executor
                # on one host: first bind wins the scrape port, the rest skip
                self._metrics_http = None
        return self.server.address_bytes()

    def close(self) -> None:
        self.recorder.close()  # unhook TransportError capture before teardown
        if self._metrics_http is not None:
            close_http_server(self._metrics_http)
            self._metrics_http = None
        with self._tag_lock:
            self._replica_run = False
            self._replica_queue.clear()
        self._replica_wake.set()
        if self.stats_agg is not None:
            for s in self.wire_lane_stats():
                self.stats_agg.record_counters(
                    "wire",
                    rx_bytes=s["rx_bytes"],
                    rx_syscalls=s["rx_syscalls"],
                    rx_stall_ns=s["rx_stall_ns"],
                )
        with self._conn_lock:
            conns = list(self._conns.values()) + self._zombies
            self._conns.clear()
            self._zombies = []
        for c in conns:
            c.close()
        # snapshot + clear under the tag lock: a recv thread can still be
        # resolving an ack while we tear down (found by the lock-discipline pass)
        with self._tag_lock:
            inflight = list(self._inflight.values())
            self._inflight.clear()
            self._stripe_rx.clear()
        for reqs, _, _, _ in inflight:
            for r in reqs:
                if not r.completed():
                    r.cancel()
        if self.server is not None:
            self.server.close()
        self.store.close()

    # -- membership --------------------------------------------------------

    def add_executor(self, executor_id: ExecutorId, address: bytes) -> None:
        host, _, port = address.decode().rpartition(":")
        with self._conn_lock:
            self._conn_addrs[executor_id] = (host, int(port))

    def remove_executor(self, executor_id: ExecutorId) -> None:
        with self._conn_lock:
            self._conn_addrs.pop(executor_id, None)
            doomed = [k for k in self._conns if k[0] == executor_id]
            conns = [self._conns.pop(k) for k in doomed]
        for conn in conns:
            conn.close()

    # -- gossip-free membership observations -------------------------------
    #
    # No heartbeats: liveness is observation-driven.  A wire failure sends a
    # MEMBER_SUSPECT to every peer; an executor coming back announces itself
    # with MEMBER_REJOIN.  Both land in the local ClusterMembership when one
    # is installed (self.membership), and are silently dropped otherwise —
    # membership-unaware deployments see zero behavior change.

    def note_peer_failed(self, executor_id: ExecutorId, reason: str) -> None:
        """Report a wire failure against ``executor_id``: suspect it locally
        (debounced by ``membership.suspectAfterMs``) and, only when the
        suspicion NEWLY killed the executor, tell the other peers — re-observed
        failures of an already-dead peer must not re-broadcast every progress
        pump.  Called from the send path and progress(), NEVER from ``_evict``
        — broadcasting opens connections, and a broadcast failure must not
        recurse into eviction."""
        if self.membership is None:
            return
        if self.membership.suspect(executor_id, reason):
            self._broadcast_member_event(AmId.MEMBER_SUSPECT, executor_id)

    def announce_rejoin(self) -> None:
        """This executor is back: mark self alive and tell every peer, so the
        full mesh returns at the next shuffle's epoch check."""
        if self.membership is None:
            return
        self.membership.mark_alive(self.executor_id)
        self._broadcast_member_event(AmId.MEMBER_REJOIN, self.executor_id)

    def _broadcast_member_event(self, am_id: AmId, subject: ExecutorId) -> None:
        epoch = self.membership.epoch if self.membership is not None else 0
        frame = pack_frame(am_id, pack_member_event(epoch, subject, self.executor_id))
        with self._conn_lock:
            eids = [e for e in self._conn_addrs if e != subject]
        for eid in eids:
            try:
                self._connection(eid).send(frame)
            except (TransportError, OSError):
                pass  # best-effort: an unreachable peer learns from its own wire

    def _on_member_event(
        self, am_id: int, epoch: int, subject: ExecutorId, observer: ExecutorId
    ) -> None:
        """BlockServer sink for MEMBER_SUSPECT/MEMBER_REJOIN frames (runs on a
        server conn thread).  Rumors about ourselves are ignored — a live
        executor is the authority on its own liveness."""
        if self.membership is None or subject == self.executor_id:
            return
        if am_id == AmId.MEMBER_SUSPECT:
            self.membership.suspect(
                subject, f"peer {observer} reported a wire failure (epoch {epoch})"
            )
        elif am_id == AmId.MEMBER_REJOIN:
            self.membership.mark_alive(subject)

    def _slot(self) -> int:
        # Round-robin threads onto worker slots via a thread-local (raw thread
        # idents are pointer-aligned, so ident % n would collapse onto slot 0).
        slot = getattr(self._slot_local, "slot", None)
        if slot is None:
            with self._tag_lock:
                slot = self._slot_rr % max(1, self.conf.num_client_workers)
                self._slot_rr += 1
            self._slot_local.slot = slot
        return slot

    def pre_connect(self) -> None:
        """Eager connection establishment (UcxExecutorRpcEndpoint.scala:19-39)."""
        with self._conn_lock:
            missing = [e for e in self._conn_addrs if (e, self._slot()) not in self._conns]
        for eid in missing:
            self._connection(eid)

    def _connection(self, executor_id: ExecutorId) -> _PeerConnection:
        # Two racing threads must not both build a connection for one key (the
        # loser's socket would be orphaned from the cache and progress() would
        # never drain its acks) — but the blocking TCP connect must NOT happen
        # under the global lock, or one unreachable peer stalls every healthy
        # fetch for the connect timeout.  A per-key pending event gates racers
        # while the winner connects outside the lock.
        key = (executor_id, self._slot())
        while True:
            with self._conn_lock:
                conn = self._conns.get(key)
                if conn is not None and conn.alive:
                    return conn
                pending = self._connecting.get(key)
                if pending is None:
                    addr = self._conn_addrs.get(executor_id)
                    if addr is None:
                        raise TransportError(f"unknown executor {executor_id}")
                    if conn is not None:  # dead cached conn: release its fd
                        del self._conns[key]
                        conn.close()
                    pending = threading.Event()
                    self._connecting[key] = pending
                    break
            pending.wait(timeout=60)
        try:
            conn = self._open_connection(addr)
        except OSError:
            with self._conn_lock:
                self._connecting.pop(key, None)
            pending.set()
            raise
        with self._conn_lock:
            self._conns[key] = conn
            self._connecting.pop(key, None)
        pending.set()
        return conn

    def _open_connection(self, addr: Tuple[str, int]) -> Union[_PeerConnection, _StripeGroup]:
        """One lane (wire.streams = 1, the byte-identical historical wire) or
        a K-lane stripe group announced to the server via WIRE_HELLO.

        With ``compress.codec`` on, even ``wire.streams = 1`` uses the stripe
        (chunked-reply) path as a single-lane group: the codec ext rides
        chunk headers, so compressed replies need per-chunk framing — and the
        monolithic single-lane reply stays byte-identical to its golden
        capture, pinned at codec=off only."""
        streams = max(1, self.conf.wire_streams)
        if streams == 1 and self.conf.wire_compress_codec == "off":
            return _PeerConnection(
                addr,
                ack_buffers=self._ack_buffers,
                ack_done=self._ack_buffers_done,
                activity=self._activity,
                conf=self.conf,
            )
        group_id = int.from_bytes(os.urandom(8), "little")
        lanes: List[_PeerConnection] = []
        try:
            for lane in range(streams):
                c = _PeerConnection(
                    addr,
                    activity=self._activity,
                    conf=self.conf,
                    lane=lane,
                    chunk_sink=self._chunk_buffers,
                    chunk_done=self._chunk_done,
                    manifest_sink=self._on_manifest,
                )
                lanes.append(c)
                c.send(
                    pack_frame(
                        AmId.WIRE_HELLO,
                        pack_wire_hello(group_id, lane, streams, self.conf.wire_chunk_bytes),
                    )
                )
        except OSError:
            for c in lanes:
                c.close()
            raise
        return _StripeGroup(group_id, lanes)

    # -- server side -------------------------------------------------------

    def register(self, block_id: BlockId, block: Block) -> None:
        with self._registry_lock:
            self._registry[block_id] = block

    def mutate(self, block_id: BlockId, block: Block, callback: Optional[OperationCallback]) -> None:
        with self._registry_lock:
            self._registry[block_id] = block
        if callback is not None:
            callback(OperationResult(OperationStatus.SUCCESS))

    def unregister(self, block_id: BlockId) -> None:
        with self._registry_lock:
            block = self._registry.pop(block_id, None)
        if block is not None:
            block.close()  # release serving resources (cached mmaps) eagerly

    def unregister_shuffle(self, shuffle_id: int) -> None:
        with self._registry_lock:
            doomed = [
                b for b in self._registry
                if isinstance(b, ShuffleBlockId) and b.shuffle_id == shuffle_id
            ]
            blocks = [self._registry.pop(b) for b in doomed]
        for block in blocks:
            block.close()
        if self.server is not None:
            # no tier may serve a stale hit after removal: the decoded-block
            # ServeCache drops via store.remove_shuffle below, the encoded-
            # chunk pool must drop here (same shuffle-id immutability scope)
            self.server.drop_shuffle_chunks(shuffle_id)
        self.store.remove_shuffle(shuffle_id)

    def registered_block(self, block_id: BlockId) -> Optional[Block]:
        with self._registry_lock:
            return self._registry.get(block_id)

    # -- client side -------------------------------------------------------

    def fetch_blocks_by_block_ids(
        self,
        executor_id: ExecutorId,
        block_ids: Sequence[BlockId],
        result_buffers: Sequence[MemoryBlock],
        callbacks: Sequence[Optional[OperationCallback]],
    ) -> List[Request]:
        if not (len(block_ids) == len(result_buffers) == len(callbacks)):
            raise ValueError("length mismatch")
        for b in block_ids:
            if not isinstance(b, ShuffleBlockId):
                raise TransportError(f"PeerTransport fetches ShuffleBlockIds, got {b!r}")
        requests = [Request(OperationStats()) for _ in block_ids]
        # window by maxBlocksPerRequest (UcxShuffleClient.scala:53-58)
        step = self.conf.max_blocks_per_request
        for w in range(0, len(block_ids), step):
            self._send_batch(
                executor_id,
                list(block_ids[w : w + step]),
                requests[w : w + step],
                list(result_buffers[w : w + step]),
                list(callbacks[w : w + step]),
            )
        return requests

    def _send_batch(self, executor_id, bids, reqs, bufs, cbs) -> None:
        with self._tag_lock:
            tag = self._next_tag
            self._next_tag += 1
            self._inflight[tag] = (reqs, bufs, cbs, None)
        conn = None
        try:
            conn = self._connection(executor_id)
            with self._tag_lock:
                if tag in self._inflight:
                    self._inflight[tag] = (reqs, bufs, cbs, conn)
                    if isinstance(conn, _StripeGroup):
                        # reply will arrive as manifest + chunks on the
                        # group's lanes: start the receive accounting now,
                        # before any chunk can race the request send
                        self._stripe_rx[tag] = _StripeRx()
            trace = None
            if self.conf.obs_trace_context and TRACER.active:
                ctx = TRACER.current_context()
                if ctx is not None:
                    trace = (ctx.trace_id, ctx.span_id)
            conn.send(
                pack_frame(
                    AmId.FETCH_BLOCK_REQ,
                    pack_batch_fetch_req(
                        tag,
                        bids,
                        app_id=self.app_id if self.conf.tenants_enabled else None,
                        trace=trace,
                    ),
                )
            )
        except (TransportError, OSError) as e:
            # endpoint failure: evict the cached connection and fail the batch —
            # the reference's error-handler drop-from-cache path
            # (UcxShuffleTransport.scala:93-103, UcxWorkerWrapper.scala:248-253),
            # distinguishing connection reset like its CONNECTION_RESET branch.
            reset = isinstance(e, (ConnectionResetError, BrokenPipeError))
            logger.warning(
                "send to executor %s failed%s: %s",
                executor_id,
                " (connection reset)" if reset else "",
                e,
            )
            self._evict(executor_id)
            self.note_peer_failed(executor_id, f"fetch send failed: {e}")
            self.record_peer_failure(executor_id, f"fetch send failed: {e}")
            with self._tag_lock:
                self._inflight.pop(tag, None)
                self._stripe_rx.pop(tag, None)
            err = e if isinstance(e, TransportError) else TransportError(str(e))
            # A send can race the recv thread tearing the socket down after a
            # typed death (ServerBusy shed, crc mismatch): the OSError here is
            # just "fd closed" — surface the recv loop's killer instead, same
            # contract as _fail_conn_inflight.
            base = getattr(conn, "last_error", None) if conn is not None else None
            if isinstance(base, (BlockCorruptError, ResourceExhaustedError)):
                err = base
            for req, buf, cb in zip(reqs, bufs, cbs):
                req.stats.mark_done()
                result = OperationResult(OperationStatus.FAILURE, error=err, stats=req.stats)
                req.complete(result)
                if cb is not None:
                    cb(result)

    def _evict(self, executor_id: ExecutorId) -> None:
        key = (executor_id, self._slot())
        with self._conn_lock:
            conn = self._conns.pop(key, None)
            if conn is not None:
                # keep the evicted conn visible to progress() until every tag
                # riding it resolves — a mid-scatter ack must still be able to
                # park and complete (or be swept once the recv thread dies)
                self._zombies.append(conn)
        if conn is not None:
            conn.close()
            # Other batches still riding this connection will never get acks —
            # fail them now rather than leaving their reducers spinning.
            self._fail_conn_inflight([conn])

    def _fail_conn_inflight(self, conns) -> None:
        # honor acks that already arrived: drain parked frames first so only
        # genuinely unanswered batches are failed
        for conn in conns:
            while True:
                frame = conn.drain_one()
                if frame is None:
                    break
                self._handle_frame(frame)
        with self._tag_lock:
            doomed = [
                (tag, entry)
                for tag, entry in self._inflight.items()
                # a tag mid-scatter is skipped: its recv thread owns the
                # buffers right now; it will either park the frame (normal
                # completion) or die, after which the next sweep collects it
                if entry[3] in conns and tag not in self._scattering
            ]
            for tag, _ in doomed:
                del self._inflight[tag]
                self._stripe_rx.pop(tag, None)
        for tag, (reqs, bufs, cbs, conn) in doomed:
            peer = getattr(conn, "peer", "?")
            logger.warning(
                "connection to peer %s lost with %d in-flight request(s)", peer, len(reqs)
            )
            # Surface the recv loop's typed killer when it carries more signal
            # than "connection lost" — a crc mismatch (BlockCorruptError) must
            # reach the reducer as corruption, and a load-shed
            # (ResourceExhaustedError) as retryable pressure, not as a
            # generic peer death.
            base = getattr(conn, "last_error", None)
            if isinstance(base, (BlockCorruptError, ResourceExhaustedError)):
                err: TransportError = base
            else:
                err = TransportError(f"peer connection lost ({peer}, fetch tag {tag})")
            for req, buf, cb in zip(reqs, bufs, cbs):
                if req.completed():
                    continue
                req.stats.mark_done()
                result = OperationResult(OperationStatus.FAILURE, error=err, stats=req.stats)
                req.complete(result)
                if cb is not None:
                    cb(result)

    def progress(self) -> None:
        """Drain parked ack frames and complete their requests — the explicit
        progress pump (ShuffleTransport.scala:158-165).  Also detects dead
        connections and fails their in-flight batches (the reference only logs
        and leaks them, UcxWorkerWrapper.scala:351-353 — we do better)."""
        with self._conn_lock:
            by_conn = [(eid, conn) for (eid, _slot), conn in self._conns.items()]
            zombies = list(self._zombies)
        conns = [conn for _eid, conn in by_conn]
        for eid, conn in by_conn + [(None, z) for z in zombies]:
            while True:
                frame = conn.drain_one()
                if frame is None:
                    break
                self._handle_frame(frame, from_executor=eid)
        dead = [c for c in conns + zombies if not c.alive]
        if dead:
            self._fail_conn_inflight(dead)
            # attribute the deaths while we still know which executor each
            # cached conn belongs to (zombies lost that mapping; the original
            # eviction already reported them)
            for eid, conn in by_conn:
                if not conn.alive:
                    why = getattr(conn, "last_error", None)
                    self.note_peer_failed(
                        eid, f"peer connection died: {why if why is not None else 'EOF'}"
                    )
                    self.record_peer_failure(
                        eid, f"peer connection died: {why if why is not None else 'EOF'}"
                    )
        if zombies:
            # retire zombies once nothing references them: no inflight tag
            # rides them and their inbox is drained
            with self._tag_lock:
                riding = {entry[3] for entry in self._inflight.values()}
            with self._conn_lock:
                self._zombies = [z for z in self._zombies if z in riding or z.inbox]

    def _handle_frame(
        self,
        frame: Tuple[AmId, bytes, bytes, bool],
        from_executor: Optional[ExecutorId] = None,
    ) -> None:
        am_id, header, body, scattered = frame
        if am_id == AmId.REPLICA_ACK:
            try:
                sid, src, _rnd = unpack_replica_ack(header)
            except struct.error:
                return
            if src == self.executor_id:
                # from_executor (when the draining path knows the conn's peer)
                # attributes the ack to its successor for replication_wait
                self._replica_acked(sid, executor_id=from_executor)
            return
        if am_id in (AmId.TRACE_PULL, AmId.METRICS_PULL, AmId.HOT_SET_PULL):
            # pull-RPC reply (obs / popularity plane): tag echo in the header,
            # JSON event buffer / Prometheus text / packed hot-set table in
            # the body
            if len(header) < _TAG.size:
                return
            (tag,) = _TAG.unpack_from(header, 0)
            with self._tag_lock:
                pending = self._pull_pending.get(tag)
            if pending is not None:
                pending["body"] = bytes(body)
                pending["done"].set()
            return
        if am_id != AmId.FETCH_BLOCK_REQ_ACK:
            return
        if len(header) < _TAG.size + _COUNT.size:
            return  # not even a tag to resolve; the recv loop killed the conn
        (tag,) = _TAG.unpack_from(header, 0)
        (count,) = _COUNT.unpack_from(header, _TAG.size)
        with self._tag_lock:
            entry = self._inflight.pop(tag, None)
            # normally already gone for striped tags; covers the server's
            # unstriped-fallback reply and malformed manifests
            self._stripe_rx.pop(tag, None)
        if entry is None:
            return
        reqs, bufs, cbs, _conn = entry
        # validate BEFORE unpacking: a truncated header must fail the batch,
        # not raise struct.error out of progress() with the entry already popped
        truncated = len(header) < _TAG.size + _COUNT.size + count * _SIZE.size
        sizes = (
            []
            if truncated
            else [
                _SIZE.unpack_from(header, _TAG.size + _COUNT.size + i * _SIZE.size)[0]
                for i in range(count)
            ]
        )
        # Scattered acks (explicit flag from the recv thread): the payload
        # already sits in the result buffers; only completion remains here.
        pre_filled = scattered
        # A peer whose size list disagrees with the frame body (or with the
        # batch size) produced an ack we cannot slice safely: fail the whole
        # batch with FAILURE results instead of raising mid-loop out of
        # progress() and leaving the rest of the batch incomplete.
        malformed = (
            truncated
            or count != len(reqs)
            or (not pre_filled and sum(s for s in sizes if s > 0) != len(body))
        )
        if malformed:
            err = TransportError(
                f"malformed fetch ack: {count} sizes summing to "
                f"{sum(s for s in sizes if s > 0)} B for a {len(reqs)}-request "
                f"batch with a {len(body)} B body"
            )
            for req, cb in zip(reqs, cbs):
                if req.completed():
                    continue
                req.stats.mark_done()
                result = OperationResult(OperationStatus.FAILURE, error=err, stats=req.stats)
                req.complete(result)
                if cb is not None:
                    cb(result)
            return
        pos = 0
        for i, (req, buf, cb) in enumerate(zip(reqs, bufs, cbs)):
            size = sizes[i]
            if size < 0:
                req.stats.mark_done()
                peer = getattr(_conn, "peer", "?")
                if size == SIZE_UNKNOWN_TENANT:
                    err: TransportError = UnknownTenantError(
                        self.app_id or "?",
                        f"peer {peer} rejected the fetch: tenant not registered there",
                    )
                elif size == SIZE_QUOTA_EXCEEDED:
                    err = TenantQuotaExceededError(
                        self.app_id or "?",
                        -1,
                        detail=f"peer {peer} could not stage the block within quota",
                    )
                elif size == SIZE_RESOURCE_EXHAUSTED:
                    # gray-failure arm: the peer is under memory pressure —
                    # typed retryable, readers back off and retry (same or a
                    # replica holder) instead of failing the job
                    err = ResourceExhaustedError(
                        detail=f"peer {peer} is under memory pressure serving this block"
                    )
                else:
                    err = TransportError("block not found on peer")
                result = OperationResult(
                    OperationStatus.FAILURE, error=err, stats=req.stats
                )
            else:
                view = buf.host_view()
                if size > view.size:
                    pos += size
                    req.stats.mark_done()
                    result = OperationResult(
                        OperationStatus.FAILURE,
                        error=TransportError(
                            f"block ({size} B) exceeds result buffer ({view.size} B)"
                        ),
                        stats=req.stats,
                    )
                else:
                    if not pre_filled:
                        view[:size] = np.frombuffer(body[pos : pos + size], dtype=np.uint8)
                        pos += size
                    buf.size = size
                    req.stats.mark_done(recv_size=size)
                    if from_executor is not None:
                        # health scoring: a completed fetch is this peer's
                        # success sample (latency folds into the EWMA)
                        self.record_peer_success(from_executor, req.stats.elapsed_ns())
                    result = OperationResult(OperationStatus.SUCCESS, stats=req.stats, data=buf)
                    if self.stats_agg is not None:
                        self.stats_agg.record("fetch", req.stats)
            req.complete(result)
            if cb is not None:
                cb(result)

    # -- staged-store extensions ------------------------------------------

    def init_executor(self, num_mappers: int, num_reducers: int) -> None:
        """Handshake with every known peer (InitExecutorReq/Ack,
        UcxWorkerWrapper.scala:286-322).  Blocks until acked like the reference."""
        with self._conn_lock:
            eids = list(self._conn_addrs)
        for eid in eids:
            conn = self._connection(eid)
            conn.send(
                pack_frame(
                    AmId.INIT_EXECUTOR_REQ,
                    _TAG.pack(self.executor_id),
                    f"{num_mappers}x{num_reducers}".encode(),
                )
            )
            # spin for the ack (the reference blocks at :320)
            import time as _time

            deadline = _time.monotonic() + 10
            acked = False
            while _time.monotonic() < deadline and not acked:
                frame = conn.drain_one()
                if frame is None:
                    _time.sleep(0.001)
                    continue
                if frame[0] == AmId.INIT_EXECUTOR_ACK:
                    acked = True
                else:
                    self._handle_frame(frame)
            if not acked:
                raise TransportError(f"InitExecutorAck timeout from executor {eid}")

    def commit_block(self, mapper_info_blob: bytes, callback: Optional[OperationCallback] = None) -> None:
        """Broadcast MapperInfo to all peers (AM id 2 — the reference sends to its
        local DPU; here every peer's server learns the commit)."""
        MapperInfo.unpack(mapper_info_blob)  # validate
        with self._conn_lock:
            eids = list(self._conn_addrs)
        for eid in eids:
            try:
                self._connection(eid).send(pack_frame(AmId.MAPPER_INFO, b"", mapper_info_blob))
            except (TransportError, OSError):
                pass
        if callback is not None:
            callback(OperationResult(OperationStatus.SUCCESS))

    # -- asynchronous neighbor replication --------------------------------

    def replication_neighbors(self) -> List[ExecutorId]:
        """The ``replication_factor`` ring successors of this executor among
        the known cluster members (self + every added peer), sorted-id ring —
        the redistribution-plan placement of arXiv:2112.01075 degenerated to
        nearest ICI neighbors."""
        from sparkucx_tpu.shuffle.resolver import ring_neighbors

        with self._conn_lock:
            peers = list(self._conn_addrs)
        return ring_neighbors(
            self.executor_id, [self.executor_id] + peers, self.conf.replication_factor
        )

    def _on_store_seal(self, shuffle_id: int) -> None:
        """Store seal hook: enqueue the shuffle for the single replicator
        worker (never blocks the sealing caller; the map-side superstep
        proceeds immediately).

        The queue is bounded by ``replication.maxBacklogBytes``: when the live
        backlog gauge is over the cap, the OLDEST still-queued shuffle is
        dropped (its rounds counted in ``dropped_rounds``) rather than letting
        a slow successor grow the backlog without bound.  Dropping replicas is
        safe — replication is best-effort durability, and a shuffle whose
        replicas were dropped simply becomes unrecoverable if its primary
        later dies (the degraded-recovery path reports exactly that)."""
        if self.conf.replication_factor <= 0:
            return
        with self._tag_lock:
            cap = self.conf.replication_max_backlog_bytes
            if (
                cap
                and self.replica_stats["replica_backlog_bytes"] > cap
                and self._replica_queue
            ):
                dropped, _ = self._replica_queue.popleft()
                self._replica_pushing.discard(dropped)
                try:
                    self.replica_stats["dropped_rounds"] += self.store.num_rounds(dropped)
                except TransportError:
                    self.replica_stats["dropped_rounds"] += 1
                logger.warning(
                    "replica backlog over %d B: dropped queued shuffle %d",
                    cap, dropped,
                )
            self._enqueue_replica_job_locked(shuffle_id, None)
        self._replica_wake.set()

    def _enqueue_replica_job_locked(
        self, shuffle_id: int, neighbors: Optional[List[ExecutorId]]
    ) -> None:
        """Queue one replication job (caller holds ``_tag_lock``; caller sets
        ``_replica_wake`` after releasing it).  ``neighbors=None`` = the ring
        successors resolved at push time; a list = a popularity widen job."""
        self._replica_pushing.add(shuffle_id)
        self._replica_queue.append((shuffle_id, neighbors))
        worker = self._replica_worker
        if worker is None or not worker.is_alive():
            worker = threading.Thread(
                target=self._replica_loop,
                daemon=True,
                name=f"replicator-{self.executor_id}",
            )
            self._replica_worker = worker
            worker.start()

    def _on_hot_transition(self, shuffle_id: int, hot: bool) -> None:
        """Block-server hot sink (runs on a serve thread, must stay cheap).

        Promote: widen the shuffle's replica set to ``serve.hotReplicas``
        ring successors by queuing a push to the holders BEYOND the seal-time
        ``replication.factor`` set (those already hold the rounds), and
        advertise the full holder list through HOT_SET_PULL so readers
        spread their fetches.  Demote: drop the advertisement — readers fall
        back to the primary; the pushed copies stay (never below the
        fault-tolerance floor, and a re-promotion reuses them for free)."""
        if not hot:
            with self._tag_lock:
                self._hot_shuffles.pop(shuffle_id, None)
            return
        from sparkucx_tpu.shuffle.resolver import widened_ring_neighbors

        with self._conn_lock:
            peers = list(self._conn_addrs)
        members = [self.executor_id] + peers
        base, extra = widened_ring_neighbors(
            self.executor_id,
            members,
            self.conf.replication_factor,
            self.conf.serve_hot_replicas,
        )
        with self._tag_lock:
            self._hot_shuffles[shuffle_id] = sorted(
                {self.executor_id, *base, *extra}
            )
            if extra:
                self._enqueue_replica_job_locked(shuffle_id, extra)
        if extra:
            self._replica_wake.set()

    def _replica_loop(self) -> None:
        """Single replicator worker: drains the seal queue one shuffle at a
        time, so replica pushes never fan out into thread-per-seal."""
        while True:
            with self._tag_lock:
                if not self._replica_run:
                    return
                job = self._replica_queue.popleft() if self._replica_queue else None
            if job is None:
                if not self._replica_wake.wait(timeout=0.2):
                    with self._tag_lock:
                        # idle and nothing queued: retire; the next seal respawns
                        if not self._replica_queue:
                            self._replica_worker = None
                            return
                self._replica_wake.clear()
                continue
            self._replicate_push(*job)

    def _replicate_push(
        self, shuffle_id: int, neighbors: Optional[List[ExecutorId]] = None
    ) -> None:
        """Push one shuffle's sealed rounds to ``neighbors`` (None = the
        ring's ``replication.factor`` successors; an explicit list = a
        popularity widen job targeting only the extra holders)."""
        try:
            faults.check("replica.push", shuffle_id=shuffle_id, executor=self.executor_id)
            if neighbors is None:
                neighbors = self.replication_neighbors()
            rounds = self.store.replica_source(shuffle_id) if neighbors else []
            round_bytes = sum(len(body) for _, _, body in rounds)
            with self._tag_lock:
                self._replica_pending[shuffle_id] = (
                    self._replica_pending.get(shuffle_id, 0) + len(neighbors) * len(rounds)
                )
                unacked = self._replica_unacked.setdefault(shuffle_id, {})
                for eid in neighbors:
                    unacked[eid] = unacked.get(eid, 0) + len(rounds)
                self.replica_stats["replica_backlog_bytes"] += round_bytes * len(neighbors)
            checksum = self.conf.wire_checksum
            cspec = CompressSpec.from_conf(self.conf)
            trace_on = self.conf.obs_trace_context and TRACER.active
            for eid in neighbors:
                for rnd, entries, body in rounds:
                    header = pack_replica_put(shuffle_id, self.executor_id, rnd, entries)
                    wire_body = body
                    if cspec.enabled:
                        # whole-round page encode; the codec ext rides after
                        # the entry table, before the crc trailer (residues
                        # 8/12, core/definitions.py)
                        cid, enc = encode_chunk(cspec, body)
                        if enc is not None:
                            wire_body = enc
                        header += pack_chunk_codec_ext(cid, len(body))
                    if checksum:
                        # self-describing: receivers detect the crc tail by
                        # header length (knob off = golden replica frames);
                        # the crc covers the WIRE (possibly encoded) body
                        header += _CRC.pack(crc32c(wire_body))
                    span_ctx = None
                    if trace_on:
                        # trace ext rides LAST (after crc): the receiver
                        # strips it before the crc/codec residue dispatch
                        with TRACER.executor_scope(self.executor_id):
                            span_ctx = TRACER.start_span(
                                "replica.push",
                                shuffle_id=shuffle_id,
                                round=rnd,
                                dst=eid,
                            )
                        header += pack_replica_trace_ext(
                            span_ctx.trace_id, span_ctx.span_id
                        )
                    frame = pack_frame(AmId.REPLICA_PUT, header, wire_body)
                    try:
                        self._connection(eid).send(frame)
                        with self._tag_lock:
                            self.replica_stats["pushed_rounds"] += 1
                            self.replica_stats["pushed_bytes"] += len(body)
                    except (TransportError, OSError) as e:
                        logger.warning(
                            "replication of shuffle %d round %d to executor %s failed: %s",
                            shuffle_id, rnd, eid, e,
                        )
                        self._replica_acked(shuffle_id, failed=True, executor_id=eid)
                    finally:
                        if span_ctx is not None:
                            with TRACER.executor_scope(self.executor_id):
                                TRACER.end_span(span_ctx)
                        with self._tag_lock:
                            self.replica_stats["replica_backlog_bytes"] = max(
                                0, self.replica_stats["replica_backlog_bytes"] - len(body)
                            )
        except Exception:
            logger.exception("replicator for shuffle %d died", shuffle_id)
        finally:
            with self._tag_lock:
                # a widen job can queue behind the seal push for the same
                # shuffle: the pushing flag (replication_wait's gate) must
                # survive until the LAST queued job for the shuffle drains
                if all(s != shuffle_id for s, _ in self._replica_queue):
                    self._replica_pushing.discard(shuffle_id)
            self._activity.set()

    def _replica_acked(
        self,
        shuffle_id: int,
        failed: bool = False,
        executor_id: Optional[ExecutorId] = None,
    ) -> None:
        with self._tag_lock:
            left = self._replica_pending.get(shuffle_id, 0) - 1
            self._replica_pending[shuffle_id] = max(0, left)
            self.replica_stats["failed" if failed else "acks"] += 1
            unacked = self._replica_unacked.get(shuffle_id)
            if unacked:
                if executor_id is None:
                    # ack arrived on a path that lost its origin (zombie conn):
                    # settle any outstanding successor so totals still converge
                    executor_id = next(
                        (e for e, c in unacked.items() if c > 0), None
                    )
                if executor_id is not None and unacked.get(executor_id, 0) > 0:
                    unacked[executor_id] -= 1

    def replication_wait(
        self, shuffle_id: int, timeout: float = 10.0, strict: bool = False
    ) -> bool:
        """Pump progress until every replica push for ``shuffle_id`` is acked
        (or failed-and-accounted).  True = replication settled.  Tests and
        graceful shutdown use this; the data path never has to.

        ``strict`` turns a timeout into a ``TransportError`` naming the
        successor executor(s) whose acks never came — the operator-facing
        answer to "which neighbor is stalling my replication?"."""
        deadline = time.monotonic() + timeout
        while True:
            with self._tag_lock:
                settled = (
                    shuffle_id not in self._replica_pushing
                    and self._replica_pending.get(shuffle_id, 0) == 0
                )
            if settled:
                return True
            if time.monotonic() > deadline:
                if strict:
                    with self._tag_lock:
                        stalled = sorted(
                            e
                            for e, c in self._replica_unacked.get(shuffle_id, {}).items()
                            if c > 0
                        )
                    raise TransportError(
                        f"replication of shuffle {shuffle_id} did not settle in "
                        f"{timeout:.1f}s: successor executor(s) {stalled} have "
                        f"unacknowledged replica rounds"
                    )
                return False
            self.progress()
            self.wait_for_activity(0.005)

    def fetch_block(
        self,
        executor_id: ExecutorId,
        shuffle_id: int,
        map_id: int,
        reduce_id: int,
        result_buffer: MemoryBlock,
        callback: Optional[OperationCallback] = None,
    ) -> Request:
        [req] = self.fetch_blocks_by_block_ids(
            executor_id,
            [ShuffleBlockId(shuffle_id, map_id, reduce_id)],
            [result_buffer],
            [callback],
        )
        return req
