"""In-process loopback transport (L3a) — the testing backend.

The reference's ``ShuffleTransport`` trait was explicitly designed to admit a
standalone/test implementation (ShuffleTransport.scala:124-128) but the repo never
shipped one (SURVEY.md section 4: no unit tests).  This loopback transport is that
missing piece: a fully in-process implementation of the trait, including the fork's
staged-store extensions, so every layer above L3 is unit-testable without TPU
hardware or sockets.

Fidelity notes:

* Block registry is a concurrent dict keyed by BlockId — the reference's ``TrieMap``
  registry (UcxShuffleTransport.scala:88, register/unregister/unregisterShuffle
  :229-269).
* Fetches are *deferred*: they complete only under ``progress()``, reproducing the
  reference's explicit-poll contract (ShuffleTransport.scala:158-165) so tests
  exercise the same spin loops the real reader uses
  (UcxShuffleReader.scala:116-134).
* Executor addressing: peers are other ``LoopbackTransport`` instances registered in
  a shared in-process "fabric" dict, standing in for the socket-address endpoint
  cache (UcxWorkerWrapper.scala:64,233-276).
* Staged-store extensions (init_executor/commit_block/fetch_block) are backed by a
  plain in-memory store keyed by (shuffle, map, reduce) with a MapperInfo-driven
  offset table — the NvkvHandler offset-table semantics (NvkvHandler.scala:258-265)
  without a device.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import Block, BlockId, MemoryBlock, ShuffleBlockId
from sparkucx_tpu.core.operation import (
    OperationCallback,
    OperationResult,
    OperationStats,
    OperationStatus,
    Request,
    TransportError,
)
from sparkucx_tpu.core.transport import ExecutorId, ShuffleTransport


class LoopbackFabric:
    """Shared address space connecting LoopbackTransports (the test 'wire')."""

    def __init__(self) -> None:
        self._members: Dict[ExecutorId, "LoopbackTransport"] = {}
        self._lock = threading.Lock()

    def attach(self, executor_id: ExecutorId, transport: "LoopbackTransport") -> bytes:
        with self._lock:
            self._members[executor_id] = transport
        return f"loopback:{executor_id}".encode()

    def detach(self, executor_id: ExecutorId) -> None:
        with self._lock:
            self._members.pop(executor_id, None)

    def resolve(self, executor_id: ExecutorId) -> "LoopbackTransport":
        with self._lock:
            t = self._members.get(executor_id)
        if t is None:
            raise TransportError(f"no executor {executor_id} on fabric")
        return t


class LoopbackTransport(ShuffleTransport):
    """See module docstring."""

    def __init__(
        self,
        conf: Optional[TpuShuffleConf] = None,
        executor_id: ExecutorId = 0,
        fabric: Optional[LoopbackFabric] = None,
    ) -> None:
        self.conf = conf or TpuShuffleConf()
        self.executor_id = executor_id
        self.fabric = fabric or LoopbackFabric()
        self._registry: Dict[BlockId, Block] = {}
        self._registry_lock = threading.Lock()
        self._peers: Dict[ExecutorId, bytes] = {}
        # (op, request) so close() can cancel what it drops instead of orphaning
        # callers spinning in Request.wait().
        self._pending: Deque[Tuple[Callable[[], None], Request]] = deque()
        self._pending_lock = threading.Lock()
        self._initialized = False
        # staged-store state (NVKV analogue)
        self._store: Dict[Tuple[int, int, int], bytes] = {}
        self._store_lock = threading.Lock()
        self.progress_count = 0

    # -- lifecycle ---------------------------------------------------------

    def init(self) -> bytes:
        addr = self.fabric.attach(self.executor_id, self)
        self._initialized = True
        return addr

    def close(self) -> None:
        self.fabric.detach(self.executor_id)
        with self._pending_lock:
            doomed = list(self._pending)
            self._pending.clear()
        for _, req in doomed:
            req.cancel()
        self._initialized = False

    # -- membership --------------------------------------------------------

    def add_executor(self, executor_id: ExecutorId, address: bytes) -> None:
        self._peers[executor_id] = address

    def remove_executor(self, executor_id: ExecutorId) -> None:
        self._peers.pop(executor_id, None)

    # -- server side -------------------------------------------------------

    def register(self, block_id: BlockId, block: Block) -> None:
        with self._registry_lock:
            self._registry[block_id] = block

    def mutate(self, block_id: BlockId, block: Block, callback: Optional[OperationCallback]) -> None:
        with self._registry_lock:
            old = self._registry.get(block_id)
            if old is not None:
                with old.lock:
                    self._registry[block_id] = block
            else:
                self._registry[block_id] = block
        if callback is not None:
            callback(OperationResult(OperationStatus.SUCCESS))

    def unregister(self, block_id: BlockId) -> None:
        with self._registry_lock:
            self._registry.pop(block_id, None)

    def unregister_shuffle(self, shuffle_id: int) -> None:
        with self._registry_lock:
            doomed = [
                b
                for b in self._registry
                if isinstance(b, ShuffleBlockId) and b.shuffle_id == shuffle_id
            ]
            for b in doomed:
                del self._registry[b]
        with self._store_lock:
            for k in [k for k in self._store if k[0] == shuffle_id]:
                del self._store[k]

    def staged_payload(self, shuffle_id: int, map_id: int, reduce_id: int):
        """Peer-visible read of one staged block (the loopback analogue of a
        served FetchBlockReq); returns None when the block is absent."""
        with self._store_lock:
            return self._store.get((shuffle_id, map_id, reduce_id))

    def registered_block(self, block_id: BlockId) -> Optional[Block]:
        with self._registry_lock:
            return self._registry.get(block_id)

    # -- client side -------------------------------------------------------

    def fetch_blocks_by_block_ids(
        self,
        executor_id: ExecutorId,
        block_ids: Sequence[BlockId],
        result_buffers: Sequence[MemoryBlock],
        callbacks: Sequence[Optional[OperationCallback]],
    ) -> List[Request]:
        if len(block_ids) != len(result_buffers) or len(block_ids) != len(callbacks):
            raise ValueError("block_ids / result_buffers / callbacks length mismatch")
        requests: List[Request] = []
        for bid, buf, cb in zip(block_ids, result_buffers, callbacks):
            req = Request(OperationStats())
            requests.append(req)
            self._enqueue(lambda b=bid, o=buf, c=cb, r=req, e=executor_id: self._serve(e, b, o, c, r), req)
        return requests

    def _serve(
        self,
        executor_id: ExecutorId,
        block_id: BlockId,
        out: MemoryBlock,
        callback: Optional[OperationCallback],
        req: Request,
    ) -> None:
        try:
            peer = self.fabric.resolve(executor_id)
            block = peer.registered_block(block_id)
            if block is None:
                raise TransportError(f"block {block_id} not registered on executor {executor_id}")
            with block.lock:  # size + copy under one lock: mutate() can swap the payload
                nbytes = block.get_size()
                if nbytes > out.host_view().size:
                    raise TransportError(
                        f"block {block_id} ({nbytes} B) exceeds result buffer ({out.host_view().size} B)"
                    )
                block.get_block(out.host_view())
            out.size = nbytes  # shrink to received length (peer/tpu contract)
            req.stats.mark_done(recv_size=nbytes)
            result = OperationResult(OperationStatus.SUCCESS, stats=req.stats, data=out)
        except Exception as e:  # any serve failure must complete the request
            req.stats.mark_done()
            err = e if isinstance(e, TransportError) else TransportError(str(e))
            result = OperationResult(OperationStatus.FAILURE, error=err, stats=req.stats)
        req.complete(result)
        if callback is not None:
            callback(result)

    def progress(self) -> None:
        """Drain one pending op per call — fetches never complete without progress
        (the trait's contract, ShuffleTransport.scala:158-165)."""
        self.progress_count += 1
        with self._pending_lock:
            entry = self._pending.popleft() if self._pending else None
        if entry is not None:
            entry[0]()

    def _enqueue(self, op: Callable[[], None], req: Request) -> None:
        with self._pending_lock:
            self._pending.append((op, req))

    # -- staged-store extensions ------------------------------------------

    def init_executor(self, num_mappers: int, num_reducers: int) -> None:
        self.num_mappers = num_mappers
        self.num_reducers = num_reducers

    def store_write(self, shuffle_id: int, map_id: int, reduce_id: int, payload: bytes) -> None:
        """Direct write into the in-memory staged store (test convenience)."""
        with self._store_lock:
            self._store[(shuffle_id, map_id, reduce_id)] = bytes(payload)

    def commit_block(self, mapper_info_blob: bytes, callback: Optional[OperationCallback] = None) -> None:
        from sparkucx_tpu.core.definitions import MapperInfo

        MapperInfo.unpack(mapper_info_blob)  # validate the wire format
        if callback is not None:
            callback(OperationResult(OperationStatus.SUCCESS))

    def fetch_block(
        self,
        executor_id: ExecutorId,
        shuffle_id: int,
        map_id: int,
        reduce_id: int,
        result_buffer: MemoryBlock,
        callback: Optional[OperationCallback] = None,
    ) -> Request:
        req = Request(OperationStats())

        def serve() -> None:
            try:
                peer = self.fabric.resolve(executor_id)
                payload = peer.staged_payload(shuffle_id, map_id, reduce_id)
                if payload is None:
                    raise TransportError(
                        f"no staged block ({shuffle_id},{map_id},{reduce_id}) on executor {executor_id}"
                    )
                view = result_buffer.host_view()
                if len(payload) > view.size:
                    raise TransportError(
                        f"staged block ({len(payload)} B) exceeds result buffer ({view.size} B)"
                    )
                view[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
                result_buffer.size = len(payload)
                req.stats.mark_done(recv_size=len(payload))
                result = OperationResult(OperationStatus.SUCCESS, stats=req.stats, data=result_buffer)
            except Exception as e:  # any serve failure must complete the request
                req.stats.mark_done()
                err = e if isinstance(e, TransportError) else TransportError(str(e))
                result = OperationResult(OperationStatus.FAILURE, error=err, stats=req.stats)
            req.complete(result)
            if callback is not None:
                callback(result)

        self._enqueue(serve, req)
        return req
