"""Multi-controller (SPMD) shuffle executor — the multi-host data plane.

``TpuShuffleCluster`` (transport/tpu.py) drives all executors from one
controller — right for one TPU VM.  A TPU *pod* is multi-controller: one process
per host, each owning its local chips, every process executing the same program.
This module is that deployment: the counterpart of the reference's one
``UcxShuffleTransport`` per Spark executor wired together by driver RPC
(CommonUcxShuffleManager.scala:67-99), with

* the JAX coordination service as the driver (``jax.distributed.initialize`` —
  parallel/mesh.py), after which ``jax.devices()`` shows the global mesh the way
  ``IntroduceAllExecutors`` shows the executor set,
* the collective exchange compiled over the **global** mesh and executed by all
  processes in lockstep (XLA ICI/DCN collectives — the NCCL/MPI analogue),
* the peer socket plane (transport/peer.py) for what stays point-to-point:
  MapperInfo commit broadcast (AM id 2) and the per-block pull fallback
  (AM ids 3/4).

SPMD discipline: every process must call ``run_exchange`` for each shuffle in
the same order — the same contract as every collective backend (SURVEY.md
section 7 "multi-controller discipline").
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.definitions import MapperInfo
from sparkucx_tpu.core.operation import ExecutorLostError, TransportError
from sparkucx_tpu.core.transport import ExecutorId
from sparkucx_tpu.ops.exchange import (
    ExchangeSpec,
    bucket_send_rows,
    build_exchange,
    rebucket_slots,
)
from sparkucx_tpu.ops.skew import (
    chunk_size_rows,
    plan_exchange,
    quota_slot_rows,
    reassemble_round,
    slice_subround,
)
from sparkucx_tpu.store.hbm_store import HbmBlockStore, default_peer_ranges
from sparkucx_tpu.transport.peer import PeerTransport
from sparkucx_tpu.transport.pipeline import RoundPipeline
from sparkucx_tpu.utils.logging import get_logger
from sparkucx_tpu.utils.stats import StatsAggregator
from sparkucx_tpu.utils.trace import TRACER, merge_events

logger = get_logger("transport.spmd")


class SpmdShuffleExecutor:
    """One process of the multi-controller deployment."""

    def __init__(
        self,
        conf: Optional[TpuShuffleConf] = None,
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ) -> None:
        import jax
        from jax.sharding import Mesh

        from sparkucx_tpu.parallel.mesh import apply_platform_env

        apply_platform_env()
        if coordinator_address is not None:
            # Must run before anything touches the XLA backend (including
            # jax.process_count()); tolerate an already-initialized service.
            from jax._src import distributed as _dist

            if _dist.global_state.client is None:
                if (jax.config.jax_platforms or "").startswith("cpu"):
                    # CPU multi-controller (tests, dryruns) needs the gloo
                    # collectives backend picked before the client exists.
                    from sparkucx_tpu.ops._compat import enable_cpu_cross_process_collectives

                    enable_cpu_cross_process_collectives()
                jax.distributed.initialize(
                    coordinator_address, num_processes=num_processes, process_id=process_id
                )
        self.conf = conf or TpuShuffleConf()
        self.num_executors = jax.process_count()
        self.executor_id: ExecutorId = jax.process_index()

        # One mesh slot per process: its first local device (executor<->chip
        # mapping; multi-device hosts designate a lead chip for the exchange).
        per_proc: Dict[int, object] = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        self.mesh = Mesh(
            np.array([per_proc[p] for p in range(self.num_executors)]),
            (self.conf.mesh_axis_name,),
        )
        self.device = per_proc[self.executor_id]

        # The store seals onto this process's lead device, so device-staged
        # rounds (conf.device_staging) hand the exchange an HBM-resident
        # payload with no host round trip.
        self.store = HbmBlockStore(
            self.conf, device=self.device, executor_id=self.executor_id
        )
        self.peer = PeerTransport(self.conf, executor_id=self.executor_id, store=self.store)
        # Liveness view fed by the wire plane (peer send failures + gossiped
        # MEMBER_SUSPECT/MEMBER_REJOIN frames).  The SPMD exchange cannot
        # shrink unilaterally — every process executes the same compiled
        # collective — so a degraded view fails the superstep FAST with a
        # typed error instead of hanging in a collective the dead process
        # will never join.  Elastic shrink/regrow is the single-controller
        # cluster's recovery path (transport/tpu.py).
        from sparkucx_tpu.parallel.membership import ClusterMembership

        self.membership = ClusterMembership(
            range(self.num_executors), self.conf.membership_suspect_after_ms
        )
        self.peer.membership = self.membership
        self._mapper_infos: Dict[int, Dict[int, MapperInfo]] = {}
        self._recv: Dict[int, Tuple[List[np.ndarray], List[np.ndarray]]] = {}
        self._meta: Dict[int, Tuple[int, int, List[Tuple[int, int]]]] = {}
        self._exchange_fns: Dict[int, object] = {}
        #: memmap spill files per shuffle as (path, charged nbytes) —
        #: host_recv_mode='memmap'; the refund uses the tracked charge.
        #: _host_shard runs on the pipeline DRAIN worker while remove_shuffle
        #: runs on the caller thread — both sides take _spill_lock.
        self._recv_spill: Dict[int, List[Tuple[str, int]]] = {}  #: guarded by self._spill_lock
        self._recv_spill_bytes = 0  #: guarded by self._spill_lock (vs conf.spill_disk_cap_bytes)
        self._spill_lock = threading.Lock()
        #: per-stage pipeline timings (same occupancy view as the cluster's)
        self.stats = StatsAggregator()
        if self.conf.host_recv_mode not in ("array", "memmap"):
            # fail at construction, not after round 0's collective has run on
            # every host: 'device' needs retained HBM shards this executor
            # releases after the collective; anything else is a typo
            raise ValueError(
                f"host_recv_mode {self.conf.host_recv_mode!r} is not supported "
                "by the SPMD executor (array|memmap)"
            )

    # -- control plane -----------------------------------------------------

    def init(self) -> bytes:
        return self.peer.init()

    def add_executor(self, executor_id: ExecutorId, address: bytes) -> None:
        self.peer.add_executor(executor_id, address)

    def close(self) -> None:
        self.peer.close()

    # -- obs plane ---------------------------------------------------------

    def export_trace(self, path: str) -> int:
        """Merge the whole mesh's trace buffers into ONE Perfetto file with
        pid = executor id: every peer's ring is pulled over the TRACE_PULL
        Active Message, the local ring read directly.  Unreachable peers are
        skipped — a postmortem export must work on a degraded mesh."""
        buffers = [
            [dict(e, eid=e.get("eid", self.executor_id)) for e in TRACER.events]
        ]
        for eid in range(self.num_executors):
            if eid == self.executor_id:
                continue
            try:
                buf = self.peer.pull_trace(eid)
                buffers.append(
                    [dict(e, eid=e.get("eid", eid)) for e in buf.get("events", [])]
                )
            except (TransportError, OSError):
                continue
        merged = merge_events(buffers)
        import json as _json

        with open(path, "w") as f:
            _json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
        return len(merged)

    def metrics_text(self) -> str:
        """Prometheus exposition for the whole mesh: the local registry's
        text plus every reachable peer's METRICS_PULL reply, concatenated
        (rows stay distinct — each executor labels its own samples)."""
        parts = [self.peer.metrics.prometheus_text()]
        for eid in range(self.num_executors):
            if eid == self.executor_id:
                continue
            try:
                parts.append(self.peer.pull_metrics(eid))
            except (TransportError, OSError):
                continue
        return "".join(parts)

    # -- shuffle lifecycle -------------------------------------------------

    def create_shuffle(self, shuffle_id: int, num_mappers: int, num_reducers: int) -> None:
        ranges = default_peer_ranges(num_reducers, self.num_executors)
        self.store.create_shuffle(shuffle_id, num_mappers, num_reducers, peer_ranges=ranges)
        self._meta[shuffle_id] = (num_mappers, num_reducers, ranges)
        self._mapper_infos[shuffle_id] = {}

    def map_owner(self, map_id: int) -> ExecutorId:
        """Round-robin map-task placement convention (all processes agree)."""
        return map_id % self.num_executors

    def commit_map(self, writer) -> MapperInfo:
        """Commit a local map task: record locally + broadcast AM id 2."""
        info = writer.commit()
        self._mapper_infos[info.shuffle_id][info.map_id] = info
        self.peer.commit_block(info.pack())
        return info

    def _await_commits(self, shuffle_id: int, timeout: float = 60.0) -> None:
        """Wait until every map's MapperInfo arrived (local or via AM id 2)."""
        num_mappers, _, _ = self._meta[shuffle_id]
        infos = self._mapper_infos[shuffle_id]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for m in self.store.committed_map_ids(shuffle_id):
                if m not in infos:
                    # peer commit landed in the store table; reconstruct info
                    infos[m] = self.store.mapper_info(shuffle_id, m)
            if len(infos) >= num_mappers:
                return
            time.sleep(0.005)
        raise TransportError(
            f"timed out waiting for map commits ({len(infos)}/{num_mappers})"
        )

    # -- the superstep -----------------------------------------------------

    def run_exchange(self, shuffle_id: int) -> None:
        """Collective superstep — ALL processes must call this in lockstep."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        snap = self.membership.snapshot()
        if snap["dead"]:
            # fail before entering the collective: a lockstep exchange with a
            # dead process hangs every live process until the backend timeout
            first_dead = min(snap["dead"])
            raise ExecutorLostError(
                first_dead,
                snap["epoch"],
                "SPMD exchange requires every process; degraded recovery is "
                f"the single-controller cluster's path — dead: {snap['dead']}",
            )
        self._await_commits(shuffle_id)
        rounds = self.store.seal(shuffle_id)
        if self.conf.slot_quota_rows > 0:
            # Skew-aware path (ops/skew.py): quota-capped slots, hot lanes
            # chunked across extra pipelined sub-rounds.  Separate engine so
            # quota-off keeps this single-shot path byte-for-byte.
            self._run_exchange_quota(shuffle_id, rounds)
            return
        n = self.num_executors
        ax = self.conf.mesh_axis_name
        send_rows, lane = int(rounds[0][0].shape[0]), int(rounds[0][0].shape[1])
        # Capacity bucketing (same discipline as the cluster's _exchange_fn):
        # varying-size shuffles share one compiled exchange per power-of-two
        # slot bucket; payloads relocate into the bucketed slot layout below.
        bucketed = bucket_send_rows(send_rows, n)
        fn = self._exchange_fn_for(bucketed, lane)

        data_sharding = NamedSharding(self.mesh, P(ax, None))
        sizes_sharding = NamedSharding(self.mesh, P(ax, None))

        # Agree on the global round count (spill rounds may differ per host):
        # a one-int all_gather, served by the same mesh the payload uses.
        my_rounds = np.array([[len(rounds)]], dtype=np.int32)
        rc_shard = jax.device_put(my_rounds, self.device)
        rc = jax.make_array_from_single_device_arrays(
            (n, 1), sizes_sharding, [rc_shard]
        )
        num_rounds = int(np.max(jax.jit(lambda x: jnp.max(x), out_shardings=None)(rc)))

        def _submit(rnd):
            """Assemble + H2D + collective dispatch for one round (all JAX
            async dispatch — SPMD order is preserved because every process
            submits rounds in the same order, whatever the depth)."""
            if rnd < len(rounds):
                payload, sizes = rounds[rnd]
                if isinstance(payload, jax.Array):
                    # Sealed straight onto the device (device staging or the
                    # single-round host seal): relocate slots on-device, no
                    # host round trip; device_put is then a no-op pin.
                    payload = rebucket_slots(payload, n, bucketed, xp=jnp)
                else:
                    payload = rebucket_slots(np.asarray(payload), n, bucketed)
            else:
                payload = np.zeros((bucketed, lane), dtype=np.int32)
                sizes = np.zeros(n, dtype=np.int32)
            local_payload = jax.device_put(payload, self.device)
            local_sizes = jax.device_put(sizes[None, :].astype(np.int32), self.device)
            data = jax.make_array_from_single_device_arrays(
                (n * bucketed, lane), data_sharding, [local_payload]
            )
            size_mat = jax.make_array_from_single_device_arrays(
                (n, n), sizes_sharding, [local_sizes]
            )
            recv, rs = fn(data, size_mat)
            my_recv = next(
                s.data for s in recv.addressable_shards if s.device == self.device
            )
            my_rs = next(
                s.data for s in rs.addressable_shards if s.device == self.device
            )
            # start D2H of this process's shard while later rounds run
            my_recv.copy_to_host_async()
            my_rs.copy_to_host_async()
            return my_recv, my_rs

        def _drain(rnd, ticket):
            """Host-side completion: materialize this process's shard and
            apply host_recv_mode (memmap spill runs on the drain worker)."""
            my_recv, my_rs = ticket
            shard = self._host_shard(
                shuffle_id, rnd, np.asarray(my_recv).reshape(-1).view(np.uint8)
            )
            return shard, np.asarray(my_rs).reshape(-1)

        depth = max(1, int(self.conf.pipeline_depth))
        pipe = RoundPipeline(
            depth, _submit, _drain, name="exchange.pipeline", stats=self.stats,
            result_bytes=lambda r: int(r[1].sum()) * self.conf.block_alignment,
            # per-round staging occupancy of this process's shard (the slot
            # padding conf.slot_quota_rows exists to shrink)
            result_rows=lambda r: (int(r[1].sum()), bucketed - int(r[1].sum())),
        )
        results = pipe.run(num_rounds)
        recv_shards = [shard for shard, _ in results]
        recv_sizes_rows = [sizes for _, sizes in results]
        for sizes in recv_sizes_rows:
            active = int(np.count_nonzero(sizes))
            self.stats.record_rows("exchange.lanes", active, sizes.size - active)
        self._recv[shuffle_id] = (recv_shards, recv_sizes_rows)
        logger.info(
            "exchange done: shuffle=%d rounds=%d depth=%d",
            shuffle_id, num_rounds, depth,
        )

    def _exchange_fn_for(self, bucketed_rows: int, lane: int):
        """Compiled-exchange cache lookup, keyed on the bucketed slot layout.

        ``bucketed_rows`` is re-bucketed here (``bucket_send_rows`` is a fixed
        point on pow2-slot multiples, so callers that already bucketed — the
        default path's ``bucket_send_rows``, the quota path's
        ``quota_slot_rows * n`` — pass through unchanged) so a raw staging
        size can never become a compile-cache key."""
        n = self.num_executors
        bucketed_rows = bucket_send_rows(bucketed_rows, n)
        from sparkucx_tpu.ops.ici_exchange import resolve_exchange_impl

        impl = resolve_exchange_impl(
            self.conf.exchange_impl,
            self.mesh.devices.reshape(-1)[0].platform,
            n,
        )
        key = (bucketed_rows, lane, self.conf.num_slices, impl)
        fn = self._exchange_fns.get(key)
        if fn is None:
            spec = ExchangeSpec(
                num_executors=n, send_rows=bucketed_rows, recv_rows=bucketed_rows,
                lane=lane, axis_name=self.conf.mesh_axis_name,
            )
            if self.conf.num_slices > 1:
                # multi-slice multi-host: the two-phase ICI+DCN route over the
                # same global devices, slice-major (ops/hierarchy.py)
                from sparkucx_tpu.ops.hierarchy import (
                    build_hierarchical_exchange,
                    make_hierarchical_mesh,
                )

                hmesh = make_hierarchical_mesh(
                    self.conf.num_slices,
                    n // self.conf.num_slices,
                    devices=list(self.mesh.devices.reshape(-1)),
                )
                if impl == "pallas":
                    from sparkucx_tpu.ops.ici_exchange import (
                        DEFAULT_CHUNKS_PER_DEST,
                        build_ici_exchange,
                    )

                    fn = build_ici_exchange(
                        hmesh, spec.resolve_impl(),
                        chunks_per_dest=DEFAULT_CHUNKS_PER_DEST,
                    )
                else:
                    fn = build_hierarchical_exchange(hmesh, spec.resolve_impl())
            elif impl == "pallas":
                # FAST-scheduled ring exchange (ops/ici_exchange.py):
                # bit-identical, remote-DMA on TPU, scheduled permutes here
                from sparkucx_tpu.ops.ici_exchange import (
                    DEFAULT_CHUNKS_PER_DEST,
                    build_ici_exchange,
                )

                fn = build_ici_exchange(
                    self.mesh, spec, chunks_per_dest=DEFAULT_CHUNKS_PER_DEST
                )
            else:
                fn = build_exchange(self.mesh, spec)
            self._exchange_fns[key] = fn
        return fn

    def _run_exchange_quota(self, shuffle_id: int, rounds) -> None:
        """Quota-capped exchange (conf.slot_quota_rows > 0), SPMD flavor.

        Every process derives the SAME sub-round plan — the per-round hottest
        lane is all-gathered over the mesh (a tiny int collective, like the
        round-count agreement) before planning, so the collective schedule
        stays in lockstep.  The drain worker splices each staging round's
        chunks back into the exact tight sender-major shard the single-shot
        path produces (bit-equality pinned in tests/test_skew.py)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = self.num_executors
        ax = self.conf.mesh_axis_name
        send_rows, lane = int(rounds[0][0].shape[0]), int(rounds[0][0].shape[1])
        staging_slot = send_rows // n
        q = quota_slot_rows(staging_slot, self.conf.slot_quota_rows)
        bucketed = q * n
        fn = self._exchange_fn_for(bucketed, lane)

        data_sharding = NamedSharding(self.mesh, P(ax, None))
        sizes_sharding = NamedSharding(self.mesh, P(ax, None))

        # Agree on the global round count, then on each round's hottest lane
        # (max used rows over all senders/destinations): two tiny int
        # all-gathers so every process plans the identical sub-round schedule.
        my_rounds = np.array([[len(rounds)]], dtype=np.int32)
        rc = jax.make_array_from_single_device_arrays(
            (n, 1), sizes_sharding, [jax.device_put(my_rounds, self.device)]
        )
        num_rounds = int(np.max(jax.jit(lambda x: jnp.max(x), out_shardings=None)(rc)))
        local_maxes = np.zeros((1, num_rounds), dtype=np.int32)
        for rnd in range(min(len(rounds), num_rounds)):
            local_maxes[0, rnd] = int(np.max(rounds[rnd][1], initial=0))
        mx = jax.make_array_from_single_device_arrays(
            (n, num_rounds), sizes_sharding, [jax.device_put(local_maxes, self.device)]
        )
        gm = jax.jit(lambda x: jnp.max(x, axis=0), out_shardings=None)(mx)
        plan = plan_exchange(
            [int(gm[rnd]) for rnd in range(num_rounds)],
            staging_slot,
            self.conf.slot_quota_rows,
        )
        subs = plan.subrounds()

        def _submit_quota(sub_idx):
            """One sub-round's assemble + H2D + collective dispatch: slice the
            chunk window out of every peer slot (all processes submit the same
            sub-round order, whatever the depth)."""
            rnd, chunk, _ = subs[sub_idx]
            if rnd < len(rounds):
                payload, sizes = rounds[rnd]
                sub_sizes = chunk_size_rows(sizes, chunk, q)
                xp = jnp if isinstance(payload, jax.Array) else np
                piece = slice_subround(payload, n, chunk, q, xp=xp)
            else:
                piece = np.zeros((bucketed, lane), dtype=np.int32)
                sub_sizes = np.zeros(n, dtype=np.int32)
            local_payload = jax.device_put(piece, self.device)
            local_sizes = jax.device_put(
                np.reshape(sub_sizes, (1, n)).astype(np.int32), self.device
            )
            data = jax.make_array_from_single_device_arrays(
                (n * bucketed, lane), data_sharding, [local_payload]
            )
            size_mat = jax.make_array_from_single_device_arrays(
                (n, n), sizes_sharding, [local_sizes]
            )
            recv, rs = fn(data, size_mat)
            my_recv = next(
                s.data for s in recv.addressable_shards if s.device == self.device
            )
            my_rs = next(
                s.data for s in rs.addressable_shards if s.device == self.device
            )
            my_recv.copy_to_host_async()
            my_rs.copy_to_host_async()
            return my_recv, my_rs

        # this staging round's drained sub-rounds, oldest first: appended and
        # consumed ONLY by the pipeline's single in-order drain worker, so no
        # lock is needed (closure-local, single-thread access by construction)
        pending = []

        def _drain_quota(sub_idx, ticket):
            """Materialize one sub-round's shard; on a staging round's FINAL
            chunk, splice the chunks back into the single-shot layout, apply
            host_recv_mode, and emit the round's result (None otherwise)."""
            rnd, chunk, nchunks = subs[sub_idx]
            my_recv, my_rs = ticket
            pending.append(
                (
                    np.asarray(my_recv).reshape(-1).view(np.uint8),
                    np.asarray(my_rs).reshape(-1),
                )
            )
            if chunk < nchunks - 1:
                return None
            parts = list(pending)  # exactly this round's sub-rounds, in order
            pending.clear()
            sub_sizes = [s for _, s in parts]
            logical = np.sum(sub_sizes, axis=0).astype(np.int32)
            assembled = reassemble_round(
                [b for b, _ in parts], sub_sizes, self.conf.block_alignment
            )
            shard = self._host_shard(shuffle_id, rnd, assembled)
            used = int(logical.sum())
            return shard, logical, (used, nchunks * bucketed - used)

        depth = max(1, int(self.conf.pipeline_depth))
        pipe = RoundPipeline(
            depth, _submit_quota, _drain_quota, name="exchange.pipeline",
            stats=self.stats,
            result_bytes=lambda r: (
                0 if r is None else int(r[1].sum()) * self.conf.block_alignment
            ),
            result_rows=lambda r: (0, 0) if r is None else r[2],
        )
        results = [r for r in pipe.run(len(subs)) if r is not None]
        recv_shards = [shard for shard, _, _ in results]
        recv_sizes_rows = [sizes for _, sizes, _ in results]
        for sizes in recv_sizes_rows:
            active = int(np.count_nonzero(sizes))
            self.stats.record_rows("exchange.lanes", active, sizes.size - active)
        self._recv[shuffle_id] = (recv_shards, recv_sizes_rows)
        logger.info(
            "exchange done (quota): shuffle=%d rounds=%d subrounds=%d "
            "quota_slot=%d depth=%d",
            shuffle_id, num_rounds, len(subs), q, depth,
        )

    # -- post-exchange reads ----------------------------------------------

    def owner_of_reduce(self, shuffle_id: int, reduce_id: int) -> ExecutorId:
        _, _, ranges = self._meta[shuffle_id]
        for p, (s, e) in enumerate(ranges):
            if s <= reduce_id < e:
                return p
        raise ValueError(f"reduce {reduce_id} unowned")

    def read_received_block(self, shuffle_id: int, map_id: int, reduce_id: int) -> bytes:
        """Read a block this executor received in the exchange."""
        if self.owner_of_reduce(shuffle_id, reduce_id) != self.executor_id:
            raise TransportError(
                f"reducer {reduce_id} not owned by executor {self.executor_id}"
            )
        if shuffle_id not in self._recv:
            raise TransportError(f"shuffle {shuffle_id} not exchanged")
        info = self._mapper_infos[shuffle_id].get(map_id)
        if info is None:
            raise TransportError(f"map {map_id} never committed")
        abs_offset, length = info.partitions[reduce_id]
        if length == 0:
            return b""
        rnd = info.round_of(reduce_id)
        sender = self.map_owner(map_id)
        region_bytes = self.store.region_bytes(shuffle_id)
        region_rel = abs_offset - self.executor_id * region_bytes
        shards, sizes_rows = self._recv[shuffle_id]
        chunk_start = int(sizes_rows[rnd][:sender].sum()) * self.conf.block_alignment
        start = chunk_start + region_rel
        return bytes(shards[rnd][start : start + length])

    def _host_shard(self, shuffle_id: int, rnd: int, host: np.ndarray) -> np.ndarray:
        """Apply ``conf.host_recv_mode`` to one received round: 'array' keeps
        the RAM copy (historical behavior), 'memmap' spills it to a read-only
        disk mapping so per-host RSS stays bounded by one round — the same
        budget discipline as the single-controller cluster (transport/tpu.py
        ``_memmap_round``): every spilled byte reserves against
        ``spill_disk_cap_bytes`` up front and a failed write refunds and
        unlinks (mode validity is checked at construction)."""
        if self.conf.host_recv_mode == "array":
            return host
        import os
        import tempfile

        cap = self.conf.spill_disk_cap_bytes
        nbytes = int(host.nbytes)
        if nbytes == 0:
            # nothing received this round (quota-path tight shards can be
            # empty); np.memmap cannot map a zero-byte file — keep the array
            return host
        # reserve-then-write: check+charge atomic under the spill lock (the
        # drain worker charges here while remove_shuffle refunds concurrently)
        with self._spill_lock:
            if cap and self._recv_spill_bytes + nbytes > cap:
                raise TransportError(
                    f"received-shard spill would exceed spill_disk_cap_bytes "
                    f"({self._recv_spill_bytes + nbytes} > {cap}) on executor "
                    f"{self.executor_id}"
                )
            self._recv_spill_bytes += nbytes
        spill_dir = self.conf.spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        fd, path = tempfile.mkstemp(
            prefix=f"sparkucx_tpu_spmd_recv_s{shuffle_id}_r{rnd}_e{self.executor_id}_",
            dir=spill_dir,
        )
        os.close(fd)
        shape = host.shape
        try:
            mm = np.memmap(path, dtype=np.uint8, mode="w+", shape=shape)
            mm[:] = host
            mm.flush()
        except BaseException:
            with self._spill_lock:
                self._recv_spill_bytes -= nbytes
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        del mm, host  # drop the dirty mapping; reopen read-only (RSS falls)
        # track the CHARGED bytes with the path: the refund must mirror the
        # charge, not os.path.getsize (block-size rounding / sparse files /
        # truncation by an operator would drift _recv_spill_bytes permanently)
        with self._spill_lock:
            self._recv_spill.setdefault(shuffle_id, []).append((path, nbytes))
        return np.memmap(path, dtype=np.uint8, mode="r", shape=shape)

    def remove_shuffle(self, shuffle_id: int) -> None:
        self.store.remove_shuffle(shuffle_id)
        self._recv.pop(shuffle_id, None)
        self._meta.pop(shuffle_id, None)
        self._mapper_infos.pop(shuffle_id, None)
        import os

        with self._spill_lock:
            doomed = self._recv_spill.pop(shuffle_id, [])
        for path, nbytes in doomed:
            try:
                os.unlink(path)
                freed = True
            except FileNotFoundError:
                freed = True  # already gone: still refund
            except OSError:
                freed = False  # still on disk: keep it charged
            if freed:
                with self._spill_lock:
                    self._recv_spill_bytes -= nbytes
