"""Multi-controller (SPMD) shuffle executor — the multi-host data plane.

``TpuShuffleCluster`` (transport/tpu.py) drives all executors from one
controller — right for one TPU VM.  A TPU *pod* is multi-controller: one process
per host, each owning its local chips, every process executing the same program.
This module is that deployment: the counterpart of the reference's one
``UcxShuffleTransport`` per Spark executor wired together by driver RPC
(CommonUcxShuffleManager.scala:67-99), with

* the JAX coordination service as the driver (``jax.distributed.initialize`` —
  parallel/mesh.py), after which ``jax.devices()`` shows the global mesh the way
  ``IntroduceAllExecutors`` shows the executor set,
* the collective exchange compiled over the **global** mesh and executed by all
  processes in lockstep (XLA ICI/DCN collectives — the NCCL/MPI analogue),
* the peer socket plane (transport/peer.py) for what stays point-to-point:
  MapperInfo commit broadcast (AM id 2) and the per-block pull fallback
  (AM ids 3/4).

SPMD discipline: every process must call ``run_exchange`` for each shuffle in
the same order — the same contract as every collective backend (SURVEY.md
section 7 "multi-controller discipline").
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.definitions import MapperInfo
from sparkucx_tpu.core.operation import ExecutorLostError, TransportError
from sparkucx_tpu.core.transport import ExecutorId
from sparkucx_tpu.ops.exchange import bucket_send_rows
from sparkucx_tpu.ops.planner import PlanContext, PlanSignals, make_planner
from sparkucx_tpu.ops.skew import (
    chunk_size_rows,
    reassemble_round,
    slice_subround,
)
from sparkucx_tpu.store.hbm_store import HbmBlockStore, default_peer_ranges
from sparkucx_tpu.transport.executor import (
    build_plan_exchange,
    execute_plan,
    validate_host_recv_mode,
)
from sparkucx_tpu.transport.peer import PeerTransport
from sparkucx_tpu.utils.logging import get_logger
from sparkucx_tpu.utils.stats import StatsAggregator
from sparkucx_tpu.utils.trace import TRACER, instant, merge_events

logger = get_logger("transport.spmd")


class SpmdShuffleExecutor:
    """One process of the multi-controller deployment."""

    def __init__(
        self,
        conf: Optional[TpuShuffleConf] = None,
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ) -> None:
        import jax
        from jax.sharding import Mesh

        from sparkucx_tpu.parallel.mesh import apply_platform_env

        apply_platform_env()
        if coordinator_address is not None:
            # Must run before anything touches the XLA backend (including
            # jax.process_count()); tolerate an already-initialized service.
            from jax._src import distributed as _dist

            if _dist.global_state.client is None:
                if (jax.config.jax_platforms or "").startswith("cpu"):
                    # CPU multi-controller (tests, dryruns) needs the gloo
                    # collectives backend picked before the client exists.
                    from sparkucx_tpu.ops._compat import enable_cpu_cross_process_collectives

                    enable_cpu_cross_process_collectives()
                jax.distributed.initialize(
                    coordinator_address, num_processes=num_processes, process_id=process_id
                )
        self.conf = conf or TpuShuffleConf()
        self.num_executors = jax.process_count()
        self.executor_id: ExecutorId = jax.process_index()

        # One mesh slot per process: its first local device (executor<->chip
        # mapping; multi-device hosts designate a lead chip for the exchange).
        per_proc: Dict[int, object] = {}
        for d in jax.devices():
            per_proc.setdefault(d.process_index, d)
        self.mesh = Mesh(
            np.array([per_proc[p] for p in range(self.num_executors)]),
            (self.conf.mesh_axis_name,),
        )
        self.device = per_proc[self.executor_id]

        # The store seals onto this process's lead device, so device-staged
        # rounds (conf.device_staging) hand the exchange an HBM-resident
        # payload with no host round trip.
        self.store = HbmBlockStore(
            self.conf, device=self.device, executor_id=self.executor_id
        )
        self.peer = PeerTransport(self.conf, executor_id=self.executor_id, store=self.store)
        # Liveness view fed by the wire plane (peer send failures + gossiped
        # MEMBER_SUSPECT/MEMBER_REJOIN frames).  The SPMD exchange cannot
        # shrink unilaterally — every process executes the same compiled
        # collective — so a degraded view fails the superstep FAST with a
        # typed error instead of hanging in a collective the dead process
        # will never join.  Elastic shrink/regrow is the single-controller
        # cluster's recovery path (transport/tpu.py).
        from sparkucx_tpu.parallel.membership import ClusterMembership

        self.membership = ClusterMembership(
            range(self.num_executors), self.conf.membership_suspect_after_ms
        )
        self.peer.membership = self.membership
        self._mapper_infos: Dict[int, Dict[int, MapperInfo]] = {}
        self._recv: Dict[int, Tuple[List[np.ndarray], List[np.ndarray]]] = {}
        self._meta: Dict[int, Tuple[int, int, List[Tuple[int, int]]]] = {}
        self._exchange_fns: Dict[int, object] = {}
        #: memmap spill files per shuffle as (path, charged nbytes) —
        #: host_recv_mode='memmap'; the refund uses the tracked charge.
        #: _host_shard runs on the pipeline DRAIN worker while remove_shuffle
        #: runs on the caller thread — both sides take _spill_lock.
        self._recv_spill: Dict[int, List[Tuple[str, int]]] = {}  #: guarded by self._spill_lock
        self._recv_spill_bytes = 0  #: guarded by self._spill_lock (vs conf.spill_disk_cap_bytes)
        self._spill_lock = threading.Lock()
        #: per-stage pipeline timings (same occupancy view as the cluster's)
        self.stats = StatsAggregator()
        #: the exchange planner (ops/planner.py) — the collective-schedule
        #: fields of its plans derive only from all-gathered quantities, so
        #: every process stays in lockstep whatever the local telemetry says
        self.planner = make_planner(self.conf)
        # ONE host_recv_mode gate (transport/executor.py): fail at
        # construction, not after round 0's collective has run on every host
        # — 'device' needs retained HBM shards this executor releases after
        # the collective; anything else is a typo.
        validate_host_recv_mode(
            self.conf.host_recv_mode,
            allowed=("array", "memmap"),
            where="the SPMD executor",
        )

    # -- control plane -----------------------------------------------------

    def init(self) -> bytes:
        return self.peer.init()

    def add_executor(self, executor_id: ExecutorId, address: bytes) -> None:
        self.peer.add_executor(executor_id, address)

    def close(self) -> None:
        self.peer.close()

    # -- obs plane ---------------------------------------------------------

    def export_trace(self, path: str) -> int:
        """Merge the whole mesh's trace buffers into ONE Perfetto file with
        pid = executor id: every peer's ring is pulled over the TRACE_PULL
        Active Message, the local ring read directly.  Unreachable peers are
        skipped — a postmortem export must work on a degraded mesh."""
        buffers = [
            [dict(e, eid=e.get("eid", self.executor_id)) for e in TRACER.events]
        ]
        for eid in range(self.num_executors):
            if eid == self.executor_id:
                continue
            try:
                buf = self.peer.pull_trace(eid)
                buffers.append(
                    [dict(e, eid=e.get("eid", eid)) for e in buf.get("events", [])]
                )
            except (TransportError, OSError):
                continue
        merged = merge_events(buffers)
        import json as _json

        with open(path, "w") as f:
            _json.dump({"traceEvents": merged, "displayTimeUnit": "ms"}, f)
        return len(merged)

    def metrics_text(self) -> str:
        """Prometheus exposition for the whole mesh: the local registry's
        text plus every reachable peer's METRICS_PULL reply, concatenated
        (rows stay distinct — each executor labels its own samples)."""
        parts = [self.peer.metrics.prometheus_text()]
        for eid in range(self.num_executors):
            if eid == self.executor_id:
                continue
            try:
                parts.append(self.peer.pull_metrics(eid))
            except (TransportError, OSError):
                continue
        return "".join(parts)

    # -- shuffle lifecycle -------------------------------------------------

    def create_shuffle(self, shuffle_id: int, num_mappers: int, num_reducers: int) -> None:
        ranges = default_peer_ranges(num_reducers, self.num_executors)
        self.store.create_shuffle(shuffle_id, num_mappers, num_reducers, peer_ranges=ranges)
        self._meta[shuffle_id] = (num_mappers, num_reducers, ranges)
        self._mapper_infos[shuffle_id] = {}

    def map_owner(self, map_id: int) -> ExecutorId:
        """Round-robin map-task placement convention (all processes agree)."""
        return map_id % self.num_executors

    def commit_map(self, writer) -> MapperInfo:
        """Commit a local map task: record locally + broadcast AM id 2."""
        info = writer.commit()
        self._mapper_infos[info.shuffle_id][info.map_id] = info
        self.peer.commit_block(info.pack())
        return info

    def _await_commits(self, shuffle_id: int, timeout: float = 60.0) -> None:
        """Wait until every map's MapperInfo arrived (local or via AM id 2)."""
        num_mappers, _, _ = self._meta[shuffle_id]
        infos = self._mapper_infos[shuffle_id]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for m in self.store.committed_map_ids(shuffle_id):
                if m not in infos:
                    # peer commit landed in the store table; reconstruct info
                    infos[m] = self.store.mapper_info(shuffle_id, m)
            if len(infos) >= num_mappers:
                return
            time.sleep(0.005)
        raise TransportError(
            f"timed out waiting for map commits ({len(infos)}/{num_mappers})"
        )

    # -- the superstep -----------------------------------------------------

    def run_exchange(self, shuffle_id: int) -> None:
        """Collective superstep — ALL processes must call this in lockstep."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        snap = self.membership.snapshot()
        if snap["dead"]:
            # fail before entering the collective: a lockstep exchange with a
            # dead process hangs every live process until the backend timeout
            first_dead = min(snap["dead"])
            raise ExecutorLostError(
                first_dead,
                snap["epoch"],
                "SPMD exchange requires every process; degraded recovery is "
                f"the single-controller cluster's path — dead: {snap['dead']}",
            )
        self._await_commits(shuffle_id)
        rounds = self.store.seal(shuffle_id)
        n = self.num_executors
        ax = self.conf.mesh_axis_name
        send_rows, lane = int(rounds[0][0].shape[0]), int(rounds[0][0].shape[1])
        staging_slot = send_rows // n

        data_sharding = NamedSharding(self.mesh, P(ax, None))
        sizes_sharding = NamedSharding(self.mesh, P(ax, None))

        # Agree on the plan inputs cluster-wide (spill rounds and skew may
        # differ per host, but every process must derive the IDENTICAL
        # collective schedule): a one-int round-count all_gather, then one
        # (n, rounds + 1) gather carrying each process's per-round hottest
        # lane and its used-row total — the geometry the planner's
        # collective-schedule decisions are a pure function of.  Local
        # telemetry (PlanSignals) only steers serve-plane fields that never
        # enter a collective.
        my_rounds = np.array([[len(rounds)]], dtype=np.int32)
        rc = jax.make_array_from_single_device_arrays(
            (n, 1), sizes_sharding, [jax.device_put(my_rounds, self.device)]
        )
        num_rounds = int(np.max(jax.jit(lambda x: jnp.max(x), out_shardings=None)(rc)))
        local = np.zeros((1, num_rounds + 1), dtype=np.int32)
        for rnd in range(min(len(rounds), num_rounds)):
            local[0, rnd] = int(np.max(rounds[rnd][1], initial=0))
        local[0, num_rounds] = sum(int(np.sum(r[1])) for r in rounds)
        mx = jax.make_array_from_single_device_arrays(
            (n, num_rounds + 1), sizes_sharding, [jax.device_put(local, self.device)]
        )
        maxes, total = jax.jit(
            lambda x: (jnp.max(x[:, :-1], axis=0), jnp.sum(x[:, -1])),
            out_shardings=None,
        )(mx)
        ctx = PlanContext(
            num_executors=n,
            staging_slot_rows=staging_slot,
            round_max_rows=tuple(int(v) for v in np.asarray(maxes)),
            used_rows_total=int(total),
            row_bytes=self.conf.block_alignment,
            platform=self.mesh.devices.reshape(-1)[0].platform,
            # raw block shuffles: no aggregation geometry (agg_partial False)
            # -> plan.combine is always 'off' here; the fields are filled by
            # the aggregation plane.  All-gathered geometry only (maxes/total
            # above), so every process derives the SAME tier — SPMD lockstep
            signals=PlanSignals.from_registry(self.peer.metrics),
        )
        plan = self.planner.plan(ctx)
        instant(
            "exchange.plan",
            shuffle_id=shuffle_id,
            planner=type(self.planner).__name__,
            **plan.describe(),
            **{f"signal_{k}": v for k, v in ctx.signals.describe().items()},
        )
        q = plan.slot_rows
        # Capacity bucketing (same discipline as the cluster's _exchange_fn):
        # varying-size shuffles share one compiled exchange per power-of-two
        # slot bucket; payloads relocate into the bucketed slot layout below.
        bucketed = q * n
        fn = self._exchange_fn_for(bucketed, lane, plan.lowering)

        def _submit(rnd, chunk, nchunks):
            """One sub-round's assemble + H2D + collective dispatch (all JAX
            async dispatch — SPMD order is preserved because every process
            submits the same plan's sub-rounds in the same order, whatever
            the depth)."""
            if rnd < len(rounds):
                payload, sizes = rounds[rnd]
                sub_sizes = chunk_size_rows(sizes, chunk, q)
                if isinstance(payload, jax.Array):
                    # Sealed straight onto the device (device staging or the
                    # single-round host seal): relocate/slice on-device, no
                    # host round trip; device_put is then a no-op pin.  A
                    # single-shot plan whose bucket equals the staging slot
                    # donates the sealed payload as-is (historical fast path).
                    piece = (
                        payload
                        if plan.single_shot and q == staging_slot
                        else slice_subround(payload, n, chunk, q, xp=jnp)
                    )
                else:
                    piece = slice_subround(np.asarray(payload), n, chunk, q)
            else:
                piece = np.zeros((bucketed, lane), dtype=np.int32)
                sub_sizes = np.zeros(n, dtype=np.int32)
            local_payload = jax.device_put(piece, self.device)
            local_sizes = jax.device_put(
                np.reshape(np.asarray(sub_sizes), (1, n)).astype(np.int32), self.device
            )
            data = jax.make_array_from_single_device_arrays(
                (n * bucketed, lane), data_sharding, [local_payload]
            )
            size_mat = jax.make_array_from_single_device_arrays(
                (n, n), sizes_sharding, [local_sizes]
            )
            recv, rs = fn(data, size_mat)
            my_recv = next(
                s.data for s in recv.addressable_shards if s.device == self.device
            )
            my_rs = next(
                s.data for s in rs.addressable_shards if s.device == self.device
            )
            # start D2H of this process's shard while later sub-rounds run
            my_recv.copy_to_host_async()
            my_rs.copy_to_host_async()
            return my_recv, my_rs

        def _drain_chunk(rnd, chunk, nchunks, ticket):
            """Materialize one sub-round's shard host-side (drain worker)."""
            my_recv, my_rs = ticket
            return (
                np.asarray(my_recv).reshape(-1).view(np.uint8),
                np.asarray(my_rs).reshape(-1),
            )

        def _finish_round(rnd, nchunks, parts):
            """Emit one staging round's receive state: single-shot rounds
            keep their whole padded shard (historical layout); chunked rounds
            splice back into the exact single-shot layout (bit-equality
            pinned in tests/test_skew.py).  host_recv_mode applies here, on
            the drain worker — memmap spill stays off the submit thread."""
            if plan.single_shot:
                raw, sizes = parts[0]
                shard = self._host_shard(shuffle_id, rnd, raw)
                used = int(sizes.sum())
                return shard, sizes, (used, bucketed - used)
            sub_sizes = [s for _, s in parts]
            logical = np.sum(sub_sizes, axis=0).astype(np.int32)
            assembled = reassemble_round(
                [b for b, _ in parts], sub_sizes, self.conf.block_alignment
            )
            shard = self._host_shard(shuffle_id, rnd, assembled)
            used = int(logical.sum())
            return shard, logical, (used, nchunks * bucketed - used)

        results = execute_plan(
            plan,
            submit=_submit,
            drain_chunk=_drain_chunk,
            finish_round=_finish_round,
            result_bytes=lambda r: int(r[1].sum()) * self.conf.block_alignment,
            # per-round staging occupancy of this process's shard (the slot
            # padding the planner's quota/chunking exists to shrink)
            occupancy=lambda r: r[2],
            stats=self.stats,
        )
        recv_shards = [shard for shard, _, _ in results]
        recv_sizes_rows = [sizes for _, sizes, _ in results]
        for sizes in recv_sizes_rows:
            active = int(np.count_nonzero(sizes))
            self.stats.record_rows("exchange.lanes", active, sizes.size - active)
        self._recv[shuffle_id] = (recv_shards, recv_sizes_rows)
        logger.info(
            "exchange done: shuffle=%d rounds=%d subrounds=%d slot=%d depth=%d "
            "single_shot=%s",
            shuffle_id, num_rounds, plan.num_subrounds, q,
            plan.pipeline_depth, plan.single_shot,
        )

    def _exchange_fn_for(self, bucketed_rows: int, lane: int, lowering=None):
        """Compiled-exchange cache lookup, keyed on the bucketed slot layout.

        ``bucketed_rows`` is re-bucketed here (``bucket_send_rows`` is a fixed
        point on pow2-slot multiples, so plans — whose ``slot_rows`` are
        already pow2-bucketed — pass through unchanged) so a raw staging size
        can never become a compile-cache key.  The lowering itself lives in
        ``transport/executor.build_plan_exchange`` — this method owns only
        the cache."""
        n = self.num_executors
        bucketed_rows = bucket_send_rows(bucketed_rows, n)
        from sparkucx_tpu.ops.ici_exchange import resolve_exchange_impl

        impl = resolve_exchange_impl(
            lowering or self.conf.exchange_impl,
            self.mesh.devices.reshape(-1)[0].platform,
            n,
        )
        key = (bucketed_rows, lane, self.conf.num_slices, impl)
        fn = self._exchange_fns.get(key)
        if fn is None:
            fn = build_plan_exchange(
                self.mesh,
                num_executors=n,
                send_rows=bucketed_rows,
                lane=lane,
                axis_name=self.conf.mesh_axis_name,
                impl=impl,
                num_slices=self.conf.num_slices,
            )
            self._exchange_fns[key] = fn
        return fn

    # -- post-exchange reads ----------------------------------------------

    def owner_of_reduce(self, shuffle_id: int, reduce_id: int) -> ExecutorId:
        _, _, ranges = self._meta[shuffle_id]
        for p, (s, e) in enumerate(ranges):
            if s <= reduce_id < e:
                return p
        raise ValueError(f"reduce {reduce_id} unowned")

    def read_received_block(self, shuffle_id: int, map_id: int, reduce_id: int) -> bytes:
        """Read a block this executor received in the exchange."""
        if self.owner_of_reduce(shuffle_id, reduce_id) != self.executor_id:
            raise TransportError(
                f"reducer {reduce_id} not owned by executor {self.executor_id}"
            )
        if shuffle_id not in self._recv:
            raise TransportError(f"shuffle {shuffle_id} not exchanged")
        info = self._mapper_infos[shuffle_id].get(map_id)
        if info is None:
            raise TransportError(f"map {map_id} never committed")
        abs_offset, length = info.partitions[reduce_id]
        if length == 0:
            return b""
        rnd = info.round_of(reduce_id)
        sender = self.map_owner(map_id)
        region_bytes = self.store.region_bytes(shuffle_id)
        region_rel = abs_offset - self.executor_id * region_bytes
        shards, sizes_rows = self._recv[shuffle_id]
        chunk_start = int(sizes_rows[rnd][:sender].sum()) * self.conf.block_alignment
        start = chunk_start + region_rel
        return bytes(shards[rnd][start : start + length])

    def _host_shard(self, shuffle_id: int, rnd: int, host: np.ndarray) -> np.ndarray:
        """Apply ``conf.host_recv_mode`` to one received round: 'array' keeps
        the RAM copy (historical behavior), 'memmap' spills it to a read-only
        disk mapping so per-host RSS stays bounded by one round — the same
        budget discipline as the single-controller cluster (transport/tpu.py
        ``_memmap_round``): every spilled byte reserves against
        ``spill_disk_cap_bytes`` up front and a failed write refunds and
        unlinks (mode validity is checked at construction)."""
        if self.conf.host_recv_mode == "array":
            return host
        import os
        import tempfile

        cap = self.conf.spill_disk_cap_bytes
        nbytes = int(host.nbytes)
        if nbytes == 0:
            # nothing received this round (quota-path tight shards can be
            # empty); np.memmap cannot map a zero-byte file — keep the array
            return host
        # reserve-then-write: check+charge atomic under the spill lock (the
        # drain worker charges here while remove_shuffle refunds concurrently)
        with self._spill_lock:
            if cap and self._recv_spill_bytes + nbytes > cap:
                raise TransportError(
                    f"received-shard spill would exceed spill_disk_cap_bytes "
                    f"({self._recv_spill_bytes + nbytes} > {cap}) on executor "
                    f"{self.executor_id}"
                )
            self._recv_spill_bytes += nbytes
        spill_dir = self.conf.spill_dir
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        fd, path = tempfile.mkstemp(
            prefix=f"sparkucx_tpu_spmd_recv_s{shuffle_id}_r{rnd}_e{self.executor_id}_",
            dir=spill_dir,
        )
        os.close(fd)
        shape = host.shape
        try:
            mm = np.memmap(path, dtype=np.uint8, mode="w+", shape=shape)
            mm[:] = host
            mm.flush()
        except BaseException:
            with self._spill_lock:
                self._recv_spill_bytes -= nbytes
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        del mm, host  # drop the dirty mapping; reopen read-only (RSS falls)
        # track the CHARGED bytes with the path: the refund must mirror the
        # charge, not os.path.getsize (block-size rounding / sparse files /
        # truncation by an operator would drift _recv_spill_bytes permanently)
        with self._spill_lock:
            self._recv_spill.setdefault(shuffle_id, []).append((path, nbytes))
        return np.memmap(path, dtype=np.uint8, mode="r", shape=shape)

    def remove_shuffle(self, shuffle_id: int) -> None:
        self.store.remove_shuffle(shuffle_id)
        self._recv.pop(shuffle_id, None)
        self._meta.pop(shuffle_id, None)
        self._mapper_infos.pop(shuffle_id, None)
        import os

        with self._spill_lock:
            doomed = self._recv_spill.pop(shuffle_id, [])
        for path, nbytes in doomed:
            try:
                os.unlink(path)
                freed = True
            except FileNotFoundError:
                freed = True  # already gone: still refund
            except OSError:
                freed = False  # still on disk: keep it charged
            if freed:
                with self._spill_lock:
                    self._recv_spill_bytes -= nbytes
