"""Size-bucketed pool of staged/registered buffers (L1).

Counterpart of ``shuffle/ucx/memory/MemoryPool.scala`` (147 LoC):

* sizes rounded up to powers of two with floor ``min_buffer_size``
  (MemoryPool.scala:34-49),
* a per-size free stack backed by real allocations (MemoryPool.scala:55-110),
* small sizes batch-preallocated in ``min_allocation_size`` slabs carved into
  refcounted views (MemoryPool.scala:64-70,84-95; refcounting cf.
  UcxRefCountMemoryBlock, UcxWorkerWrapper.scala:36-56),
* ``preallocate(size, count)`` warm-up from config (MemoryPool.scala:141-147),
* ``close()`` releases every allocation (MemoryPool.scala:97-109).

TPU-first substitutions: where the reference registers host memory with the RDMA NIC
(``ucxContext.memoryMap``), we allocate page-aligned host arrays through the native
arena when built (sparkucx_tpu/native, the jucx/nvkv replacement) or 64-byte-aligned
numpy arrays otherwise — both are zero-copy convertible to ``jax.Array`` via
``jax.device_put`` (the HBM staging path).  "Registration" on TPU means keeping the
buffer alive and aligned so XLA's host-to-device DMA path can use it.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from sparkucx_tpu.config import TpuShuffleConf
from sparkucx_tpu.core.block import MemoryBlock
from sparkucx_tpu.core.operation import ResourceExhaustedError
from sparkucx_tpu.memory import sanitizer as _sanitizer
from sparkucx_tpu.testing import faults


def round_up_to_next_power_of_two(size: int) -> int:
    """MemoryPool.scala:34-41."""
    if size <= 0:
        return 1
    return 1 << (size - 1).bit_length()


_DEFAULT_ALIGNMENT = 64


def _alloc_aligned(nbytes: int, alignment: int = _DEFAULT_ALIGNMENT):
    """Allocate an aligned uint8 array.

    Prefers the native pinned allocator (the registered-memory analogue of
    ``ucxContext.memoryMap``, MemoryPool.scala:55-110) — pinned pages let XLA's
    host->HBM DMA stream without bouncing; falls back to an over-allocated numpy
    array.  Returns (array, closer)."""
    try:
        from sparkucx_tpu import native

        if native.native_available():
            buf = native.PinnedBuffer(nbytes, alignment=max(alignment, 4096), pin=True)
            return buf.array, buf.close
    except Exception:
        pass
    raw = np.empty(nbytes + alignment, dtype=np.uint8)
    offset = (-raw.ctypes.data) % alignment
    return raw[offset : offset + nbytes], None


class _PoolBudget:
    """Pool-wide backing-allocation budget (``store.hardWatermark``).

    Shared by every :class:`AllocatorStack` of one pool so the hard watermark
    bounds the SUM of slab allocations, not each bucket independently.  The
    lock is a leaf: nothing is called while it is held.
    """

    __slots__ = ("hard", "allocated", "lock")

    def __init__(self, hard: int) -> None:
        self.hard = int(hard)
        self.allocated = 0  #: guarded by self.lock
        self.lock = threading.Lock()

    def charge(self, nbytes: int) -> None:
        """Admit a slab allocation or raise the retryable typed error."""
        with self.lock:
            if self.hard > 0 and self.allocated + nbytes > self.hard:
                raise ResourceExhaustedError(
                    requested=nbytes,
                    used=self.allocated,
                    watermark=self.hard,
                    detail="memory pool hard watermark",
                )
            self.allocated += nbytes


class _Slab:
    """One backing allocation, possibly shared by many pooled views.

    The refcount mirrors the shared-refcount slab carve-up of
    MemoryPool.scala:64-70 — the slab is only releasable when every view is back.
    """

    __slots__ = ("array", "refcount", "lock", "closer")

    def __init__(self, array: np.ndarray, closer=None) -> None:
        self.array = array
        self.refcount = 0
        self.lock = threading.Lock()
        self.closer = closer

    def release(self) -> None:
        self.array = None
        if self.closer is not None:
            self.closer()
            self.closer = None


class AllocatorStack:
    """Free-stack of equal-sized buffers for one bucket (MemoryPool.scala:55-110)."""

    def __init__(
        self,
        size: int,
        min_allocation_size: int,
        alignment: int = _DEFAULT_ALIGNMENT,
        sanitizer: Optional[_sanitizer.BufferSanitizer] = None,
        budget: Optional[_PoolBudget] = None,
    ) -> None:
        self.size = size
        self.min_allocation_size = min_allocation_size
        self.alignment = alignment
        self.sanitizer = sanitizer or _sanitizer.DISABLED
        self.budget = budget
        self._free: List[MemoryBlock] = []  #: guarded by self._lock
        self._slabs: List[_Slab] = []  #: guarded by self._lock
        self._lock = threading.Lock()
        self.total_allocated = 0  #: guarded by self._lock (bytes of backing allocations)
        self.total_requested = 0  #: guarded by self._lock (get() count for stats)

    def _carve(self, slab: _Slab) -> List[MemoryBlock]:
        """Split a slab into ``size``-byte refcounted views."""
        views = []
        n = slab.array.size // self.size
        for i in range(n):
            view = slab.array[i * self.size : (i + 1) * self.size]
            views.append(self._wrap(view, slab))
        return views

    def _wrap(self, view: np.ndarray, slab: _Slab) -> MemoryBlock:
        # refcount counts *checked-out* views: incremented in get(), decremented
        # on recycle — the slab is releasable iff refcount == 0.
        def recycle(mb: MemoryBlock, _slab=slab) -> None:
            # _closed stays True while the block sits in the free stack (re-armed
            # at checkout in get()) so a stale holder's second close() is a no-op
            # instead of a double-free.  Sanitize mode runs first: it raises on
            # live exported views (block stays checked out) and poisons the
            # bucket bytes before the handle becomes claimable again.
            self.sanitizer.on_release(mb)
            with _slab.lock:
                _slab.refcount -= 1
            with self._lock:
                self._free.append(mb)

        mb = MemoryBlock(
            data=view,
            size=self.size,
            is_host_memory=True,
            _on_close=recycle,
            _on_double_close=self.sanitizer.on_double_release,
        )
        mb.allocator_token = slab
        return mb

    def _allocate_more(self) -> None:
        """Grow the free list by one slab; caller holds ``self._lock``.

        Budget bytes charged here are never refunded per-slab — the charge's
        ownership transfers to the slab list, which lives until ``close()``
        tears the whole stack down; pooled buffers recycle, slabs do not."""
        # Small buckets allocate min_allocation_size slabs and carve them up;
        # buckets >= the slab size allocate exactly one buffer (MemoryPool.scala:64-70).
        alloc_size = max(self.size, self.min_allocation_size)
        # Memory-pressure gate BEFORE the backing allocation mutates any
        # state: a shed growth leaves the stack exactly as it was, and the
        # caller's get()/get_n() surfaces the retryable typed error.  The
        # chaos point fires first so tests can inject pressure with the
        # watermark knobs off (byte-identical defaults).
        faults.check("store.mem_pressure", site="pool_grow", nbytes=alloc_size)
        if self.budget is not None:
            self.budget.charge(alloc_size)
        array, closer = _alloc_aligned(alloc_size, self.alignment)
        slab = _Slab(array, closer)
        self._slabs.append(slab)
        self.total_allocated += alloc_size
        self._free.extend(self._carve(slab))

    def get(self) -> MemoryBlock:
        # The pop, the refcount increment, and the close re-arm happen under the
        # stack lock so a concurrent close() can never observe a checked-out
        # block with refcount 0.
        with self._lock:
            self.total_requested += 1
            if not self._free:
                self._allocate_more()
            mb = self._free.pop()
            slab = mb.allocator_token
            with slab.lock:
                slab.refcount += 1
            mb.rearm()
        self.sanitizer.on_checkout(mb)
        return mb

    def get_n(self, count: int) -> List[MemoryBlock]:
        """Batch checkout: ``count`` blocks for ONE lock round-trip — the
        fetch reader allocates whole request windows at a time, and taking
        the stack lock per block showed up once windows grew credit-deep."""
        out: List[MemoryBlock] = []
        with self._lock:
            self.total_requested += count
            while len(self._free) < count:
                self._allocate_more()
            for _ in range(count):
                mb = self._free.pop()
                slab = mb.allocator_token
                with slab.lock:
                    slab.refcount += 1
                mb.rearm()
                out.append(mb)
        for mb in out:
            self.sanitizer.on_checkout(mb)
        return out

    def preallocate(self, count: int) -> None:
        """MemoryPool.scala:141-147 warm-up."""
        with self._lock:
            while len(self._free) < count:
                self._allocate_more()

    @property
    def num_free(self) -> int:
        with self._lock:
            return len(self._free)

    def close(self) -> None:
        with self._lock:
            leaked = [s for s in self._slabs if s.refcount > 0]
            releasable = [s for s in self._slabs if s.refcount == 0]
            self._free.clear()
            self._slabs.clear()
            for s in releasable:
                s.release()
            if leaked:
                raise ResourceWarning(
                    f"AllocatorStack(size={self.size}): {len(leaked)} slabs still referenced at close"
                )


class MemoryPool:
    """Bucketed host bounce-buffer pool (``UcxHostBounceBuffersPool`` analogue).

    ``get(size)`` returns a MemoryBlock whose ``size`` is the *requested* size but
    whose backing buffer is the power-of-two bucket (the reference returns a sized
    view the same way, MemoryPool.scala:117-131).  ``put``/``MemoryBlock.close()``
    recycles.
    """

    def __init__(self, conf: Optional[TpuShuffleConf] = None) -> None:
        self.conf = conf or TpuShuffleConf()
        #: lifecycle tracker (conf.sanitize; no-op when disabled) — public so
        #: the reader attaches view bookkeeping without reaching into pool
        #: internals (analysis: private-access pass)
        self.sanitizer = _sanitizer.from_conf(self.conf)
        #: pool-wide slab budget (store.hardWatermark); 0 = unbounded
        self._budget = _PoolBudget(getattr(self.conf, "store_hard_watermark", 0))
        self._stacks: Dict[int, AllocatorStack] = {}  #: guarded by self._lock
        self._lock = threading.Lock()
        self._closed = False  #: guarded by self._lock

    def _bucket(self, size: int) -> int:
        return max(round_up_to_next_power_of_two(size), self.conf.min_buffer_size)

    def _stack_for(self, bucket: int) -> AllocatorStack:
        with self._lock:
            if self._closed:
                raise RuntimeError("MemoryPool is closed")
            stack = self._stacks.get(bucket)
            if stack is None:
                stack = AllocatorStack(
                    bucket,
                    self.conf.min_allocation_size,
                    sanitizer=self.sanitizer,
                    budget=self._budget,
                )
                self._stacks[bucket] = stack
            return stack

    def get(self, size: int) -> MemoryBlock:
        if size <= 0:
            raise ValueError(f"invalid allocation size {size}")
        mb = self._stack_for(self._bucket(size)).get()
        mb.size = size  # sized view over the bucket buffer
        return mb

    def get_many(self, sizes) -> List[MemoryBlock]:
        """Order-preserving batch checkout: requests are grouped by bucket so
        a fetch window of K same-bucket blocks pays one stack-lock round-trip
        instead of K (the credit-pipelined reader's allocation path)."""
        sizes = list(sizes)
        for s in sizes:
            if s <= 0:
                raise ValueError(f"invalid allocation size {s}")
        by_bucket: Dict[int, List[int]] = {}
        for i, s in enumerate(sizes):
            by_bucket.setdefault(self._bucket(s), []).append(i)
        out: List[Optional[MemoryBlock]] = [None] * len(sizes)
        for bucket, idxs in by_bucket.items():
            for i, mb in zip(idxs, self._stack_for(bucket).get_n(len(idxs))):
                mb.size = sizes[i]  # sized view over the bucket buffer
                out[i] = mb
        return out

    def put(self, mb: MemoryBlock) -> None:
        mb.close()

    def preallocate(self, size: int, count: int) -> None:
        self._stack_for(self._bucket(size)).preallocate(count)

    def preallocate_from_conf(self) -> None:
        """spark.shuffle.tpu.memory.preAllocateBuffers warm-up (MemoryPool.scala:141-147)."""
        for size, count in self.conf.prealloc_buffers.items():
            self.preallocate(size, count)

    def stats(self) -> Dict[int, Dict[str, int]]:
        with self._lock:
            return {
                b: {
                    "allocated_bytes": s.total_allocated,
                    "requests": s.total_requested,
                    "free": s.num_free,
                }
                for b, s in sorted(self._stacks.items())
            }

    def close(self) -> None:
        with self._lock:
            stacks, self._stacks = list(self._stacks.values()), {}
            self._closed = True
        errors = []
        for s in stacks:
            try:
                s.close()
            except ResourceWarning as e:  # collect, keep closing (MemoryPool.scala:97-109)
                errors.append(e)
        if errors:
            raise ResourceWarning("; ".join(str(e) for e in errors))

    def __enter__(self) -> "MemoryPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
