"""Runtime buffer sanitizer (``spark.shuffle.tpu.sanitize``, default off).

The pool's zero-copy design trades safety for speed on purpose: pooled
``MemoryBlock`` handles park in a free list with ``close()`` idempotent (a
stale holder's second close is a no-op, not a double-free), and the reader
hands out read-only memoryviews straight over fetch buffers.  Both idioms
fail *silently* when misused — a consumer that keeps reading a released view
sees whatever the next checkout wrote there (the exact stale-registered-
buffer hazard SparkUCX documents around its RDMA pool).

Sanitize mode makes every such misuse loud, the ASan playbook applied to the
pool:

* **double-release** — a second ``close()`` on a released pooled handle
  raises :class:`SanitizerError` instead of no-op'ing.  The normal-mode
  contract stays *idempotent* (free-list parking depends on it); sanitize
  mode tightens it to *raise* so tests can pin the offender.
* **use-after-release** — ``BlockFetchResult.data`` raises after
  ``release()``/``detach()`` dropped the buffer.
* **poisoning** — freed host buffers are filled with ``POISON`` (0xDD)
  before re-pooling, so any surviving view reads garbage *deterministically*
  rather than plausible stale bytes.
* **re-pool with live views** — recycling a buffer while exported views are
  outstanding (the reader registered a view and nobody released it) raises.

The sanitizer is attached to the pool as the PUBLIC ``MemoryPool.sanitizer``
attribute; the reader picks it up from there.  When disabled (default) every
hook is a cheap no-op and no state is kept.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

import numpy as np

#: fill byte for freed buffers (0xDD, the classic "dead memory" marker)
POISON = 0xDD


class SanitizerError(RuntimeError):
    """A buffer-lifecycle invariant was violated under sanitize mode."""


class _HandleState:
    """Lifecycle record of one checked-out pooled handle."""

    __slots__ = ("live", "exports")

    def __init__(self) -> None:
        self.live = True
        self.exports = 0


class BufferSanitizer:
    """Tracks pooled-handle lifecycles; all methods are thread-safe.

    Handles are keyed by ``id(block)`` — pooled MemoryBlock objects are
    themselves pooled (the free list parks the wrapper, not just the bytes),
    so object identity is stable across a checkout/release cycle and the
    entry is refreshed at every checkout.
    """

    def __init__(self, enabled: bool = False) -> None:
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._handles: Dict[int, _HandleState] = {}  #: guarded by self._lock
        # counters (observability; read them without the lock at your peril)
        self.checkouts = 0  #: guarded by self._lock
        self.releases = 0  #: guarded by self._lock
        self.poisoned_bytes = 0  #: guarded by self._lock

    # -- pool hooks --------------------------------------------------------

    def on_checkout(self, block) -> None:
        """A pooled handle left the free list (AllocatorStack.get)."""
        if not self.enabled:
            return
        with self._lock:
            self.checkouts += 1
            self._handles[id(block)] = _HandleState()

    def on_release(self, block) -> None:
        """A handle is about to re-pool (recycle hook).  Raises on live
        exported views; poisons the backing bytes."""
        if not self.enabled:
            return
        with self._lock:
            state = self._handles.get(id(block))
            if state is not None and state.exports > 0:
                raise SanitizerError(
                    f"re-pooling buffer with {state.exports} live exported "
                    f"view(s) — release every BlockFetchResult before closing "
                    f"its MemoryBlock"
                )
            if state is not None:
                state.live = False
            self.releases += 1
            self.poisoned_bytes += int(getattr(block.data, "nbytes", 0))
        # poison OUTSIDE the lock: a big memset under it would serialize the
        # pool.  The block is already off every consumer's hands (exports==0).
        data = block.data
        if isinstance(data, np.ndarray):
            data.reshape(-1).view(np.uint8)[:] = POISON

    def on_double_release(self, block) -> None:
        """Second close() of a parked handle — a latent double-free."""
        if not self.enabled:
            return
        raise SanitizerError(
            "double release: MemoryBlock.close() called on a handle already "
            "parked in the free list (idempotent in normal mode; sanitize "
            "mode raises to pin the offender)"
        )

    # -- view hooks (reader zero-copy results) -----------------------------

    def export_view(self, block) -> None:
        """A zero-copy view over ``block`` was handed to a consumer."""
        if not self.enabled or block is None:
            return
        with self._lock:
            state = self._handles.setdefault(id(block), _HandleState())
            state.exports += 1

    def release_view(self, block) -> None:
        """The consumer's view was released/detached before the buffer."""
        if not self.enabled or block is None:
            return
        with self._lock:
            state = self._handles.get(id(block))
            if state is not None and state.exports > 0:
                state.exports -= 1

    def check_view_released(self, what: str) -> None:
        """Access to an already-released view: raise with context."""
        if not self.enabled:
            return
        raise SanitizerError(
            f"use-after-release: {what} accessed after release()/detach() "
            f"returned its buffer to the pool"
        )

    # -- introspection -----------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "checkouts": self.checkouts,
                "releases": self.releases,
                "poisoned_bytes": self.poisoned_bytes,
                "tracked_handles": len(self._handles),
            }


#: shared no-op instance for call sites without a pool/conf
DISABLED = BufferSanitizer(enabled=False)


def from_conf(conf) -> BufferSanitizer:
    """Build from ``TpuShuffleConf`` (``spark.shuffle.tpu.sanitize``).

    The ``SPARKUCX_TPU_SANITIZE`` environment variable force-enables the
    sanitizer regardless of conf — CI's sanitize-mode test subset flips the
    whole suite on without threading a conf through every fixture."""
    enabled = bool(getattr(conf, "sanitize", False)) or (
        os.environ.get("SPARKUCX_TPU_SANITIZE", "").lower() in ("1", "true")
    )
    return BufferSanitizer(enabled=enabled)
