// Native memory arena + batch copy for the TPU shuffle framework.
//
// This is the in-repo replacement for the two JNI libraries the reference
// delegates all native work to (SURVEY.md §2 "Native / non-JVM components"):
//
//  * jucx's registered-memory role (ucxContext.memoryMap behind
//    MemoryPool.scala:55-110): ts_alloc_aligned/ts_mlock provide page-aligned,
//    optionally pinned host slabs that XLA's host->HBM DMA path can stream from
//    without bouncing.
//  * nvkv's shared block-device role (NvkvHandler.scala): ts_shm_* exposes a
//    named shared-memory arena so executor processes on one host stage and
//    serve shuffle blocks zero-copy — the single-host analogue of the
//    DPU-attached NVMe store every executor's daemon can read.
//  * the server-side parallel block gather (ForkJoin ioThreadPool,
//    UcxWorkerWrapper.scala:416-426): ts_batch_copy moves N scattered segments
//    with a thread team sized to the total byte count.
//
// Plain C ABI; bound from Python with ctypes (no pybind11 in the image).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Aligned (optionally pinned) private allocations
// ---------------------------------------------------------------------------

void* ts_alloc_aligned(uint64_t size, uint64_t alignment) {
  void* ptr = nullptr;
  if (posix_memalign(&ptr, alignment, size) != 0) return nullptr;
  return ptr;
}

void ts_free_aligned(void* ptr) { free(ptr); }

// Pin pages (registered-memory analogue). Returns 0 on success, errno on failure
// (callers treat failure as advisory: unpinned staging still works, like the
// reference running UCX without ODP).
int ts_mlock(void* ptr, uint64_t size) {
  return mlock(ptr, size) == 0 ? 0 : errno;
}

int ts_munlock(void* ptr, uint64_t size) {
  return munlock(ptr, size) == 0 ? 0 : errno;
}

// ---------------------------------------------------------------------------
// Named shared-memory arenas (cross-process staging)
// ---------------------------------------------------------------------------

struct TsShm {
  void* addr;
  uint64_t size;
  int fd;
};

// create=1: O_CREAT|O_EXCL + ftruncate (the owner); create=0: attach existing.
TsShm* ts_shm_open(const char* name, uint64_t size, int create) {
  int flags = create ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
  int fd = shm_open(name, flags, 0600);
  if (fd < 0) return nullptr;
  if (create && ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  if (!create) {
    struct stat st;
    if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < size) {
      close(fd);
      return nullptr;
    }
  }
  void* addr = mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (addr == MAP_FAILED) {
    close(fd);
    if (create) shm_unlink(name);
    return nullptr;
  }
  TsShm* handle = new TsShm{addr, size, fd};
  return handle;
}

void* ts_shm_addr(TsShm* handle) { return handle ? handle->addr : nullptr; }
uint64_t ts_shm_size(TsShm* handle) { return handle ? handle->size : 0; }

void ts_shm_close(TsShm* handle) {
  if (!handle) return;
  munmap(handle->addr, handle->size);
  close(handle->fd);
  delete handle;
}

int ts_shm_unlink(const char* name) {
  return shm_unlink(name) == 0 ? 0 : errno;
}

// ---------------------------------------------------------------------------
// Batched scattered copy (server-side gather / client-side scatter)
// ---------------------------------------------------------------------------

struct TsSegment {
  uint64_t dst_off;
  uint64_t src_off;
  uint64_t len;
};

// Copy n segments from src to dst. Splits the segment list across a thread team
// when total bytes exceed ~4 MiB (below that, spawn cost dominates).
void ts_batch_copy(uint8_t* dst, const uint8_t* src, const TsSegment* segs,
                   uint64_t n, int max_threads) {
  uint64_t total = 0;
  for (uint64_t i = 0; i < n; ++i) total += segs[i].len;
  int hw = (int)std::thread::hardware_concurrency();
  int threads = max_threads > 0 ? max_threads : (hw > 0 ? hw : 1);
  if (total < (4u << 20) || threads <= 1 || n <= 1) {
    for (uint64_t i = 0; i < n; ++i)
      memcpy(dst + segs[i].dst_off, src + segs[i].src_off, segs[i].len);
    return;
  }
  std::atomic<uint64_t> next{0};
  auto worker = [&]() {
    for (;;) {
      uint64_t i = next.fetch_add(1);
      if (i >= n) return;
      memcpy(dst + segs[i].dst_off, src + segs[i].src_off, segs[i].len);
    }
  };
  std::vector<std::thread> team;
  int spawn = threads - 1;
  for (int t = 0; t < spawn; ++t) team.emplace_back(worker);
  worker();
  for (auto& th : team) th.join();
}

uint64_t ts_version() { return 1; }

}  // extern "C"
