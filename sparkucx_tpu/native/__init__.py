"""ctypes bindings for the native arena (the jucx/nvkv replacement).

Builds ``libtpushuffle.so`` from ``arena.cpp`` on first import (g++, cached next
to the source; rebuilt when the source is newer).  Everything degrades
gracefully: if no compiler is available the pure-Python paths keep working and
``native_available()`` returns False — native code accelerates, it never gates.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "arena.cpp")
_SO = os.path.join(_DIR, "libtpushuffle.so")
_LOCK = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


class TsSegment(ctypes.Structure):
    _fields_ = [
        ("dst_off", ctypes.c_uint64),
        ("src_off", ctypes.c_uint64),
        ("len", ctypes.c_uint64),
    ]


def _build() -> Optional[str]:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        _SRC, "-o", _SO,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"build failed: {e}"
    if proc.returncode != 0:
        return f"g++ failed: {proc.stderr[-2000:]}"
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_error
    with _LOCK:
        if _lib is not None or _build_error is not None:
            return _lib
        needs_build = not os.path.exists(_SO) or (
            os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        )
        if needs_build:
            err = _build()
            if err is not None:
                _build_error = err
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            _build_error = str(e)
            return None
        lib.ts_alloc_aligned.restype = ctypes.c_void_p
        lib.ts_alloc_aligned.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        lib.ts_free_aligned.argtypes = [ctypes.c_void_p]
        lib.ts_mlock.restype = ctypes.c_int
        lib.ts_mlock.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ts_munlock.restype = ctypes.c_int
        lib.ts_munlock.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.ts_shm_open.restype = ctypes.c_void_p
        lib.ts_shm_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int]
        lib.ts_shm_addr.restype = ctypes.c_void_p
        lib.ts_shm_addr.argtypes = [ctypes.c_void_p]
        lib.ts_shm_size.restype = ctypes.c_uint64
        lib.ts_shm_size.argtypes = [ctypes.c_void_p]
        lib.ts_shm_close.argtypes = [ctypes.c_void_p]
        lib.ts_shm_unlink.restype = ctypes.c_int
        lib.ts_shm_unlink.argtypes = [ctypes.c_char_p]
        lib.ts_batch_copy.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.POINTER(TsSegment), ctypes.c_uint64, ctypes.c_int,
        ]
        lib.ts_version.restype = ctypes.c_uint64
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def build_error() -> Optional[str]:
    _load()
    return _build_error


def _as_np(addr: int, size: int) -> np.ndarray:
    buf = (ctypes.c_uint8 * size).from_address(addr)
    return np.frombuffer(buf, dtype=np.uint8)


class PinnedBuffer:
    """Page-aligned (optionally mlocked) host buffer — the registered-memory
    analogue of the reference's ``ucxContext.memoryMap`` slabs."""

    def __init__(self, size: int, alignment: int = 4096, pin: bool = True) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native arena unavailable: {_build_error}")
        self._lib = lib
        self.size = size
        self._ptr = lib.ts_alloc_aligned(size, alignment)
        if not self._ptr:
            raise MemoryError(f"ts_alloc_aligned({size}) failed")
        self.pinned = pin and lib.ts_mlock(self._ptr, size) == 0
        self.array = _as_np(self._ptr, size)

    def close(self) -> None:
        if self._ptr:
            if self.pinned:
                self._lib.ts_munlock(self._ptr, self.size)
            self.array = None
            self._lib.ts_free_aligned(self._ptr)
            self._ptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SharedArena:
    """Named cross-process shared-memory arena — the NVKV-store analogue for
    single-host multi-executor deployments.  The creating process passes
    ``create=True`` and should ``unlink()`` at teardown."""

    def __init__(self, name: str, size: int, create: bool) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native arena unavailable: {_build_error}")
        self._lib = lib
        self.name = name
        self.size = size
        self.created = create
        self._handle = lib.ts_shm_open(name.encode(), size, 1 if create else 0)
        if not self._handle:
            raise OSError(f"ts_shm_open({name!r}, create={create}) failed")
        self.array = _as_np(lib.ts_shm_addr(self._handle), size)

    def close(self) -> None:
        if self._handle:
            self.array = None
            self._lib.ts_shm_close(self._handle)
            self._handle = None

    def unlink(self) -> None:
        self._lib.ts_shm_unlink(self.name.encode())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        if self.created:
            self.unlink()


def batch_copy(
    dst: np.ndarray,
    src: np.ndarray,
    segments,  # iterable of (dst_off, src_off, length)
    max_threads: int = 0,
) -> None:
    """Copy scattered segments src->dst.  Native threaded path when available,
    else a numpy loop (same semantics)."""
    lib = _load()
    segs = list(segments)
    if lib is None:
        d = dst.reshape(-1).view(np.uint8)
        s = src.reshape(-1).view(np.uint8)
        for dst_off, src_off, length in segs:
            d[dst_off : dst_off + length] = s[src_off : src_off + length]
        return
    arr = (TsSegment * len(segs))(*[TsSegment(d, s, l) for d, s, l in segs])
    dptr = dst.ctypes.data if isinstance(dst, np.ndarray) else dst
    sptr = src.ctypes.data if isinstance(src, np.ndarray) else src
    lib.ts_batch_copy(dptr, sptr, arr, len(segs), max_threads)
