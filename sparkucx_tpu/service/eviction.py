"""Tiered eviction: epoch/LRU demotion of sealed rounds, restage-on-fetch.

The store already has three tiers for a sealed round's bytes — HBM-resident
``jax.Array`` exchange payload, host staging snapshot, and ``np.memmap`` disk
spill (``HbmBlockStore._spill_round``) — but until now a round only ever
moved DOWN at rollover time and never back.  The EvictionManager turns those
tiers into a managed cache:

* **Demotion**: every epoch (``spark.shuffle.tpu.eviction.epochMs``, or a
  manual :meth:`run_epoch`), the least-recently-fetched sealed rounds are
  demoted one tier (``hbm`` -> ``host`` -> ``disk``) through
  ``HbmBlockStore.demote_round``.  Cold shuffles drain out of HBM and RAM;
  fetches keep working at every tier (``read_block`` serves memmaps too).
* **Restage-on-fetch**: the store notifies :meth:`on_access` on every block
  read; a fetch that lands on a disk-tier round restages it to host RAM
  (``restage_round``) so the rest of the round's fan-in runs at RAM speed.
  Restages are timed into the StatsAggregator (``eviction.restage`` kind) —
  ``restage_p99_ns`` is the tail penalty a cold fetch pays.
* **Restage ordering**: when several rounds must come back (a cold shuffle's
  whole fan-in arriving at once), :meth:`restage_plan` orders them by
  ascending staged footprint — the memory-footprint-aware scheduling of
  arXiv:2112.01075 applied to tier promotion: smallest rounds first, so peak
  transient staging (memmap pages + the new RAM copy coexist during the
  copy) grows as slowly as service is restored.

Quota interplay: demoting a round to disk releases its bytes from the owning
tenant's HBM charge, and restaging re-charges them — so a tenant over its
quota gets a typed ``TenantQuotaExceededError`` from the restage, which the
serving plane returns over the wire as a fail-fast addressed error.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from sparkucx_tpu.core.operation import OperationStats
from sparkucx_tpu.utils.stats import StatsAggregator


class ServeCache:
    """Bounded serve-side decoded-block cache ABOVE the eviction tiers.

    Hot blocks — promoted by the popularity tracker — are pinned here as
    immutable ``bytes`` in a byte-budgeted LRU (``serve.cacheBytes``), so a
    fetch storm on a demoted round is served from RAM without paying the
    disk restage, and demotion/restage churn below never touches the hot
    set.  The cache stores COPIES (decoded payload snapshots), never views
    into the store's staging buffers: entries stay valid across demotion,
    restage, and round rollover, and are dropped only by LRU pressure or
    :meth:`invalidate_shuffle` when the shuffle itself is removed.

    Quota interplay is orchestrated by the store, not here: the store
    charges the owning tenant BEFORE :meth:`put` and releases the bytes of
    whatever :meth:`put`/:meth:`invalidate_shuffle` return as evicted —
    sequential lock scopes, so ``ServeCache._lock`` stays a leaf and never
    nests with ``HbmBlockStore._lock``.
    """

    def __init__(self, capacity_bytes: int) -> None:
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()  # LEAF: no calls out while held
        #: (shuffle_id, map_id, reduce_id) -> payload; guarded by self._lock
        self._entries: "OrderedDict[Tuple[int, int, int], bytes]" = OrderedDict()
        self._used = 0  #: guarded by self._lock
        self.stats: Dict[str, int] = {
            "cache_hits": 0,
            "cache_misses": 0,
            "cache_evictions": 0,
            "cache_rejects": 0,
        }  #: guarded by self._lock

    def get(self, key: Tuple[int, int, int]) -> Optional[bytes]:
        """Cached payload for ``(shuffle, map, reduce)`` or None; a hit
        refreshes the entry's LRU position."""
        with self._lock:
            data = self._entries.get(key)
            if data is None:
                self.stats["cache_misses"] += 1
                return None
            self._entries.move_to_end(key)
            self.stats["cache_hits"] += 1
            return data

    def put(self, key: Tuple[int, int, int], data: bytes) -> List[Tuple[Tuple[int, int, int], int]]:
        """Insert (or refresh) one decoded block; evicts LRU entries to fit.
        Returns ``[(key, nbytes)]`` for every entry evicted so the caller can
        release their tenant charges.  A block larger than the whole budget
        is rejected (counted, nothing evicted for it)."""
        nbytes = len(data)
        with self._lock:
            if nbytes > self.capacity_bytes:
                self.stats["cache_rejects"] += 1
                return []
            prev = self._entries.pop(key, None)
            if prev is not None:
                self._used -= len(prev)
            evicted: List[Tuple[Tuple[int, int, int], int]] = []
            while self._used + nbytes > self.capacity_bytes and self._entries:
                old_key, old_data = self._entries.popitem(last=False)
                self._used -= len(old_data)
                self.stats["cache_evictions"] += 1
                evicted.append((old_key, len(old_data)))
            self._entries[key] = data
            self._used += nbytes
            if prev is not None:
                evicted.append((key, len(prev)))
            return evicted

    def invalidate_shuffle(self, shuffle_id: int) -> List[Tuple[Tuple[int, int, int], int]]:
        """Drop every entry of one shuffle (shuffle removal); returns the
        dropped ``[(key, nbytes)]`` so the caller releases tenant charges."""
        with self._lock:
            doomed = [k for k in self._entries if k[0] == shuffle_id]
            out: List[Tuple[Tuple[int, int, int], int]] = []
            for k in doomed:
                data = self._entries.pop(k)
                self._used -= len(data)
                out.append((k, len(data)))
            return out

    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> Dict[str, int]:
        """Counter snapshot for MetricsRegistry export."""
        with self._lock:
            out = dict(self.stats)
            out["cache_used_bytes"] = self._used
            out["cache_entries"] = len(self._entries)
            return out


class EvictionManager:
    """LRU tier demotion + restage policy over one ``HbmBlockStore``."""

    def __init__(
        self,
        store,
        stats: Optional[StatsAggregator] = None,
        epoch_ms: int = 0,
        restage_on_fetch: bool = True,
    ) -> None:
        self._store = store
        self._stats = stats if stats is not None else StatsAggregator()
        self.epoch_ms = int(epoch_ms)
        self.restage_on_fetch = restage_on_fetch
        self._access: Dict[Tuple[int, int], int] = {}  #: guarded by self._lock
        self._clock = 0  #: guarded by self._lock
        self._demotions = 0  #: guarded by self._lock
        self._restages = 0  #: guarded by self._lock
        self._closed = False  #: guarded by self._lock
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()

    # -- access tracking / restage-on-fetch ------------------------------
    def on_access(self, shuffle_id: int, round_idx: int) -> None:
        """Store hook: a block of ``(shuffle_id, round_idx)`` is being read.
        Bumps the LRU clock; a disk-tier round is restaged first so the fetch
        (and the rest of its fan-in) serves from RAM."""
        with self._lock:
            self._clock += 1
            self._access[(shuffle_id, round_idx)] = self._clock
            restage = self.restage_on_fetch and not self._closed
        if restage and self._store.round_tier(shuffle_id, round_idx) == "disk":
            self.restage(shuffle_id, round_idx)

    def forget_shuffle(self, shuffle_id: int) -> None:
        """Store hook on ``remove_shuffle``: drop the shuffle's LRU-clock
        entries so the access table can't grow monotonically across shuffle
        lifetimes (and a recycled shuffle id can't inherit the old id's
        recency, surviving demotion sweeps it should lose)."""
        with self._lock:
            for key in [k for k in self._access if k[0] == shuffle_id]:
                del self._access[key]

    def restage(self, shuffle_id: int, round_idx: int) -> bool:
        """Promote one round disk -> host, timed into ``eviction.restage``.
        Raises TenantQuotaExceededError when the owning tenant has no quota
        headroom left for the round's bytes."""
        op = OperationStats()
        moved = self._store.restage_round(shuffle_id, round_idx)
        if moved:
            op.mark_done(self._store.round_bytes(shuffle_id, round_idx))
            self._stats.record("eviction.restage", op)
            with self._lock:
                self._restages += 1
        return moved

    # -- demotion ---------------------------------------------------------
    def run_epoch(self, max_demotions: Optional[int] = None) -> int:
        """One demotion sweep: order every demotable sealed round by LRU
        clock (never-fetched rounds first) and demote each one tier, up to
        ``max_demotions`` (None = all candidates).  Returns demotion count."""
        candidates = self._store.eviction_candidates()
        with self._lock:
            access = dict(self._access)
        candidates.sort(key=lambda c: (access.get((c[0], c[1]), 0), -c[3]))
        demoted = 0
        for sid, rnd, _tier, _nbytes in candidates:
            if max_demotions is not None and demoted >= max_demotions:
                break
            if self._store.demote_round(sid, rnd) is not None:
                demoted += 1
        if demoted:
            with self._lock:
                self._demotions += demoted
            self._stats.record_counters("eviction", demotions=demoted)
        return demoted

    # -- restage planning -------------------------------------------------
    def restage_plan(
        self, rounds: Sequence[Tuple[int, int]]
    ) -> List[Tuple[int, int]]:
        """Order ``(shuffle_id, round_idx)`` pairs for bulk restage: ascending
        staged footprint (arXiv:2112.01075's memory-footprint-aware ordering
        applied to tier promotion), ties broken by round order so the plan is
        deterministic across processes."""
        return sorted(
            rounds,
            key=lambda r: (self._store.round_bytes(r[0], r[1]), r[0], r[1]),
        )

    def restage_all(self, shuffle_id: int) -> int:
        """Bring every disk-tier round of a shuffle back to host RAM, in
        footprint-bounded plan order.  Returns the number restaged."""
        demoted = [
            (sid, rnd)
            for sid, rnd, tier, _ in self._store.eviction_candidates()
            if sid == shuffle_id and tier == "disk"
        ]
        count = 0
        for sid, rnd in self.restage_plan(demoted):
            if self.restage(sid, rnd):
                count += 1
        return count

    # -- background epochs -------------------------------------------------
    def start(self) -> None:
        """Run :meth:`run_epoch` every ``epoch_ms`` on a daemon thread.
        No-op when epoch_ms == 0 (manual epochs only)."""
        if self.epoch_ms <= 0 or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._epoch_loop, name="sparkucx-eviction", daemon=True
        )
        self._thread.start()

    def _epoch_loop(self) -> None:
        while True:
            self._wake.wait(timeout=self.epoch_ms / 1000.0)
            with self._lock:
                if self._closed:
                    return
            try:
                self.run_epoch()
            except Exception:
                # Eviction is best-effort background hygiene: a transient
                # store error (shuffle being removed mid-sweep) must not kill
                # the epoch thread.
                pass

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- observability -----------------------------------------------------
    def eviction_stats(self) -> Dict[str, int]:
        """Demotion/restage counters + restage tail latency, for report()."""
        with self._lock:
            demotions, restages = self._demotions, self._restages
        summ = self._stats.summary("eviction.restage")
        p99 = getattr(summ, "p99_ns", None) if summ is not None else None
        return {
            "demotions": demotions,
            "restages": restages,
            "restage_p99_ns": int(p99) if p99 is not None else 0,
        }
