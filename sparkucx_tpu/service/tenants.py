"""Tenant registry + admission control (the multi-tenant half of ROADMAP 4).

One shuffle service, many Spark applications: each app registers under its
``app_id`` with an HBM byte quota, and every store region allocation is
admission-checked against that budget at the moment the bytes are claimed
(``HbmBlockStore`` calls :meth:`TenantRegistry.charge` under its own lock from
``close_partition`` / ``write_partition_device``).  An over-quota write raises
the typed :class:`~sparkucx_tpu.core.operation.TenantQuotaExceededError`
instead of eating a neighbor tenant's HBM; an operation naming an app that
never registered raises
:class:`~sparkucx_tpu.core.operation.UnknownTenantError`.

Shuffle ids become ``(app_id, shuffle_id)``: every tenant keeps its own local
shuffle-id namespace and the registry translates to a process-unique internal
id (:meth:`sid_for` / :meth:`translate`) used by the store and transport.  On
the wire the tenant rides as a self-describing ``FETCH_BLOCK_REQ`` header
extension (transport/peer.py) — absent by default, so single-tenant frames
stay byte-identical to the golden captures.

Fairness: the reduce-side ``CreditGate`` (transport/pipeline.py) is
generalized here to per-tenant byte budgets — :meth:`gate` hands out one gate
per tenant, and the serving plane acquires reply bytes against the requesting
tenant's gate, so one tenant's fan-in cannot starve every lane.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from sparkucx_tpu.core.operation import TenantQuotaExceededError, UnknownTenantError
from sparkucx_tpu.transport.pipeline import CreditGate

#: Internal shuffle ids allocated for tenant-owned shuffles start here, far
#: above any id a single-tenant caller passes directly, so translated and
#: untranslated ids never collide in one store.
TENANT_SID_BASE = 1 << 20


class Tenant:
    """One registered application: quota, usage, and its wire-credit gate."""

    def __init__(self, app_id: str, hbm_quota_bytes: int, credit_bytes: int) -> None:
        self.app_id = app_id
        #: HBM staging budget in bytes; 0 = unlimited (no admission checks).
        self.hbm_quota_bytes = int(hbm_quota_bytes)
        #: Per-tenant serving-plane byte budget (CreditGate budget); 0 = no gate.
        self.credit_bytes = int(credit_bytes)
        self.used_bytes = 0  #: guarded by TenantRegistry._lock
        self._gate: Optional[CreditGate] = None  #: guarded by TenantRegistry._lock

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Tenant({self.app_id!r}, used={self.used_bytes},"
            f" quota={self.hbm_quota_bytes})"
        )


class TenantRegistry:
    """Thread-safe registry of tenants and their shuffle-id namespaces.

    The registry is the single admission-control authority of a serving
    process: the store charges/releases HBM bytes through it, the transport
    translates ``(app_id, local shuffle id)`` pairs through it, and the
    serving plane draws per-tenant wire credits from it.
    """

    def __init__(
        self,
        default_quota_bytes: int = 0,
        default_credit_bytes: int = 0,
    ) -> None:
        #: Quota applied when ``register`` is called without one
        #: (``spark.shuffle.tpu.tenants.hbmQuotaBytes``); 0 = unlimited.
        self.default_quota_bytes = int(default_quota_bytes)
        #: Serving-plane CreditGate budget per tenant; 0 disables the gates.
        self.default_credit_bytes = int(default_credit_bytes)
        self._tenants: Dict[str, Tenant] = {}  #: guarded by self._lock
        self._sids: Dict[Tuple[str, int], int] = {}  #: guarded by self._lock
        self._next_sid = TENANT_SID_BASE  #: guarded by self._lock
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------
    def register(
        self,
        app_id: str,
        hbm_quota_bytes: Optional[int] = None,
        credit_bytes: Optional[int] = None,
    ) -> Tenant:
        """Register (or re-register) an application.  Re-registering updates
        the budgets but keeps usage and the shuffle-id namespace — the
        executor-restart case, where the app reconnects mid-flight."""
        with self._lock:
            t = self._tenants.get(app_id)
            if t is None:
                t = Tenant(
                    app_id,
                    self.default_quota_bytes if hbm_quota_bytes is None else hbm_quota_bytes,
                    self.default_credit_bytes if credit_bytes is None else credit_bytes,
                )
                self._tenants[app_id] = t
            else:
                if hbm_quota_bytes is not None:
                    t.hbm_quota_bytes = int(hbm_quota_bytes)
                if credit_bytes is not None:
                    t.credit_bytes = int(credit_bytes)
            return t

    def unregister(self, app_id: str) -> None:
        """Drop a tenant: its charges, its shuffle-id translations, its gate.
        Unknown app_ids are ignored (unregister is idempotent)."""
        with self._lock:
            self._tenants.pop(app_id, None)
            for key in [k for k in self._sids if k[0] == app_id]:
                del self._sids[key]

    def resolve(self, app_id: str) -> Tenant:
        """The tenant for ``app_id``, or a typed UnknownTenantError."""
        with self._lock:
            t = self._tenants.get(app_id)
        if t is None:
            raise UnknownTenantError(app_id)
        return t

    def known(self, app_id: str) -> bool:
        with self._lock:
            return app_id in self._tenants

    def app_ids(self):
        with self._lock:
            return sorted(self._tenants)

    # -- (app_id, shuffle_id) namespace --------------------------------
    def sid_for(self, app_id: str, shuffle_id: int) -> int:
        """Get-or-allocate the internal shuffle id for a tenant's local
        ``shuffle_id``.  The allocating side (the app creating its shuffle)
        uses this; serving-side lookups use :meth:`translate`."""
        with self._lock:
            if app_id not in self._tenants:
                raise UnknownTenantError(app_id, "register before creating shuffles")
            key = (app_id, int(shuffle_id))
            sid = self._sids.get(key)
            if sid is None:
                sid = self._next_sid
                self._next_sid += 1
                self._sids[key] = sid
            return sid

    def translate(self, app_id: str, shuffle_id: int) -> int:
        """Serving-side translation of a wire ``(app_id, shuffle_id)`` pair to
        the internal store id.  Unknown tenants raise UnknownTenantError;
        a known tenant with an unknown local shuffle id returns the local id
        untranslated (the store then reports its usual unknown-shuffle error,
        which the wire maps to block-not-found — retryable, unlike tenant
        errors)."""
        with self._lock:
            if app_id not in self._tenants:
                raise UnknownTenantError(app_id)
            return self._sids.get((app_id, int(shuffle_id)), int(shuffle_id))

    # -- admission control ---------------------------------------------
    def charge(self, app_id: str, shuffle_id: int, nbytes: int) -> None:
        """Claim ``nbytes`` of HBM staging against the tenant's quota.
        Called by the store at region-allocation time (and at restage time by
        the eviction manager), under the store lock — this lock nests inside
        it, never the other way around."""
        if nbytes <= 0:
            return
        with self._lock:
            t = self._tenants.get(app_id)
            if t is None:
                raise UnknownTenantError(app_id, "charge on unregistered tenant")
            if t.hbm_quota_bytes and t.used_bytes + nbytes > t.hbm_quota_bytes:
                raise TenantQuotaExceededError(
                    app_id,
                    shuffle_id,
                    requested=nbytes,
                    quota=t.hbm_quota_bytes,
                    used=t.used_bytes,
                )
            t.used_bytes += nbytes

    def release(self, app_id: str, nbytes: int) -> None:
        """Return previously charged bytes (shuffle removed, round demoted to
        disk, store closed).  Tolerates unknown tenants — release must never
        fail a cleanup path."""
        if nbytes <= 0:
            return
        with self._lock:
            t = self._tenants.get(app_id)
            if t is not None:
                t.used_bytes = max(0, t.used_bytes - nbytes)

    def usage(self, app_id: str) -> int:
        with self._lock:
            t = self._tenants.get(app_id)
            return 0 if t is None else t.used_bytes

    # -- per-tenant wire credits ----------------------------------------
    def gate(self, app_id: str) -> Optional[CreditGate]:
        """The tenant's serving-plane CreditGate (lazily created), or None
        when the tenant has no credit budget — callers skip gating then.
        Unknown tenants raise, like every other tenant-addressed operation."""
        with self._lock:
            t = self._tenants.get(app_id)
            if t is None:
                raise UnknownTenantError(app_id)
            if t.credit_bytes <= 0:
                return None
            if t._gate is None:
                t._gate = CreditGate(t.credit_bytes)
            return t._gate

    # -- observability ---------------------------------------------------
    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant usage snapshot: used/quota bytes and shuffle count."""
        with self._lock:
            out = {}
            for app_id, t in self._tenants.items():
                out[app_id] = {
                    "used_bytes": t.used_bytes,
                    "quota_bytes": t.hbm_quota_bytes,
                    "num_shuffles": sum(1 for k in self._sids if k[0] == app_id),
                }
            return out
