"""Shared event-loop serving plane: one selector, a bounded worker pool.

The historical serving planes spawn a thread per accepted connection
(shuffle/daemon.py, transport/peer.py BlockServer) — fine for a handful of
reducers, a non-starter for production fan-in where thousands of reducers
hold idle connections between fetch windows.  This reactor holds every idle
connection in ONE ``selectors`` event loop and only occupies a worker thread
while a connection actually has a frame to serve:

* the loop thread ``select()``\\ s over all registered sockets,
* a readable listener accepts (drains the accept queue) and hands each new
  connection to the owner's ``on_accept`` callback, which registers it,
* a readable connection is *unregistered* and a ``serve_once(conn)`` task is
  submitted to the bounded pool; the task reads exactly one frame with the
  owner's existing blocking frame reader, dispatches it, and returns True to
  re-arm the connection (or False to drop it),
* re-arming goes back through the loop thread (a self-pipe wakes the
  ``select``), so selector mutation stays single-threaded.

Because readiness is edge-driven per frame, a connection is never owned by
two workers at once, and the owner's per-connection serve code runs unchanged
— same blocking reads, same timeouts, same error handling — just multiplexed
over ``workers`` threads instead of one thread per connection.
"""

from __future__ import annotations

import selectors
import socket
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

#: Pool size used when the reactor is requested (tenants.enabled) but
#: ``server.workers`` was left at 0.
DEFAULT_WORKERS = 8


class Reactor:
    """Selector loop + bounded worker pool for frame-at-a-time serving."""

    def __init__(
        self,
        workers: int = 0,
        name: str = "sparkucx-reactor",
        accept_backlog: int = 0,
    ) -> None:
        self.workers = int(workers) if workers and workers > 0 else DEFAULT_WORKERS
        #: Load-shedding bound (``server.acceptBacklog``): with more than this
        #: many resident connections, new accepts get a best-effort ServerBusy
        #: frame and an immediate close instead of queueing unboundedly.
        #: 0 = off (accept everything), the byte-identical default.
        self.accept_backlog = int(accept_backlog)
        self._sel = selectors.DefaultSelector()
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix=f"{name}-worker"
        )
        # Self-pipe: worker threads and external callers wake the select() to
        # apply selector mutations on the loop thread.
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, ("wake", None, None))
        self._pending: List[Tuple] = []  #: guarded by self._lock
        self._conns: Dict[socket.socket, Tuple] = {}  #: guarded by self._lock
        self._listeners: List[socket.socket] = []  #: guarded by self._lock
        self._closed = False  #: guarded by self._lock
        self._frames_served = 0  #: worker-pool dispatches; guarded by self._lock
        self._sheds = 0  #: connections shed over accept_backlog; guarded by self._lock
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    # -- registration ---------------------------------------------------
    def add_listener(self, sock: socket.socket, on_accept: Callable[[socket.socket], None]) -> None:
        """Serve accepts from ``sock`` (made non-blocking) on the loop thread;
        ``on_accept(conn)`` must register the new connection (cheaply)."""
        sock.setblocking(False)
        with self._lock:
            if self._closed:
                raise RuntimeError("reactor is closed")
            self._listeners.append(sock)
            self._pending.append(("listener", sock, on_accept, None))
        self._wake()

    def add_connection(
        self,
        conn: socket.socket,
        serve_once: Callable[[socket.socket], bool],
        on_close: Optional[Callable[[socket.socket], None]] = None,
    ) -> None:
        """Arm ``conn``: next readable event submits ``serve_once(conn)`` to
        the pool.  ``serve_once`` returns True to re-arm, False to drop (then
        ``on_close(conn)`` runs and the socket is closed)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("reactor is closed")
            self._conns[conn] = (serve_once, on_close)
            self._pending.append(("conn", conn, serve_once, on_close))
        self._wake()

    def drop_connection(self, conn: socket.socket) -> None:
        """Forget a connection without closing it (the owner took it over)."""
        with self._lock:
            self._conns.pop(conn, None)
            self._pending.append(("forget", conn, None, None))
        self._wake()

    @property
    def num_connections(self) -> int:
        with self._lock:
            return len(self._conns)

    def stats(self) -> Dict[str, int]:
        """Serving-plane health for the metrics registry: resident
        connections, pool width, frames dispatched to workers so far."""
        with self._lock:
            return {
                "connections": len(self._conns),
                "workers": self.workers,
                "frames_served": self._frames_served,
                "sheds": self._sheds,
            }

    # -- internals ------------------------------------------------------
    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    def _apply_pending(self) -> None:
        with self._lock:
            ops, self._pending = self._pending, []
        for kind, sock, a, b in ops:
            try:
                if kind == "listener":
                    self._sel.register(sock, selectors.EVENT_READ, ("listener", a, b))
                elif kind == "conn":
                    self._sel.register(sock, selectors.EVENT_READ, ("conn", a, b))
                elif kind == "forget":
                    try:
                        self._sel.unregister(sock)
                    except (KeyError, ValueError):
                        pass
            except (KeyError, ValueError, OSError):
                # Socket died between queueing and registration; the worker
                # that owned it already ran its close path.
                continue

    def _loop(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    break
            self._apply_pending()
            try:
                events = self._sel.select(timeout=0.5)
            except OSError:
                continue
            for key, _ in events:
                kind, a, b = key.data
                if kind == "wake":
                    try:
                        while self._wake_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                elif kind == "listener":
                    self._drain_accepts(key.fileobj, a)
                else:  # conn
                    try:
                        self._sel.unregister(key.fileobj)
                    except (KeyError, ValueError):
                        pass
                    self._pool.submit(self._serve, key.fileobj, a, b)

    def _drain_accepts(self, sock: socket.socket, on_accept) -> None:
        while True:
            try:
                conn, _ = sock.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            if self.accept_backlog > 0:
                with self._lock:
                    shed = len(self._conns) >= self.accept_backlog
                    if shed:
                        self._sheds += 1
                if shed:
                    self._shed(conn)
                    continue
            try:
                on_accept(conn)
            except Exception:
                try:
                    conn.close()
                except OSError:
                    pass

    @staticmethod
    def _shed(conn: socket.socket) -> None:
        """Refuse an over-backlog connection with a typed busy reply.

        Runs ON the loop thread, so it must never block: the ServerBusy frame
        goes out best-effort on a non-blocking socket (20 bytes fits any sane
        send buffer) and the connection closes either way.  Clients surface
        the frame — or the bare reset — as a retryable condition.
        """
        from sparkucx_tpu.core.definitions import AmId, pack_frame

        try:
            conn.setblocking(False)
            conn.send(pack_frame(AmId.SERVER_BUSY))
        except OSError:
            pass
        try:
            conn.close()
        except OSError:
            pass

    def _serve(self, conn: socket.socket, serve_once, on_close) -> None:
        keep = False
        with self._lock:
            self._frames_served += 1
        try:
            keep = bool(serve_once(conn))
        except Exception:
            keep = False
        with self._lock:
            closed = self._closed
            if not keep or closed:
                self._conns.pop(conn, None)
        if keep and not closed:
            with self._lock:
                self._pending.append(("conn", conn, serve_once, on_close))
            self._wake()
            return
        if on_close is not None:
            try:
                on_close(conn)
            except Exception:
                pass
        try:
            conn.close()
        except OSError:
            pass

    def close(self) -> None:
        """Stop the loop, drain workers, close every held socket."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wake()
        me = threading.current_thread()
        if me is not self._thread:
            self._thread.join(timeout=5)
        # close() can arrive FROM a pool worker (a served frame asked the
        # owner to shut down) — waiting would self-join that worker
        self._pool.shutdown(wait=me not in getattr(self._pool, "_threads", ()))
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
            listeners, self._listeners = self._listeners, []
        for sock in conns + listeners:
            try:
                self._sel.unregister(sock)
            except (KeyError, ValueError, OSError):
                pass
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._sel.unregister(self._wake_r)
        except (KeyError, ValueError, OSError):
            pass
        self._wake_r.close()
        self._wake_w.close()
        self._sel.close()
