"""Multi-tenant shuffle service layer (ROADMAP item 4).

Production Spark clusters run hundreds of concurrent applications against one
shuffle service; the rest of this codebase assumes a single app owns the chip.
This package layers multi-tenancy over the existing cluster without touching
its single-tenant hot paths:

* :mod:`sparkucx_tpu.service.tenants` — per-application registration
  (``app_id``), HBM byte quotas with admission control at the store's
  region-allocation point, ``(app_id, shuffle_id)`` -> internal shuffle-id
  translation, and per-tenant wire credit budgets (the ``CreditGate``
  generalized so one tenant cannot starve the lanes).
* :mod:`sparkucx_tpu.service.eviction` — epoch/LRU demotion of sealed rounds
  down the store's existing tiers (HBM-resident ``jax.Array`` -> host
  snapshot -> ``np.memmap`` spill) with transparent restage-on-fetch, restage
  ordering chosen to bound peak staging footprint (the memory-footprint-aware
  redistribution planning of arXiv:2112.01075 applied to tier scheduling).
* :mod:`sparkucx_tpu.service.reactor` — a shared ``selectors``-based event
  loop + bounded worker pool that replaces thread-per-connection serving in
  ``shuffle/daemon.py`` and the ``transport/peer.py`` block server, so one
  process holds thousands of reducer connections.

Everything is gated behind ``spark.shuffle.tpu.tenants.enabled`` (default
off): with it off no tenant state exists, no wire extension is sent, and the
serving planes keep their historical thread-per-connection behavior —
byte-identical to the single-tenant build.
"""

from sparkucx_tpu.service.eviction import EvictionManager
from sparkucx_tpu.service.reactor import Reactor
from sparkucx_tpu.service.tenants import Tenant, TenantRegistry

__all__ = ["EvictionManager", "Reactor", "Tenant", "TenantRegistry"]
