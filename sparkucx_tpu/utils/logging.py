"""Namespaced logging (the Spark ``Logging`` trait analogue).

The reference logs through Spark's Logging trait everywhere, with a dedicated
named logger for the writer ("LEO", NvkvShuffleMapOutputWriter.scala:71-73) and a
compile-gated debug wrapper (``nvkvLogDebug``, NvkvHandler.scala:42-48).  Here:
one namespace root, per-module child loggers, and an env-tunable level
(``SPARKUCX_TPU_LOG=debug`` — the UCX_LOG_LEVEL analogue, test.sh:126-127).
"""

from __future__ import annotations

import logging
import os

ROOT = "sparkucx_tpu"

_configured = False


def _configure() -> None:
    global _configured
    if _configured:
        return
    _configured = True
    root = logging.getLogger(ROOT)
    level_name = os.environ.get("SPARKUCX_TPU_LOG", "warning").upper()
    root.setLevel(getattr(logging, level_name, logging.WARNING))
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        root.addHandler(handler)
    root.propagate = False


def get_logger(name: str) -> logging.Logger:
    _configure()
    return logging.getLogger(f"{ROOT}.{name}")
