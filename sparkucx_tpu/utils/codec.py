"""Typed, non-executing record codec — the data plane's default serializer.

The shuffle data plane delivers peer-produced bytes into the reduce-side
record pipeline (shuffle/reader.py).  Spark's default ``JavaSerializer``
deserializes attacker-controllable streams with full object construction;
this build's control plane explicitly bans that (parallel/bootstrap.py: "must
not execute peer-controlled bytes"), and the same rule applies here: the
default codec decodes a closed set of value shapes with explicit type tags
and bounds checks, and nothing else.  ``pickle`` remains available as an
explicit opt-in for trusted single-host runs (see shuffle/reader.py's
``pickle_deserializer``).

Wire format, per record (records concatenate back-to-back; each is
self-delimiting):

    N                      None
    T / F                  True / False
    i <int64 be>           int fitting 64 bits
    j <u32 len> <bytes>    arbitrary-precision int (two's complement, be)
    f <float64 be>         float
    s <u32 len> <utf8>     str
    b <u32 len> <bytes>    bytes
    t <u32 count> <items>  tuple
    l <u32 count> <items>  list
    m <u32 count> <k v>*   dict

Anything else — unknown tags, truncated frames, nesting deeper than
``MAX_DEPTH`` — raises ``ValueError``.  Decoding allocates only containers
and scalars; there is no code path to object construction or callables.
"""

from __future__ import annotations

import struct
from typing import Any, Iterable, Iterator

import numpy as np

#: Container-nesting bound: a crafted frame of a million nested tuples would
#: otherwise turn the recursive decoder into a stack-overflow primitive.
MAX_DEPTH = 100

_U32 = struct.Struct(">I")
_I64 = struct.Struct(">q")
_F64 = struct.Struct(">d")


def _encode(obj: Any, out: bytearray, depth: int = 0) -> None:
    if depth > MAX_DEPTH:
        raise ValueError(f"record nests deeper than MAX_DEPTH={MAX_DEPTH}")
    if obj is None:
        out += b"N"
    elif obj is True:
        out += b"T"
    elif obj is False:
        out += b"F"
    elif isinstance(obj, (bool, np.bool_)):  # np.bool_ is not `is True`
        out += b"T" if bool(obj) else b"F"
    elif isinstance(obj, (int, np.integer)):
        v = int(obj)
        if -(2**63) <= v < 2**63:
            out += b"i"
            out += _I64.pack(v)
        else:
            raw = v.to_bytes((v.bit_length() + 8) // 8, "big", signed=True)
            out += b"j"
            out += _U32.pack(len(raw))
            out += raw
    elif isinstance(obj, (float, np.floating)):
        out += b"f"
        out += _F64.pack(float(obj))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out += b"s"
        out += _U32.pack(len(raw))
        out += raw
    elif isinstance(obj, (bytes, bytearray)):
        # zero-copy append: bytearray.__iadd__ copies straight out of the
        # source buffer — materializing an intermediate bytes() doubled the
        # allocation on the map-side hot path (PERF.md codec microbench)
        out += b"b"
        out += _U32.pack(len(obj))
        out += obj
    elif isinstance(obj, memoryview):
        # len() counts ELEMENTS, not bytes, on shaped views — use nbytes and
        # flatten to a byte view; only a non-contiguous view pays a copy
        mv = obj if obj.contiguous else memoryview(obj.tobytes())
        out += b"b"
        out += _U32.pack(mv.nbytes)
        out += mv.cast("B")
    elif isinstance(obj, tuple):
        out += b"t"
        out += _U32.pack(len(obj))
        for item in obj:
            _encode(item, out, depth + 1)
    elif isinstance(obj, list):
        out += b"l"
        out += _U32.pack(len(obj))
        for item in obj:
            _encode(item, out, depth + 1)
    elif isinstance(obj, dict):
        out += b"m"
        out += _U32.pack(len(obj))
        for k, v in obj.items():
            _encode(k, out, depth + 1)
            _encode(v, out, depth + 1)
    else:
        raise TypeError(
            f"type {type(obj).__name__} is outside the safe codec's value set "
            "(None/bool/int/float/str/bytes/tuple/list/dict); pass an explicit "
            "pickle serializer for trusted single-host runs"
        )


def encode_record(obj: Any) -> bytes:
    """Encode one record into the typed wire format."""
    out = bytearray()
    _encode(obj, out)
    return bytes(out)


def encode_records(records: Iterable[Any]) -> bytes:
    """Encode a record stream (back-to-back self-delimiting frames)."""
    out = bytearray()
    for rec in records:
        _encode(rec, out)
    return bytes(out)


def _need(payload: bytes, pos: int, n: int) -> None:
    if pos + n > len(payload):
        raise ValueError(
            f"truncated record frame: need {n} bytes at offset {pos}, "
            f"have {len(payload) - pos}"
        )


def _decode(payload: bytes, pos: int, depth: int = 0):
    if depth > MAX_DEPTH:
        raise ValueError(f"record nests deeper than MAX_DEPTH={MAX_DEPTH}")
    _need(payload, pos, 1)
    tag = payload[pos : pos + 1]
    pos += 1
    if tag == b"N":
        return None, pos
    if tag == b"T":
        return True, pos
    if tag == b"F":
        return False, pos
    if tag == b"i":
        _need(payload, pos, 8)
        return _I64.unpack_from(payload, pos)[0], pos + 8
    if tag == b"f":
        _need(payload, pos, 8)
        return _F64.unpack_from(payload, pos)[0], pos + 8
    if tag in (b"j", b"s", b"b"):
        _need(payload, pos, 4)
        (n,) = _U32.unpack_from(payload, pos)
        pos += 4
        _need(payload, pos, n)
        raw = payload[pos : pos + n]
        pos += n
        if tag == b"j":
            return int.from_bytes(raw, "big", signed=True), pos
        if tag == b"s":
            return str(raw, "utf-8"), pos
        return bytes(raw), pos
    if tag in (b"t", b"l", b"m"):
        _need(payload, pos, 4)
        (n,) = _U32.unpack_from(payload, pos)
        pos += 4
        if tag == b"m":
            d = {}
            for _ in range(n):
                k, pos = _decode(payload, pos, depth + 1)
                v, pos = _decode(payload, pos, depth + 1)
                try:
                    d[k] = v
                except TypeError:
                    # container-typed key in a crafted frame: keep the
                    # documented ValueError error contract
                    raise ValueError(
                        f"unhashable map key of type {type(k).__name__}"
                    ) from None
            return d, pos
        items = []
        for _ in range(n):
            item, pos = _decode(payload, pos, depth + 1)
            items.append(item)
        return (tuple(items) if tag == b"t" else items), pos
    raise ValueError(f"unknown record tag {bytes(tag)!r} at offset {pos - 1}")


def decode_records(payload) -> Iterator[Any]:
    """Decode a stream of records; raises ``ValueError`` on any malformation
    (unknown tag, truncation, over-deep nesting) — never executes anything.
    ``payload`` may be any bytes-like (``bytes`` or a read-only ``memoryview``
    served zero-copy by the fetch iterator, shuffle/reader.py)."""
    pos = 0
    n = len(payload)
    while pos < n:
        rec, pos = _decode(payload, pos)
        yield rec
