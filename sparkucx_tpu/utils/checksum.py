"""CRC32C (Castagnoli) — the wire-integrity checksum (conf.wire_checksum).

CRC32C is the checksum the storage/network world standardized on for exactly
this job (iSCSI, ext4, RDMA NICs, Hadoop block transfer) because its error
detection at short message lengths beats CRC32/IEEE and hardware computes it
for free (SSE4.2 ``crc32`` instruction, ARMv8 ``CRC32C``).  Python's stdlib
only ships the IEEE polynomial (``zlib.crc32``), so this module carries a
table-driven software implementation of the reflected Castagnoli polynomial
``0x82F63B78`` — no new dependency, byte-compatible with every hardware
implementation (google/crc32c test vectors pinned in tests/test_wire.py).

The byte-at-a-time table walk runs at CPython speed (tens of MB/s), which is
fine for what it guards: the knob defaults off, and when on it trades wire
throughput for end-to-end integrity — the same trade Hadoop's
``dfs.checksum.type=CRC32C`` makes.  Deployments that need both swap in a
hardware binding behind this function; the wire format doesn't change.
"""

from __future__ import annotations

_POLY = 0x82F63B78  # Castagnoli, reflected


def _build_table():
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ _POLY if crc & 1 else crc >> 1
        table.append(crc)
    return tuple(table)


_TABLE = _build_table()


def crc32c(data, value: int = 0) -> int:
    """CRC32C of ``data`` (bytes-like), continuing from ``value`` (a previous
    call's return) for incremental use.  Returns an unsigned 32-bit int."""
    crc = (value ^ 0xFFFFFFFF) & 0xFFFFFFFF
    table = _TABLE
    for b in bytes(data):
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF
