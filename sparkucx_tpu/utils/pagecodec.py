"""Lossless per-page columnar codecs for exchange payloads (compress tier a).

The striped wire's FETCH_BLOCK_CHUNK frames are self-addressing — (tag, block,
offset-within-block) — so every chunk can be encoded and decoded independently
of its siblings: the codec-id/raw-len pair rides as a chunk-header extension
(core/definitions.py) and each lane's recv thread decodes straight into the
chunk's final buffer offset.  The codecs here are the page-level encoders that
back that path (and the REPLICA_PUT body compression): numpy-vectorized, no
per-byte Python loops, tuned for the shapes the data plane actually moves —
int32 exchange rows with low-cardinality key columns (dict), word runs from
clustered keys and padding/sealed zeros (rle), and sorted/clustered numeric
columns (delta + zigzag, byte-aligned widths).

Every codec treats the page as little-endian u32 words plus a <=3-byte raw
tail, because u32 words ARE the unit of this data plane (ops/columnar.py
packs every lane as int32).  That choice is also what makes the encoders
fast enough to sit on the serve path: word-level RLE sees the period-4
patterns that byte-level RLE is blind to, and the dict encoder can afford a
full ``np.unique`` (sort-only, no inverse — the inverse comes from a direct
or hashed lookup table, never from the 20x-slower ``return_inverse`` path).

Contract:

* ``encode_page(codec_id, data) -> bytes | None`` — None means "not
  profitable / not applicable"; the caller ships the page raw
  (``CODEC_RAW``).  An encoder NEVER returns an encoding as large as the
  input, so codec-id raw on the wire always means "payload == page bytes".
* ``decode_page(codec_id, payload, out)`` — decodes exactly ``out.nbytes``
  bytes into ``out`` or raises :class:`CodecError`.  Every length is checked
  against the payload's actual size BEFORE any array is built: truncated,
  oversized, or internally inconsistent encodings raise, they never over-read
  or scatter out of bounds.  The transport converts a ``CodecError`` on the
  fetch path into ``BlockCorruptError`` so corruption enters the reducer's
  existing retry/failover path (transport/peer.py).

Codec ids are wire format — pinned by tests/test_wire.py alongside the AM
ids; renumbering is a protocol break.
"""

from __future__ import annotations

import struct
from typing import Optional

import numpy as np

#: Wire codec ids (chunk-header extension field, core/definitions.py).
CODEC_RAW = 0  #: payload is the page verbatim (unprofitable-page fallback)
CODEC_DICT = 1  #: u32-word dictionary + u8/u16 indices (low-cardinality pages)
CODEC_RLE = 2  #: u32-word run-length (clustered keys / padding / zero runs)
CODEC_DELTA = 3  #: u32-word zigzag deltas, byte-aligned (sorted/clustered pages)

#: conf ``compress.codec`` values -> wire codec id ('off' never reaches here).
WIRE_CODECS = {"dict": CODEC_DICT, "rle": CODEC_RLE, "delta": CODEC_DELTA}

CODEC_NAMES = {CODEC_RAW: "raw", CODEC_DICT: "dict", CODEC_RLE: "rle", CODEC_DELTA: "delta"}

_RLE_HDR = struct.Struct("<I")  # nruns (u32 run lengths + u32 run values follow)
_DICT_HDR = struct.Struct("<IIB")  # nwords, nuniq, index width (1|2)
_DELTA_HDR = struct.Struct("<IIB")  # nwords, first word, bytes per delta (1|2|3)

#: dict-encode inverse strategy bounds: alphabets whose value span fits a
#: direct LUT use one; wider alphabets up to this cardinality go through a
#: collision-checked multiplicative hash table (2**_DICT_HASH_BITS slots);
#: anything bigger falls back to searchsorted (correct, just slower — such
#: pages also compress worst, u16 indices cap the ratio at 2x).
_DICT_LUT_SPAN = 1 << 22
_DICT_HASH_MAX = 1 << 10
_DICT_HASH_BITS = 22
_DICT_HASH_MULTS = (
    np.uint64(0x9E3779B97F4A7C15),
    np.uint64(0xC2B2AE3D27D4EB4F),
    np.uint64(0xFF51AFD7ED558CCD),
    np.uint64(0x2545F4914F6CDD1D),
)


class CodecError(ValueError):
    """A page failed to decode: truncated/oversized payload, inconsistent
    header fields, or out-of-range dictionary indices.  Deliberately a
    ``ValueError`` subclass — the same malformed-input contract as
    utils/codec.py — and never allowed to escape the transport as-is (the
    fetch path converts it to ``BlockCorruptError``)."""


def _as_bytes_array(data) -> np.ndarray:
    return np.frombuffer(data, dtype=np.uint8)


# ----------------------------------------------------------------------------
# RLE — u32-word runs
# ----------------------------------------------------------------------------


def _encode_rle(arr: np.ndarray) -> Optional[bytes]:
    # Word-level runs, not byte-level: a clustered low-cardinality int32 key
    # column is a sequence of repeated WORDS, which byte RLE cannot see (the
    # byte stream has period 4, runs of length 1).  Padding/zero pages are
    # word runs too, so nothing is lost on the constant-page case.
    nwords = arr.size // 4
    if nwords == 0:
        return None
    words = arr[: 4 * nwords].view("<u4")
    tail = arr[4 * nwords :]
    change = np.flatnonzero(words[1:] != words[:-1])
    starts = np.concatenate([np.zeros(1, np.int64), change + 1])
    nruns = starts.size
    if _RLE_HDR.size + 8 * nruns + tail.size >= arr.size:
        return None
    bounds = np.concatenate([starts, np.array([nwords], np.int64)])
    lengths = np.diff(bounds).astype("<u4")
    values = words[starts]
    return (
        _RLE_HDR.pack(nruns)
        + lengths.tobytes()
        + values.astype("<u4").tobytes()
        + tail.tobytes()
    )


def _decode_rle(payload: np.ndarray, out: np.ndarray) -> None:
    if payload.size < _RLE_HDR.size:
        raise CodecError(f"rle page truncated: {payload.size} B, need header")
    (nruns,) = _RLE_HDR.unpack_from(payload)
    nwords = out.size // 4
    tail_len = out.size - 4 * nwords
    if payload.size != _RLE_HDR.size + 8 * nruns + tail_len:
        raise CodecError(
            f"rle page claims {nruns} runs ({_RLE_HDR.size + 8 * nruns + tail_len} B)"
            f" but payload is {payload.size} B"
        )
    pos = _RLE_HDR.size
    lengths = payload[pos : pos + 4 * nruns].view("<u4")
    pos += 4 * nruns
    values = payload[pos : pos + 4 * nruns].view("<u4")
    pos += 4 * nruns
    total = int(lengths.sum(dtype=np.int64))
    if total != nwords:
        raise CodecError(
            f"rle runs expand to {total} words, destination holds {nwords}"
        )
    out[: 4 * nwords].view("<u4")[:] = np.repeat(values, lengths.astype(np.int64))
    out[4 * nwords :] = payload[pos:]


# ----------------------------------------------------------------------------
# DICT — u32-word dictionary
# ----------------------------------------------------------------------------


def _dict_inverse(uniq: np.ndarray, words: np.ndarray, idx_dtype) -> np.ndarray:
    """Map every word to its index in ``uniq`` (which covers all of them).

    ``np.unique(return_inverse=True)`` pays an argsort of the whole page —
    measured 20x slower than the sort-only ``np.unique`` — so the inverse is
    rebuilt from the alphabet instead: a direct LUT over the value span when
    it fits, else a multiplicative hash table whose collision freedom is
    verified on the alphabet itself (cheap: the alphabet is small), which
    makes it injective for every word on the page by construction.  No
    per-word validation pass is needed on any path because ``uniq`` came
    from ``words``."""
    base = uniq[0]
    span = int(uniq[-1]) - int(base)
    if span <= _DICT_LUT_SPAN:
        lut = np.empty(span + 1, idx_dtype)
        lut[(uniq - base).astype(np.int64)] = np.arange(uniq.size, dtype=idx_dtype)
        return lut[words - base]
    if uniq.size <= _DICT_HASH_MAX:
        shift = np.uint64(64 - _DICT_HASH_BITS)
        u64 = uniq.astype(np.uint64)
        for mult in _DICT_HASH_MULTS:
            slots = (u64 * mult) >> shift
            if np.unique(slots).size != uniq.size:
                continue  # alphabet collision under this multiplier: next
            lut = np.empty(1 << _DICT_HASH_BITS, idx_dtype)
            lut[slots] = np.arange(uniq.size, dtype=idx_dtype)
            return lut[(words.astype(np.uint64) * mult) >> shift]
    # wide span AND (large or hash-unlucky) alphabet: binary search.  Slower,
    # but such pages are also the worst compressors (u16 indices, ratio <= 2).
    return np.searchsorted(uniq, words).astype(idx_dtype)


def _encode_dict(arr: np.ndarray) -> Optional[bytes]:
    nwords = arr.size // 4
    if nwords == 0:
        return None
    words = arr[: 4 * nwords].view("<u4")
    tail = arr[4 * nwords :]
    uniq = np.unique(words)
    if uniq.size <= 0xFF + 1:
        width, idx_dtype = 1, np.uint8
    elif uniq.size <= 0xFFFF + 1:
        width, idx_dtype = 2, np.dtype("<u2")
    else:
        return None
    size = _DICT_HDR.size + 4 * uniq.size + width * nwords + tail.size
    if size >= arr.size:
        return None
    idx = _dict_inverse(uniq, words, idx_dtype)
    return (
        _DICT_HDR.pack(nwords, uniq.size, width)
        + uniq.astype("<u4").tobytes()
        + idx.tobytes()
        + tail.tobytes()
    )


def _decode_dict(payload: np.ndarray, out: np.ndarray) -> None:
    if payload.size < _DICT_HDR.size:
        raise CodecError(f"dict page truncated: {payload.size} B, need header")
    nwords, nuniq, width = _DICT_HDR.unpack_from(payload)
    if width not in (1, 2):
        raise CodecError(f"dict page has invalid index width {width}")
    tail_len = out.size - 4 * nwords
    if tail_len < 0 or tail_len >= 4:
        raise CodecError(
            f"dict page claims {nwords} words for a {out.size} B destination"
        )
    need = _DICT_HDR.size + 4 * nuniq + width * nwords + tail_len
    if payload.size != need:
        raise CodecError(
            f"dict page needs {need} B ({nwords} words, {nuniq} entries, "
            f"width {width}) but payload is {payload.size} B"
        )
    pos = _DICT_HDR.size
    uniq = payload[pos : pos + 4 * nuniq].view("<u4")
    pos += 4 * nuniq
    idx_dtype = np.uint8 if width == 1 else np.dtype("<u2")
    idx = payload[pos : pos + width * nwords].view(idx_dtype)
    pos += width * nwords
    if nuniq == 0 and nwords:
        raise CodecError("dict page has words but an empty dictionary")
    try:
        # take(mode="raise") bounds-checks every index itself, and the out=
        # form writes straight into the destination — the separate max() scan
        # plus gather-into-temp-then-copy cost a third of decode throughput
        np.take(uniq, idx, out=out[: 4 * nwords].view("<u4"))
    except IndexError:
        raise CodecError("dict page index out of dictionary range") from None
    out[4 * nwords :] = payload[pos:]


# ----------------------------------------------------------------------------
# DELTA — u32-word zigzag deltas, byte-aligned widths
# ----------------------------------------------------------------------------
#
# The first word rides in the header raw: it is a full-magnitude value whose
# zigzag would otherwise force the page-wide delta width to 32 bits (one page
# = one width).  Deltas are modular in the u32 domain (wraparound-exact) and
# packed at 1, 2 or 3 bytes each — byte alignment decodes via dtype casts at
# GB/s where arbitrary bit widths paid two ``packbits`` passes (measured 79
# MB/s, 25x slower); the ratio lost to rounding a width like 13 bits up to 16
# is far smaller than the throughput kept.


def _encode_delta(arr: np.ndarray) -> Optional[bytes]:
    nwords = arr.size // 4
    if nwords == 0:
        return None
    words = arr[: 4 * nwords].view("<u4")
    tail = arr[4 * nwords :]
    d = words[1:] - words[:-1]  # u32 arithmetic: wraparound-exact
    di = d.view(np.int32)
    zz = ((di << 1) ^ (di >> 31)).view(np.uint32)
    top = int(zz.max()) if zz.size else 0
    nbytes = (max(1, top.bit_length()) + 7) // 8
    if nbytes > 3:
        return None
    size = _DELTA_HDR.size + nbytes * (nwords - 1) + tail.size
    if size >= arr.size:
        return None
    if nbytes == 1:
        packed = zz.astype(np.uint8)
    elif nbytes == 2:
        packed = zz.astype("<u2")
    else:
        packed = zz.astype("<u4").view(np.uint8).reshape(-1, 4)[:, :3]
    return (
        _DELTA_HDR.pack(nwords, int(words[0]), nbytes)
        + packed.tobytes()
        + tail.tobytes()
    )


def _decode_delta(payload: np.ndarray, out: np.ndarray) -> None:
    if payload.size < _DELTA_HDR.size:
        raise CodecError(f"delta page truncated: {payload.size} B, need header")
    nwords, first, nbytes = _DELTA_HDR.unpack_from(payload)
    if nbytes not in (1, 2, 3):
        raise CodecError(f"delta page has invalid delta width {nbytes}")
    if nwords == 0:
        raise CodecError("delta page claims zero words")
    tail_len = out.size - 4 * nwords
    if tail_len < 0 or tail_len >= 4:
        raise CodecError(
            f"delta page claims {nwords} words for a {out.size} B destination"
        )
    packed_len = nbytes * (nwords - 1)
    need = _DELTA_HDR.size + packed_len + tail_len
    if payload.size != need:
        raise CodecError(
            f"delta page needs {need} B ({nwords} words x {nbytes} B deltas) "
            f"but payload is {payload.size} B"
        )
    packed = payload[_DELTA_HDR.size : _DELTA_HDR.size + packed_len]
    if nbytes == 1:
        zz = packed.astype(np.uint32)
    elif nbytes == 2:
        zz = packed.view("<u2").astype(np.uint32)
    else:
        b = packed.reshape(-1, 3).astype(np.uint32)
        zz = b[:, 0] | (b[:, 1] << np.uint32(8)) | (b[:, 2] << np.uint32(16))
    d = (zz >> np.uint32(1)) ^ (np.uint32(0) - (zz & np.uint32(1)))
    words = out[: 4 * nwords].view("<u4")
    words[0] = first
    # u32 cumsum wraps mod 2**32 — the exact inverse of the modular diff
    np.cumsum(d, dtype=np.uint32, out=words[1:])
    words[1:] += np.uint32(first)
    out[4 * nwords :] = payload[_DELTA_HDR.size + packed_len :]


# ----------------------------------------------------------------------------
# Entry points
# ----------------------------------------------------------------------------

_ENCODERS = {CODEC_DICT: _encode_dict, CODEC_RLE: _encode_rle, CODEC_DELTA: _encode_delta}
_DECODERS = {CODEC_DICT: _decode_dict, CODEC_RLE: _decode_rle, CODEC_DELTA: _decode_delta}


def encode_page(codec_id: int, data) -> Optional[bytes]:
    """Encode one page under ``codec_id``.  ``data`` is any contiguous
    bytes-like; returns the encoded bytes, or None when the encoding would
    not shrink the page (ship raw).  ``CODEC_RAW`` always returns None."""
    if codec_id == CODEC_RAW:
        return None
    enc = _ENCODERS.get(codec_id)
    if enc is None:
        raise ValueError(f"unknown codec id {codec_id}")
    arr = _as_bytes_array(data)
    if arr.size == 0:
        return None
    return enc(arr)


def decode_page(codec_id: int, payload, out) -> None:
    """Decode ``payload`` (the encoded page) into ``out`` (a writable
    bytes-like of exactly the page's raw size).  Raises :class:`CodecError`
    on ANY malformation — lengths are validated before touching the data, so
    a hostile/corrupt payload can neither over-read nor write out of range."""
    dst = np.frombuffer(out, dtype=np.uint8)
    src = _as_bytes_array(payload)
    if codec_id == CODEC_RAW:
        if src.size != dst.size:
            raise CodecError(
                f"raw page is {src.size} B but destination expects {dst.size} B"
            )
        dst[:] = src
        return
    dec = _DECODERS.get(codec_id)
    if dec is None:
        raise CodecError(f"unknown codec id {codec_id}")
    dec(src, dst)
