"""Transport-level stats aggregation.

The reference exposes per-op stats only (``UcxStats``,
UcxShuffleTransport.scala:36-53) and relies on Spark's shuffle metrics for
aggregates.  With no Spark UI underneath, this module provides the aggregate
view: a ``StatsAggregator`` transports feed each completed operation into, with
latency percentiles and byte totals — what the benchmark prints and what an
operator would scrape.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from sparkucx_tpu.core.operation import OperationStats


@dataclass
class StatsSummary:
    ops: int = 0
    bytes: int = 0
    total_ns: int = 0
    min_ns: Optional[int] = None
    max_ns: Optional[int] = None
    p50_ns: Optional[int] = None
    p99_ns: Optional[int] = None

    @property
    def mean_ns(self) -> float:
        return self.total_ns / self.ops if self.ops else 0.0

    @property
    def throughput_gbps(self) -> float:
        return self.bytes / self.total_ns if self.total_ns else 0.0  # bytes/ns == GB/s


class StatsAggregator:
    """Thread-safe sink for completed OperationStats, bucketed by op kind."""

    _RESERVOIR = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # record() is called from the pipeline's submit AND drain lanes
        # concurrently — every counter mutates under _lock (lock-discipline)
        self._ops: Dict[str, int] = {}  #: guarded by self._lock
        self._bytes: Dict[str, int] = {}  #: guarded by self._lock
        self._total_ns: Dict[str, int] = {}  #: guarded by self._lock
        self._samples: Dict[str, List[int]] = {}  #: guarded by self._lock

    def record(self, kind: str, stats: OperationStats) -> None:
        elapsed = stats.elapsed_ns()
        with self._lock:
            self._ops[kind] = self._ops.get(kind, 0) + 1
            self._bytes[kind] = self._bytes.get(kind, 0) + stats.recv_size
            self._total_ns[kind] = self._total_ns.get(kind, 0) + elapsed
            samples = self._samples.setdefault(kind, [])
            if len(samples) < self._RESERVOIR:
                samples.append(elapsed)
            else:  # cheap deterministic reservoir: overwrite round-robin
                samples[self._ops[kind] % self._RESERVOIR] = elapsed

    def summary(self, kind: str) -> StatsSummary:
        with self._lock:
            ops = self._ops.get(kind, 0)
            if not ops:
                return StatsSummary()
            samples = sorted(self._samples.get(kind, []))
            return StatsSummary(
                ops=ops,
                bytes=self._bytes[kind],
                total_ns=self._total_ns[kind],
                min_ns=samples[0] if samples else None,
                max_ns=samples[-1] if samples else None,
                p50_ns=samples[len(samples) // 2] if samples else None,
                p99_ns=samples[min(len(samples) - 1, int(len(samples) * 0.99))] if samples else None,
            )

    def kinds(self) -> List[str]:
        with self._lock:
            return sorted(self._ops)

    def report(self) -> str:
        lines = []
        for kind in self.kinds():
            s = self.summary(kind)
            lines.append(
                f"{kind}: ops={s.ops} bytes={s.bytes} mean={s.mean_ns/1e3:.1f}us "
                f"p50={0 if s.p50_ns is None else s.p50_ns/1e3:.1f}us "
                f"p99={0 if s.p99_ns is None else s.p99_ns/1e3:.1f}us"
            )
        return "\n".join(lines)
