"""Transport-level stats aggregation.

The reference exposes per-op stats only (``UcxStats``,
UcxShuffleTransport.scala:36-53) and relies on Spark's shuffle metrics for
aggregates.  With no Spark UI underneath, this module provides the aggregate
view: a ``StatsAggregator`` transports feed each completed operation into, with
latency percentiles and byte totals — what the benchmark prints and what an
operator would scrape.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from sparkucx_tpu.core.operation import OperationStats


@dataclass
class StatsSummary:
    ops: int = 0
    bytes: int = 0
    total_ns: int = 0
    min_ns: Optional[int] = None
    max_ns: Optional[int] = None
    p50_ns: Optional[int] = None
    p99_ns: Optional[int] = None
    #: exchange staging occupancy (ops/skew.py telemetry): rows that carried
    #: payload vs rows staged only as slot padding, summed over this kind's ops
    used_rows: int = 0
    padded_rows: int = 0

    @property
    def mean_ns(self) -> float:
        return self.total_ns / self.ops if self.ops else 0.0

    @property
    def throughput_gbps(self) -> float:
        return self.bytes / self.total_ns if self.total_ns else 0.0  # bytes/ns == GB/s

    @property
    def padding_fraction(self) -> float:
        """Fraction of staged rows that were slot padding — the imbalance the
        skew planner (conf.slot_quota_rows) exists to shrink."""
        staged = self.used_rows + self.padded_rows
        return self.padded_rows / staged if staged else 0.0


class StatsAggregator:
    """Thread-safe sink for completed OperationStats, bucketed by op kind."""

    _RESERVOIR = 4096

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # record() is called from the pipeline's submit AND drain lanes
        # concurrently — every counter mutates under _lock (lock-discipline)
        self._ops: Dict[str, int] = {}  #: guarded by self._lock
        self._bytes: Dict[str, int] = {}  #: guarded by self._lock
        self._total_ns: Dict[str, int] = {}  #: guarded by self._lock
        self._samples: Dict[str, List[int]] = {}  #: guarded by self._lock
        # padding telemetry (ops/skew.py): written from the pipeline drain
        # worker alongside the timing counters — same lock, same discipline
        self._used_rows: Dict[str, int] = {}  #: guarded by self._lock
        self._padded_rows: Dict[str, int] = {}  #: guarded by self._lock
        # free-form named counters (striped-wire per-lane bytes / syscalls /
        # stall time): kind -> counter name -> accumulated value
        self._counters: Dict[str, Dict[str, int]] = {}  #: guarded by self._lock

    def record(
        self,
        kind: str,
        stats: OperationStats,
        *,
        used_rows: int = 0,
        padded_rows: int = 0,
    ) -> None:
        elapsed = stats.elapsed_ns()
        with self._lock:
            self._ops[kind] = self._ops.get(kind, 0) + 1
            self._bytes[kind] = self._bytes.get(kind, 0) + stats.recv_size
            self._total_ns[kind] = self._total_ns.get(kind, 0) + elapsed
            self._used_rows[kind] = self._used_rows.get(kind, 0) + used_rows
            self._padded_rows[kind] = self._padded_rows.get(kind, 0) + padded_rows
            samples = self._samples.setdefault(kind, [])
            if len(samples) < self._RESERVOIR:
                samples.append(elapsed)
            else:  # cheap deterministic reservoir: overwrite round-robin
                samples[self._ops[kind] % self._RESERVOIR] = elapsed

    def record_rows(self, kind: str, used_rows: int, padded_rows: int) -> None:
        """Occupancy-only record (no timed operation behind it): per-round
        lane-occupancy counters the transports emit once per exchange."""
        with self._lock:
            self._used_rows[kind] = self._used_rows.get(kind, 0) + used_rows
            self._padded_rows[kind] = self._padded_rows.get(kind, 0) + padded_rows

    def record_counters(self, kind: str, **counters: int) -> None:
        """Accumulate named counters under a kind — the wire path's per-lane
        telemetry (rx_bytes / rx_syscalls / rx_stall_ns) lands here, where an
        operator's report() can pick it up next to the op summaries."""
        with self._lock:
            dst = self._counters.setdefault(kind, {})
            for name, value in counters.items():
                dst[name] = dst.get(name, 0) + int(value)

    def counters(self, kind: str) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters.get(kind, {}))

    def summary(self, kind: str) -> StatsSummary:
        with self._lock:
            ops = self._ops.get(kind, 0)
            used = self._used_rows.get(kind, 0)
            padded = self._padded_rows.get(kind, 0)
            if not ops:
                # row-only kinds (record_rows) still surface their occupancy
                return StatsSummary(used_rows=used, padded_rows=padded)
            samples = sorted(self._samples.get(kind, []))
            return StatsSummary(
                ops=ops,
                bytes=self._bytes[kind],
                total_ns=self._total_ns[kind],
                min_ns=samples[0] if samples else None,
                max_ns=samples[-1] if samples else None,
                p50_ns=samples[len(samples) // 2] if samples else None,
                p99_ns=samples[min(len(samples) - 1, int(len(samples) * 0.99))] if samples else None,
                used_rows=used,
                padded_rows=padded,
            )

    def kinds(self) -> List[str]:
        with self._lock:
            return sorted(set(self._ops) | set(self._used_rows) | set(self._counters))

    def report(self) -> str:
        lines = []
        for kind in self.kinds():
            s = self.summary(kind)
            line = (
                f"{kind}: ops={s.ops} bytes={s.bytes} mean={s.mean_ns/1e3:.1f}us "
                f"p50={0 if s.p50_ns is None else s.p50_ns/1e3:.1f}us "
                f"p99={0 if s.p99_ns is None else s.p99_ns/1e3:.1f}us"
            )
            if s.used_rows or s.padded_rows:
                line += (
                    f" used_rows={s.used_rows} padded_rows={s.padded_rows} "
                    f"padding={s.padding_fraction:.1%}"
                )
            counters = self.counters(kind)
            if counters:
                line += "".join(f" {k}={v}" for k, v in sorted(counters.items()))
            lines.append(line)
        return "\n".join(lines)
