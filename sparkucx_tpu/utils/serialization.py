"""Address/buffer codecs for control-plane payloads.

Counterpart of ``utils/SerializableDirectBuffer.scala`` (88 LoC): the reference
wraps direct ByteBuffers for Java serialization (:20-48) and codes
``InetSocketAddress`` as ``{int port, utf8 host}`` (:71-88).  Python needs no
direct-buffer wrapper (bytes are picklable/sendable as-is); the address codec is
kept wire-compatible in spirit: little-endian port then utf-8 host.
"""

from __future__ import annotations

import struct
from typing import Tuple

_PORT = struct.Struct("<i")


def pack_address(host: str, port: int) -> bytes:
    """SerializationUtils.serializeInetAddress analogue
    (SerializableDirectBuffer.scala:71-80)."""
    return _PORT.pack(port) + host.encode("utf-8")


def unpack_address(data: bytes) -> Tuple[str, int]:
    """SerializationUtils.deserializeInetAddress analogue (:82-88)."""
    (port,) = _PORT.unpack_from(data)
    return data[_PORT.size :].decode("utf-8"), port
