"""Address/buffer codecs for control-plane payloads.

Counterpart of ``utils/SerializableDirectBuffer.scala`` (88 LoC): the reference
wraps direct ByteBuffers for Java serialization (:20-48) and codes
``InetSocketAddress`` as ``{int port, utf8 host}`` (:71-88).  Python needs no
direct-buffer wrapper (bytes are picklable/sendable as-is); the address codec is
kept wire-compatible in spirit: little-endian port then utf-8 host.

The in-tree control planes deliberately use self-describing encodings instead
(JSON frames in parallel/bootstrap.py, ``b"host:port"`` transport addresses) —
this codec is the InetSocketAddress-shaped twin for engines that want the
reference's byte layout, contract-tested in tests/test_aux.py.
"""

from __future__ import annotations

import struct
from typing import Tuple

_PORT = struct.Struct("<i")


def pack_address(host: str, port: int) -> bytes:
    """SerializationUtils.serializeInetAddress analogue
    (SerializableDirectBuffer.scala:71-80)."""
    return _PORT.pack(port) + host.encode("utf-8")


def unpack_address(data: bytes) -> Tuple[str, int]:
    """SerializationUtils.deserializeInetAddress analogue (:82-88)."""
    (port,) = _PORT.unpack_from(data)
    return data[_PORT.size :].decode("utf-8"), port
