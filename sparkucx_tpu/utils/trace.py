"""Span tracer with chrome://tracing export — an aux subsystem the reference
lacks entirely (SURVEY.md section 5.1: "No tracer"; it has only per-op nanoTime
deltas in debug logs, UcxWorkerWrapper.scala:388-390).

Usage::

    from sparkucx_tpu.utils.trace import TRACER, span

    with span("exchange.superstep", shuffle_id=0):
        ...
    TRACER.export("/tmp/shuffle_trace.json")   # open in chrome://tracing / Perfetto

Disabled by default: every ``span`` is a no-op unless the tracer is enabled
(constructor, ``TRACER.enable()``, or the ``SPARKUCX_TPU_TRACE`` env var, whose
value — if not "1" — is a path auto-exported at interpreter exit).  Events are
"X" (complete) events with thread/process ids, so concurrent mapper threads,
server threads, and the collective lane out per-track in the viewer.

The obs plane (PR 14) grew this into a distributed tracer:

* Every span carries real ids — ``trace_id`` (the root fetch/superstep that
  started the causal chain), ``span_id`` (this span), ``parent_id`` (the
  enclosing span, possibly on ANOTHER executor when the context arrived over
  the wire as a FetchBlockReq/ReplicaPut trace extension).  Ids ride as
  top-level event fields so the ``args`` shape stays what it always was.
* Storage is a bounded ring (``capacity`` events, drop-oldest) with a dropped
  counter — the flight recorder.  ``recording`` keeps the ring warm even when
  full tracing is off, so a postmortem bundle always has a trace tail;
  ``enabled`` additionally lights up the env-var export path.  Both off means
  the module-level ``span()`` returns a shared no-op — no dict build, no
  generator frame — the hot submit lane's fast path.
* ``current_context()`` exposes the innermost open span for wire pickup and
  ``activate()``/``remote_context()`` re-parent server-side work under it.
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional

#: Flight-recorder ring default: bounded so long-running tracing can't OOM an
#: executor (conf ``obs.ringCapacity`` overrides per cluster).
DEFAULT_RING_CAPACITY = 8192

#: Process-scoped id generator: the pid in the top bits keeps ids distinct
#: across daemon worker processes, the counter keeps them distinct in-process
#: (the loopback cluster shares one TRACER across every virtual executor).
_ids = itertools.count(1)


def _new_id() -> int:
    return ((os.getpid() & 0xFFFF) << 48) | next(_ids)


@dataclass
class SpanCtx:
    """An open span's identity — what travels over the wire and what children
    parent under.  ``trace_id`` names the causal chain, ``span_id`` this span,
    ``parent_id`` the enclosing span (0 = root)."""

    trace_id: int
    span_id: int
    parent_id: int = 0
    name: str = ""
    category: str = "shuffle"
    t0: int = 0  # perf_counter_ns at open; 0 for remote/synthetic contexts
    args: Dict[str, object] = field(default_factory=dict)


class _NoopSpan:
    """Shared do-nothing context manager returned by the module-level
    ``span()`` when tracing AND recording are both off: a plain object with
    empty ``__enter__``/``__exit__`` beats entering a generator-backed
    contextmanager by an order of magnitude on the hot submit lane."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


class Tracer:
    def __init__(
        self,
        enabled: bool = False,
        recording: bool = False,
        capacity: int = DEFAULT_RING_CAPACITY,
    ) -> None:
        self.enabled = enabled
        #: flight recorder: keep the ring warm without full tracing on
        self.recording = recording
        self._events: Deque[dict] = deque(maxlen=max(1, int(capacity)))  #: guarded by self._lock
        self._dropped = 0  #: guarded by self._lock
        self._lock = threading.Lock()
        self._tls = threading.local()

    # -- switches ----------------------------------------------------------

    @property
    def active(self) -> bool:
        """Anything to do at all?  False = the no-op fast path."""
        return self.enabled or self.recording

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def set_capacity(self, capacity: int) -> None:
        """Resize the flight-recorder ring, keeping the newest events."""
        with self._lock:
            self._events = deque(self._events, maxlen=max(1, int(capacity)))

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    @property
    def dropped(self) -> int:
        """Events evicted from the ring since the last clear()."""
        with self._lock:
            return self._dropped

    # -- thread-local span stack / scopes ----------------------------------

    def _stack(self) -> List[SpanCtx]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_context(self) -> Optional[SpanCtx]:
        """The innermost open span on THIS thread — what a transport packs
        into the wire trace extension.  None when no span is open."""
        st = getattr(self._tls, "stack", None)
        return st[-1] if st else None

    def current_executor(self) -> Optional[int]:
        return getattr(self._tls, "eid", None)

    @contextmanager
    def executor_scope(self, executor_id: Optional[int]):
        """Attribute events on this thread to a virtual executor — the
        loopback cluster runs every executor in one process, so pid alone
        can't tell their tracks apart; ``export_merged`` maps eid -> pid."""
        prev = getattr(self._tls, "eid", None)
        self._tls.eid = executor_id
        try:
            yield
        finally:
            self._tls.eid = prev

    @contextmanager
    def activate(self, ctx: Optional[SpanCtx]):
        """Make ``ctx`` the parent for spans opened on this thread — used to
        re-parent pipelined-window awaits and server-side serve spans under
        a span opened elsewhere (another thread, or another executor via
        ``remote_context``).  No event is recorded for ``ctx`` itself."""
        if ctx is None or not self.active:
            yield
            return
        st = self._stack()
        st.append(ctx)
        try:
            yield
        finally:
            st.pop()

    @staticmethod
    def remote_context(trace_id: int, span_id: int) -> SpanCtx:
        """A synthetic ctx for a span open on ANOTHER executor (arrived as a
        wire trace extension); activate() it to parent local spans there."""
        return SpanCtx(trace_id=trace_id, span_id=span_id, name="<remote>")

    # -- span lifecycle ----------------------------------------------------

    def start_span(self, name: str, category: str = "shuffle", **args) -> Optional[SpanCtx]:
        """Open a span WITHOUT entering it on the thread-local stack — the
        explicit half of the API for spans whose open and close straddle
        threads or interleave (pipelined fetch windows).  Pair with
        ``end_span``; parent under it elsewhere via ``activate``."""
        if not self.active:
            return None
        parent = self.current_context()
        return SpanCtx(
            trace_id=parent.trace_id if parent else _new_id(),
            span_id=_new_id(),
            parent_id=parent.span_id if parent else 0,
            name=name,
            category=category,
            t0=time.perf_counter_ns(),
            args={k: _jsonable(v) for k, v in args.items()} if args else {},
        )

    def end_span(self, ctx: Optional[SpanCtx], **extra_args) -> None:
        if ctx is None or not self.active:
            return
        if extra_args:
            ctx.args.update({k: _jsonable(v) for k, v in extra_args.items()})
        self._record_span(ctx, time.perf_counter_ns() - ctx.t0)

    @contextmanager
    def span(self, name: str, category: str = "shuffle", **args):
        """Time a region; nested spans nest in the viewer (same tid)."""
        if not self.active:
            yield
            return
        ctx = self.start_span(name, category=category, **args)
        st = self._stack()
        st.append(ctx)
        try:
            yield ctx
        finally:
            st.pop()
            self._record_span(ctx, time.perf_counter_ns() - ctx.t0)

    def _record_span(self, ctx: SpanCtx, dur_ns: int) -> None:
        ev = {
            "name": ctx.name,
            "cat": ctx.category,
            "ph": "X",
            "ts": ctx.t0 / 1e3,  # microseconds, the chrome trace unit
            "dur": dur_ns / 1e3,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "uid": _new_id(),
            "trace_id": ctx.trace_id,
            "span_id": ctx.span_id,
            "parent_id": ctx.parent_id,
        }
        if ctx.args:
            ev["args"] = ctx.args
        eid = getattr(self._tls, "eid", None)
        if eid is not None:
            ev["eid"] = eid
        self._append(ev)

    def instant(self, name: str, category: str = "shuffle", **args) -> None:
        """Zero-duration marker (commits, failures, retries)."""
        if not self.active:
            return
        parent = self.current_context()
        ev = {
            "name": name,
            "cat": category,
            "ph": "i",
            "s": "t",
            "ts": time.perf_counter_ns() / 1e3,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFFFFFF,
            "uid": _new_id(),
            "trace_id": parent.trace_id if parent else 0,
            "span_id": _new_id(),
            "parent_id": parent.span_id if parent else 0,
        }
        if args:
            ev["args"] = {k: _jsonable(v) for k, v in args.items()}
        eid = getattr(self._tls, "eid", None)
        if eid is not None:
            ev["eid"] = eid
        self._append(ev)

    def _append(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1  # ring full: deque drops the oldest
            self._events.append(ev)

    # -- export ------------------------------------------------------------

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def tail(self, n: int) -> List[dict]:
        """The newest ``n`` ring events without copying the whole ring —
        the flight recorder's capture path runs on error paths and must
        stay cheap even with a full ring."""
        with self._lock:
            if n >= len(self._events):
                return list(self._events)
            out = list(itertools.islice(reversed(self._events), n))
        out.reverse()
        return out

    def to_json(self) -> str:
        return json.dumps({"traceEvents": self.events, "displayTimeUnit": "ms"})

    def export(self, path: str) -> int:
        """Write the chrome trace file; returns the event count."""
        events = self.events
        with open(path, "w") as f:
            f.write(json.dumps({"traceEvents": events, "displayTimeUnit": "ms"}))
        return len(events)


def merge_events(buffers: List[List[dict]]) -> List[dict]:
    """Merge per-executor event buffers into one Perfetto-ready list.

    Events that carry an ``eid`` (executor scope) get ``pid = eid`` so every
    executor lands on its own process track in the viewer; duplicates are
    dropped by event ``uid`` (the loopback cluster shares one TRACER across
    executors, so a TRACE_PULL sweep returns overlapping views)."""
    seen = set()
    merged: List[dict] = []
    for buf in buffers:
        for ev in buf:
            uid = ev.get("uid")
            if uid is not None:
                if uid in seen:
                    continue
                seen.add(uid)
            ev = dict(ev)
            if ev.get("eid") is not None:
                ev["pid"] = ev["eid"]
            merged.append(ev)
    merged.sort(key=lambda e: e.get("ts", 0))
    return merged


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def _from_env() -> "Tracer":
    flag = os.environ.get("SPARKUCX_TPU_TRACE", "")
    t = Tracer(enabled=bool(flag))
    if flag and flag != "1":
        atexit.register(lambda: t.events and t.export(flag))
    return t


#: Process-wide default tracer (env-gated); libraries call ``span(...)``.
TRACER = _from_env()


def span(name: str, category: str = "shuffle", **args):
    if not TRACER.active:  # hot-path guard: no kwargs dict churn, no generator
        return _NOOP_SPAN
    return TRACER.span(name, category=category, **args)


def instant(name: str, category: str = "shuffle", **args) -> None:
    if not TRACER.active:
        return
    TRACER.instant(name, category=category, **args)
