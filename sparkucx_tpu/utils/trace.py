"""Span tracer with chrome://tracing export — an aux subsystem the reference
lacks entirely (SURVEY.md section 5.1: "No tracer"; it has only per-op nanoTime
deltas in debug logs, UcxWorkerWrapper.scala:388-390).

Usage::

    from sparkucx_tpu.utils.trace import TRACER, span

    with span("exchange.superstep", shuffle_id=0):
        ...
    TRACER.export("/tmp/shuffle_trace.json")   # open in chrome://tracing / Perfetto

Disabled by default: every ``span`` is a no-op unless the tracer is enabled
(constructor, ``TRACER.enable()``, or the ``SPARKUCX_TPU_TRACE`` env var, whose
value — if not "1" — is a path auto-exported at interpreter exit).  Events are
"X" (complete) events with thread/process ids, so concurrent mapper threads,
server threads, and the collective lane out per-track in the viewer.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import List, Optional


class Tracer:
    def __init__(self, enabled: bool = False) -> None:
        self.enabled = enabled
        self._events: List[dict] = []
        self._lock = threading.Lock()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events = []

    @contextmanager
    def span(self, name: str, category: str = "shuffle", **args):
        """Time a region; nested spans nest in the viewer (same tid)."""
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            dur = time.perf_counter_ns() - t0
            ev = {
                "name": name,
                "cat": category,
                "ph": "X",
                "ts": t0 / 1e3,  # microseconds, the chrome trace unit
                "dur": dur / 1e3,
                "pid": os.getpid(),
                "tid": threading.get_ident() & 0xFFFFFFFF,
            }
            if args:
                ev["args"] = {k: _jsonable(v) for k, v in args.items()}
            with self._lock:
                self._events.append(ev)

    def instant(self, name: str, category: str = "shuffle", **args) -> None:
        """Zero-duration marker (commits, failures, retries)."""
        if not self.enabled:
            return
        ev = {
            "name": name,
            "cat": category,
            "ph": "i",
            "s": "t",
            "ts": time.perf_counter_ns() / 1e3,
            "pid": os.getpid(),
            "tid": threading.get_ident() & 0xFFFFFFFF,
        }
        if args:
            ev["args"] = {k: _jsonable(v) for k, v in args.items()}
        with self._lock:
            self._events.append(ev)

    @property
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def to_json(self) -> str:
        return json.dumps({"traceEvents": self.events, "displayTimeUnit": "ms"})

    def export(self, path: str) -> int:
        """Write the chrome trace file; returns the event count."""
        events = self.events
        with open(path, "w") as f:
            f.write(json.dumps({"traceEvents": events, "displayTimeUnit": "ms"}))
        return len(events)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def _from_env() -> "Tracer":
    flag = os.environ.get("SPARKUCX_TPU_TRACE", "")
    t = Tracer(enabled=bool(flag))
    if flag and flag != "1":
        atexit.register(lambda: t.events and t.export(flag))
    return t


#: Process-wide default tracer (env-gated); libraries call ``span(...)``.
TRACER = _from_env()


def span(name: str, category: str = "shuffle", **args):
    return TRACER.span(name, category=category, **args)


def instant(name: str, category: str = "shuffle", **args) -> None:
    TRACER.instant(name, category=category, **args)
