"""Cluster membership: the liveness/epoch layer under elastic meshes.

The collective plane (transport/tpu.py, transport/spmd.py) compiles for a
fixed executor count; the wire plane (transport/peer.py) already survives
executor loss via neighbor replication + reducer failover (PR 7).  This module
is the piece that connects them: a tiny membership table that turns addressed
wire errors, ``wire.timeoutMs`` trips, and the chaos harness's
``kill_executor`` into *epoch bumps* the exchange can observe — abort the
in-flight round, shrink to the surviving pow2 bucket, restage from replicas,
re-run (see ``TpuShuffleCluster._run_exchange``).

Design notes:

* **Observation-driven, not heartbeat-driven.**  Failures are detected where
  the reference detects them — at the wire (``UcxShuffleTransport`` evicts a
  connection on send failure) — and propagated as ``MemberSuspect`` frames on
  the peer plane.  There is no background failure detector thread; a silent
  executor that nobody talks to is, by definition, not blocking anyone.
* **Epochs are local, convergence is by union.**  Every mark_dead/mark_alive
  bumps the local epoch.  Views converge because suspects are broadcast and
  re-applying a known fact is a no-op (no epoch bump, no re-broadcast storm).
* **Suspicion can be debounced** (``membership.suspectAfterMs``): the first
  wire error records a pending suspicion; only an error that persists past the
  window marks the executor dead.  0 (default) trusts the first addressed
  error — wire errors here are already post-retry.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence


class ClusterMembership:
    """Liveness + epoch table over a fixed executor id space.

    The id space (``executors``) never changes — elasticity shrinks the set of
    *alive* ids, never renumbers.  Thread-safe; every mutation that changes
    the alive set bumps ``epoch``, which is what the exchange snapshots before
    a round and re-checks after (a changed epoch means the round's plan is
    stale).
    """

    def __init__(self, executors: Sequence[int], suspect_after_ms: int = 0) -> None:
        self._executors = sorted(int(e) for e in executors)
        self._alive = set(self._executors)  #: guarded by self._lock
        self._dead: Dict[int, str] = {}  #: guarded by self._lock
        #: executor -> monotonic ns of first un-expired suspicion (debounce)
        self._suspects: Dict[int, int] = {}  #: guarded by self._lock
        self._suspect_after_ns = max(0, int(suspect_after_ms)) * 1_000_000
        self._epoch = 0  #: guarded by self._lock
        self._lock = threading.Lock()

    # -- queries -----------------------------------------------------------

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def degraded(self) -> bool:
        with self._lock:
            return bool(self._dead)

    def is_alive(self, executor_id: int) -> bool:
        with self._lock:
            return executor_id in self._alive

    def alive(self) -> List[int]:
        with self._lock:
            return sorted(self._alive)

    def dead(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._dead)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "epoch": self._epoch,
                "alive": sorted(self._alive),
                "dead": dict(self._dead),
            }

    # -- transitions -------------------------------------------------------

    def suspect(self, executor_id: int, reason: str) -> bool:
        """Record a failure observation.  Returns True when the observation
        newly killed the executor (first error with no debounce window, or an
        error that persisted past ``suspectAfterMs``); False when absorbed
        (unknown id, already dead, or still inside the debounce window)."""
        if executor_id not in self._executors:
            return False
        if self._suspect_after_ns:
            now = time.monotonic_ns()
            with self._lock:
                if executor_id not in self._alive:
                    return False
                first = self._suspects.setdefault(executor_id, now)
                if now - first < self._suspect_after_ns:
                    return False
        return self.mark_dead(executor_id, reason)

    def mark_dead(self, executor_id: int, reason: str) -> bool:
        """Declare an executor dead.  Returns True if this changed the alive
        set (and bumped the epoch); False for unknown/already-dead ids."""
        with self._lock:
            if executor_id not in self._alive:
                return False
            self._alive.discard(executor_id)
            self._dead[executor_id] = reason
            self._suspects.pop(executor_id, None)
            self._epoch += 1
            return True

    def mark_alive(self, executor_id: int) -> bool:
        """Rejoin: restore an executor to the alive set.  Returns True if it
        was dead (epoch bumped — the full mesh returns at the next shuffle
        epoch); False for unknown/already-alive ids."""
        if executor_id not in self._executors:
            return False
        with self._lock:
            if executor_id in self._alive:
                # A liveness observation about an already-alive executor still
                # clears any pending (debounced) suspicion: the peer was seen
                # working, so the suspicion window must restart from scratch.
                self._suspects.pop(executor_id, None)
                return False
            self._alive.add(executor_id)
            self._dead.pop(executor_id, None)
            self._suspects.pop(executor_id, None)
            self._epoch += 1
            return True
