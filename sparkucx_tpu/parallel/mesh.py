"""Topology discovery and executor<->chip mapping (L2).

The reference's bootstrap publishes each executor's UCX worker address and lets the
driver introduce members (rpc/UcxDriverRpcEndpoint.scala:21-42); the TPU analogue
must additionally discover the *slice topology* so executors map onto chips in ICI
order (BASELINE.json north star: "executor bootstrap discovers the TPU slice
topology to build the executor<->chip mapping").

``discover_topology`` inspects the JAX backend; ``executor_mesh`` orders devices by
their physical coords so mesh-adjacent executors are ICI neighbors (XLA schedules
ragged all_to_all over neighbor links; a coords-sorted ring keeps per-hop distance
minimal on v4/v5 tori).  ``init_distributed`` wraps ``jax.distributed.initialize``
— the multi-controller analogue of the reference's driver RpcEnv bootstrap
(CommonUcxShuffleManager.scala:45-62).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh


@dataclass(frozen=True)
class TopologyInfo:
    platform: str
    num_devices: int
    num_local_devices: int
    process_index: int
    process_count: int
    device_kinds: Tuple[str, ...]
    coords: Tuple[Optional[Tuple[int, ...]], ...]  # physical chip coords when exposed

    @property
    def is_tpu(self) -> bool:
        return self.platform == "tpu"

    @property
    def multi_host(self) -> bool:
        return self.process_count > 1


def apply_platform_env() -> None:
    """Make the ``JAX_PLATFORMS`` env var effective even when a site hook pinned
    ``jax_platforms`` via ``jax.config`` at interpreter start (observed with
    vendor PJRT plugins: the hook's config.update overrides the env var).  Call
    before first backend use in entry-point processes (daemon, CLIs)."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    if jax.config.jax_platforms != want:
        jax.config.update("jax_platforms", want)


def discover_topology() -> TopologyInfo:
    import jax

    devices = jax.devices()
    coords = tuple(getattr(d, "coords", None) for d in devices)
    return TopologyInfo(
        platform=devices[0].platform,
        num_devices=len(devices),
        num_local_devices=len(jax.local_devices()),
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        device_kinds=tuple(d.device_kind for d in devices),
        coords=coords,
    )


def _ici_order(devices: Sequence) -> List:
    """Order devices so consecutive executors are physical ICI neighbors.

    Snake-orders by (z, y, x) coords when the backend exposes them (TPU), so the
    1-D executor ring embeds into the torus with unit-distance hops; otherwise
    keeps backend order (CPU/GPU test meshes)."""
    coords = [getattr(d, "coords", None) for d in devices]
    if any(c is None for c in coords):
        return list(devices)

    def key(d):
        c = d.coords
        # snake along x within each y-row to keep wraparound hops short
        x, y, z = (list(c) + [0, 0, 0])[:3]
        sx = x if y % 2 == 0 else -x
        return (z, y, sx, getattr(d, "core_on_chip", 0))

    return sorted(devices, key=key)


def executor_mesh(
    num_executors: int, axis_name: str = "ex", devices: Optional[Sequence] = None
) -> Mesh:
    """The executor mesh, ICI-ordered.  One executor per chip, mirroring the
    reference's one-transport-per-executor model
    (CommonUcxShuffleManager.scala:67-99)."""
    import jax

    devs = _ici_order(list(devices if devices is not None else jax.devices()))
    if len(devs) < num_executors:
        raise ValueError(f"need {num_executors} devices, have {len(devs)}")
    return Mesh(np.array(devs[:num_executors]), (axis_name,))


def surviving_submesh(mesh: Mesh, phys: Sequence[int], axis_name: Optional[str] = None) -> Mesh:
    """The shrunk mesh for degraded-mode recovery (elastic.enabled): the
    devices of the surviving executor slots ``phys`` (already the pow2 bucket
    chosen by ``shuffle.resolver.degraded_plan``), in the full mesh's ICI
    order.  Preserving the parent's device order keeps surviving neighbors
    ICI-adjacent — the shrunk ring is a sub-ring of the full ring, so no
    re-ordering (and no new topology probe) is needed."""
    flat = list(mesh.devices.reshape(-1))
    devs = [flat[p] for p in phys]
    return Mesh(np.array(devs), (axis_name or mesh.axis_names[0],))


def executor_for_device(mesh: Mesh, device) -> int:
    flat = list(mesh.devices.reshape(-1))
    return flat.index(device)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> TopologyInfo:
    """Multi-host bootstrap: initialize the JAX coordination service, then
    discover the global topology.  On TPU pods the arguments are auto-detected
    from the environment; explicit values serve CPU/GPU clusters.

    This replaces the reference's dedicated "ucx-rpc-env" + driver endpoint
    address exchange (CommonUcxShuffleManager.scala:73-99): the coordination
    service plays the driver, ``jax.devices()`` after init plays
    ``IntroduceAllExecutors``."""
    import jax

    if jax.process_count() == 1 and (coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")):
        if (jax.config.jax_platforms or "").startswith("cpu"):
            from sparkucx_tpu.ops._compat import enable_cpu_cross_process_collectives

            enable_cpu_cross_process_collectives()
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return discover_topology()
