"""Control-plane bootstrap: driver/executor address exchange over TCP (L2).

Counterpart of the reference's Spark-RPC control plane (rpc/ directory):

* ``DriverEndpoint`` == ``UcxDriverRpcEndpoint`` (UcxDriverRpcEndpoint.scala:21-42):
  on ``ExecutorAdded`` it replies with ``IntroduceAllExecutors`` (current
  membership) and broadcasts the newcomer to every registered executor.
* ``ExecutorEndpoint`` == ``UcxExecutorRpcEndpoint`` (UcxExecutorRpcEndpoint.scala:19-39):
  applies both message types by calling ``transport.add_executor(s)`` and
  ``pre_connect`` on a worker thread.
* Messages carry opaque serialized addresses like the reference's
  ``SerializableDirectBuffer`` payloads (UcxRpcMessages.scala:15-21); here they are
  length-prefixed JSON frames with base64 address blobs (no pickle — the control
  plane must not execute peer-controlled bytes).

The reference rides Spark's RpcEnv; this build has no Spark at the bottom, so the
driver is a small threaded TCP server — the same role the dedicated "ucx-rpc-env"
plays (CommonUcxShuffleManager.scala:73-78).
"""

from __future__ import annotations

import base64
import json
import socket
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from sparkucx_tpu.core.transport import ExecutorId, ShuffleTransport

_LEN = struct.Struct("<I")
_MAX_FRAME = 16 << 20


def _send_msg(sock: socket.socket, msg: dict) -> None:
    payload = json.dumps(msg).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_msg(sock: socket.socket) -> Optional[dict]:
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (length,) = _LEN.unpack(hdr)
    if length > _MAX_FRAME:
        raise ValueError(f"control frame too large: {length}")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return json.loads(payload)


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


class DriverEndpoint:
    """The membership authority.  Thread-per-connection; connections stay open so
    the driver can push ``ExecutorAdded`` broadcasts (the reference keeps
    endpoint refs the same way, UcxDriverRpcEndpoint.scala:17-19)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.address: Tuple[str, int] = self._srv.getsockname()
        self._members: Dict[ExecutorId, str] = {}  # executor -> b64 address blob
        self._conns: Dict[ExecutorId, socket.socket] = {}
        self._lock = threading.Lock()
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        eid: Optional[ExecutorId] = None
        try:
            while self._running:
                msg = _recv_msg(conn)
                if msg is None:
                    return
                if msg["type"] == "ExecutorAdded":
                    eid = int(msg["executor_id"])
                    with self._lock:
                        existing = dict(self._members)
                        self._members[eid] = msg["address"]
                        peers = list(self._conns.items())
                        self._conns[eid] = conn
                    # reply with current membership (UcxDriverRpcEndpoint.scala:30-33)
                    _send_msg(conn, {"type": "IntroduceAllExecutors", "executors": existing})
                    # broadcast the newcomer to everyone else (:34-41)
                    for peer_id, peer_conn in peers:
                        try:
                            _send_msg(
                                peer_conn,
                                {
                                    "type": "ExecutorAdded",
                                    "executor_id": eid,
                                    "address": msg["address"],
                                },
                            )
                        except OSError:
                            pass
        except (OSError, ValueError, KeyError):
            pass
        finally:
            if eid is not None:
                with self._lock:
                    if self._conns.get(eid) is conn:
                        del self._conns[eid]
            conn.close()

    @property
    def members(self) -> Dict[ExecutorId, bytes]:
        with self._lock:
            return {k: _unb64(v) for k, v in self._members.items()}

    def close(self) -> None:
        self._running = False
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


class ExecutorEndpoint:
    """Executor-side client: registers, applies membership, listens for joins."""

    def __init__(
        self,
        driver_address: Tuple[str, int],
        executor_id: ExecutorId,
        transport: ShuffleTransport,
        on_member: Optional[Callable[[ExecutorId, bytes], None]] = None,
    ) -> None:
        self.executor_id = executor_id
        self.transport = transport
        self.on_member = on_member
        self._sock = socket.create_connection(driver_address, timeout=10)
        self.known: Dict[ExecutorId, bytes] = {}
        self._lock = threading.Lock()
        self._running = True
        self._introduced = threading.Event()
        self._listener = threading.Thread(target=self._listen_loop, daemon=True)

    def register(self, local_address: bytes, timeout: float = 10.0) -> None:
        """ExecutorAdded ask + IntroduceAllExecutors apply
        (CommonUcxShuffleManager.scala:91-97)."""
        _send_msg(
            self._sock,
            {"type": "ExecutorAdded", "executor_id": self.executor_id, "address": _b64(local_address)},
        )
        self._listener.start()
        if not self._introduced.wait(timeout):
            raise TimeoutError("driver did not introduce executors in time")

    def _apply(self, eid: ExecutorId, addr: bytes) -> None:
        with self._lock:
            self.known[eid] = addr
        self.transport.add_executor(eid, addr)
        self.transport.pre_connect()
        if self.on_member is not None:
            self.on_member(eid, addr)

    def _listen_loop(self) -> None:
        try:
            while self._running:
                msg = _recv_msg(self._sock)
                if msg is None:
                    return
                if msg["type"] == "IntroduceAllExecutors":
                    for eid_s, addr in msg["executors"].items():
                        self._apply(int(eid_s), _unb64(addr))
                    self._introduced.set()
                elif msg["type"] == "ExecutorAdded":
                    self._apply(int(msg["executor_id"]), _unb64(msg["address"]))
        except (OSError, ValueError, KeyError):
            pass

    def close(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass
