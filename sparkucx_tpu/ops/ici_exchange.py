"""Scheduled inter-chip exchange — FAST-style flow scheduling over the ICI ring.

The stock n>1 data plane (ops/exchange.py) hands the superstep to ONE opaque
collective (``ragged_all_to_all`` / tiled ``all_to_all``) and takes whatever
flow schedule XLA picks.  FAST (PAPERS.md, arXiv:2505.09764) shows that for
all-to-all traffic the schedule itself is the headroom: chunk each
destination's payload and interleave the chunks across link-steps so a hot
lane streams on both ring directions instead of serializing behind one
transfer.  This module applies that argument to the TPU ICI torus:

* **Schedule model** (pure python, unit-testable): a
  :class:`RingSchedule` is a sequence of supersteps; each step carries at
  most one :class:`SendItem` per ring direction, so the per-step link budget
  is honored BY CONSTRUCTION.  Items are enumerated chunk-major
  (chunk 0 of every destination before chunk 1 of any), which is exactly the
  FAST interleaving: a hot destination's chunks land ``dim-1`` steps apart
  rather than back-to-back.  Offsets take the short way around the ring
  (direction +1 for d <= dim/2), antipodal offsets alternate direction by
  chunk parity so both directions carry equal load.
* **Lowerings** (mirroring the scatter's dma/tiled/xla tiers,
  ops/pallas_kernels.py):

  - ``'dma'`` — Pallas kernel over ``pltpu.make_async_remote_copy``
    (pallas_kernels.ring_exchange_grid): per step, one remote DMA per ring
    direction, both in flight at once; TPU-only.
  - ``'xla'`` — the portable fallback: the SAME schedule executed as one
    ``jax.lax.ppermute`` per item inside shard_map.  This is what the 8-way
    CPU mesh and the SPMD suite run, so CI exercises the full schedule logic
    (delivery, placement, compaction) without TPU hardware.
  - ``'interpret'`` — the Pallas kernel under ``interpret=True``: on flat
    meshes CI runs it on CPU and asserts bit-equality with stock, so the
    kernel body (schedule walk, remote-copy placement, ring-position ->
    logical-device-id rebasing) is executed without TPU hardware.
    Hierarchical meshes fall back to 'xla' here (jax's interpret discharge
    of remote DMA is single-axis only).

  Remote DMA cannot cross slice boundaries: any ring classified ``'dcn'``
  by the topology probe (flat meshes spanning slices, hand-built (dcn, ici)
  meshes with mixed rows) is forced onto the 'xla' tier by
  :func:`resolve_schedule_lowering`, mirroring the hardcoded permute tier
  of the hierarchical DCN phase.

  Both lowerings land received windows in the SAME sender-major slot grid the
  dense lowering's all_to_all produces and share its compaction math
  (hierarchy.compact_slots), so results are bit-identical to the stock
  collective — pinned by tests/test_ici_exchange.py and the CI ici gate.

* **Fused send side**: :func:`build_fused_ici_exchange` composes the block
  scatter (the device-staging write, ops/pallas_kernels.build_block_scatter)
  with the scheduled exchange in ONE kernel/jit — staging->wire with no
  intermediate HBM round trip and no separate scatter launch.

* **Hierarchy**: on a (dcn, ici) mesh the two phases of the hierarchical
  route (ops/hierarchy.py) each get their OWN ring schedule
  (hierarchy.hop_schedule classifies hops from the device topology): the ICI
  phase may lower to the remote-DMA kernel, the DCN phase always rides
  scheduled XLA permutes (remote DMA cannot cross slices).

Selection: ``spark.shuffle.tpu.exchange.impl`` = ``stock`` (default, the
byte-for-byte ragged/dense path) | ``pallas`` | ``auto`` (pallas on
multi-chip TPU meshes).  The transports key their compiled-exchange caches on
the resolved impl, so both paths coexist per bucket.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops._compat import shard_map
from sparkucx_tpu.ops.exchange import (
    ExchangeSpec,
    build_exchange,
    gather_size_matrix,
)
from sparkucx_tpu.ops.hierarchy import (
    compact_slots,
    device_slice_ids,
    region_permutation,
)

LOWERINGS = ("auto", "dma", "xla", "interpret")

# Per-destination chunks the transports request (clamped per phase by
# schedule_chunks): 2 gives one level of FAST interleaving — a hot lane's
# windows ride both ring directions across two passes — without inflating
# step count; deeper chunking is a benchmark/experiment knob.
DEFAULT_CHUNKS_PER_DEST = 2


# ----------------------------------------------------------------------------
# Schedule model (pure python — no jax below this line until the lowerings)
# ----------------------------------------------------------------------------


@dataclass(frozen=True)
class SendItem:
    """One scheduled transfer: every device sends its chunk ``chunk`` of the
    slot destined ``offset`` hops ahead on the ring, riding the links of
    ``direction`` (+1 / -1).  ``kind`` labels the fabric ('ici' | 'dcn')."""

    offset: int
    chunk: int
    direction: int
    kind: str = "ici"


@dataclass(frozen=True)
class RingSchedule:
    """Supersteps over one ring axis; each step holds <= 1 item per direction.

    SPMD-symmetric: every device executes the same item list, so item
    ``(offset d, chunk c)`` simultaneously means "send my window for ``me+d``"
    and "receive the matching window from ``me-d``"."""

    dim: int
    chunks: int
    kind: str
    steps: Tuple[Tuple[SendItem, ...], ...]

    @property
    def num_steps(self) -> int:
        return len(self.steps)

    def items(self) -> List[SendItem]:
        return [item for step in self.steps for item in step]

    def raw_steps(self) -> Tuple[Tuple[Tuple[int, int, int], ...], ...]:
        """Plain-tuple view for the Pallas kernel (ops/pallas_kernels.py)."""
        return tuple(
            tuple((it.offset, it.chunk, it.direction) for it in step)
            for step in self.steps
        )


@dataclass(frozen=True)
class HierarchicalSchedule:
    """Distinct per-fabric schedules for the two-phase hierarchical route:
    the ICI phase permutes chips within a slice, the DCN phase permutes
    slices.  A phase of dim 1 is ``None`` (nothing to exchange on that axis)."""

    num_slices: int
    chips_per_slice: int
    ici: Optional[RingSchedule]
    dcn: Optional[RingSchedule]

    @property
    def num_steps(self) -> int:
        return sum(s.num_steps for s in (self.ici, self.dcn) if s is not None)


def schedule_chunks(group_rows: int, requested: int) -> int:
    """Clamp a requested per-destination chunk count to a pow2 divisor of the
    transfer group — the bucketing step that keeps chunk windows static and
    compile-cache keys pow2 (analysis/config.py BUCKETING_MARKERS)."""
    if group_rows <= 0:
        raise ValueError(f"group_rows must be positive, got {group_rows}")
    r = max(1, int(requested))
    c = 1 << (r - 1).bit_length()  # pow2 ceil
    c = min(c, group_rows)
    return math.gcd(c, group_rows)  # largest pow2 divisor of group_rows <= c


def ring_schedule(dim: int, chunks_per_dest: int = 1, kind: str = "ici") -> RingSchedule:
    """Build the bidirectional-ring flow schedule for ``dim`` devices.

    Enumeration is chunk-major — chunk 0 of EVERY destination before chunk 1
    of any (the FAST hot-lane interleaving) — split into a '+' and a '-'
    queue by short-way routing; step i pairs the i-th item of each queue, so
    "<= 1 chunk per link direction per step" holds by construction and every
    ``(offset, chunk)`` appears exactly once by enumeration."""
    if dim < 2:
        raise ValueError(f"ring schedule needs dim >= 2, got {dim}")
    if chunks_per_dest < 1:
        raise ValueError(f"chunks_per_dest must be >= 1, got {chunks_per_dest}")
    plus: List[SendItem] = []
    minus: List[SendItem] = []
    for c in range(chunks_per_dest):
        for d in range(1, dim):
            if 2 * d < dim:
                direction = 1
            elif 2 * d > dim:
                direction = -1
            else:  # antipodal offset: alternate by chunk so both rings share it
                direction = 1 if c % 2 == 0 else -1
            item = SendItem(offset=d, chunk=c, direction=direction, kind=kind)
            (plus if direction > 0 else minus).append(item)
    steps = tuple(
        tuple(q[i] for q in (plus, minus) if i < len(q))
        for i in range(max(len(plus), len(minus)))
    )
    return RingSchedule(dim=dim, chunks=chunks_per_dest, kind=kind, steps=steps)


def simulate_ring(schedule: RingSchedule):
    """Pure-python executor for schedule property tests.

    Returns ``(deliveries, link_load)``: ``deliveries[(src, dst, chunk)]`` =
    times that window was sent (must be exactly 1 for every src != dst);
    ``link_load[(step, src, direction)]`` = windows device ``src`` injected
    into that ring direction at that step (must be <= 1)."""
    n = schedule.dim
    deliveries: Dict[Tuple[int, int, int], int] = {}
    link_load: Dict[Tuple[int, int, int], int] = {}
    for si, step in enumerate(schedule.steps):
        for item in step:
            for src in range(n):
                dst = (src + item.offset) % n
                key = (src, dst, item.chunk)
                deliveries[key] = deliveries.get(key, 0) + 1
                lkey = (si, src, item.direction)
                link_load[lkey] = link_load.get(lkey, 0) + 1
    return deliveries, link_load


def step_occupancy(schedule: RingSchedule) -> List[Tuple[int, int]]:
    """Per-superstep (used, idle) link-direction slots per device — the
    span telemetry the 'ici' benchmark mode records via StatsAggregator."""
    return [(len(step), 2 - len(step)) for step in schedule.steps]


def resolve_exchange_impl(
    impl: str, platform: str, num_executors: int
) -> str:
    """conf.exchange_impl -> concrete engine: 'stock' | 'pallas'.

    ``auto`` picks the scheduled kernel only where the remote-DMA path can
    actually win — multi-chip TPU meshes; everywhere else the stock
    collective stays the byte-for-byte default."""
    if impl == "stock":
        return "stock"
    if impl == "pallas":
        return "pallas"
    if impl == "auto":
        return "pallas" if platform == "tpu" and num_executors > 1 else "stock"
    raise ValueError(f"unknown exchange impl {impl!r}")


def resolve_ici_lowering(lowering: str, platform: str) -> str:
    if lowering == "auto":
        return "dma" if platform == "tpu" else "xla"
    if lowering not in ("dma", "xla", "interpret"):
        raise ValueError(f"unknown ici lowering {lowering!r}")
    return lowering


def resolve_schedule_lowering(lowering: str, kind: str) -> str:
    """Fabric guard: remote DMA cannot cross slices, so any ring whose hops
    are classified ``'dcn'`` (hierarchy.hop_schedule — flat meshes spanning
    slices, or hand-built (dcn, ici) meshes whose rows mix slices) is forced
    onto the scheduled-XLA lowering — the same rule the hierarchical route
    hardcodes for its DCN phase.  'interpret' is left alone (debug tier, no
    real DMA)."""
    if kind == "dcn" and lowering == "dma":
        return "xla"
    return lowering


# ----------------------------------------------------------------------------
# Lowerings
# ----------------------------------------------------------------------------


def _axis_grid_xla(ax, dim: int, group_rows: int, sched: Optional[RingSchedule], flat, me):
    """Scheduled-permute equivalent of one tiled all_to_all over ``ax``.

    ``flat`` is the destination-major group layout (group g = rows
    ``[g*group_rows, (g+1)*group_rows)`` for axis-peer g); the result is the
    sender-major grid (row ``k*group_rows + r`` = row r of what peer k sent
    me) — exactly the all_to_all(split0, concat0, tiled) output, one
    ``ppermute`` per scheduled item instead of one opaque collective."""
    if sched is None:  # dim == 1: the group is already mine
        return flat
    lane = flat.shape[1]
    w = group_rows // sched.chunks
    grid = jnp.zeros_like(flat)
    own = jax.lax.dynamic_slice(flat, (me * group_rows, 0), (group_rows, lane))
    grid = jax.lax.dynamic_update_slice(grid, own, (me * group_rows, 0))
    for step in sched.steps:
        for item in step:
            d = item.offset
            send_row = ((me + d) % dim) * group_rows + item.chunk * w
            window = jax.lax.dynamic_slice(flat, (send_row, 0), (w, lane))
            got = jax.lax.ppermute(
                window, ax, [(i, (i + d) % dim) for i in range(dim)]
            )
            recv_row = ((me - d) % dim) * group_rows + item.chunk * w
            grid = jax.lax.dynamic_update_slice(grid, got, (recv_row, 0))
    return grid


def _axis_grid(ax, dim, group_rows, sched, flat, me, lowering, mesh_axes=None):
    """Dispatch one exchange phase to its lowering tier.  ``mesh_axes`` (full
    ordered (name, size) mesh layout) rebases ring positions to logical
    device ids for the remote-DMA tier when ``ax`` is a sub-axis."""
    if sched is None:
        return _axis_grid_xla(ax, dim, group_rows, sched, flat, me)
    lowering = resolve_schedule_lowering(lowering, sched.kind)
    if lowering == "xla":
        return _axis_grid_xla(ax, dim, group_rows, sched, flat, me)
    from sparkucx_tpu.ops.pallas_kernels import ring_exchange_grid

    return ring_exchange_grid(
        ax,
        dim,
        group_rows,
        group_rows // sched.chunks,
        sched.raw_steps(),
        flat,
        mesh_axes=mesh_axes,
        interpret=(lowering == "interpret"),
    )


def _ici_shard(spec: ExchangeSpec, sched: RingSchedule, lowering: str, data, size_row):
    """Flat-mesh shard body: scheduled grid + the dense lowering's compaction
    (bit-identical receive layout and metadata)."""
    me, sizes = gather_size_matrix(spec, size_row)
    recv_sizes = sizes[:, me]
    grid = _axis_grid(
        spec.axis_name, spec.num_executors, spec.slot_rows, sched, data, me, lowering
    )
    out = compact_slots(grid, recv_sizes, spec.slot_rows, spec.recv_rows)
    return out, recv_sizes[None, :]


def _hier_sched_shard(
    spec: ExchangeSpec, sched: HierarchicalSchedule, lowering: str, data, size_row
):
    """Hierarchical shard body: the two-phase route of hierarchy._hier_shard
    with each all_to_all replaced by that phase's OWN scheduled exchange —
    ICI hops may ride the remote-DMA kernel, DCN hops always ride scheduled
    XLA permutes (remote DMA cannot cross slices)."""
    S, C = sched.num_slices, sched.chips_per_slice
    slot = spec.slot_rows
    s_idx = jax.lax.axis_index("dcn")
    c_idx = jax.lax.axis_index("ici")
    me = s_idx * C + c_idx

    sizes = jax.lax.all_gather(size_row, ("dcn", "ici"), tiled=True)
    recv_sizes = sizes[:, me]

    perm_a = region_permutation(S, C, slot)  # (s',c') -> (c',s')
    grouped = data[perm_a]
    # the ICI ring runs over a SUB-axis: ring position c is logical device
    # s_idx * C + c, so the DMA tier needs the full mesh layout to rebase
    a = _axis_grid(
        "ici", C, S * slot, sched.ici, grouped, c_idx, lowering,
        mesh_axes=(("dcn", S), ("ici", C)),
    )
    perm_b = region_permutation(C, S, slot)  # (c_src,s') -> (s',c_src)
    staged = a[perm_b]
    b = _axis_grid("dcn", S, C * slot, sched.dcn, staged, s_idx, "xla")
    out = compact_slots(b, recv_sizes, slot, spec.recv_rows)
    return out, recv_sizes[None, :]


# ----------------------------------------------------------------------------
# Builders (same contract as ops/exchange.build_exchange)
# ----------------------------------------------------------------------------


def build_ici_exchange(
    mesh: Mesh,
    spec: ExchangeSpec,
    *,
    chunks_per_dest: int = 1,
    lowering: str = "auto",
    schedule=None,
):
    """Compile the scheduled exchange: ``fn(data, size_matrix) -> (recv,
    recv_sizes)`` — the exact contract, shardings, and donation rule of
    ``build_exchange`` (see its docstring for the layouts), with the
    collective replaced by the FAST-scheduled ring.

    Accepts flat meshes (one ring over ``spec.axis_name``) and (dcn, ici)
    meshes (a ring per phase — hierarchy.hop_schedule).  ``chunks_per_dest``
    is clamped to a pow2 divisor of each phase's transfer group
    (``schedule_chunks``); pass ``schedule`` to override entirely.
    ``lowering``: 'auto' (remote-DMA kernel on TPU, scheduled permutes
    elsewhere) | 'dma' | 'xla' | 'interpret'.
    """
    if spec.num_executors != mesh.devices.size:
        raise ValueError(
            f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}"
        )
    platform = mesh.devices.reshape(-1)[0].platform
    resolved = spec.resolve_impl(platform=platform)
    resolved.validate()
    if resolved.num_executors == 1:
        return build_exchange(mesh, spec)  # n=1: nothing to schedule
    low = resolve_ici_lowering(lowering, platform)
    hierarchical = set(mesh.axis_names) == {"dcn", "ici"}
    if schedule is None:
        from sparkucx_tpu.ops.hierarchy import hop_schedule

        schedule = hop_schedule(
            mesh, chunks_per_dest=chunks_per_dest, slot_rows=resolved.slot_rows
        )
    if hierarchical:
        if not isinstance(schedule, HierarchicalSchedule):
            raise ValueError("hierarchical mesh needs a HierarchicalSchedule")
        S, C = mesh.shape["dcn"], mesh.shape["ici"]
        if (schedule.num_slices, schedule.chips_per_slice) != (S, C):
            raise ValueError(
                f"schedule factorization {schedule.num_slices}x"
                f"{schedule.chips_per_slice} != mesh {S}x{C}"
            )
        # per-phase mirror of the flat branch's checks: a chunk count that
        # doesn't divide the phase's transfer group would truncate
        # window_rows and silently drop the tail of every transfer
        if schedule.ici is not None:
            if schedule.ici.dim != C:
                raise ValueError(
                    f"ici schedule dim {schedule.ici.dim} != mesh ici axis {C}"
                )
            if (S * resolved.slot_rows) % schedule.ici.chunks:
                raise ValueError(
                    f"ici chunks {schedule.ici.chunks} must divide the ICI "
                    f"transfer group {S * resolved.slot_rows} rows"
                )
        if schedule.dcn is not None:
            if schedule.dcn.dim != S:
                raise ValueError(
                    f"dcn schedule dim {schedule.dcn.dim} != mesh dcn axis {S}"
                )
            if (C * resolved.slot_rows) % schedule.dcn.chunks:
                raise ValueError(
                    f"dcn chunks {schedule.dcn.chunks} must divide the DCN "
                    f"transfer group {C * resolved.slot_rows} rows"
                )
        # effective tier: the DCN phase always rides xla; the ICI phase keeps
        # the DMA tier only when its hops really are intra-slice ICI
        if schedule.ici is None:
            low = "xla"
        else:
            low = resolve_schedule_lowering(low, schedule.ici.kind)
            if low == "interpret":
                # jax's interpret discharge of remote DMA only supports
                # single-axis meshes; the schedule logic is still exercised
                low = "xla"
        body = functools.partial(_hier_sched_shard, resolved, schedule, low)
        pspec = P(("dcn", "ici"), None)
    else:
        if not isinstance(schedule, RingSchedule):
            raise ValueError("flat mesh needs a RingSchedule")
        if schedule.dim != resolved.num_executors:
            raise ValueError(
                f"schedule dim {schedule.dim} != num_executors {resolved.num_executors}"
            )
        if resolved.slot_rows % schedule.chunks:
            raise ValueError(
                f"chunks {schedule.chunks} must divide slot_rows {resolved.slot_rows}"
            )
        # flat mesh spanning slices: hop_schedule classifies every hop 'dcn'
        # (remote DMA cannot cross slices) — ride scheduled permutes instead
        low = resolve_schedule_lowering(low, schedule.kind)
        body = functools.partial(_ici_shard, resolved, schedule, low)
        pspec = P(resolved.axis_name, None)

    shard = shard_map(
        body,
        mesh=mesh,
        in_specs=(pspec, pspec),
        out_specs=(pspec, pspec),
        check_vma=False,
    )
    sharding = NamedSharding(mesh, pspec)
    # Donation rule shared with build_exchange: staging recycles into the
    # receive buffer only when shapes match; the size matrix is never donated.
    donate = (0,) if resolved.send_rows == resolved.recv_rows else ()
    fn = jax.jit(
        shard,
        in_shardings=(sharding, sharding),
        out_shardings=(sharding, sharding),
        donate_argnums=donate,
    )
    fn.spec = resolved
    fn.schedule = schedule
    fn.lowering = low
    return fn


def build_fused_ici_exchange(
    mesh: Mesh,
    spec: ExchangeSpec,
    num_blocks: int,
    *,
    chunks_per_dest: int = 1,
    lowering: str = "auto",
    schedule=None,
    max_block_rows: Optional[int] = None,
):
    """Compile the fused send side: ``fn(starts, counts, outs, packed,
    staging, size_matrix) -> (recv, recv_sizes)`` — block scatter + scheduled
    exchange in ONE launch, no intermediate HBM round trip.

    The plan triple follows ``build_block_scatter`` (per device: starts =
    slot-layout destination rows, counts, outs = packed source offsets,
    zero-count blocks no-ops), shipped as (n, num_blocks) int32 row-sharded
    arrays; ``packed`` is the row-sharded packed map output and ``staging``
    the row-sharded slot-layout staging whose untouched rows carry through.
    On TPU the whole pipeline is one Pallas kernel
    (pallas_kernels.fused_scatter_ring_grid, staging aliased + donated); the
    portable lowering composes the window-scan scatter with the scheduled
    permutes inside the same jit — either way the separate staging kernel
    launch is gone.  Flat meshes only (device staging is a flat-cluster
    feature)."""
    if set(mesh.axis_names) == {"dcn", "ici"}:
        raise ValueError("fused exchange supports flat meshes only")
    if spec.num_executors != mesh.devices.size:
        raise ValueError(
            f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}"
        )
    platform = mesh.devices.reshape(-1)[0].platform
    resolved = spec.resolve_impl(platform=platform)
    resolved.validate()
    if resolved.num_executors == 1:
        raise ValueError("fused ici exchange needs num_executors > 1")
    low = resolve_ici_lowering(lowering, platform)
    if schedule is None:
        # same fabric classification as hierarchy.hop_schedule: a flat mesh
        # spanning slices means every offset crosses DCN for some source
        ids = device_slice_ids(mesh.devices.reshape(-1))
        kind = "ici" if ids is None or len(set(ids)) == 1 else "dcn"
        chunks = schedule_chunks(resolved.slot_rows, chunks_per_dest)
        schedule = ring_schedule(resolved.num_executors, chunks, kind=kind)
    if resolved.slot_rows % schedule.chunks:
        raise ValueError(
            f"chunks {schedule.chunks} must divide slot_rows {resolved.slot_rows}"
        )
    low = resolve_schedule_lowering(low, schedule.kind)
    window = max(1, max_block_rows if max_block_rows is not None else resolved.slot_rows)
    n = resolved.num_executors
    slot = resolved.slot_rows

    def body(starts, counts, outs, packed, staging, size_row):
        starts = starts.reshape(-1)
        counts = counts.reshape(-1)
        outs = outs.reshape(-1)
        me, sizes = gather_size_matrix(resolved, size_row)
        recv_sizes = sizes[:, me]
        if low == "xla":
            from sparkucx_tpu.ops.pallas_kernels import xla_scatter_windows

            staged = xla_scatter_windows(
                window, resolved.send_rows, starts, counts, outs, packed, staging
            )
            grid = _axis_grid_xla(
                resolved.axis_name, n, slot, schedule, staged, me
            )
        else:
            from sparkucx_tpu.ops.pallas_kernels import fused_scatter_ring_grid

            grid, _staged = fused_scatter_ring_grid(
                resolved.axis_name,
                n,
                slot,
                slot // schedule.chunks,
                schedule.raw_steps(),
                starts,
                counts,
                outs,
                packed,
                staging,
                interpret=(low == "interpret"),
            )
        out = compact_slots(grid, recv_sizes, slot, resolved.recv_rows)
        return out, recv_sizes[None, :]

    ax = resolved.axis_name
    pspec = P(ax, None)
    shard = shard_map(
        body,
        mesh=mesh,
        in_specs=(pspec, pspec, pspec, pspec, pspec, pspec),
        out_specs=(pspec, pspec),
        check_vma=False,
    )
    sharding = NamedSharding(mesh, pspec)
    # staging (argnum 4) is consumed by the fused kernel; donation makes the
    # in-kernel scatter a true in-place append on TPU (CPU donation warns).
    donate = (4,) if platform == "tpu" else ()
    fn = jax.jit(
        shard,
        in_shardings=(sharding,) * 6,
        out_shardings=(sharding, sharding),
        donate_argnums=donate,
    )
    fn.spec = resolved
    fn.schedule = schedule
    fn.lowering = low
    return fn


# ----------------------------------------------------------------------------
# Quantized builders (tier-b payload reduction, ops/compress.py)
# ----------------------------------------------------------------------------


def _quantized_prep(mesh: Mesh, spec, quantize, lowering: str, chunks_per_dest, schedule):
    """Shared validation + schedule resolution for the quantized builders
    (flat meshes only — the quantized payload rides one ring)."""
    if set(mesh.axis_names) == {"dcn", "ici"}:
        raise ValueError("quantized exchange supports flat meshes only")
    if spec.num_executors != mesh.devices.size:
        raise ValueError(
            f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}"
        )
    quantize.validate()
    if not quantize.enabled:
        raise ValueError(
            "quantized exchange needs quantize mode 'int8'|'blockfloat'; "
            "use build_ici_exchange for the lossless path"
        )
    platform = mesh.devices.reshape(-1)[0].platform
    resolved = spec.resolve_impl(platform=platform)
    resolved.validate()
    if resolved.num_executors == 1:
        raise ValueError("quantized ici exchange needs num_executors > 1")
    low = resolve_ici_lowering(lowering, platform)
    if schedule is None:
        ids = device_slice_ids(mesh.devices.reshape(-1))
        kind = "ici" if ids is None or len(set(ids)) == 1 else "dcn"
        chunks = schedule_chunks(resolved.slot_rows, chunks_per_dest)
        schedule = ring_schedule(resolved.num_executors, chunks, kind=kind)
    if not isinstance(schedule, RingSchedule):
        raise ValueError("flat mesh needs a RingSchedule")
    if resolved.slot_rows % schedule.chunks:
        raise ValueError(
            f"chunks {schedule.chunks} must divide slot_rows {resolved.slot_rows}"
        )
    low = resolve_schedule_lowering(low, schedule.kind)
    return platform, resolved, low, schedule


def build_quantized_exchange(
    mesh: Mesh,
    spec,
    quantize,
    *,
    chunks_per_dest: int = 1,
    lowering: str = "auto",
    schedule=None,
):
    """Compile the quantized scheduled exchange: ``fn(data, size_matrix) ->
    (recv, recv_sizes)`` where ``data`` is FLOAT32 ``(n * send_rows, lane)``
    — the ``build_ici_exchange`` contract with tier-b block quantization
    (ops/compress.py QuantizeSpec) fused around the collective: quantize on
    the send side, ring-exchange the int8x4-packed int32 payload
    (``quantize.quantized_width(lane)`` lanes — 4x fewer ICI bytes per float
    lane plus scales), dequantize after compaction — all inside ONE jit, so
    staging→wire stays one launch.  OPT-IN LOSSY: per-block error is bounded
    by ``quantize.error_bound`` (tests/test_compress.py tolerance gate); row
    counts and size semantics are unchanged (quantization is per-row)."""
    from sparkucx_tpu.ops.compress import dequantize_rows, quantize_rows

    platform, resolved, low, schedule = _quantized_prep(
        mesh, spec, quantize, lowering, chunks_per_dest, schedule
    )
    n, slot = resolved.num_executors, resolved.slot_rows

    def body(data, size_row):
        me, sizes = gather_size_matrix(resolved, size_row)
        recv_sizes = sizes[:, me]
        q = quantize_rows(quantize, data)
        grid = _axis_grid(resolved.axis_name, n, slot, schedule, q, me, low)
        outq = compact_slots(grid, recv_sizes, slot, resolved.recv_rows)
        out = dequantize_rows(quantize, outq, resolved.lane)
        return out, recv_sizes[None, :]

    pspec = P(resolved.axis_name, None)
    shard = shard_map(
        body, mesh=mesh, in_specs=(pspec, pspec), out_specs=(pspec, pspec),
        check_vma=False,
    )
    sharding = NamedSharding(mesh, pspec)
    # same donation rule as build_ici_exchange: the f32 staging recycles into
    # the f32 receive buffer only when shapes match
    donate = (0,) if resolved.send_rows == resolved.recv_rows else ()
    fn = jax.jit(
        shard,
        in_shardings=(sharding, sharding),
        out_shardings=(sharding, sharding),
        donate_argnums=donate,
    )
    fn.spec = resolved
    fn.schedule = schedule
    fn.lowering = low
    fn.qspec = quantize
    return fn


# ----------------------------------------------------------------------------
# Fused-combine lowering + builder (receive-side compute-in-exchange)
# ----------------------------------------------------------------------------


def _combine_axis_grid_xla(ax, dim: int, slot_rows: int, sched: RingSchedule, flat, me, cspec):
    """Scheduled-permute fold: one ppermute per item, but every landed window
    goes straight into the dense accumulator — the sender-major grid is never
    materialized, so even this tier's post-exchange memory is O(groups).

    Fold order is the canonical one every lowering shares (own slot, then
    schedule items in step order) — bit-equality across tiers for exact
    dtypes rests on it."""
    from sparkucx_tpu.ops.combine import acc_init, combine_window

    lane = flat.shape[1]
    accv, accc = acc_init(cspec)
    own = jax.lax.dynamic_slice(flat, (me * slot_rows, 0), (slot_rows, lane))
    accv, accc = combine_window(cspec, own, accv, accc)
    w = slot_rows // sched.chunks
    for step in sched.steps:
        for item in step:
            d = item.offset
            send_row = ((me + d) % dim) * slot_rows + item.chunk * w
            window = jax.lax.dynamic_slice(flat, (send_row, 0), (w, lane))
            got = jax.lax.ppermute(
                window, ax, [(i, (i + d) % dim) for i in range(dim)]
            )
            accv, accc = combine_window(cspec, got, accv, accc)
    return accv, accc


def combine_axis_grid(
    ax, dim, slot_rows, sched, flat, me, cspec, lowering, mesh_axes=None
):
    """Dispatch one fused-combine exchange phase to its lowering tier and
    return the ``(acc_vals, acc_counts)`` accumulator pair (identity-seeded —
    callers merge running accumulators via ``merge_accumulators``).  Also the
    shard-body entry point for ops/relational.py's fused aggregate, which
    runs its own shard_map."""
    lowering = resolve_schedule_lowering(lowering, sched.kind)
    if lowering == "xla":
        return _combine_axis_grid_xla(ax, dim, slot_rows, sched, flat, me, cspec)
    from sparkucx_tpu.ops.combine import acc_init, combine_window
    from sparkucx_tpu.ops.pallas_kernels import ring_combine_grid

    _grid, accv, accc = ring_combine_grid(
        ax,
        dim,
        slot_rows,
        slot_rows // sched.chunks,
        sched.raw_steps(),
        functools.partial(combine_window, cspec),
        functools.partial(acc_init, cspec),
        cspec.num_groups,
        cspec.width,
        flat,
        mesh_axes=mesh_axes,
        interpret=(lowering == "interpret"),
    )
    # the landed grid stays on device and unread — the accumulator IS the
    # receive side; XLA drops the unused output buffer from the drain
    return accv, accc


def _combine_prep(mesh: Mesh, spec, cspec, lowering: str, chunks_per_dest, schedule):
    """Shared validation + schedule resolution for the fused-combine builder
    (flat meshes only — the combinable payload rides one ring)."""
    if set(mesh.axis_names) == {"dcn", "ici"}:
        raise ValueError("combine exchange supports flat meshes only")
    if spec.num_executors != mesh.devices.size:
        raise ValueError(
            f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}"
        )
    cspec.validate()
    if spec.lane != cspec.row_width:
        raise ValueError(
            f"spec.lane={spec.lane} != combine row width {cspec.row_width} "
            f"(key + payload + count)"
        )
    platform = mesh.devices.reshape(-1)[0].platform
    resolved = spec.resolve_impl(platform=platform)
    resolved.validate()
    if resolved.num_executors == 1:
        raise ValueError("combine ici exchange needs num_executors > 1")
    low = resolve_ici_lowering(lowering, platform)
    if schedule is None:
        ids = device_slice_ids(mesh.devices.reshape(-1))
        kind = "ici" if ids is None or len(set(ids)) == 1 else "dcn"
        chunks = schedule_chunks(resolved.slot_rows, chunks_per_dest)
        schedule = ring_schedule(resolved.num_executors, chunks, kind=kind)
    if not isinstance(schedule, RingSchedule):
        raise ValueError("flat mesh needs a RingSchedule")
    if resolved.slot_rows % schedule.chunks:
        raise ValueError(
            f"chunks {schedule.chunks} must divide slot_rows {resolved.slot_rows}"
        )
    low = resolve_schedule_lowering(low, schedule.kind)
    return platform, resolved, low, schedule


def build_combine_exchange(
    mesh: Mesh,
    spec,
    cspec,
    *,
    chunks_per_dest: int = 1,
    lowering: str = "auto",
    schedule=None,
):
    """Compile the fused-combine exchange: ``fn(data, size_matrix, acc_vals,
    acc_counts) -> (acc_vals, acc_counts, recv_sizes)`` — the scheduled ring
    with the receive side REPLACED by the dense per-group fold
    (ops/combine.py): landed windows are dequantized and combined as they
    arrive, never compacted into a recv buffer.

    * ``data``: ``(n * send_rows, lane)`` slot-layout partial-aggregate
      staging, rows in the combine layout ``[key | payload | count]``
      (``cspec.row_width`` lanes, enforced against ``spec.lane``).
    * ``acc_vals`` ``(n * num_groups, width)`` / ``acc_counts``
      ``(n * num_groups, 1)`` — the RUNNING accumulator, merged with this
      exchange's fold and returned.  Both are donated (argnums 2, 3): quota
      sub-rounds thread one accumulator through every call in place instead
      of staging O(rows) per sub-round.  Seed fresh rounds with
      ``ops/combine.acc_init`` under shard_map (or tile its host values).
    * ``recv_sizes``: the usual ``(n, n)`` receive-size metadata — row
      accounting is unchanged, only the payload drain shrinks to O(groups).

    ``lowering`` follows ``build_ici_exchange``: 'dma' is ONE fused kernel
    launch (pallas_kernels.ring_combine_grid) on TPU, 'xla' the scheduled
    permutes with per-window folds, 'interpret' the kernel body under the
    Pallas interpreter (CI).  Bit-equality across tiers for exact dtypes is
    pinned by tests/test_fused_combine.py.  Flat meshes only."""
    from sparkucx_tpu.ops.combine import merge_accumulators

    platform, resolved, low, schedule = _combine_prep(
        mesh, spec, cspec, lowering, chunks_per_dest, schedule
    )
    n, slot = resolved.num_executors, resolved.slot_rows

    def body(data, size_row, accv, accc):
        me, sizes = gather_size_matrix(resolved, size_row)
        recv_sizes = sizes[:, me]
        av, ac = combine_axis_grid(
            resolved.axis_name, n, slot, schedule, data, me, cspec, low
        )
        accv, accc = merge_accumulators(cspec, (accv, accc), (av, ac))
        return accv, accc, recv_sizes[None, :]

    pspec = P(resolved.axis_name, None)
    shard = shard_map(
        body, mesh=mesh, in_specs=(pspec,) * 4, out_specs=(pspec,) * 3,
        check_vma=False,
    )
    sharding = NamedSharding(mesh, pspec)
    # the running accumulator is consumed and re-emitted with identical
    # shape/sharding every call — donate so sub-round chaining is in place
    fn = jax.jit(
        shard,
        in_shardings=(sharding,) * 4,
        out_shardings=(sharding,) * 3,
        donate_argnums=(2, 3),
    )
    fn.spec = resolved
    fn.schedule = schedule
    fn.lowering = low
    fn.cspec = cspec
    return fn


def build_quantized_fused_exchange(
    mesh: Mesh,
    spec,
    quantize,
    num_blocks: int,
    *,
    chunks_per_dest: int = 1,
    lowering: str = "auto",
    schedule=None,
    max_block_rows: Optional[int] = None,
):
    """Quantized twin of ``build_fused_ici_exchange``: ``fn(starts, counts,
    outs, packed, staging, size_matrix) -> (recv, recv_sizes)`` with FLOAT32
    packed/staging — block scatter, send-side quantize, scheduled ring
    exchange of the int32 payload, and receive-side dequantize composed in
    ONE jit/launch.  The scatter always rides the window-scan lowering
    (``xla_scatter_windows`` — the quantize sits between scatter and ring,
    so the monolithic scatter+ring kernel cannot apply); the ring itself
    still lowers per ``lowering`` ('dma' = the remote-DMA Pallas kernel on
    the quantized grid)."""
    from sparkucx_tpu.ops.compress import dequantize_rows, quantize_rows

    platform, resolved, low, schedule = _quantized_prep(
        mesh, spec, quantize, lowering, chunks_per_dest, schedule
    )
    n, slot = resolved.num_executors, resolved.slot_rows
    window = max(1, max_block_rows if max_block_rows is not None else resolved.slot_rows)

    def body(starts, counts, outs, packed, staging, size_row):
        from sparkucx_tpu.ops.pallas_kernels import xla_scatter_windows

        starts = starts.reshape(-1)
        counts = counts.reshape(-1)
        outs = outs.reshape(-1)
        me, sizes = gather_size_matrix(resolved, size_row)
        recv_sizes = sizes[:, me]
        staged = xla_scatter_windows(
            window, resolved.send_rows, starts, counts, outs, packed, staging
        )
        q = quantize_rows(quantize, staged)
        grid = _axis_grid(resolved.axis_name, n, slot, schedule, q, me, low)
        outq = compact_slots(grid, recv_sizes, slot, resolved.recv_rows)
        out = dequantize_rows(quantize, outq, resolved.lane)
        return out, recv_sizes[None, :]

    pspec = P(resolved.axis_name, None)
    shard = shard_map(
        body, mesh=mesh, in_specs=(pspec,) * 6, out_specs=(pspec, pspec),
        check_vma=False,
    )
    sharding = NamedSharding(mesh, pspec)
    # staging (argnum 4) is consumed by the in-jit scatter, exactly like
    # build_fused_ici_exchange (CPU donation warns, so TPU only)
    donate = (4,) if platform == "tpu" else ()
    fn = jax.jit(
        shard,
        in_shardings=(sharding,) * 6,
        out_shardings=(sharding, sharding),
        donate_argnums=donate,
    )
    fn.spec = resolved
    fn.schedule = schedule
    fn.lowering = low
    fn.qspec = quantize
    return fn
