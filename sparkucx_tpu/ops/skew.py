"""Skew-aware exchange planning — per-destination quotas and hot-lane chunking.

``bucket_send_rows`` (ops/exchange.py) sizes every peer slot to the *global*
hottest destination, so one skewed reduce partition inflates staging HBM to
``n * max_peer`` rows, forces earlier spill rollovers, widens the compile
bucket, and — under the portable dense lowering, which moves whole slots —
ships the padding over the wire.  Real shuffle workloads are Zipf-skewed;
both FAST's all-to-all scheduling and "Memory-efficient array redistribution
through portable collective communication" (PAPERS.md) decompose a skewed
all-to-all into balanced, capacity-capped phases that recover the bandwidth
and memory the padded single-shot lowering wastes.

This module is that decomposition, host-side and data-free: given the sealed
size matrix and a row quota (``conf.slot_quota_rows``), it caps the per-peer
slot at the quota and *chunks* oversized peer payloads across additional
pipelined sub-rounds — the extra rounds ride the existing ``RoundPipeline``
depth-d overlap (transport/pipeline.py), so hot-lane bytes stream while cold
lanes finish.  Everything here is pure geometry over host ints/arrays:

* ``quota_slot_rows`` — the quota-capped, pow2-bucketed slot (the compile
  bucket both transports key their exchange cache on);
* ``plan_exchange`` / ``ExchangePlan`` — per staging round, how many
  quota-sized sub-rounds cover the hottest lane;
* ``chunk_size_rows`` / ``slice_subround`` — the sender side: one
  sub-round's size row and payload slice (``xp=np`` host, ``xp=jnp`` for
  device-sealed payloads — same expressions either way);
* ``piece_slices`` / ``reassemble_round`` — the receiver side: splice the
  sub-rounds' tight sender-major shards back into the exact buffer the
  single-shot exchange would have produced (bit-equality is asserted in
  tests/test_skew.py);
* ``staging_occupancy`` / ``pad_rows_pow2`` — telemetry and device-shard
  shape hygiene.

The planner never sees payload bytes, only the size matrix — the same
metadata-before-data discipline as the reference's MapperInfo commit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Tuple

import numpy as np


def quota_slot_rows(slot_rows: int, quota_rows: int) -> int:
    """The quota-capped compile bucket for a per-peer slot: cap ``slot_rows``
    at ``quota_rows`` (``<= 0`` disables the cap — today's behavior), then
    round up to the next power of two.

    The result is what the transports hand ``_exchange_fn`` (times ``n``), so
    skewed and uniform shuffles whose caps land in one bucket share a compiled
    executable — a pow2 slot is a fixed point of ``bucket_send_rows``, so the
    existing cache keying applies unchanged."""
    if slot_rows <= 0:
        raise ValueError("slot_rows must be positive")
    cap = slot_rows if quota_rows <= 0 else min(slot_rows, quota_rows)
    bucket = 1
    while bucket < cap:
        bucket <<= 1
    return bucket


@dataclass(frozen=True)
class ExchangePlan:
    """One shuffle's declarative exchange schedule — THE exchange interface.

    The geometry core is unchanged: ``chunks_per_round[r]`` quota-sized
    sub-rounds cover staging round ``r``'s hottest lane, and ``slot_rows`` is
    the per-peer slot every sub-round stages (the compile bucket).  Around it,
    the plan now carries everything the unified executor
    (transport/executor.py) interprets and the serve plane reads:

    * ``single_shot`` — drain style.  True is the historical quota-off
      engine: whole padded shards retained directly (supports donation of
      device-sealed payloads and elastic degraded recovery).  False is the
      chunked engine: each staging round's tight sub-round shards are
      spliced back into the exact single-shot layout (bit-identical over the
      valid prefix; no trailing padding kept).
    * ``round_order`` — submission order over staging rounds (a permutation;
      empty = natural order).  Produced by the staging-footprint reordering
      pass (ops/planner.py, after arXiv:2112.01075); results are always
      emitted back in natural round order.
    * ``lowering`` — the collective tier (``conf.exchange_impl`` vocabulary:
      'stock' | 'pallas' | 'auto'), interpreted by ``build_plan_exchange``.
    * ``pipeline_depth`` — the superstep overlap window for this shuffle.
    * ``streams`` / ``codec`` / ``quantize_mode`` + ``quantize_block`` /
      ``hedge_ms`` — the serve/wire-plane tiers chosen for this shuffle's
      traffic (fetch striping, page codec, lossy aggregation quantization,
      hedged-fetch delay).  The collective executor never quantizes shuffle
      bytes (payloads are exact); these fields parameterize the fetch path,
      the aggregation plane, and the bench harness, and land in the per-
      shuffle ``exchange.plan`` trace event.
    * ``combine`` — the receive-side compute-in-exchange tier for partial
      grouped aggregations (``'off' | 'dense' | 'sorted'``).  ``dense`` folds
      every landed window into a fixed per-group accumulator inside the
      exchange (O(groups) post-exchange memory and drain bytes, one fused
      kernel launch under the DMA lowering); ``sorted`` is the bounded
      per-superstep sort/merge fallback when the key domain is not
      dense-representable.  Only meaningful when the shuffle carries an
      ``AggregateSpec`` with partial aggregation; raw block exchanges ignore
      it.  Chosen from all-gathered geometry only (SPMD lockstep — see
      ops/planner.py).
    """

    slot_rows: int
    chunks_per_round: Tuple[int, ...]
    single_shot: bool = False
    round_order: Tuple[int, ...] = ()
    lowering: str = "stock"
    pipeline_depth: int = 2
    streams: int = 1
    codec: str = "off"
    quantize_mode: str = "off"
    quantize_block: int = 128
    hedge_ms: int = 0
    combine: str = "off"

    @property
    def num_subrounds(self) -> int:
        return sum(self.chunks_per_round)

    def subrounds(self) -> List[Tuple[int, int, int]]:
        """Flat submission order: ``(staging_round, chunk, num_chunks)`` per
        sub-round, chunk-major within each staging round — the order the
        pipeline submits and the single drain worker reassembles."""
        out: List[Tuple[int, int, int]] = []
        for rnd, nchunks in enumerate(self.chunks_per_round):
            for chunk in range(nchunks):
                out.append((rnd, chunk, nchunks))
        return out

    def ordered_subrounds(self) -> List[Tuple[int, int, int]]:
        """``subrounds()`` permuted by ``round_order``: whole staging rounds
        are reordered as units (chunk order within a round is load-bearing —
        the splice reassembles in chunk order), so the executor can submit
        lighter rounds first while the drain still groups by round."""
        if not self.round_order:
            return self.subrounds()
        if sorted(self.round_order) != list(range(len(self.chunks_per_round))):
            raise ValueError(
                f"round_order {self.round_order} is not a permutation of "
                f"{len(self.chunks_per_round)} staging rounds"
            )
        out: List[Tuple[int, int, int]] = []
        for rnd in self.round_order:
            nchunks = self.chunks_per_round[rnd]
            for chunk in range(nchunks):
                out.append((rnd, chunk, nchunks))
        return out

    def staged_rows(self, num_executors: int) -> int:
        """Total staged rows across the whole exchange (``n`` executors x
        ``n`` slots x ``slot_rows``, summed over sub-rounds) — the memory/wire
        quantity the quota exists to shrink; under the dense lowering this
        times ``row_bytes`` is exactly the wire traffic."""
        n = num_executors
        return self.num_subrounds * n * n * self.slot_rows

    def describe(self) -> dict:
        """JSON-safe flat view for the per-shuffle ``exchange.plan`` trace
        event and the flight recorder (every value a scalar or short list)."""
        return {
            "slot_rows": self.slot_rows,
            "chunks_per_round": list(self.chunks_per_round),
            "num_subrounds": self.num_subrounds,
            "single_shot": self.single_shot,
            "round_order": list(self.round_order),
            "lowering": self.lowering,
            "pipeline_depth": self.pipeline_depth,
            "streams": self.streams,
            "codec": self.codec,
            "quantize_mode": self.quantize_mode,
            "quantize_block": self.quantize_block,
            "hedge_ms": self.hedge_ms,
            "combine": self.combine,
        }


def plan_exchange(
    round_max_rows: Sequence[int], slot_rows: int, quota_rows: int
) -> ExchangePlan:
    """Plan the sub-round schedule from per-staging-round hottest-lane sizes.

    ``round_max_rows[r]`` is the max over (sender, destination) of the used
    rows in staging round ``r`` — cluster-wide (all executors' seals; the SPMD
    executor all-gathers it so every process derives the same plan).  Each
    round gets ``ceil(max / quota_slot)`` chunks, at least one so empty rounds
    still run their collective (SPMD lockstep)."""
    q = quota_slot_rows(slot_rows, quota_rows)
    chunks = tuple(max(1, -(-int(m) // q)) for m in round_max_rows)
    return ExchangePlan(slot_rows=q, chunks_per_round=chunks)


def chunk_size_rows(size_row, chunk: int, quota_slot: int, *, xp=np):
    """One sub-round's size-matrix row: the rows of each destination's payload
    that fall in window ``[chunk * quota_slot, (chunk + 1) * quota_slot)``.

    Summing over chunks reproduces ``size_row`` exactly (row conservation —
    property-tested), so the logical per-round receive sizes every consumer
    slices by are the sums the drain worker accumulates."""
    lo = chunk * quota_slot
    return xp.clip(
        xp.asarray(size_row, dtype=xp.int32) - xp.int32(lo), 0, quota_slot
    ).astype(xp.int32)


def slice_subround(payload, num_executors: int, chunk: int, quota_slot: int, *, xp=np):
    """The sender side of one sub-round: slice row window ``chunk`` out of
    every peer slot of a ``(n * staging_slot, lane)`` slot-layout payload and
    relocate into the quota-capped ``(n * quota_slot, lane)`` slot layout.

    With ``chunk == 0`` and ``quota_slot >= staging_slot`` this is exactly
    ``rebucket_slots`` (the unchunked relocation).  Rows of the window beyond
    a destination's used count are staging garbage/zeros — the sub-round's
    size row (``chunk_size_rows``) keeps them out of every lowering's valid
    output, same contract as the unchunked exchange.  ``xp=jnp`` slices a
    device-sealed payload on its device (no host round trip)."""
    rows, lane = int(payload.shape[0]), int(payload.shape[1])
    n = num_executors
    if rows % n:
        raise ValueError(f"payload rows {rows} not a multiple of {n} executors")
    slot = rows // n
    lo = chunk * quota_slot
    if lo >= slot:
        # window entirely past the staging slot: all-pad sub-round (this
        # executor's lanes are cold while a hotter peer still streams)
        return xp.zeros((n * quota_slot, lane), dtype=payload.dtype)
    hi = min(lo + quota_slot, slot)
    grid = payload.reshape(n, slot, lane)
    piece = grid[:, lo:hi, :]
    if hi - lo < quota_slot:
        piece = xp.pad(piece, ((0, 0), (0, quota_slot - (hi - lo)), (0, 0)))
    return piece.reshape(n * quota_slot, lane)


def piece_slices(sub_sizes: Sequence[np.ndarray]) -> List[Tuple[int, int, int]]:
    """Receiver-side splice plan for one staging round: given each sub-round's
    received size row (``sub_sizes[c][i]`` = rows received from sender ``i``
    in sub-round ``c``, each a tight sender-major shard), the pieces of the
    reassembled buffer in sender-major order as ``(sub_round, start_row,
    rows)`` — sender ``i``'s chunks concatenate across sub-rounds in chunk
    order, restoring the exact layout the single-shot exchange produces.
    Zero-row pieces are skipped."""
    starts = [np.concatenate([[0], np.cumsum(s)[:-1]]).astype(np.int64) for s in sub_sizes]
    out: List[Tuple[int, int, int]] = []
    n = len(sub_sizes[0]) if sub_sizes else 0
    for sender in range(n):
        for c, sizes in enumerate(sub_sizes):
            rows = int(sizes[sender])
            if rows:
                out.append((c, int(starts[c][sender]), rows))
    return out


def reassemble_round(
    sub_shards: Sequence[np.ndarray], sub_sizes: Sequence[np.ndarray], row_bytes: int
) -> np.ndarray:
    """Splice one receiver's sub-round shards (flat uint8, tight sender-major)
    back into the single-shot receive buffer: byte-for-byte what the flat
    exchange would have produced over the valid prefix."""
    pieces = [
        sub_shards[c][start * row_bytes : (start + rows) * row_bytes]
        for c, start, rows in piece_slices(sub_sizes)
    ]
    if not pieces:
        return np.zeros(0, dtype=np.uint8)
    return np.concatenate(pieces)


def staging_occupancy(size_rows, slot_rows: int) -> Tuple[int, int]:
    """(used, padded) rows of a staged slot-layout buffer: ``size_rows`` used
    rows spread over ``size_rows.size`` slots of ``slot_rows`` capacity.  The
    padding telemetry both transports feed ``StatsAggregator`` — padded /
    (used + padded) is the fraction of staged HBM (and, under the dense
    lowering, wire bytes) the skew wastes."""
    arr = np.asarray(size_rows)
    used = int(arr.sum())
    return used, int(arr.size) * slot_rows - used


def pad_rows_pow2(shard, *, xp=np):
    """Pad a ``(rows, lane)`` array with zero rows up to the next power of
    two.  Reassembled device shards have data-dependent row counts; the
    device block gather is jit-compiled against its source shape, so handing
    it raw sizes would recompile per shuffle — pow2 rows keep the compile
    set bounded (the ``_gather_fn`` bucketing discipline)."""
    rows = int(shard.shape[0])
    bucket = 1 << max(rows - 1, 0).bit_length()
    if bucket == rows:
        return shard
    return xp.pad(shard, ((0, bucket - rows), (0, 0)))
