"""Device-resident transitive closure — the ``SparkTC`` workload.

The reference's integration gate is ``run_groupby_test && run_tc_test``
(buildlib/test.sh:175-179,196): SparkTC computes the transitive closure of a
random edge set by iterating ``tc = (tc union tc.join(edges)).distinct()`` to a
fixpoint, with the driver re-counting after every round.  The reference
accelerates only the shuffle under that job's joins/distincts; here — like
ops/sort.py for TeraSort and ops/relational.py for the SQL plans — the ENTIRE
iteration runs on the executor mesh as one jitted SPMD step:

    hash-exchange tc by dst + edges by src  ->  local sort-merge expansion
    (new paths a->c from a->b and b->c)     ->  union with tc  ->
    hash-exchange pairs by mix(a,b)         ->  local lex-sort dedup (DISTINCT)

The Python-side loop only compares the global pair count between rounds —
exactly the role Spark's driver plays (``while (nextCount != oldCount)``); the
per-round work is 3 ragged collectives + device-local compute, no
data-dependent shapes.

Vertex ids must be < 0xFFFFFFFF (the KEY_MAX padding sentinel — the same
discipline as ops/sort.py).  All capacities are static; every step reports true
totals so overflow is detectable, the SortSpec.recv_capacity contract.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops._compat import shard_map
from sparkucx_tpu.ops.columnar import ColumnarSpec
from sparkucx_tpu.ops.relational import exchange_keyed_rows, expand_matches, padded_keys
from sparkucx_tpu.ops.sort import KEY_MAX

_MIX_A = np.uint32(2654435761)  # Knuth multiplicative
_MIX_B = np.uint32(40503)       # 16-bit Fibonacci constant, odd


def _pair_mix(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Mix a pair of uint32s into one uint32 partitioning key (only duplicate
    pairs MUST collide; quality just balances shards)."""
    h = a.astype(jnp.uint32) * _MIX_A
    h = h ^ ((h >> 15) | (b.astype(jnp.uint32) * _MIX_B))
    return h * _MIX_A


@dataclass(frozen=True)
class TcSpec:
    """Static description of one compiled TC iteration.

    ``edge_capacity``: per-executor input edges.  ``tc_capacity``: per-executor
    closure rows — must hold each shard's slice of the final closure (hash of
    the pair mix balances shards, so ~|closure|/n with headroom).
    ``join_capacity``: per-executor new-path expansion bound per round.
    ``recv_*`` default to the matching capacity; raise them for skewed graphs
    (a high-degree hub vertex routes all its paths to one shard in the join)."""

    num_executors: int
    edge_capacity: int
    tc_capacity: int
    join_capacity: int
    edge_recv_capacity: Optional[int] = None
    tc_recv_capacity: Optional[int] = None
    axis_name: str = "ex"
    impl: str = "auto"

    @property
    def edge_recv(self) -> int:
        return self.edge_recv_capacity or self.edge_capacity

    @property
    def tc_recv(self) -> int:
        return self.tc_recv_capacity or self.tc_capacity

    def resolve_impl(self, platform: Optional[str] = None) -> "TcSpec":
        if self.impl != "auto":
            return self
        if platform is None:
            platform = jax.devices()[0].platform
        return replace(self, impl="ragged" if platform == "tpu" else "dense")

    def validate(self) -> None:
        if self.impl not in ("ragged", "dense"):
            raise ValueError(f"unknown impl {self.impl!r}")


def _lex_dedup(a: jnp.ndarray, b: jnp.ndarray, valid: jnp.ndarray, out_rows: int):
    """Sort pairs lexicographically ((a, b), padding last) and keep one of each
    — the device DISTINCT.  Returns (a', b', count) with the distinct pairs as
    a tight ascending prefix."""
    a = padded_keys(a, valid)
    b = jnp.where(valid, b.astype(jnp.uint32), KEY_MAX)
    # two-pass stable sort = lexicographic (b minor, a major)
    order_b = jnp.argsort(b, stable=True)
    order = order_b[jnp.argsort(a[order_b], stable=True)]
    sa, sb = a[order], b[order]
    svalid = valid[order]
    first = jnp.concatenate(
        [jnp.ones(1, bool), (sa[1:] != sa[:-1]) | (sb[1:] != sb[:-1])]
    ) & svalid
    seg = jnp.where(svalid, jnp.cumsum(first.astype(jnp.int32)) - 1, out_rows)
    count = first.sum().astype(jnp.int32)
    out_a = jnp.full(out_rows, KEY_MAX, jnp.uint32).at[seg].set(sa, mode="drop")
    out_b = jnp.full(out_rows, KEY_MAX, jnp.uint32).at[seg].set(sb, mode="drop")
    return out_a, out_b, count


def _as_val(x: jnp.ndarray) -> jnp.ndarray:
    return jax.lax.bitcast_convert_type(x.astype(jnp.uint32), jnp.int32)[:, None]


def _cspec(spec: TcSpec, cap: int, recv: int, width: int) -> ColumnarSpec:
    return ColumnarSpec(
        num_executors=spec.num_executors, capacity=cap, recv_capacity=recv,
        width=width + 1, dtype=np.dtype(np.int32), axis_name=spec.axis_name,
        impl=spec.impl,
    )


def _tc_prep_body(spec: TcSpec, e_src, e_dst, e_num):
    """One-time build-side prep: hash-exchange the immutable edge set by src
    and sort it — every iterated round reuses the result instead of repeating
    the exchange + sort (the edges never change)."""
    e_valid = jnp.arange(spec.edge_capacity, dtype=jnp.int32) < e_num[0]
    rek, rev, revalid, re_total = exchange_keyed_rows(
        _cspec(spec, spec.edge_capacity, spec.edge_recv, 1), e_src, _as_val(e_dst), e_valid
    )
    btotal = revalid.sum().astype(jnp.int32)
    border = jnp.argsort(padded_keys(rek, revalid), stable=True)
    sbk = padded_keys(rek, revalid)[border]
    sbc = jax.lax.bitcast_convert_type(rev[border][:, 0], jnp.uint32)
    return sbk, sbc, btotal[None], re_total[None]


def _tc_step_body(spec: TcSpec, tc_a, tc_b, tc_num, sbk, sbc, btotal):
    tc_valid = jnp.arange(spec.tc_capacity, dtype=jnp.int32) < tc_num[0]

    # 1. co-locate paths a->b (keyed by b) with the pre-sorted edges b->c
    rtk, rtv, rtvalid, rt_total = exchange_keyed_rows(
        _cspec(spec, spec.tc_capacity, spec.tc_recv, 1), tc_b, _as_val(tc_a), tc_valid
    )

    # 2. sort-merge expansion (shared with the hash join): probe = tc rows,
    #    build = edges; each match emits the new path (a, c)
    j, li, new_ok, _, new_total = expand_matches(
        spec.join_capacity, sbk, btotal[0], rtk, rtvalid, spec.tc_recv, spec.edge_recv
    )
    new_a = jnp.where(
        new_ok, jax.lax.bitcast_convert_type(rtv[j][:, 0], jnp.uint32), KEY_MAX
    )
    new_c = jnp.where(new_ok, sbc[li], KEY_MAX)

    # 3. union tc ++ new paths, re-partition by pair hash so duplicates collide
    u_a = jnp.concatenate([jnp.where(tc_valid, tc_a.astype(jnp.uint32), KEY_MAX), new_a])
    u_b = jnp.concatenate([jnp.where(tc_valid, tc_b.astype(jnp.uint32), KEY_MAX), new_c])
    u_valid = jnp.concatenate([tc_valid, new_ok])
    u_cap = spec.tc_capacity + spec.join_capacity
    ruk, ruv, ruvalid, ru_total = exchange_keyed_rows(
        _cspec(spec, u_cap, u_cap, 2),
        _pair_mix(u_a, u_b),
        jnp.concatenate([_as_val(u_a), _as_val(u_b)], axis=1),
        u_valid,
    )

    # 4. DISTINCT -> the next round's tc shard
    da = jax.lax.bitcast_convert_type(ruv[:, 0], jnp.uint32)
    db = jax.lax.bitcast_convert_type(ruv[:, 1], jnp.uint32)
    out_a, out_b, count = _lex_dedup(da, db, ruvalid, spec.tc_capacity)
    global_count = jax.lax.psum(count, spec.axis_name)
    overflow = jnp.stack([rt_total, new_total, ru_total, count])
    return out_a, out_b, count[None], global_count[None], overflow[None, :]


def _resolve(mesh: Mesh, spec: TcSpec) -> TcSpec:
    if spec.num_executors != mesh.devices.size:
        raise ValueError(f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}")
    spec = spec.resolve_impl(platform=mesh.devices.reshape(-1)[0].platform)
    spec.validate()
    return spec


def build_tc_prep(mesh: Mesh, spec: TcSpec):
    """Compile the one-time edge prep: ``fn(e_src, e_dst, e_num) ->
    (sorted_keys, sorted_dsts, btotals, recv_totals)`` — the edge set
    hash-partitioned by src and sorted, per shard.  ``recv_totals`` (n,) above
    ``edge_recv`` means the edge exchange truncated.  Feed the first three
    outputs to every ``build_tc_step`` call."""
    spec = _resolve(mesh, spec)
    ax = spec.axis_name
    shard = shard_map(
        functools.partial(_tc_prep_body, spec),
        mesh=mesh,
        in_specs=(P(ax), P(ax), P(ax)),
        out_specs=(P(ax), P(ax), P(ax), P(ax)),
        check_vma=False,
    )
    key_sh = NamedSharding(mesh, P(ax))
    fn = jax.jit(shard, in_shardings=(key_sh,) * 3, out_shardings=(key_sh,) * 4)
    fn.spec = spec
    return fn


def build_tc_step(mesh: Mesh, spec: TcSpec):
    """Compile one TC iteration for ``mesh``.

    Returns jitted ``fn(tc_a, tc_b, tc_num, sorted_keys, sorted_dsts, btotals)
    -> (tc_a', tc_b', tc_num', global_count, overflow)``:

    * ``tc_a``/``tc_b``: (n * tc_capacity,) uint32 sharded — current closure
      pairs a->b as a tight prefix per shard (tail = KEY_MAX padding);
    * ``tc_num``: (n,) int32 sharded — valid rows per shard;
    * ``sorted_keys``/``sorted_dsts``/``btotals`` — ``build_tc_prep`` outputs
      (the immutable edge set, partitioned and sorted exactly once);
    * outputs: next closure (same layout, now hash-partitioned by pair),
      per-shard and global distinct pair counts, and ``overflow`` (n, 4) int32 —
      per shard: (tc rows received, new paths expanded, union rows received,
      distinct pairs).  Any of the first three above its corresponding capacity
      (tc_recv / join_capacity / tc_capacity + join_capacity), or distinct
      pairs above tc_capacity, means truncation: re-run with more headroom.

    Iterate with ``run_transitive_closure`` (the SparkTC driver loop).
    """
    spec = _resolve(mesh, spec)
    ax = spec.axis_name

    shard = shard_map(
        functools.partial(_tc_step_body, spec),
        mesh=mesh,
        in_specs=(P(ax), P(ax), P(ax)) * 2,
        out_specs=(P(ax), P(ax), P(ax), P(ax), P(ax, None)),
        check_vma=False,
    )
    key_sh = NamedSharding(mesh, P(ax))
    fn = jax.jit(
        shard,
        in_shardings=(key_sh,) * 6,
        out_shardings=(key_sh, key_sh, key_sh, key_sh, NamedSharding(mesh, P(ax, None))),
    )
    fn.spec = spec
    return fn


def run_transitive_closure(
    mesh: Mesh,
    spec: TcSpec,
    edges: np.ndarray,
    max_rounds: int = 64,
) -> Tuple[np.ndarray, int]:
    """The SparkTC driver loop: seed tc = edges, iterate the compiled step
    until the global pair count stops growing (or ``max_rounds``).

    ``edges``: (E, 2) uint32 host array.  Returns (closure pairs (C, 2) uint32
    ascending-unique, rounds executed).  Raises on any capacity overflow and
    when the fixpoint is not reached within ``max_rounds`` (a partial closure
    is never returned silently).
    """
    spec = _resolve(mesh, spec)
    n = spec.num_executors
    prep = build_tc_prep(mesh, spec)
    fn = build_tc_step(mesh, spec)
    key_sh = NamedSharding(mesh, P(spec.axis_name))

    def shard_pairs(pairs: np.ndarray, cap: int):
        """Round-robin pairs over shards as tight padded prefixes."""
        a = np.full(n * cap, 0xFFFFFFFF, np.uint32)
        b = np.full(n * cap, 0xFFFFFFFF, np.uint32)
        num = np.zeros(n, np.int32)
        for s in range(n):
            mine = pairs[s::n]
            if len(mine) > cap:
                raise ValueError(f"shard {s} holds {len(mine)} pairs > capacity {cap}")
            a[s * cap : s * cap + len(mine)] = mine[:, 0]
            b[s * cap : s * cap + len(mine)] = mine[:, 1]
            num[s] = len(mine)
        return (
            jax.device_put(a, key_sh),
            jax.device_put(b, key_sh),
            jax.device_put(num, key_sh),
        )

    edges = np.unique(edges.astype(np.uint32), axis=0)
    if (edges >= 0xFFFFFFFF).any():
        raise ValueError("vertex ids must be < 0xFFFFFFFF (padding sentinel)")
    tc_a, tc_b, tc_num = shard_pairs(edges, spec.tc_capacity)
    e_src, e_dst, e_num = shard_pairs(edges, spec.edge_capacity)
    sbk, sbc, btotals, e_recv_totals = prep(e_src, e_dst, e_num)
    if (np.asarray(e_recv_totals) > spec.edge_recv).any():
        raise RuntimeError(
            f"edge_recv overflow (max {int(np.asarray(e_recv_totals).max())} > "
            f"{spec.edge_recv}) — re-run with more headroom"
        )

    count = int(np.asarray(tc_num).sum())
    converged = False
    rounds = 0
    for rounds in range(1, max_rounds + 1):
        tc_a, tc_b, tc_num, global_count, overflow = fn(
            tc_a, tc_b, tc_num, sbk, sbc, btotals
        )
        ov = np.asarray(overflow)
        caps = (
            spec.tc_recv,
            spec.join_capacity,
            spec.tc_capacity + spec.join_capacity,
            spec.tc_capacity,
        )
        names = ("tc_recv", "join_capacity", "union recv", "tc_capacity")
        for col, (cap, name) in enumerate(zip(caps, names)):
            if (ov[:, col] > cap).any():
                raise RuntimeError(
                    f"round {rounds}: {name} overflow (max {int(ov[:, col].max())} > {cap}) "
                    f"— re-run with more headroom"
                )
        new_count = int(np.asarray(global_count)[0])
        if new_count == count:
            converged = True
            break
        count = new_count
    if not converged:
        raise RuntimeError(
            f"no fixpoint after {max_rounds} rounds ({count} pairs and growing) — "
            f"raise max_rounds (rounds needed ~ graph diameter)"
        )

    # collect: valid prefixes of each shard
    a = np.asarray(tc_a).reshape(n, spec.tc_capacity)
    b = np.asarray(tc_b).reshape(n, spec.tc_capacity)
    num = np.asarray(tc_num)
    pairs = np.concatenate(
        [np.stack([a[s, : num[s]], b[s, : num[s]]], axis=1) for s in range(n)]
    )
    order = np.lexsort((pairs[:, 1], pairs[:, 0]))
    return pairs[order], rounds


def oracle_tc(edges: np.ndarray) -> np.ndarray:
    """CPU reference closure: iterated composition until fixpoint, returned as
    ascending-unique (C, 2) uint32 pairs."""
    tc = {tuple(e) for e in np.unique(edges.astype(np.uint32), axis=0)}
    by_src = {}
    for s, d in tc:
        by_src.setdefault(s, set()).add(d)
    while True:
        new = {(a, c) for a, b in tc for c in by_src.get(b, ())} - tc
        if not new:
            break
        tc |= new
    out = np.array(sorted(tc), np.uint32).reshape(-1, 2)
    return out
