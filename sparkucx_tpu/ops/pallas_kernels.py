"""Pallas TPU kernels for the block data plane — ragged block gather ("fetch
pack") and its inverse, the ragged block scatter ("device staging write").

The hot serving primitive of the reference is packing many variable-length
shuffle blocks into ONE contiguous registered buffer and shipping that single
buffer (``UcxWorkerWrapper.handleFetchBlockRequest``: parallel positioned file
reads into one pooled bounce buffer ``[tag | sizes | data...]``, one AM reply —
UcxWorkerWrapper.scala:397-448).  On TPU the blocks already live in HBM after
the exchange collective (transport/tpu.py), so the equivalent primitive is a
**device-side ragged gather**: copy B variable-length row runs out of an
HBM-resident source into one packed HBM destination, without the bytes ever
visiting the host.

``build_block_scatter`` is the write-side inverse (the NvkvHandler.write
analogue for device-born map output, store/hbm_store.py device staging): copy
B variable-length row runs out of ONE packed device buffer into their
slot-layout staging positions in an HBM-resident staging array, so map output
produced on the chip reaches the exchange without a D2H -> host memcpy -> H2D
round trip.

Three interchangeable lowerings each (bit-identical results):

* ``impl='dma'`` — Pallas kernel, one *dynamic-size* HBM->HBM DMA per block,
  K-deep pipelined on a rotating semaphore ring (the DMA engine streams block
  i+1..i+K while block i completes).  This is the TPU analogue of the
  reference's ForkJoin parallel file reads (UcxWorkerWrapper.scala:416-426):
  the DMA engine plays the IO thread pool.  TPU-only (Mosaic supports
  dynamic-size DMA slices; the interpreter does not).
* ``impl='tiled'`` — Pallas kernel with *static-size* tile DMAs (full tiles +
  an overlapping shifted tail, single-row DMAs for sub-tile blocks).  Portable
  to ``interpret=True``, which is how CI tests the kernel structure on CPU.
* ``impl='xla'`` — pure jnp fallback: searchsorted + take for the gather,
  masked ``dynamic_update_slice`` windows for the scatter; the portable path
  and the oracle the Pallas paths are tested against.

Sizes here are **rows** of ``lane`` 32-bit elements — the exchange's wire unit
(one row = the store's block alignment; ops/exchange.py module docstring).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sparkucx_tpu.ops._compat import tpu_compiler_params

# Pipelining depth of the dynamic-DMA path: how many block copies may be in
# flight at once (the numIoThreads analogue, UcxShuffleConf.scala:66-71).
DMA_PIPELINE_DEPTH = 8

# Rows per static-size DMA in the tiled path: 8 sublanes is the int32 native
# tile height, so a (8, 128) tile is one 4 KiB descriptor.
TILE_ROWS = 8


def _gather_dma_kernel(starts_ref, counts_ref, outs_ref, src_ref, out_ref, sems):
    """One dynamic-size DMA per block, K-deep pipelined.

    Grid-free: a single program walks all B blocks with a fori_loop, starting
    DMA i and waiting on DMA i-K, so up to K copies are in flight.  The wait
    reconstructs the same descriptor (the standard Pallas double-buffer
    pattern); empty blocks are skipped symmetrically on start and wait.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    num_blocks = starts_ref.shape[0]
    k = DMA_PIPELINE_DEPTH

    def get_dma(i):
        return pltpu.make_async_copy(
            src_ref.at[pl.ds(starts_ref[i], counts_ref[i])],
            out_ref.at[pl.ds(outs_ref[i], counts_ref[i])],
            sems.at[jax.lax.rem(i, k)],
        )

    def body(i, _):
        # clamp so the traced SMEM read stays in bounds even when i < k (the
        # i >= k predicate discards the value but not the read itself)
        @pl.when(jnp.logical_and(i >= k, counts_ref[jnp.maximum(i - k, 0)] > 0))
        def _wait_prev():
            get_dma(i - k).wait()

        @pl.when(counts_ref[i] > 0)
        def _start():
            get_dma(i).start()

        return 0

    jax.lax.fori_loop(0, num_blocks, body, 0)

    def drain(i, _):
        @pl.when(counts_ref[i] > 0)
        def _wait():
            get_dma(i).wait()

        return 0

    jax.lax.fori_loop(jnp.maximum(num_blocks - k, 0), num_blocks, drain, 0)


def _gather_tiled_kernel(starts_ref, counts_ref, outs_ref, src_ref, out_ref, sem):
    """Static-size tile DMAs: portable to the Pallas interpreter.

    Per block: full TILE_ROWS tiles, then either one overlapping shifted tail
    tile (count >= TILE_ROWS — rewrites a few already-correct rows, which is
    safe because src and dst shift together) or single-row DMAs (count <
    TILE_ROWS).  Serial start/wait — this lowering is for correctness testing,
    the dynamic path is the perf path.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    num_blocks = starts_ref.shape[0]

    def copy(src_row, dst_row, rows):
        dma = pltpu.make_async_copy(
            src_ref.at[pl.ds(src_row, rows)],
            out_ref.at[pl.ds(dst_row, rows)],
            sem,
        )
        dma.start()
        dma.wait()

    def block_body(b, _):
        start, count, out = starts_ref[b], counts_ref[b], outs_ref[b]
        full = count // TILE_ROWS

        def tile_body(t, _):
            copy(start + t * TILE_ROWS, out + t * TILE_ROWS, TILE_ROWS)
            return 0

        jax.lax.fori_loop(0, full, tile_body, 0)

        tail = count - full * TILE_ROWS

        @pl.when(jnp.logical_and(tail > 0, count >= TILE_ROWS))
        def _shifted_tail():
            copy(start + count - TILE_ROWS, out + count - TILE_ROWS, TILE_ROWS)

        @pl.when(count < TILE_ROWS)
        def _tiny_block():
            def row_body(r, _):
                copy(start + r, out + r, 1)
                return 0

            jax.lax.fori_loop(0, count, row_body, 0)

        return 0

    jax.lax.fori_loop(0, num_blocks, block_body, 0)


def _pallas_gather(kernel, interpret: bool, out_rows: int, starts, counts, outs, src):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    sem_shape = (
        pltpu.SemaphoreType.DMA((DMA_PIPELINE_DEPTH,))
        if kernel is _gather_dma_kernel
        else pltpu.SemaphoreType.DMA
    )
    # The tiled kernel's (predicated) tail copy traces an 8-row slice even when
    # it can never run, so the buffer must be at least one tile tall; the
    # caller-visible shape is restored by the slice below.
    alloc_rows = max(out_rows, TILE_ROWS)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((alloc_rows, src.shape[1]), src.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[sem_shape],
        ),
        compiler_params=tpu_compiler_params(has_side_effects=True),
        interpret=interpret,
    )(starts, counts, outs, src)
    return out[:out_rows]


def _xla_gather(out_rows: int, starts, counts, outs, src):
    """Portable lowering: map each output row to its source row.

    Output row p belongs to block b iff outs[b] <= p < outs[b]+counts[b]; rows
    not covered by any block keep zeros.  Blocks must be packed (outs =
    exclusive cumsum of counts) for the searchsorted inversion to hold — the
    wrapper guarantees it.
    """
    ends = outs + counts
    pos = jnp.arange(out_rows, dtype=jnp.int32)
    b = jnp.clip(
        jnp.searchsorted(ends, pos, side="right").astype(jnp.int32),
        0,
        jnp.maximum(starts.shape[0] - 1, 0),
    )
    src_row = starts[b] + (pos - outs[b])
    covered = (pos >= outs[b]) & (pos < ends[b])
    rows = src[jnp.clip(src_row, 0, src.shape[0] - 1)]
    return jnp.where(covered[:, None], rows, jnp.zeros((), dtype=src.dtype))


def build_block_gather(
    num_blocks: int,
    out_rows: int,
    impl: Optional[str] = None,
    interpret: bool = False,
):
    """Compile a ragged block gather: ``fn(starts, counts, outs, src) -> packed``.

    * ``starts``/``counts``/``outs``: (num_blocks,) int32 — source row offset,
      row count, and destination row offset per block.  Destinations must be
      packed ascending (``outs`` = exclusive cumsum of ``counts``) — the layout
      ``pack_plan`` produces and the reference's reply buffer uses.
    * ``src``: (S, lane) int32 — HBM-resident source (a received exchange shard).
    * returns (out_rows, lane) int32 — blocks packed back-to-back.  Rows past
      the packed total are UNSPECIFIED (the Pallas paths leave the buffer
      uninitialized there; the xla path happens to zero it) — callers must
      slice ``[:total_rows]``.

    ``impl``: 'dma' (TPU, pipelined dynamic-size DMAs) | 'tiled' (portable
    static-size DMAs) | 'xla' (pure jnp).  Default: 'dma' on TPU else 'xla'.
    """
    if impl is None:
        impl = "dma" if jax.devices()[0].platform == "tpu" else "xla"
    if impl == "xla":
        fn = jax.jit(functools.partial(_xla_gather, out_rows))
    elif impl in ("dma", "tiled"):
        kernel = _gather_dma_kernel if impl == "dma" else _gather_tiled_kernel
        fn = jax.jit(functools.partial(_pallas_gather, kernel, interpret, out_rows))
    else:
        raise ValueError(f"unknown impl {impl!r}")
    fn.impl = impl
    return fn


def _scatter_dma_kernel(starts_ref, counts_ref, outs_ref, src_ref, dst_ref, out_ref, sems):
    """Inverse of ``_gather_dma_kernel``: packed src -> scattered dst slots.

    ``dst_ref`` is aliased to ``out_ref`` (input_output_aliases), so rows not
    covered by any block keep their prior staging contents — that is what makes
    this an *append* into a partially-filled staging round rather than a
    rebuild.  Same K-deep rotating-semaphore pipeline as the gather.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del dst_ref  # present only to carry the alias; all writes go through out_ref
    num_blocks = starts_ref.shape[0]
    k = DMA_PIPELINE_DEPTH

    def get_dma(i):
        return pltpu.make_async_copy(
            src_ref.at[pl.ds(outs_ref[i], counts_ref[i])],
            out_ref.at[pl.ds(starts_ref[i], counts_ref[i])],
            sems.at[jax.lax.rem(i, k)],
        )

    def body(i, _):
        @pl.when(jnp.logical_and(i >= k, counts_ref[jnp.maximum(i - k, 0)] > 0))
        def _wait_prev():
            get_dma(i - k).wait()

        @pl.when(counts_ref[i] > 0)
        def _start():
            get_dma(i).start()

        return 0

    jax.lax.fori_loop(0, num_blocks, body, 0)

    def drain(i, _):
        @pl.when(counts_ref[i] > 0)
        def _wait():
            get_dma(i).wait()

        return 0

    jax.lax.fori_loop(jnp.maximum(num_blocks - k, 0), num_blocks, drain, 0)


def _scatter_tiled_kernel(starts_ref, counts_ref, outs_ref, src_ref, dst_ref, out_ref, sem):
    """Static-size-DMA scatter, portable to ``interpret=True`` (CI's path).

    Mirrors ``_gather_tiled_kernel`` with the copy direction reversed: full
    tiles, an overlapping shifted tail when count >= TILE_ROWS (safe — src and
    dst shift together), single-row DMAs below one tile.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del dst_ref  # aliased to out_ref
    num_blocks = starts_ref.shape[0]

    def copy(src_row, dst_row, rows):
        dma = pltpu.make_async_copy(
            src_ref.at[pl.ds(src_row, rows)],
            out_ref.at[pl.ds(dst_row, rows)],
            sem,
        )
        dma.start()
        dma.wait()

    def block_body(b, _):
        start, count, out = starts_ref[b], counts_ref[b], outs_ref[b]
        full = count // TILE_ROWS

        def tile_body(t, _):
            copy(out + t * TILE_ROWS, start + t * TILE_ROWS, TILE_ROWS)
            return 0

        jax.lax.fori_loop(0, full, tile_body, 0)

        tail = count - full * TILE_ROWS

        @pl.when(jnp.logical_and(tail > 0, count >= TILE_ROWS))
        def _shifted_tail():
            copy(out + count - TILE_ROWS, start + count - TILE_ROWS, TILE_ROWS)

        @pl.when(count < TILE_ROWS)
        def _tiny_block():
            def row_body(r, _):
                copy(out + r, start + r, 1)
                return 0

            jax.lax.fori_loop(0, count, row_body, 0)

        return 0

    jax.lax.fori_loop(0, num_blocks, block_body, 0)


def _pallas_scatter(kernel, interpret: bool, out_rows: int, starts, counts, outs, src, dst):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    sem_shape = (
        pltpu.SemaphoreType.DMA((DMA_PIPELINE_DEPTH,))
        if kernel is _scatter_dma_kernel
        else pltpu.SemaphoreType.DMA
    )
    alloc_rows = max(out_rows, TILE_ROWS)
    if dst.shape[0] != alloc_rows:
        dst = jnp.pad(dst, ((0, alloc_rows - dst.shape[0]), (0, 0)))
    # The packed src can hold fewer than TILE_ROWS rows (tiny rounds); the
    # tiled kernel's TILE_ROWS-sized copies need the operand itself to be at
    # least one tile tall even though the guarded reads never leave the
    # packed region at runtime.
    if src.shape[0] < TILE_ROWS:
        src = jnp.pad(src, ((0, TILE_ROWS - src.shape[0]), (0, 0)))
    # dst is operand 4 of the FULL input tuple (scalar-prefetch args included in
    # the alias numbering), aliased to output 0: untouched rows pass through.
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((alloc_rows, src.shape[1]), src.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[sem_shape],
        ),
        input_output_aliases={4: 0},
        compiler_params=tpu_compiler_params(has_side_effects=True),
        interpret=interpret,
    )(starts, counts, outs, src, dst)
    return out[:out_rows]


def _xla_scatter(window: int, out_rows: int, starts, counts, outs, src, dst):
    """Portable lowering: one masked ``dynamic_update_slice`` window per block.

    Each scan step reads a fixed ``window``-row slice of dst at the block's
    start, overwrites the first ``count`` rows from the packed src, and writes
    it back.  Both arrays are padded by ``window`` rows so XLA's slice-start
    clamping can never shift a window (a clamped start would silently copy the
    wrong src rows); zero-count blocks degenerate to read-modify-write no-ops,
    so pow2 batch padding needs no monotonicity trick here.
    """
    lane = src.shape[1]
    src = jnp.pad(src, ((0, window), (0, 0)))
    dst = jnp.pad(dst, ((0, out_rows + window - dst.shape[0]), (0, 0)))
    row_in_window = jnp.arange(window, dtype=jnp.int32)[:, None]

    def body(d, block):
        start, count, out = block
        src_win = jax.lax.dynamic_slice(src, (out, 0), (window, lane))
        cur = jax.lax.dynamic_slice(d, (start, 0), (window, lane))
        new = jnp.where(row_in_window < count, src_win, cur)
        return jax.lax.dynamic_update_slice(d, new, (start, 0)), None

    d, _ = jax.lax.scan(body, dst, (starts, counts, outs))
    return d[:out_rows]


def build_block_scatter(
    num_blocks: int,
    out_rows: int,
    impl: Optional[str] = None,
    interpret: bool = False,
    max_block_rows: Optional[int] = None,
):
    """Compile a ragged block scatter: ``fn(starts, counts, outs, src, dst) -> dst'``.

    The inverse of :func:`build_block_gather` — the device staging write path
    (store/hbm_store.py ``write_partition_device``):

    * ``starts``: (num_blocks,) int32 — *destination* slot-layout row per block
      (``j * slot_rows + used_j`` in the staging geometry).
    * ``counts``: (num_blocks,) int32 — rows per block; zero-count entries are
      no-ops (how pow2 batch padding is expressed).
    * ``outs``: (num_blocks,) int32 — *source* row offsets in the packed
      buffer; must be the exclusive cumsum of ``counts`` (pack_plan layout).
    * ``src``: (S, lane) int32 — packed device buffer of block payloads.
    * ``dst``: (out_rows, lane) int32 — the staging array; returns a new array
      with the blocks placed and every uncovered row carried over unchanged
      (Pallas paths alias dst to the output; the xla path read-modify-writes).

    ``max_block_rows`` bounds the largest single block (xla path window size;
    defaults to ``out_rows``).  ``impl`` as in ``build_block_gather``.  On TPU
    ``dst`` is donated, making the append in-place.
    """
    if impl is None:
        impl = "dma" if jax.devices()[0].platform == "tpu" else "xla"
    if impl == "xla":
        window = max(1, max_block_rows if max_block_rows is not None else out_rows)
        f = functools.partial(_xla_scatter, window, out_rows)
    elif impl in ("dma", "tiled"):
        kernel = _scatter_dma_kernel if impl == "dma" else _scatter_tiled_kernel
        f = functools.partial(_pallas_scatter, kernel, interpret, out_rows)
    else:
        raise ValueError(f"unknown impl {impl!r}")
    # Donating dst turns the aliasing into a true in-place append; on CPU
    # donation is unimplemented and would warn every call, so gate it.
    donate = (4,) if jax.devices()[0].platform == "tpu" else ()
    fn = jax.jit(f, donate_argnums=donate)
    fn.impl = impl
    return fn


# Public alias: the fused scatter+exchange lowering (ops/ici_exchange.py)
# composes the window-scan scatter with the scheduled ring inside ONE jit.
xla_scatter_windows = _xla_scatter


# ----------------------------------------------------------------------------
# Scheduled inter-chip ring exchange (ops/ici_exchange.py's TPU lowering)
# ----------------------------------------------------------------------------
#
# One kernel invocation per device (inside shard_map over the ring axis)
# executes a static flow schedule of remote DMAs: per step, at most one chunk
# window per ICI link direction (``pltpu.make_async_remote_copy`` — the
# bidirectional-ring pattern of SNIPPETS.md [1]/[3]).  The schedule arrives as
# plain ``(offset, chunk, direction)`` tuples so this module stays free of the
# schedule dataclasses (ops/ici_exchange.py owns those and depends on us).
#
# Remote targets are LOGICAL device ids — the linearized index into the FULL
# shard_map mesh — while the schedule speaks ring POSITIONS along one mesh
# axis.  When that axis is a sub-axis (the ICI phase of a (dcn, ici) mesh)
# the two differ: chip c of slice s is logical id ``s * C + c``, not ``c``.
# ``ring_axis_layout`` provides the position->id affine map; every remote
# signal/copy below goes through it.


def ring_axis_layout(mesh_axes, axis_name):
    """Row-major strides mapping ring positions on one mesh axis to logical
    device ids.

    ``mesh_axes``: ordered ``(name, size)`` pairs of the FULL shard_map mesh
    (row-major, matching ``Mesh(devices.reshape(...), names)``).  Returns
    ``(ring_stride, other_axes)`` with ``other_axes`` = ``(name, stride)`` for
    every non-ring axis, such that the logical id of ring position ``p`` is::

        p * ring_stride + sum(axis_index(name) * stride for other axes)

    Pure python — unit-testable without a mesh (tests/test_ici_exchange.py).
    """
    mesh_axes = tuple((str(n), int(s)) for n, s in mesh_axes)
    names = [n for n, _ in mesh_axes]
    if axis_name not in names:
        raise ValueError(f"ring axis {axis_name!r} not in mesh axes {names}")
    strides = {}
    stride = 1
    for name, size in reversed(mesh_axes):
        strides[name] = stride
        stride *= size
    others = tuple((n, strides[n]) for n, _ in mesh_axes if n != axis_name)
    return strides[axis_name], others


def _ring_device_id(mesh_axes, axis_name):
    """Kernel-side ring-position -> logical-device-id map (traced; must run
    inside shard_map over ``mesh_axes``)."""
    import jax

    ring_stride, other_axes = ring_axis_layout(mesh_axes, axis_name)
    base = 0
    for name, stride in other_axes:
        base = base + jax.lax.axis_index(name) * stride
    return lambda pos: base + pos * ring_stride


def _ring_exchange_steps(
    num_devices, slot_rows, window_rows, steps, me, dev_id, data_ref, out_ref,
    send_sem, recv_sem, on_step=None,
):
    """Shared schedule walk: remote-copy every (offset, chunk) window.

    Sender ``me`` pushes its staging window for destination ``me+d`` into the
    destination's sender-major grid region (rows ``me*slot + chunk*w``).  The
    schedule is SPMD-symmetric, so each step's ``wait()`` pairs my outgoing
    descriptor with the incoming copy of the same (offset, chunk) from
    ``me-d`` — same window size, same semaphore index, both directions of the
    ring in flight at once.

    ``on_step(step)`` — optional superstep epilogue, called after the step's
    waits: every window the step delivered is landed in ``out_ref`` and may
    be consumed before the next step's copies start (the fused-combine
    kernel's receive-side fold, ``ring_combine_grid``)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    for step in steps:
        copies = []
        for offset, chunk, direction in step:
            dst_pos = jax.lax.rem(me + offset, num_devices)
            sem_idx = 0 if direction >= 0 else 1
            copy = pltpu.make_async_remote_copy(
                src_ref=data_ref.at[
                    pl.ds(dst_pos * slot_rows + chunk * window_rows, window_rows)
                ],
                dst_ref=out_ref.at[
                    pl.ds(me * slot_rows + chunk * window_rows, window_rows)
                ],
                send_sem=send_sem.at[sem_idx],
                recv_sem=recv_sem.at[sem_idx],
                device_id=dev_id(dst_pos),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            copy.start()
            copies.append(copy)
        for copy in copies:
            copy.wait()
        if on_step is not None:
            on_step(step)


def _ring_barrier(num_devices, offsets, me, dev_id):
    """Rendezvous with every schedule partner before the first remote write —
    a peer's out buffer must exist before bytes land in it (pallas collective
    discipline: barrier on the collective_id semaphore)."""
    import jax
    from jax.experimental.pallas import tpu as pltpu

    barrier = pltpu.get_barrier_semaphore()
    for d in offsets:
        pltpu.semaphore_signal(
            barrier,
            1,
            device_id=dev_id(jax.lax.rem(me + d, num_devices)),
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
    pltpu.semaphore_wait(barrier, len(offsets))


def ring_exchange_grid(
    axis_name: str,
    num_devices: int,
    slot_rows: int,
    window_rows: int,
    steps,
    data,
    *,
    mesh_axes=None,
    interpret: bool = False,
    collective_id: int = 13,
):
    """Pallas scheduled ring exchange: destination-major slots in, sender-major
    received grid out — the remote-DMA equivalent of one tiled all_to_all.

    * ``data``: (num_devices * slot_rows, lane) per-device staging shard.
    * ``steps``: sequence of steps; each step a sequence of
      ``(offset, chunk, direction)`` with at most one item per ring direction
      (ops/ici_exchange.ring_schedule guarantees it).
    * ``mesh_axes``: ordered (name, size) pairs of the FULL shard_map mesh
      when ``axis_name`` is a sub-axis (e.g. the ICI phase of a (dcn, ici)
      mesh) — remote DMA targets logical device ids, so ring positions must
      be rebased per ``ring_axis_layout``.  Defaults to a flat
      ``((axis_name, num_devices),)`` mesh where position == id.
    * returns (num_devices * slot_rows, lane): row ``k*slot_rows + r`` = row r
      of what sender k staged for me — identical layout to the dense
      lowering's all_to_all output (ops/exchange._exchange_shard_dense).

    Must be called inside shard_map over ``axis_name``.  The compiled kernel
    is TPU-only (remote DMA); ``interpret=True`` runs the same kernel body
    under the Pallas interpreter — works on single-axis meshes on any
    platform (the barrier is skipped: interpret discharge is synchronous and
    the barrier semaphore is TPU-only) and is bit-equality-tested against
    the stock collective on the CPU mesh in CI.
    """
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if mesh_axes is None:
        mesh_axes = ((axis_name, num_devices),)
    mesh_axes = tuple((str(n), int(s)) for n, s in mesh_axes)
    if dict(mesh_axes)[axis_name] != num_devices:
        raise ValueError(
            f"ring axis {axis_name!r} has size {dict(mesh_axes)[axis_name]} in "
            f"mesh_axes, expected num_devices={num_devices}"
        )
    steps = tuple(tuple(step) for step in steps)
    offsets = sorted({offset for step in steps for offset, _, _ in step})

    def kernel(data_ref, out_ref, send_sem, recv_sem, local_sem):
        me = jax.lax.axis_index(axis_name)
        dev_id = _ring_device_id(mesh_axes, axis_name)
        if not interpret:  # interpret discharge is synchronous; the barrier
            _ring_barrier(num_devices, offsets, me, dev_id)  # is TPU-only
        # own slot never crosses a link: one local HBM->HBM DMA
        local = pltpu.make_async_copy(
            data_ref.at[pl.ds(me * slot_rows, slot_rows)],
            out_ref.at[pl.ds(me * slot_rows, slot_rows)],
            local_sem,
        )
        local.start()
        local.wait()
        _ring_exchange_steps(
            num_devices, slot_rows, window_rows, steps, me, dev_id,
            data_ref, out_ref, send_sem, recv_sem,
        )

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (num_devices * slot_rows, data.shape[1]), data.dtype
        ),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interpret,
    )(data)


def ring_combine_grid(
    axis_name: str,
    num_devices: int,
    slot_rows: int,
    window_rows: int,
    steps,
    combine_fn,
    acc_init_fn,
    num_groups: int,
    acc_width: int,
    data,
    *,
    mesh_axes=None,
    interpret: bool = False,
    collective_id: int = 15,
):
    """Fused receive side: scheduled ring exchange + per-superstep combine
    fold, ONE kernel — the compute-in-exchange tier (ops/combine.py).

    Same wire schedule as :func:`ring_exchange_grid`; the difference is what
    happens to a landed window.  After each superstep's waits, every window
    the step delivered (the ``(offset, chunk)`` region from sender
    ``me - offset``) is DMA'd into VMEM and folded into a dense per-group
    accumulator held in VMEM for the whole schedule — landed rows are
    consumed the moment they arrive instead of surviving as O(rows) recv
    staging, and the post-exchange drain is the O(groups) accumulator.

    * ``combine_fn(window, acc_vals, acc_counts) -> (acc_vals, acc_counts)``
      — the fold (``ops/combine.combine_window`` closed over its spec);
      plain traced jnp over static shapes, so this module stays free of the
      combine dataclasses exactly as it stays free of the schedule ones.
    * ``acc_init_fn() -> (acc_vals (G, w), acc_counts (G, 1))`` — the fold
      identities.
    * returns ``(grid, acc_vals, acc_counts)``: the sender-major landed grid
      (callers keep it on device or discard it — it never drains) plus the
      accumulator pair.

    Window fold order is canonical — own slot first, then schedule items in
    step order — and shared with the scheduled-XLA walk
    (ops/ici_exchange.py), so exact dtypes are bit-identical across
    lowerings.  ``interpret=True`` runs the same body under the Pallas
    interpreter (CI's tier; the barrier is skipped as in
    :func:`ring_exchange_grid`).
    """
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if mesh_axes is None:
        mesh_axes = ((axis_name, num_devices),)
    mesh_axes = tuple((str(n), int(s)) for n, s in mesh_axes)
    if dict(mesh_axes)[axis_name] != num_devices:
        raise ValueError(
            f"ring axis {axis_name!r} has size {dict(mesh_axes)[axis_name]} in "
            f"mesh_axes, expected num_devices={num_devices}"
        )
    steps = tuple(tuple(step) for step in steps)
    offsets = sorted({offset for step in steps for offset, _, _ in step})
    lane = int(data.shape[1])

    def kernel(
        data_ref, grid_ref, accv_ref, accc_ref,
        send_sem, recv_sem, local_sem, accv_vmem, accc_vmem, win_vmem,
    ):
        me = jax.lax.axis_index(axis_name)
        dev_id = _ring_device_id(mesh_axes, axis_name)
        if not interpret:  # interpret discharge is synchronous; the barrier
            _ring_barrier(num_devices, offsets, me, dev_id)  # is TPU-only
        av0, ac0 = acc_init_fn()
        accv_vmem[...] = av0
        accc_vmem[...] = ac0

        def fold(row0, rows):
            # land the window in VMEM, fold it, keep the acc resident
            cp = pltpu.make_async_copy(
                grid_ref.at[pl.ds(row0, rows)],
                win_vmem.at[pl.ds(0, rows)],
                local_sem,
            )
            cp.start()
            cp.wait()
            av, ac = combine_fn(win_vmem[0:rows], accv_vmem[...], accc_vmem[...])
            accv_vmem[...] = av
            accc_vmem[...] = ac

        # own slot never crosses a link: one local HBM->HBM DMA, folded first
        # (the canonical order every lowering shares)
        local = pltpu.make_async_copy(
            data_ref.at[pl.ds(me * slot_rows, slot_rows)],
            grid_ref.at[pl.ds(me * slot_rows, slot_rows)],
            local_sem,
        )
        local.start()
        local.wait()
        fold(me * slot_rows, slot_rows)

        def epilogue(step):
            # every window this superstep delivered: sender me-d's chunk
            for offset, chunk, _direction in step:
                src = jax.lax.rem(me - offset + num_devices, num_devices)
                fold(src * slot_rows + chunk * window_rows, window_rows)

        _ring_exchange_steps(
            num_devices, slot_rows, window_rows, steps, me, dev_id,
            data_ref, grid_ref, send_sem, recv_sem, on_step=epilogue,
        )
        # drain the O(groups) accumulator to HBM — the only receive-side
        # bytes that leave the kernel
        for vmem, out in ((accv_vmem, accv_ref), (accc_vmem, accc_ref)):
            flush = pltpu.make_async_copy(
                vmem.at[pl.ds(0, num_groups)],
                out.at[pl.ds(0, num_groups)],
                local_sem,
            )
            flush.start()
            flush.wait()

    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((num_devices * slot_rows, lane), data.dtype),
            jax.ShapeDtypeStruct((num_groups, acc_width), data.dtype),
            jax.ShapeDtypeStruct((num_groups, 1), jnp.int32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
            pltpu.VMEM((num_groups, acc_width), data.dtype),
            pltpu.VMEM((num_groups, 1), jnp.int32),
            pltpu.VMEM((slot_rows, lane), data.dtype),
        ],
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interpret,
    )(data)


def fused_scatter_ring_grid(
    axis_name: str,
    num_devices: int,
    slot_rows: int,
    window_rows: int,
    steps,
    starts,
    counts,
    outs,
    packed,
    staging,
    *,
    mesh_axes=None,
    interpret: bool = False,
    collective_id: int = 14,
):
    """Fused send side: block scatter + scheduled ring exchange, ONE kernel.

    Phase 1 places the packed map-output blocks into the slot-layout staging
    (the ``_scatter_dma_kernel`` pipeline, staging aliased in-place); phase 2
    runs the ring schedule straight out of that staging — the bytes never
    round-trip HBM between the staging write and the wire, and the separate
    scatter kernel launch disappears.

    Returns ``(grid, staged)``: the sender-major received grid plus the
    staging with blocks placed (aliased to the ``staging`` operand).  Same
    plan contract as ``build_block_scatter`` (starts=dst rows, counts,
    outs=packed offsets; zero-count blocks are no-ops).
    """
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if mesh_axes is None:
        mesh_axes = ((axis_name, num_devices),)
    mesh_axes = tuple((str(n), int(s)) for n, s in mesh_axes)
    if dict(mesh_axes)[axis_name] != num_devices:
        raise ValueError(
            f"ring axis {axis_name!r} has size {dict(mesh_axes)[axis_name]} in "
            f"mesh_axes, expected num_devices={num_devices}"
        )
    steps = tuple(tuple(step) for step in steps)
    offsets = sorted({offset for step in steps for offset, _, _ in step})
    k = DMA_PIPELINE_DEPTH

    def kernel(
        starts_ref, counts_ref, outs_ref, packed_ref, staging_ref,
        grid_ref, staged_ref, send_sem, recv_sem, local_sem, scatter_sems,
    ):
        del staging_ref  # aliased to staged_ref; all writes go through it
        me = jax.lax.axis_index(axis_name)
        num_blocks = starts_ref.shape[0]

        def get_dma(i):
            return pltpu.make_async_copy(
                packed_ref.at[pl.ds(outs_ref[i], counts_ref[i])],
                staged_ref.at[pl.ds(starts_ref[i], counts_ref[i])],
                scatter_sems.at[jax.lax.rem(i, k)],
            )

        def body(i, _):
            @pl.when(jnp.logical_and(i >= k, counts_ref[jnp.maximum(i - k, 0)] > 0))
            def _wait_prev():
                get_dma(i - k).wait()

            @pl.when(counts_ref[i] > 0)
            def _start():
                get_dma(i).start()

            return 0

        jax.lax.fori_loop(0, num_blocks, body, 0)

        def drain(i, _):
            @pl.when(counts_ref[i] > 0)
            def _wait():
                get_dma(i).wait()

            return 0

        jax.lax.fori_loop(jnp.maximum(num_blocks - k, 0), num_blocks, drain, 0)

        # staging is complete on THIS device; the barrier also orders every
        # peer's scatter before any remote read of their staging
        dev_id = _ring_device_id(mesh_axes, axis_name)
        if not interpret:  # interpret discharge is synchronous; the barrier
            _ring_barrier(num_devices, offsets, me, dev_id)  # is TPU-only
        local = pltpu.make_async_copy(
            staged_ref.at[pl.ds(me * slot_rows, slot_rows)],
            grid_ref.at[pl.ds(me * slot_rows, slot_rows)],
            local_sem,
        )
        local.start()
        local.wait()
        _ring_exchange_steps(
            num_devices, slot_rows, window_rows, steps, me, dev_id,
            staged_ref, grid_ref, send_sem, recv_sem,
        )

    lane = packed.shape[1]
    # staging is operand 4 of the FULL input tuple (scalar-prefetch args
    # included in the alias numbering), aliased to output 1 (staged)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((num_devices * slot_rows, lane), packed.dtype),
            jax.ShapeDtypeStruct(staging.shape, staging.dtype),
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=(
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ),
            scratch_shapes=[
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA((DMA_PIPELINE_DEPTH,)),
            ],
        ),
        input_output_aliases={4: 1},
        compiler_params=tpu_compiler_params(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interpret,
    )(starts, counts, outs, packed, staging)


def pack_plan(
    offsets_lengths: Sequence[Tuple[int, int]], row_bytes: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Host-side plan: byte (offset, length) pairs -> row-granular (starts,
    counts, outs, total_rows) for ``build_block_gather``.

    Offsets must be row-aligned (the store aligns every block,
    store/hbm_store.py); lengths are padded up to whole rows — the per-block
    padding the reference records at close (NvkvShuffleMapOutputWriter.scala:236-246).
    """
    starts, counts = [], []
    for off, ln in offsets_lengths:
        if off % row_bytes:
            raise ValueError(f"block offset {off} not {row_bytes}-byte aligned")
        starts.append(off // row_bytes)
        counts.append(-(-ln // row_bytes))
    counts_a = np.asarray(counts, dtype=np.int32)
    outs = np.concatenate([[0], np.cumsum(counts_a)[:-1]]).astype(np.int32)
    total = int(counts_a.sum())
    return np.asarray(starts, dtype=np.int32), counts_a, outs, total
