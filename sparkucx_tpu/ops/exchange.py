"""The shuffle exchange collective — ragged all_to_all over the executor mesh (L3 hot path).

This is the TPU-native replacement for the reference's entire UCX data plane: where
SparkUCX serves each ``FetchBlockReq`` with a UCP active message carrying the block
bytes (UcxWorkerWrapper.scala:96-186, handleFetchBlockRequest :397-448), here a
*superstep* of the shuffle — every reducer fetching from every mapper — lowers to ONE
collective over the ICI mesh, letting XLA schedule the bidirectional ICI links
instead of hand-driving RDMA endpoints.

Data unit: the exchange moves **rows** of ``lane`` int32 lanes (default 128 -> one
512-byte row).  Two reasons: (a) a trailing 128-lane dimension is the shape XLA:TPU
tiles natively — a 1-D byte/int stream gets pathologically padded to (x,1,128)
tiles by the ragged-all-to-all lowering (observed 128x memory blowup); (b) 512 is
exactly the sector alignment the reference's NVKV store enforces on every block
write (NvkvHandler.scala:244-256), so block offsets are row-aligned by
construction.

Protocol (mirrors the reference's two-phase metadata+data design):

1. **Size-matrix exchange** — each executor contributes the row-counts it holds
   for every peer; an ``all_gather`` makes the full n x n matrix available
   device-side.  This is the collective analogue of the ``MapperInfo`` commit
   (NvkvShuffleMapOutputWriter.scala:116-148): senders publish sizes before any
   data moves, exactly like the DPU daemon learns the offset table before serving.
2. **Payload exchange** — two lowerings behind one interface:

   * ``impl='ragged'`` (TPU): offsets are computed inside jit from the gathered
     size matrix (slot starts for send offsets, exclusive column-cumsum for each
     receiver's landing offsets) and fed to ``jax.lax.ragged_all_to_all`` — only
     each region's used prefix crosses the wire.
   * ``impl='dense'`` (portable; XLA:CPU has no ragged-all-to-all kernel): a
     tiled ``lax.all_to_all`` moves whole fixed-size slots, then a static-shaped
     row gather compacts the receive side into the same tight sender-major layout
     the ragged path produces.  This is also the path the driver's virtual-CPU
     ``dryrun_multichip`` executes.
   * ``impl='local'`` (TPU, n=1 only): the degenerate single-executor superstep
     is a device-local prefix copy, which the Pallas DMA gather streams ~3x
     faster than ragged_all_to_all's single-device lowering (docs/PERF.md).

   All lowerings produce identical receive buffers over the valid (sized)
   prefix, so every layer above is implementation-agnostic; rows past the
   received totals are zeros under the collective lowerings and unspecified
   under 'local'.

Everything is static-shaped: staging capacities are compile-time constants, sizes
are runtime data.  No data-dependent Python control flow — the same compiled
exchange serves every superstep of every shuffle.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops._compat import ragged_all_to_all, shard_map


def exclusive_cumsum(x, axis: int = -1, xp=jnp):
    return xp.cumsum(x, axis=axis) - x


#: Lane-width band where XLA:TPU lowers a row gather ~4x slower than adjacent
#: widths (mapped empirically on v5e: 8/16/24 lanes and >=100 are fast,
#: 25..32 fall off a tiling cliff — docs/PERF.md).  Gathers whose width lands
#: in the band are chunked into <=24-lane column slices, each of which lowers
#: on the fast path; chunking a fast width makes it WORSE (W=100 chunked
#: measured 3x slower), hence the band guard rather than chunking everything.
SLOW_GATHER_LANES = (25, 32)
_GATHER_CHUNK = 24


def gather_rows(rows: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``rows[idx]`` (1-D row index) with the TPU slow-band lane chunking.

    The cliff is an XLA:TPU artifact, so non-TPU backends always take the
    plain gather.  ``jax.default_backend()`` is a trace-time proxy for the
    mesh platform — exact for every in-tree caller (meshes are built over the
    default backend's devices)."""
    w = rows.shape[1]
    if (
        SLOW_GATHER_LANES[0] <= w <= SLOW_GATHER_LANES[1]
        and jax.default_backend() == "tpu"
    ):
        return jnp.concatenate(
            [rows[:, i : i + _GATHER_CHUNK][idx] for i in range(0, w, _GATHER_CHUNK)],
            axis=1,
        )
    return rows[idx]


@dataclass(frozen=True)
class ExchangeSpec:
    """Static description of one compiled exchange.

    ``send_rows`` / ``recv_rows`` are per-executor staging sizes in rows of
    ``lane`` int32 elements (``row_bytes`` = 4*lane, default 512 — the HBM
    analogue of the reference's fixed NVKV buffers, NvkvHandler.scala:26-29).
    ``impl`` is ``'ragged'`` | ``'dense'`` | ``'auto'`` (ragged iff the backend
    lowers it, i.e. TPU).  Layout is always *slot*: peer j's chunk starts at row
    ``j * slot_rows`` — exactly the per-peer region layout the HBM store stages,
    so nothing is repacked between "map output written" and "collective run".
    """

    num_executors: int
    send_rows: int
    recv_rows: int
    lane: int = 128
    axis_name: str = "ex"
    impl: str = "auto"

    @property
    def row_bytes(self) -> int:
        return self.lane * 4

    @property
    def slot_rows(self) -> int:
        """Per-peer region size in rows."""
        return self.send_rows // self.num_executors

    def resolve_impl(self, platform: Optional[str] = None) -> "ExchangeSpec":
        """'auto' -> the fastest lowering the backend executes:

        * TPU, n == 1: ``'local'`` — the collective degenerates to a device-
          local prefix copy, and ``ragged_all_to_all``'s single-device lowering
          streams that copy at only ~175 GB/s HBM r+w where the Pallas DMA
          gather sustains ~525 (docs/PERF.md roofline table), so the DMA kernel
          IS the exchange here;
        * TPU, n > 1: ``'ragged'`` (the ICI collective — network-bound, where
          the local-copy inefficiency is irrelevant);
        * CPU: ``'dense'`` (XLA:CPU has no ragged_all_to_all kernel).
        """
        if self.impl != "auto":
            return self
        if platform is None:
            platform = jax.devices()[0].platform
        if platform != "tpu":
            return replace(self, impl="dense")
        return replace(self, impl="local" if self.num_executors == 1 else "ragged")

    def validate(self) -> None:
        if self.send_rows % self.num_executors:
            raise ValueError("send_rows must be divisible by num_executors (slot layout)")
        if self.impl not in ("ragged", "dense", "local"):
            raise ValueError(f"unknown impl {self.impl!r}")
        if self.impl == "local" and self.num_executors != 1:
            raise ValueError("impl='local' is the n=1 degenerate exchange only")
        if self.lane <= 0:
            raise ValueError("lane must be positive")


def ragged_params(sizes, me, slot_rows: Optional[int], xp=jnp):
    """The ragged lowering's offset/size formulas, factored for standalone
    verification (``xp=np`` in tests, ``xp=jnp`` traced inside the collective —
    the SAME expressions either way, so a formula regression fails the
    property tests in tests/test_ragged_plan.py even though XLA:CPU cannot
    execute ragged_all_to_all itself).

    Given the full (n, n) size matrix (``sizes[i, j]`` = rows i sends j), the
    parameters executor ``me`` passes to ``jax.lax.ragged_all_to_all``:

    * ``input_offsets[j]`` — where j's chunk starts in my send buffer: the
      slot start ``j * slot_rows`` (exchange staging layout), or the compact
      exclusive cumsum when ``slot_rows`` is None (columnar/sort layout);
    * ``send_sizes[j]`` — rows I send j: row ``me`` of the matrix;
    * ``output_offsets[j]`` — where MY chunk lands inside receiver j's buffer:
      rows from senders i < me bound for j, i.e. the exclusive cumsum down
      column j, row ``me``;
    * ``recv_sizes[i]`` — rows I receive from i: column ``me``.

    This is the layout contract of the reference's reply packing
    (UcxWorkerWrapper.scala:397-448: [sizes | data...] sender-major).
    """
    n = sizes.shape[0]
    send_sizes = sizes[me]                                      # (n,)
    recv_sizes = sizes[:, me]                                   # (n,)
    output_offsets = exclusive_cumsum(sizes, axis=0, xp=xp)[me]  # (n,)
    if slot_rows is None:
        input_offsets = exclusive_cumsum(send_sizes, xp=xp)     # (n,)
    else:
        input_offsets = xp.arange(n, dtype=xp.int32) * slot_rows
    return input_offsets, send_sizes, output_offsets, recv_sizes


def _gather_sizes(spec: ExchangeSpec, size_row: jnp.ndarray):
    """Phase 1 (shared): gather the full size matrix device-side."""
    ax = spec.axis_name
    me = jax.lax.axis_index(ax)
    sizes = jax.lax.all_gather(size_row, ax, tiled=True)  # (n, n): sizes[i, j] = i -> j rows
    return me, sizes


# Public alias: the scheduled ICI lowering (ops/ici_exchange.py) shares the
# size-matrix gather so its receive metadata is bit-identical to this module's.
gather_size_matrix = _gather_sizes


def _exchange_shard_ragged(spec: ExchangeSpec, data: jnp.ndarray, size_row: jnp.ndarray):
    """Slot-region staging -> ragged_all_to_all over rows -> tight sender-major recv.

    Only each region's used prefix crosses the wire — the padding between
    regions stays home, unlike the dense lowering."""
    me, sizes = _gather_sizes(spec, size_row)
    input_offsets, send_sizes, output_offsets, recv_sizes = ragged_params(
        sizes, me, spec.slot_rows
    )
    out = jnp.zeros((spec.recv_rows, spec.lane), dtype=data.dtype)
    out = ragged_all_to_all(
        data,
        out,
        input_offsets,
        send_sizes.astype(jnp.int32),
        output_offsets.astype(jnp.int32),
        recv_sizes.astype(jnp.int32),
        axis_name=spec.axis_name,
    )
    return out, recv_sizes[None, :]


def _exchange_shard_dense(spec: ExchangeSpec, data: jnp.ndarray, size_row: jnp.ndarray):
    """Slot staging -> tiled all_to_all -> row-gather compaction.

    The compaction maps every output row p to its (sender k, within-chunk delta)
    source inside the received slot grid, producing the same tight sender-major
    layout as the ragged path — one static gather over rows, no data-dependent
    shapes."""
    n = spec.num_executors
    slot = spec.slot_rows
    me, sizes = _gather_sizes(spec, size_row)
    recv_sizes = sizes[:, me]

    slots = data.reshape(n, slot, spec.lane)
    received = jax.lax.all_to_all(slots, spec.axis_name, split_axis=0, concat_axis=0, tiled=True)
    flat = received.reshape(n * slot, spec.lane)

    starts = exclusive_cumsum(recv_sizes)                       # (n,)
    cum = jnp.cumsum(recv_sizes)
    total = cum[-1]
    pos = jnp.arange(spec.recv_rows, dtype=jnp.int32)
    k = jnp.searchsorted(cum, pos, side="right").astype(jnp.int32)
    k = jnp.clip(k, 0, n - 1)
    src = k * slot + (pos - starts[k])
    valid = pos < total
    rows = flat[jnp.clip(src, 0, n * slot - 1)]
    out = jnp.where(valid[:, None], rows, jnp.zeros((), dtype=data.dtype))
    return out, recv_sizes[None, :]


def _build_local_exchange(mesh: Mesh, spec: ExchangeSpec):
    """The n=1 degenerate superstep: one Pallas DMA prefix copy.

    Same contract as the collective lowerings EXCEPT rows past the received
    total are UNSPECIFIED (the collective paths zero them; every consumer
    slices by ``recv_sizes``, which the transports already do).  Roughly 3x
    the single-device throughput of ragged_all_to_all's local-copy lowering
    (~525 vs ~175 GB/s HBM r+w — docs/PERF.md)."""
    from sparkucx_tpu.ops.pallas_kernels import build_block_gather

    gather = build_block_gather(1, spec.recv_rows, impl="dma")

    def local_fn(data, size_matrix):
        zero = jnp.zeros(1, dtype=jnp.int32)
        counts = size_matrix[0, :1].astype(jnp.int32)
        recv = gather(zero, counts, zero, data)
        return recv, size_matrix

    sharding = NamedSharding(mesh, P(spec.axis_name, None))
    fn = jax.jit(
        local_fn,
        in_shardings=(sharding, sharding),
        out_shardings=(sharding, sharding),
    )
    fn.spec = spec
    return fn


def build_exchange(mesh: Mesh, spec: ExchangeSpec):
    """Compile the shuffle-superstep exchange for ``mesh``.

    Returns a jitted ``fn(data, size_matrix) -> (recv, recv_sizes)`` where

    * ``data``: (n * send_rows, lane) int32, row-sharded over ``axis_name`` —
      executor i's staging buffer is shard i, slot layout (peer j's chunk at row
      ``j * slot_rows``);
    * ``size_matrix``: (n, n) int32, row-sharded — row i is executor i's send
      sizes in rows (block padding included);
    * ``recv``: (n * recv_rows, lane) row-sharded — shard j holds everything
      executor j received, tightly packed sender-major;
    * ``recv_sizes``: (n, n) int32 row-sharded — row j = rows j received from
      each sender i.

    Rows of ``recv`` past each shard's received total are zeros under the
    collective lowerings and UNSPECIFIED under ``'local'`` — consumers must
    slice by ``recv_sizes`` (all in-tree consumers do).
    """
    if spec.num_executors != mesh.devices.size:
        raise ValueError(f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}")
    spec = spec.resolve_impl(platform=mesh.devices.reshape(-1)[0].platform)
    spec.validate()
    if spec.impl == "local":
        return _build_local_exchange(mesh, spec)
    ax = spec.axis_name
    body = _exchange_shard_ragged if spec.impl == "ragged" else _exchange_shard_dense

    shard = shard_map(
        functools.partial(body, spec),
        mesh=mesh,
        in_specs=(P(ax, None), P(ax, None)),
        out_specs=(P(ax, None), P(ax, None)),
        check_vma=False,
    )
    data_sharding = NamedSharding(mesh, P(ax, None))
    sizes_sharding = NamedSharding(mesh, P(ax, None))
    # Donating the staging buffer halves peak HBM when the recv buffer can alias
    # it (same shape/dtype); XLA can't alias mismatched sizes, so only donate
    # then.  This is what lets the pipelined multi-round engine
    # (transport/pipeline.py) run a ring of in-flight rounds without
    # accumulating one extra staging buffer per round: each round's staging
    # HBM is recycled into its own receive buffer.  The size matrix (argnum 1)
    # is NEVER donated — callers chain exchanges reusing one sizes array.
    donate = (0,) if spec.send_rows == spec.recv_rows else ()
    fn = jax.jit(
        shard,
        in_shardings=(data_sharding, sizes_sharding),
        out_shardings=(data_sharding, sizes_sharding),
        donate_argnums=donate,
    )
    fn.spec = spec
    return fn


# ----------------------------------------------------------------------------
# Host-side planning helpers (used by the writer/transport and by tests)
# ----------------------------------------------------------------------------


def bucket_send_rows(send_rows: int, num_executors: int) -> int:
    """Capacity bucketing for the compiled-exchange cache: round the per-peer
    slot capacity up to the next power of two and rescale to a full staging
    size.

    Shuffles of varying size then share one compiled executable per bucket
    (the transports key ``_exchange_cache`` on the bucketed value and zero-pad
    payloads up to it) instead of recompiling per distinct ``send_rows`` —
    the same trick ``_gather_fn`` plays with request sizes.  The result is
    always a ``num_executors`` multiple, so the slot layout invariant
    (``send_rows % n == 0``) survives bucketing; padding rows carry zero
    sizes and never cross the wire under the ragged lowering."""
    if send_rows <= 0:
        raise ValueError("send_rows must be positive")
    slot = -(-send_rows // num_executors)  # ceil: tolerate non-multiples
    bucket = 1
    while bucket < slot:
        bucket <<= 1
    return bucket * num_executors


def rebucket_slots(payload, num_executors: int, bucketed_rows: int, *, xp=np):
    """Relocate a ``(send_rows, lane)`` slot-layout staging payload into a
    ``(bucketed_rows, lane)`` buffer for a bucketed exchange.

    Padding must be inserted PER SLOT, not appended at the tail: the exchange
    reads peer j's chunk at row ``j * slot_rows`` with ``slot_rows`` derived
    from the (bucketed) capacity, so each region has to move to its new slot
    origin.  Zero rows fill the grown slot tails; the size matrix still counts
    only used rows, so under the ragged lowering the padding never crosses the
    wire.  ``xp`` selects the array namespace: ``np`` relocates host-side,
    ``jnp`` on a committed device array relocates on that device (no host
    round-trip for device-sealed payloads)."""
    rows, lane = payload.shape
    if rows == bucketed_rows:
        return payload
    n = num_executors
    if rows % n or bucketed_rows % n or bucketed_rows < rows:
        raise ValueError(
            f"cannot rebucket {rows} rows to {bucketed_rows} over {n} executors "
            "(both must be executor multiples, and buckets only grow)"
        )
    grid = payload.reshape(n, rows // n, lane)
    padded = xp.pad(grid, ((0, 0), (0, (bucketed_rows - rows) // n), (0, 0)))
    return padded.reshape(bucketed_rows, lane)


def pack_chunks_slots(
    chunks: Sequence[bytes],
    slot_rows: int,
    row_bytes: int = 512,
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack per-peer byte chunks into a slot-layout staging buffer: chunk j starts
    at row ``j * slot_rows``, padded to a whole row (the writer-side 512-byte
    alignment analogue, NvkvHandler.scala:244-256).

    Returns ((n*slot_rows, row_bytes/4) int32 buffer, per-peer sizes in rows).

    The buffer is allocated with ``np.empty``: only each chunk's final-row
    tail (part of a USED row, so it does reach receivers) is zeroed.  Rows
    between the sized prefix and the slot end stay uninitialized — the size
    matrix counts only used rows, so no lowering lets them into valid receive
    output (the same contract staging garbage already rides on).
    """
    n = len(chunks)
    buf = np.empty(n * slot_rows * row_bytes, dtype=np.uint8)
    sizes = np.empty(n, dtype=np.int32)
    for j, chunk in enumerate(chunks):
        nbytes = len(chunk)
        rows = -(-nbytes // row_bytes)
        if rows > slot_rows:
            raise ValueError(f"chunk for peer {j} ({rows} rows) exceeds slot {slot_rows} rows")
        start = j * slot_rows * row_bytes
        buf[start : start + nbytes] = np.frombuffer(chunk, dtype=np.uint8)
        buf[start + nbytes : start + rows * row_bytes] = 0  # final-row tail only
        sizes[j] = rows
    return buf.view(np.int32).reshape(n * slot_rows, row_bytes // 4), sizes


def unpack_received(
    recv_shard_bytes: bytes, recv_sizes_row: np.ndarray, row_bytes: int = 512
) -> List[bytes]:
    """Split one receiver's tight sender-major buffer into per-sender chunks
    (row padding still attached; block-level slicing is the resolver's job)."""
    out: List[bytes] = []
    pos = 0
    for sz in recv_sizes_row:
        nbytes = int(sz) * row_bytes
        out.append(recv_shard_bytes[pos : pos + nbytes])
        pos += nbytes
    return out


def oracle_exchange(per_device_chunks: Sequence[Sequence[bytes]]) -> List[bytes]:
    """CPU reference: device j receives concat over senders i of chunk[i][j]
    (each chunk row-padded by the sender).

    The correctness oracle for the collective (SURVEY.md section 7: "bytes verified
    against a CPU shuffle oracle")."""
    n = len(per_device_chunks)
    return [b"".join(per_device_chunks[i][j] for i in range(n)) for j in range(n)]


def make_mesh(num_executors: int, axis_name: str = "ex", devices=None) -> Mesh:
    """Build the 1-D executor mesh over the first ``num_executors`` devices.

    Topology-aware placement lives in parallel/mesh.py; this is the plain
    test-friendly constructor."""
    devs = list(devices if devices is not None else jax.devices())[:num_executors]
    if len(devs) < num_executors:
        raise ValueError(f"need {num_executors} devices, have {len(devs)}")
    return Mesh(np.array(devs), (axis_name,))
