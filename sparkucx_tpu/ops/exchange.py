"""The shuffle exchange collective — ragged all_to_all over the executor mesh (L3 hot path).

This is the TPU-native replacement for the reference's entire UCX data plane: where
SparkUCX serves each ``FetchBlockReq`` with a UCP active message carrying the block
bytes (UcxWorkerWrapper.scala:96-186, handleFetchBlockRequest :397-448), here a
*superstep* of the shuffle — every reducer fetching from every mapper — lowers to ONE
collective over the ICI mesh, letting XLA schedule the bidirectional ICI links
instead of hand-driving RDMA endpoints.

Protocol (mirrors the reference's two-phase metadata+data design):

1. **Size-matrix exchange** — each executor contributes the row of element counts it
   holds for every peer; an ``all_gather`` makes the full n x n matrix available
   device-side.  This is the collective analogue of the ``MapperInfo`` commit
   (NvkvShuffleMapOutputWriter.scala:116-148): senders publish sizes before any
   data moves, exactly like the DPU daemon learns the offset table before serving.
2. **Payload exchange** — two lowerings behind one interface:

   * ``impl='ragged'`` (TPU): staging buffers are packed peer-major and *tight*;
     offsets are computed inside jit from the gathered size matrix (exclusive
     row-cumsum for send offsets, exclusive column-cumsum for each receiver's
     landing offsets) and fed to ``jax.lax.ragged_all_to_all`` — zero padding
     crosses the wire.
   * ``impl='dense'`` (portable; XLA:CPU has no ragged-all-to-all kernel): the
     staging buffer is carved into n fixed *slots*; a tiled ``lax.all_to_all``
     moves the slots, then a static-shaped gather compacts the receive side into
     the same tight sender-major layout the ragged path produces.  This is also
     the path the driver's virtual-CPU ``dryrun_multichip`` executes.

   Both lowerings produce bit-identical receive buffers, so every layer above is
   implementation-agnostic.

Everything is static-shaped: staging capacities are compile-time constants, sizes
are runtime data.  No data-dependent Python control flow — the same compiled
exchange serves every superstep of every shuffle.

Payload dtype: buffers are logically bytes, but the exchange runs over a wider lane
dtype (default int32) when alignment permits — ``block_alignment`` (config.py)
guarantees every per-peer chunk starts on a lane boundary, the same role NVKV's
512-byte write alignment plays in the reference (NvkvHandler.scala:244-256).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def exclusive_cumsum(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    return jnp.cumsum(x, axis=axis) - x


@dataclass(frozen=True)
class ExchangeSpec:
    """Static description of one compiled exchange.

    ``send_capacity`` / ``recv_capacity`` are per-executor staging sizes in
    *elements* of ``dtype`` (the HBM analogue of the reference's fixed 30 MB NVKV
    read buffers, NvkvHandler.scala:26-29).  ``impl`` is ``'ragged'`` | ``'dense'``
    | ``'auto'`` (ragged iff the backend lowers it, i.e. TPU).
    """

    num_executors: int
    send_capacity: int
    recv_capacity: int
    dtype: np.dtype = np.dtype(np.int32)
    axis_name: str = "ex"
    impl: str = "auto"
    #: 'tight' — peer chunks packed back-to-back (cumsum offsets; ragged only);
    #: 'slot'  — peer chunk j starts at region boundary j*slot_capacity (both
    #: impls).  'slot' is what the HBM store produces: map writers append into
    #: per-peer regions, so no repacking happens before the collective — the
    #: ragged lowering simply sends each region's used prefix.
    layout: str = "slot"

    @property
    def elem_bytes(self) -> int:
        return np.dtype(self.dtype).itemsize

    @property
    def slot_capacity(self) -> int:
        """Per-peer slot size (elements) for the dense lowering / slot packing."""
        return self.send_capacity // self.num_executors

    def resolve_impl(self, platform: Optional[str] = None) -> "ExchangeSpec":
        if self.impl != "auto":
            return self
        if platform is None:
            platform = jax.devices()[0].platform
        return replace(self, impl="ragged" if platform == "tpu" else "dense")

    def validate(self) -> None:
        if self.layout not in ("tight", "slot"):
            raise ValueError(f"unknown layout {self.layout!r}")
        if self.impl == "dense" and self.layout != "slot":
            raise ValueError("dense impl requires slot layout")
        if self.layout == "slot" and self.send_capacity % self.num_executors:
            raise ValueError("send_capacity must be divisible by num_executors for slot layout")


def _sizes_and_offsets(spec: ExchangeSpec, size_row: jnp.ndarray):
    """Phase 1 (shared): gather the size matrix, derive send/recv sizes + offsets."""
    ax = spec.axis_name
    me = jax.lax.axis_index(ax)
    sizes = jax.lax.all_gather(size_row, ax, tiled=True)  # (n, n): sizes[i, j] = i -> j
    send_sizes = sizes[me]                                # (n,)
    recv_sizes = sizes[:, me]                             # (n,)
    # Landing offset of MY chunk inside each receiver j's buffer: elements from
    # senders i < me bound for j — exclusive cumsum down each column, row `me`.
    output_offsets = exclusive_cumsum(sizes, axis=0)[me]  # (n,)
    return me, send_sizes, recv_sizes, output_offsets


def _exchange_shard_ragged(spec: ExchangeSpec, data: jnp.ndarray, size_row: jnp.ndarray):
    """Peer-major staging -> ragged_all_to_all -> tight sender-major recv.

    With slot layout only each region's used prefix crosses the wire — the
    padding between regions stays home, unlike the dense lowering."""
    _, send_sizes, recv_sizes, output_offsets = _sizes_and_offsets(spec, size_row)
    if spec.layout == "slot":
        n = spec.num_executors
        input_offsets = jnp.arange(n, dtype=jnp.int32) * spec.slot_capacity
    else:
        input_offsets = exclusive_cumsum(send_sizes)
    out = jnp.zeros((spec.recv_capacity,), dtype=data.dtype)
    out = jax.lax.ragged_all_to_all(
        data,
        out,
        input_offsets.astype(jnp.int32),
        send_sizes.astype(jnp.int32),
        output_offsets.astype(jnp.int32),
        recv_sizes.astype(jnp.int32),
        axis_name=spec.axis_name,
    )
    return out, recv_sizes[None, :]


def _exchange_shard_dense(spec: ExchangeSpec, data: jnp.ndarray, size_row: jnp.ndarray):
    """Slot-packed staging -> tiled all_to_all -> gather-compaction.

    The compaction maps every output position p to its (sender k, within-chunk
    delta) source inside the received slot grid, producing the same tight
    sender-major layout as the ragged path — one static gather, MXU/VPU friendly,
    no data-dependent shapes.
    """
    n = spec.num_executors
    slot = spec.slot_capacity
    _, _, recv_sizes, _ = _sizes_and_offsets(spec, size_row)

    slots = data.reshape(n, slot)
    received = jax.lax.all_to_all(slots, spec.axis_name, split_axis=0, concat_axis=0, tiled=True)
    flat = received.reshape(n * slot)

    starts = exclusive_cumsum(recv_sizes)                       # (n,)
    cum = jnp.cumsum(recv_sizes)
    total = cum[-1]
    pos = jnp.arange(spec.recv_capacity, dtype=jnp.int32)
    k = jnp.searchsorted(cum, pos, side="right").astype(jnp.int32)
    k = jnp.clip(k, 0, n - 1)
    src = k * slot + (pos - starts[k])
    valid = pos < total
    out = jnp.where(valid, flat[jnp.clip(src, 0, n * slot - 1)], jnp.zeros((), dtype=data.dtype))
    return out, recv_sizes[None, :]


def build_exchange(mesh: Mesh, spec: ExchangeSpec):
    """Compile the shuffle-superstep exchange for ``mesh``.

    Returns a jitted ``fn(data, size_matrix) -> (recv, recv_sizes)`` where

    * ``data``: (n * send_capacity,) elements of ``spec.dtype``, sharded over
      ``axis_name`` — executor i's staging buffer is shard i (packed per
      ``staging_layout(spec)``);
    * ``size_matrix``: (n, n) int32, row-sharded — row i is executor i's send sizes
      in elements (padded to alignment);
    * ``recv``: (n * recv_capacity,) sharded — shard j holds everything executor j
      received, tightly packed sender-major;
    * ``recv_sizes``: (n, n) int32 row-sharded — row j = elements j received from
      each sender i.
    """
    if spec.num_executors != mesh.devices.size:
        raise ValueError(f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}")
    spec = spec.resolve_impl(platform=mesh.devices.reshape(-1)[0].platform)
    spec.validate()
    ax = spec.axis_name
    body = _exchange_shard_ragged if spec.impl == "ragged" else _exchange_shard_dense

    shard = jax.shard_map(
        functools.partial(body, spec),
        mesh=mesh,
        in_specs=(P(ax), P(ax, None)),
        out_specs=(P(ax), P(ax, None)),
        check_vma=False,
    )
    data_sharding = NamedSharding(mesh, P(ax))
    sizes_sharding = NamedSharding(mesh, P(ax, None))
    # Donating the staging buffer halves peak HBM when the recv buffer can alias
    # it (same shape/dtype); XLA can't alias mismatched sizes, so only donate then.
    donate = (0,) if spec.send_capacity == spec.recv_capacity else ()
    fn = jax.jit(
        shard,
        in_shardings=(data_sharding, sizes_sharding),
        out_shardings=(data_sharding, sizes_sharding),
        donate_argnums=donate,
    )
    fn.spec = spec
    return fn


# ----------------------------------------------------------------------------
# Host-side planning helpers (used by the writer/transport and by tests)
# ----------------------------------------------------------------------------


def staging_layout(spec: ExchangeSpec) -> Optional[int]:
    """Slot size in elements for slot packing, or None for tight packing."""
    return None if spec.layout == "tight" else spec.slot_capacity


def pack_chunks_peer_major(
    chunks: Sequence[bytes],
    capacity_bytes: int,
    alignment: int,
    elem_bytes: int,
    slot_elems: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Pack per-peer byte chunks into one staging buffer, peer-major, each chunk
    padded to ``alignment`` (the writer-side 512-alignment analogue,
    NvkvHandler.scala:244-256).

    ``slot_elems=None`` packs tight (ragged layout); otherwise chunk j starts at
    slot boundary ``j * slot_elems`` (dense layout).

    Returns (uint8 buffer of length capacity_bytes, per-peer sizes in *elements*,
    padding included).
    """
    if alignment % elem_bytes:
        raise ValueError("alignment must be a multiple of the exchange element size")
    buf = np.zeros(capacity_bytes, dtype=np.uint8)
    sizes = np.zeros(len(chunks), dtype=np.int32)
    pos = 0
    for j, chunk in enumerate(chunks):
        if slot_elems is not None:
            pos = j * slot_elems * elem_bytes
        padded = -(-len(chunk) // alignment) * alignment
        if slot_elems is not None and padded > slot_elems * elem_bytes:
            raise ValueError(
                f"chunk for peer {j} ({padded} B padded) exceeds slot {slot_elems * elem_bytes} B"
            )
        if pos + padded > capacity_bytes:
            raise ValueError(f"staging overflow: need {pos + padded} bytes > capacity {capacity_bytes}")
        buf[pos : pos + len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
        sizes[j] = padded // elem_bytes
        pos += padded
    return buf, sizes


def unpack_received(
    recv_shard_bytes: bytes, recv_sizes_row: np.ndarray, elem_bytes: int
) -> List[bytes]:
    """Split one receiver's tight sender-major buffer into per-sender chunks
    (padding still attached; block-level slicing is the resolver's job)."""
    out: List[bytes] = []
    pos = 0
    for sz in recv_sizes_row:
        nbytes = int(sz) * elem_bytes
        out.append(recv_shard_bytes[pos : pos + nbytes])
        pos += nbytes
    return out


def oracle_exchange(per_device_chunks: Sequence[Sequence[bytes]]) -> List[bytes]:
    """CPU reference: device j receives concat over senders i of chunk[i][j]
    (each chunk alignment-padded by the sender).

    The correctness oracle for the collective (SURVEY.md section 7: "bytes verified
    against a CPU shuffle oracle").
    """
    n = len(per_device_chunks)
    return [b"".join(per_device_chunks[i][j] for i in range(n)) for j in range(n)]


def make_mesh(num_executors: int, axis_name: str = "ex", devices=None) -> Mesh:
    """Build the 1-D executor mesh over the first ``num_executors`` devices.

    Topology-aware placement lives in parallel/mesh.py; this is the plain
    test-friendly constructor.
    """
    devs = list(devices if devices is not None else jax.devices())[:num_executors]
    if len(devs) < num_executors:
        raise ValueError(f"need {num_executors} devices, have {len(devs)}")
    return Mesh(np.array(devs), (axis_name,))
