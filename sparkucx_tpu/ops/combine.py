"""Receive-side combine math for the compute-in-exchange path (ROADMAP 2).

The fused grouped-aggregate exchange stops materializing received rows: as
each scheduled window lands (the FAST ring's supersteps, ops/ici_exchange.py),
it is dequantized and folded into a fixed dense per-group accumulator — the
EQuARX in-collective-compute argument (PAPERS.md, arXiv:2506.17615) applied to
the shuffle's reduce side.  Post-exchange memory and D2H drain bytes go from
O(rows) to O(groups), and under the Pallas DMA lowering the whole exchange is
ONE kernel launch instead of one dispatch per scheduled item.

This module is the single source of the combine arithmetic.  Every tier —
the Pallas kernel epilogue (ops/pallas_kernels.ring_combine_grid), the
scheduled-XLA walk (ops/ici_exchange.build_combine_exchange), and the
relational fused body (ops/relational.py) — calls :func:`combine_window` on
windows in the SAME canonical order (own slot first, then schedule items in
step order), so exact dtypes are bit-identical across tiers and against the
unfused path by construction (tests/test_fused_combine.py pins it).

Window row layout is the partial-aggregate exchange row
(ops/relational._aggregate_body): ``[key (uint32 bitcast) | payload | count
(int32 bitcast)]``, all lanes in the aggregate dtype.  Validity is exactly
``count > 0``: every real partial row carries count >= 1 and staging padding
rows are all-zero, so no separate valid lane crosses the wire.  The payload
is either ``width`` plain value lanes or the quantized packing
(ops/compress.quantize_rows) dequantized per window as it lands.

``count_distinct`` needs the full value multiset, so partial aggregation —
and therefore the fused combine — rejects it upstream
(``AggregateSpec.validate``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Tuple

import numpy as np

from sparkucx_tpu.ops.compress import QuantizeSpec

#: aggregates the dense combine accumulator can fold (everything partial
#: aggregation supports; avg lanes carry SUM until the host divides)
COMBINE_AGGS: Tuple[str, ...] = ("sum", "min", "max", "avg")

#: the ``ExchangePlan.combine`` tier vocabulary
COMBINE_TIERS: Tuple[str, ...] = ("off", "dense", "sorted")


def agg_identity(agg: str, dtype):
    """The fold identity of one aggregate column (scalar, numpy dtype)."""
    dtype = np.dtype(dtype)
    if agg == "min":
        info = np.finfo(dtype) if np.issubdtype(dtype, np.floating) else np.iinfo(dtype)
        return dtype.type(info.max)
    if agg == "max":
        info = np.finfo(dtype) if np.issubdtype(dtype, np.floating) else np.iinfo(dtype)
        return dtype.type(info.min)
    return dtype.type(0)


@dataclass(frozen=True)
class CombineSpec:
    """Static geometry of one dense fused-combine accumulator.

    Frozen/hashable — part of the exchange builders' compile-cache keys, so
    callers must bucket ``num_groups`` (pow2, like every other cache key
    dimension) before constructing one.
    """

    #: dense key-domain size: keys are uint32 in [0, num_groups)
    num_groups: int
    #: per value column, in column order (VALID_AGGS minus count_distinct)
    aggs: Tuple[str, ...]
    #: aggregate value dtype (int32, or float32 under quantization)
    dtype: Any = np.int32
    #: lossy payload packing of the landed windows ('off' = plain lanes)
    quantize_mode: str = "off"
    quantize_block: int = 128

    @property
    def width(self) -> int:
        return len(self.aggs)

    @property
    def qspec(self) -> Optional[QuantizeSpec]:
        if self.quantize_mode == "off":
            return None
        return QuantizeSpec(mode=self.quantize_mode, block_size=self.quantize_block)

    @property
    def payload_width(self) -> int:
        """Value lanes of one exchange row (quantized packing included)."""
        q = self.qspec
        return q.quantized_width(self.width) if q is not None else self.width

    @property
    def row_width(self) -> int:
        """Total lanes of one exchange row: key + payload + count."""
        return 1 + self.payload_width + 1

    @property
    def acc_bytes(self) -> int:
        """Accumulator bytes per device — the O(groups) quantity that
        replaces the O(rows) recv staging (also mirrored host-side by
        ``PlanContext.combine_acc_bytes`` for the planner)."""
        return self.num_groups * (self.width * np.dtype(self.dtype).itemsize + 4)

    def validate(self) -> None:
        if self.num_groups <= 0:
            raise ValueError("num_groups must be positive")
        bad = [a for a in self.aggs if a not in COMBINE_AGGS]
        if bad:
            raise ValueError(f"aggregates {bad} not dense-combinable {COMBINE_AGGS}")
        q = self.qspec
        if q is not None:
            q.validate()
            if not np.issubdtype(np.dtype(self.dtype), np.floating):
                raise ValueError("quantized combine requires a float dtype")


def acc_init(spec: CombineSpec):
    """Fresh accumulator ``(acc_vals (G, width), acc_counts (G, 1))`` — every
    column at its fold identity, counts zero.  Traced jnp (callable inside
    kernel bodies); counts stay 2-D so the kernel's VMEM scratch never holds
    a rank-1 array."""
    import jax.numpy as jnp

    cols = [
        jnp.full((spec.num_groups, 1), agg_identity(a, spec.dtype), dtype=spec.dtype)
        for a in spec.aggs
    ]
    return jnp.concatenate(cols, axis=1), jnp.zeros((spec.num_groups, 1), jnp.int32)


def combine_window(spec: CombineSpec, window, acc_vals, acc_counts):
    """Fold ONE landed exchange window into the dense accumulator.

    ``window``: ``(rows, spec.row_width)`` in ``spec.dtype`` lanes, the
    sender-major grid region one schedule item delivered.  Pure jnp over
    static shapes (no per-row scatter): a ``(rows, num_groups)`` one-hot mask
    turns every fold into a masked column reduction — the vector shape the
    Pallas epilogue and the XLA walk both lower cleanly.  Invalid rows
    (count == 0: staging padding, quota-truncated tails) hit no group.
    """
    import jax
    import jax.numpy as jnp

    keys = jax.lax.bitcast_convert_type(window[:, 0], jnp.uint32)
    counts = jax.lax.bitcast_convert_type(window[:, -1:], jnp.int32)
    payload = window[:, 1:-1]
    q = spec.qspec
    if q is not None:
        from sparkucx_tpu.ops.compress import dequantize_rows

        words = jax.lax.bitcast_convert_type(payload, jnp.int32)
        payload = dequantize_rows(q, words, spec.width).astype(spec.dtype)
    valid = counts[:, 0] > 0
    domain = jnp.arange(spec.num_groups, dtype=jnp.uint32)
    hit = (keys[:, None] == domain[None, :]) & valid[:, None]  # (rows, G)
    acc_counts = acc_counts + jnp.sum(
        jnp.where(hit, counts, 0), axis=0, dtype=jnp.int32
    )[:, None]
    zero = jnp.zeros((), spec.dtype)
    cols = []
    for c, agg in enumerate(spec.aggs):
        col = payload[:, c : c + 1]  # (rows, 1) — broadcasts over the mask
        if agg in ("sum", "avg"):
            cols.append(acc_vals[:, c] + jnp.sum(jnp.where(hit, col, zero), axis=0))
        elif agg == "min":
            ident = agg_identity("min", spec.dtype)
            cols.append(jnp.minimum(acc_vals[:, c], jnp.min(jnp.where(hit, col, ident), axis=0)))
        else:  # max
            ident = agg_identity("max", spec.dtype)
            cols.append(jnp.maximum(acc_vals[:, c], jnp.max(jnp.where(hit, col, ident), axis=0)))
    return jnp.stack(cols, axis=1), acc_counts


def merge_accumulators(spec: CombineSpec, a, b):
    """Merge two dense accumulators (quota sub-rounds, running-plan chaining).

    Associative and commutative for min/max/counts; sum/avg columns merge in
    argument order, which every caller keeps fixed (running accumulator
    first) so float merges stay deterministic."""
    import jax.numpy as jnp

    (av, ac), (bv, bc) = a, b
    cols = []
    for c, agg in enumerate(spec.aggs):
        if agg in ("sum", "avg"):
            cols.append(av[:, c] + bv[:, c])
        elif agg == "min":
            cols.append(jnp.minimum(av[:, c], bv[:, c]))
        else:
            cols.append(jnp.maximum(av[:, c], bv[:, c]))
    return jnp.stack(cols, axis=1), ac + bc
