"""Payload-reduction layer for the data plane: lossless page codecs + lossy
block quantization (ROADMAP item 2 — send fewer bytes, keep the GB/s).

Two independent tiers, both default OFF with the off-paths byte-identical:

**Tier (a) — lossless wire compression** (:class:`CompressSpec`).  The striped
TCP wire's chunk frames are self-addressing, so each chunk is a *page* that
encodes and decodes independently (utils/pagecodec.py formats).  The server
encodes per chunk (falling back to raw when a page doesn't shrink), the codec
id + decoded length ride a chunk-header extension (core/definitions.py), and
each lane's recv thread decodes straight into the chunk's final buffer offset
— transport/peer.py owns the wiring, this module owns the policy (which
codec, the min-page gate).  Lossless always: shuffle results are
bit-identical, pinned by tests/test_compress.py.

**Tier (b) — lossy opt-in block quantization** (:class:`QuantizeSpec`).
Aggregate-tolerant float exchange payloads (groupby/join partials,
ops/relational.py) travel as int8 with one float32 scale per ``block_size``
values — 4x fewer ICI bytes per float lane, the EQuARX argument (PAPERS.md,
arXiv:2506.17615) applied to the shuffle's partial-aggregate exchange.  The
quantize step fuses into the exchange send side and dequantize into the
receive path (ops/ici_exchange.py quantized builders), so staging→wire stays
one launch.  Error is bounded per block: ``int8`` uses a linear scale
(|err| <= amax/254), ``blockfloat`` a power-of-two shared exponent
(|err| <= amax/127, but scales are exact binary — no scale rounding).  Keys
and counts are NEVER quantized; ``mode='off'`` is exactly the stock path.

Quantized row layout (all int32, so the payload rides the existing int32
exchange machinery unchanged): for a float row of width ``w`` and block size
``B`` (multiple of 4), ``wq = ceil(w/B)*B`` padded values pack 4 int8 per
int32 word — ``wq//4`` words — followed by ``nb = wq//B`` per-block float32
scales bitcast to int32 (the same bit-preserving transit trick the groupby
count lane uses).  Total ``quantized_width(w) = wq//4 + nb`` lanes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from sparkucx_tpu.utils.pagecodec import (
    CODEC_RAW,
    WIRE_CODECS,
    encode_page,
)

QUANTIZE_MODES = ("off", "int8", "blockfloat")


# ----------------------------------------------------------------------------
# Tier (a): lossless wire compression policy
# ----------------------------------------------------------------------------


@dataclass(frozen=True)
class CompressSpec:
    """Static description of the wire compression policy (tier a).

    ``codec``: 'off' | 'dict' | 'rle' | 'delta' (conf ``compress.codec``).
    ``min_chunk_bytes``: pages smaller than this ship raw without attempting
    an encode — below a few KiB the header + call overhead beats any shrink.
    """

    codec: str = "off"
    min_chunk_bytes: int = 4096

    @classmethod
    def from_conf(cls, conf) -> "CompressSpec":
        spec = cls(
            codec=conf.wire_compress_codec,
            min_chunk_bytes=conf.compress_min_chunk_bytes,
        )
        spec.validate()
        return spec

    def validate(self) -> None:
        if self.codec != "off" and self.codec not in WIRE_CODECS:
            raise ValueError(f"unknown compress codec {self.codec!r}")
        if self.min_chunk_bytes < 0:
            raise ValueError("min_chunk_bytes must be >= 0")

    @property
    def enabled(self) -> bool:
        return self.codec != "off"

    @property
    def codec_id(self) -> int:
        return WIRE_CODECS[self.codec] if self.enabled else CODEC_RAW


def encode_chunk(spec: CompressSpec, data) -> Tuple[int, Optional[bytes]]:
    """Encode one wire page under ``spec``.

    Returns ``(codec_id, encoded)``; ``encoded is None`` means "ship the raw
    slice" (codec off, page under the min-size gate, or encoding didn't
    shrink it) and the returned codec id is :data:`CODEC_RAW`."""
    if not spec.enabled or len(data) < spec.min_chunk_bytes:
        return CODEC_RAW, None
    encoded = encode_page(spec.codec_id, data)
    if encoded is None:
        return CODEC_RAW, None
    return spec.codec_id, encoded


# ----------------------------------------------------------------------------
# Tier (b): lossy block quantization (jax, fuses into the exchange jit)
# ----------------------------------------------------------------------------


@dataclass(frozen=True)
class QuantizeSpec:
    """Static description of the lossy quantization policy (tier b).

    ``mode``: 'off' | 'int8' | 'blockfloat' (conf ``quantize.mode``).
    ``block_size``: values per scale block along the row; multiple of 4
    (int8x4-in-int32 packing granularity)."""

    mode: str = "off"
    block_size: int = 128

    @classmethod
    def from_conf(cls, conf) -> "QuantizeSpec":
        spec = cls(mode=conf.quantize_mode, block_size=conf.quantize_block_size)
        spec.validate()
        return spec

    def validate(self) -> None:
        if self.mode not in QUANTIZE_MODES:
            raise ValueError(f"unknown quantize mode {self.mode!r}")
        if self.block_size <= 0 or self.block_size % 4:
            raise ValueError("quantize block_size must be a positive multiple of 4")

    @property
    def enabled(self) -> bool:
        return self.mode != "off"

    def padded_width(self, w: int) -> int:
        """Float width padded up to a whole number of blocks."""
        return -(-w // self.block_size) * self.block_size

    def num_blocks(self, w: int) -> int:
        return self.padded_width(w) // self.block_size

    def quantized_width(self, w: int) -> int:
        """int32 lanes of the quantized payload: packed int8 words + scales."""
        return self.padded_width(w) // 4 + self.num_blocks(w)

    def error_bound(self, amax: float) -> float:
        """Per-element absolute error bound for a block whose max |value| is
        ``amax`` — the dequant-tolerance gate tests assert against this."""
        if self.mode == "int8":
            return amax / 254.0  # scale = amax/127, round error <= scale/2
        if self.mode == "blockfloat":
            return amax / 127.0  # scale <= 2*amax/127 (pow2 ceil), err <= scale/2
        return 0.0


def _block_scales(spec: QuantizeSpec, amax):
    # jax imports are function-local throughout tier (b) so the host-only
    # transport (transport/peer.py) can import the tier-(a) policy above
    # without pulling jax into every peer process
    import jax.numpy as jnp

    if spec.mode == "int8":
        return jnp.where(amax > 0, amax / 127.0, 1.0)
    # blockfloat: power-of-two shared exponent — scales carry no mantissa
    # error and the int8 payload divides exactly by a binary shift
    s = jnp.where(amax > 0, amax / 127.0, 1.0)
    return jnp.where(amax > 0, jnp.exp2(jnp.ceil(jnp.log2(s))), 1.0)


def quantize_rows(spec: QuantizeSpec, x):
    """Quantize float32 rows ``(rows, w)`` -> int32 ``(rows, quantized_width(w))``.

    Row-independent (each row carries its own block scales), so quantized
    rows survive any permutation/compaction the exchange applies before
    :func:`dequantize_rows` runs on the receive side."""
    import jax
    import jax.numpy as jnp

    spec.validate()
    if not spec.enabled:
        raise ValueError("quantize_rows called with mode='off'")
    rows, w = x.shape
    wq = spec.padded_width(w)
    nb = spec.num_blocks(w)
    xp = jnp.pad(x.astype(jnp.float32), ((0, 0), (0, wq - w)))
    blocks = xp.reshape(rows, nb, spec.block_size)
    amax = jnp.max(jnp.abs(blocks), axis=2)
    scale = _block_scales(spec, amax)
    q = jnp.clip(jnp.round(blocks / scale[:, :, None]), -127, 127).astype(jnp.int32)
    qb = q.reshape(rows, wq // 4, 4) & 0xFF
    packed = qb[..., 0] | (qb[..., 1] << 8) | (qb[..., 2] << 16) | (qb[..., 3] << 24)
    scales_i32 = jax.lax.bitcast_convert_type(scale.astype(jnp.float32), jnp.int32)
    return jnp.concatenate([packed, scales_i32], axis=1)


def dequantize_rows(spec: QuantizeSpec, payload, w: int):
    """Inverse of :func:`quantize_rows`: int32 ``(rows, quantized_width(w))``
    -> float32 ``(rows, w)``.  Zero-filled payload rows (unreceived slots)
    dequantize to zero rows — scale words of 0 bitcast to 0.0 and multiply a
    zero int8 payload, so compacted tails stay zeros like the stock path."""
    import jax
    import jax.numpy as jnp

    spec.validate()
    if not spec.enabled:
        raise ValueError("dequantize_rows called with mode='off'")
    rows, qw = payload.shape
    wq = spec.padded_width(w)
    nb = spec.num_blocks(w)
    if qw != wq // 4 + nb:
        raise ValueError(
            f"payload width {qw} != quantized_width({w}) = {wq // 4 + nb}"
        )
    packed = payload[:, : wq // 4]
    scale = jax.lax.bitcast_convert_type(payload[:, wq // 4 :], jnp.float32)
    shifts = jnp.array([0, 8, 16, 24], jnp.int32)
    b = (packed[..., None] >> shifts) & 0xFF
    b = jnp.where(b >= 128, b - 256, b)  # sign-extend int8
    q = b.reshape(rows, nb, spec.block_size).astype(jnp.float32)
    x = q * scale[:, :, None]
    return x.reshape(rows, wq)[:, :w]
