"""Distributed sample sort — the device-resident TeraSort core.

BASELINE.md lists TeraSort as a headline workload ("TeraSort 10GB", north star
"shuffle-read GB/s ... TeraSort-100GB").  In Spark, TeraSort is `sortByKey`:
a range-partitioning shuffle (sampled splitters decide which reducer owns each
key range) followed by a per-partition sort.  The reference accelerates only the
shuffle *transport* of that job (UCX block fetch); here the ENTIRE job runs on
device — sampling, range partitioning, the all-to-all, and the final sort are
one jitted SPMD program over the executor mesh:

    local sort -> sample splitters (all_gather) -> range-partition owners ->
    ragged all_to_all (reuses ops/columnar machinery) -> local sort of received

After the step, executor j holds the j-th global key range, sorted; the
concatenation of shards in mesh order is the fully sorted dataset.  This is the
TPU-native answer to the job the reference's GroupByTest/TeraSort harness runs
over Spark + UCX (buildlib/test.sh:163-179, BASELINE.json configs[1]).

Rows are (key, payload-lane...) with 32-bit lanes; a 100-byte TeraSort row is
one uint32 key lane + 24 payload lanes.  Keys travel with their payload through
one exchange (bitcast into the payload dtype) so the permutation is applied
exactly once.

Skew: splitters come from `samples_per_shard` evenly spaced local samples, so a
range can exceed `recv_capacity` only under adversarial key skew; the returned
per-shard receive totals let the caller detect overflow (`counts >
recv_capacity`) and re-run with more headroom — the host-side analogue of the
multi-round spill path in transport/tpu.py.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops.columnar import (
    ColumnarSpec,
    columnar_shard_dense,
    columnar_shard_ragged,
    shard_rows_host,
    size_matrix_from_owners,
)
from sparkucx_tpu.ops.exchange import gather_rows

KEY_MAX = np.uint32(0xFFFFFFFF)  # padding sentinel; sorts last


@dataclass(frozen=True)
class SortSpec:
    """Static description of one compiled distributed sort.

    ``capacity``: per-executor input rows (pad short shards; padding keys must
    be ``KEY_MAX`` and are excluded via ``num_valid``).
    ``recv_capacity``: per-executor output rows — headroom over the balanced
    ``total/n`` guards against sampling error (1.5-2x is ample for uniform
    keys, e.g. TeraSort's).
    ``width``: payload lanes of ``dtype`` per row (>= 0); keys are uint32.
    """

    num_executors: int
    capacity: int
    recv_capacity: int
    width: int = 24  # 96-byte payload -> 100-byte rows like TeraSort
    dtype: np.dtype = np.dtype(np.int32)
    samples_per_shard: int = 64
    axis_name: str = "ex"
    impl: str = "auto"

    def resolve_impl(self, platform: Optional[str] = None) -> "SortSpec":
        """'auto' -> 'single' when one executor (sample sort degenerates to one
        local sort — no splitters, no exchange, HALF the sort work; any
        backend), else 'ragged' on TPU / 'dense' elsewhere."""
        if self.impl != "auto":
            return self
        if self.num_executors == 1 and self.recv_capacity >= self.capacity:
            return replace(self, impl="single")
        if platform is None:
            platform = jax.devices()[0].platform
        return replace(self, impl="ragged" if platform == "tpu" else "dense")

    def validate(self) -> None:
        if self.impl not in ("ragged", "dense", "single"):
            raise ValueError(f"unknown impl {self.impl!r}")
        if self.impl == "single" and (
            self.num_executors != 1 or self.recv_capacity < self.capacity
        ):
            raise ValueError(
                "impl='single' needs num_executors=1 and recv_capacity >= capacity"
            )
        if np.dtype(self.dtype).itemsize != 4:
            raise ValueError("payload dtype must be 32-bit (keys bitcast through it)")
        if self.samples_per_shard < self.num_executors:
            raise ValueError("samples_per_shard must be >= num_executors")


def _global_splitters(spec: SortSpec, sorted_keys: jnp.ndarray, num_valid: jnp.ndarray):
    """Sample each shard's sorted prefix, gather, and pick n-1 range boundaries.

    This is the on-device analogue of Spark's RangePartitioner sketch: sizes are
    published before data moves, like the MapperInfo commit the reference sends
    ahead of block serving (NvkvShuffleMapOutputWriter.scala:116-148)."""
    n = spec.num_executors
    s = spec.samples_per_shard
    # Each shard's sample weight is proportional to its fill (num_valid /
    # capacity), so a near-empty shard doesn't drag the splitters toward its few
    # keys: it uses `used` of its s sample slots, the rest are KEY_MAX sentinels
    # that sort to the top and (given any non-degenerate fill) are never cut.
    # float32 ratio: ~1e-7 relative error is irrelevant for sampling weights and
    # avoids s*num_valid int32 overflow on huge shards.
    nv = num_valid.astype(jnp.int32)
    used = jnp.minimum(
        s, (nv.astype(jnp.float32) / spec.capacity * s).astype(jnp.int32) + (nv > 0)
    )
    # Evenly spaced positions over the valid prefix: (i*nv)//used, decomposed so
    # the product can't overflow int32 for i < used (i*(nv//used) <= nv).
    i = jnp.arange(s, dtype=jnp.int32)
    u = jnp.maximum(used, 1)
    pos = i * (nv // u) + (i * (nv % u)) // u
    local = jnp.where(i < used, sorted_keys[jnp.clip(pos, 0, spec.capacity - 1)], KEY_MAX)
    allsamp = jax.lax.all_gather(local, spec.axis_name, tiled=True)  # (n*s,)
    allsamp = jnp.sort(allsamp)
    # Cut at sample-quantiles of the *real* samples only (sentinels sorted last).
    total_used = jax.lax.psum(used, spec.axis_name)
    k = jnp.arange(1, n, dtype=jnp.int32)
    cut = k * (total_used // n) + (k * (total_used % n)) // n
    return allsamp[jnp.clip(cut, 0, n * s - 1)]  # (n-1,) splitters


def _sort_body(spec: SortSpec, keys: jnp.ndarray, payload: jnp.ndarray, num_valid: jnp.ndarray):
    n = spec.num_executors
    nv = num_valid[0]

    # 1. Local sort (padding KEY_MAX rows sort last; re-force in case the
    #    caller's padding was not sentinel-keyed).
    idx = jnp.arange(spec.capacity, dtype=jnp.int32)
    keys = jnp.where(idx < nv, keys, KEY_MAX)
    order = jnp.argsort(keys)
    skeys = keys[order]
    spay = gather_rows(payload, order)

    # 2. Splitters -> per-row destination executor (padding rows -> n, never sent).
    splitters = _global_splitters(spec, skeys, nv)
    owners = jnp.searchsorted(splitters, skeys, side="right").astype(jnp.int32)
    owners = jnp.where(idx < nv, owners, n)

    # 3. One exchange moves key+payload together: key lane bitcast to dtype.
    rows = jnp.concatenate([jax.lax.bitcast_convert_type(skeys, spec.dtype)[:, None], spay], axis=1)
    # keys already sorted => owners are non-decreasing: rows are dest-contiguous.
    sizes, send_sizes, recv_sizes, output_offsets = size_matrix_from_owners(
        spec.axis_name, n, owners
    )
    cspec = ColumnarSpec(
        num_executors=n,
        capacity=spec.capacity,
        recv_capacity=spec.recv_capacity,
        width=spec.width + 1,
        dtype=spec.dtype,
        axis_name=spec.axis_name,
        impl=spec.impl,
    )
    xchg = columnar_shard_ragged if spec.impl == "ragged" else columnar_shard_dense
    recv, recv_sizes = xchg(cspec, rows, send_sizes, recv_sizes, output_offsets)

    # 4. Final local sort of the received range.
    total = recv_sizes.sum().astype(jnp.int32)
    rkeys = jax.lax.bitcast_convert_type(recv[:, 0], jnp.uint32)
    ridx = jnp.arange(spec.recv_capacity, dtype=jnp.int32)
    rkeys = jnp.where(ridx < total, rkeys, KEY_MAX)
    rorder = jnp.argsort(rkeys)
    out_keys = rkeys[rorder]
    out_pay = gather_rows(recv[:, 1:], rorder)
    return out_keys, out_pay, total[None]


def _sort_body_single(spec: SortSpec, keys: jnp.ndarray, payload: jnp.ndarray, num_valid: jnp.ndarray):
    """n=1 degenerate sample sort: ONE local sort.

    The distributed body would sort locally, self-exchange ~100 B/row, and
    sort the (recv_capacity-padded) receive buffer again — twice the sort and
    a pointless copy; halving that gives ~2x, and measurement chaining on top
    shows ~21 M rows/s on a v5e chip (docs/PERF.md, sort row + floor note)."""
    nv = num_valid[0]
    idx = jnp.arange(spec.capacity, dtype=jnp.int32)
    keys = jnp.where(idx < nv, keys, KEY_MAX)
    order = jnp.argsort(keys)
    out_keys = keys[order]
    # valid rows sort to the front (stable argsort, padding keys KEY_MAX), so
    # zeroing the tail matches the collective lowerings' output contract —
    # the caller's padding payload must not leak through the permutation
    out_pay = jnp.where((idx < nv)[:, None], gather_rows(payload, order), 0)
    pad = spec.recv_capacity - spec.capacity
    if pad:
        out_keys = jnp.concatenate([out_keys, jnp.full(pad, KEY_MAX, jnp.uint32)])
        out_pay = jnp.concatenate([out_pay, jnp.zeros((pad, spec.width), spec.dtype)])
    return out_keys, out_pay, nv[None].astype(jnp.int32)


def build_distributed_sort(mesh: Mesh, spec: SortSpec):
    """Compile the full distributed sort for ``mesh``.

    Returns jitted ``fn(keys, payload, num_valid) -> (keys_out, payload_out, counts)``:

    * ``keys``: (n * capacity,) uint32, sharded over ``axis_name``;
    * ``payload``: (n * capacity, width) of ``dtype``, row-sharded (same row
      order as ``keys``);
    * ``num_valid``: (n,) int32, sharded — valid rows per shard (rest padding);
    * ``keys_out``: (n * recv_capacity,) uint32 — shard j = j-th global key
      range, ascending; concatenating valid prefixes in mesh order yields the
      fully sorted keys.  Padding tail is KEY_MAX.
    * ``payload_out``: rows permuted identically to ``keys_out``.  The sort is
      **stable**: rows with equal keys keep their global input order (this is
      a contract, not an accident — the n=1 lowering's padding handling
      already requires stable argsort, the exchange lands senders in rank
      order, and the differential fuzz asserts row-exact agreement with
      ``np.argsort(kind='stable')`` under heavy duplication);
    * ``counts``: (n,) int32 — valid rows per output shard.  Any value >
      ``recv_capacity`` means splitter skew overflowed the headroom; re-run
      with a larger ``recv_capacity``.
    """
    if spec.num_executors != mesh.devices.size:
        raise ValueError(f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}")
    spec = spec.resolve_impl(platform=mesh.devices.reshape(-1)[0].platform)
    spec.validate()
    ax = spec.axis_name

    body = _sort_body_single if spec.impl == "single" else _sort_body
    shard = jax.shard_map(
        functools.partial(body, spec),
        mesh=mesh,
        in_specs=(P(ax), P(ax, None), P(ax)),
        out_specs=(P(ax), P(ax, None), P(ax)),
        check_vma=False,
    )
    fn = jax.jit(
        shard,
        in_shardings=(
            NamedSharding(mesh, P(ax)),
            NamedSharding(mesh, P(ax, None)),
            NamedSharding(mesh, P(ax)),
        ),
        out_shardings=(
            NamedSharding(mesh, P(ax)),
            NamedSharding(mesh, P(ax, None)),
            NamedSharding(mesh, P(ax)),
        ),
    )
    fn.spec = spec
    return fn


def oracle_sort(keys: np.ndarray, payload: np.ndarray):
    """CPU reference: globally sorted (keys, payload) for oracle checks."""
    order = np.argsort(keys, kind="stable")
    return keys[order], payload[order]


def run_distributed_sort(
    mesh: Mesh,
    spec: SortSpec,
    keys: np.ndarray,
    payload: np.ndarray,
    max_attempts: int = 3,
):
    """Host driver: shard, run the compiled sort, and retry with doubled
    ``recv_capacity`` when splitter skew overflows a shard — the re-run
    contract the spec documents, automated (the TeraSort job surface, like
    ``run_transitive_closure`` is SparkTC's).

    ``keys``: (T,) uint32; ``payload``: (T, width).  Returns (sorted keys,
    payload rows in the same order) as host arrays.  Raises after
    ``max_attempts`` doublings (pathological skew: most keys identical).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = spec.num_executors
    total = keys.shape[0]
    cap = spec.capacity
    if total > n * cap:
        raise ValueError(f"{total} rows exceed {n} x {cap} capacity")
    if mesh.devices.size != n:
        raise ValueError(f"mesh size {mesh.devices.size} != num_executors {n}")

    pk, pv, nv = shard_rows_host(
        keys, payload, n, cap, key_fill=int(KEY_MAX), value_dtype=spec.dtype
    )

    key_sh = NamedSharding(mesh, P(spec.axis_name))
    row_sh = NamedSharding(mesh, P(spec.axis_name, None))
    gk = jax.device_put(pk, key_sh)
    gv = jax.device_put(pv, row_sh)
    gn = jax.device_put(nv, key_sh)

    attempt_spec = spec
    for attempt in range(max_attempts):
        fn = build_distributed_sort(mesh, attempt_spec)
        out_keys, out_pay, counts = fn(gk, gv, gn)
        counts_h = np.asarray(counts)
        if (counts_h <= attempt_spec.recv_capacity).all():
            rc = attempt_spec.recv_capacity
            ka = np.asarray(out_keys).reshape(n, rc)
            pa = np.asarray(out_pay).reshape(n, rc, spec.width)
            sk = np.concatenate([ka[s, : counts_h[s]] for s in range(n)])
            sp = np.concatenate([pa[s, : counts_h[s]] for s in range(n)])
            return sk, sp
        attempt_spec = replace(
            attempt_spec, recv_capacity=2 * attempt_spec.recv_capacity
        )
    raise RuntimeError(
        f"sort overflowed recv_capacity {attempt_spec.recv_capacity // 2} after "
        f"{max_attempts} doublings — key distribution too skewed for range "
        f"partitioning (most keys identical?)"
    )
