"""Distributed sample sort — the device-resident TeraSort core.

BASELINE.md lists TeraSort as a headline workload ("TeraSort 10GB", north star
"shuffle-read GB/s ... TeraSort-100GB").  In Spark, TeraSort is `sortByKey`:
a range-partitioning shuffle (sampled splitters decide which reducer owns each
key range) followed by a per-partition sort.  The reference accelerates only the
shuffle *transport* of that job (UCX block fetch); here the ENTIRE job runs on
device — sampling, range partitioning, the all-to-all, and the final sort are
one jitted SPMD program over the executor mesh:

    local sort -> sample splitters (all_gather) -> range-partition owners ->
    ragged all_to_all (reuses ops/columnar machinery) -> local sort of received

After the step, executor j holds the j-th global key range, sorted; the
concatenation of shards in mesh order is the fully sorted dataset.  This is the
TPU-native answer to the job the reference's GroupByTest/TeraSort harness runs
over Spark + UCX (buildlib/test.sh:163-179, BASELINE.json configs[1]).

Rows are (key, payload-lane...) with 32-bit lanes; a 100-byte TeraSort row is
one uint32 key lane + 24 payload lanes.  Keys travel with their payload through
one exchange (bitcast into the payload dtype) so the permutation is applied
exactly once.

Skew: splitters come from `samples_per_shard` evenly spaced local samples, so a
range can exceed `recv_capacity` only under adversarial key skew; the returned
per-shard receive totals let the caller detect overflow (`counts >
recv_capacity`) and re-run with more headroom — the host-side analogue of the
multi-round spill path in transport/tpu.py.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops._compat import shard_map
from sparkucx_tpu.ops.columnar import (
    ColumnarSpec,
    columnar_shard_dense,
    columnar_shard_ragged,
    shard_rows_host,
    size_matrix_from_owners,
    unpack_shard_prefixes,
)
from sparkucx_tpu.ops.exchange import gather_rows

KEY_MAX = np.uint32(0xFFFFFFFF)  # padding sentinel; sorts last


@dataclass(frozen=True)
class SortSpec:
    """Static description of one compiled distributed sort.

    ``capacity``: per-executor input rows (pad short shards; padding keys must
    be ``KEY_MAX`` and are excluded via ``num_valid``).
    ``recv_capacity``: per-executor output rows — headroom over the balanced
    ``total/n`` guards against sampling error (1.5-2x is ample for uniform
    keys, e.g. TeraSort's).
    ``width``: payload lanes of ``dtype`` per row (>= 0); keys are uint32.
    """

    num_executors: int
    capacity: int
    recv_capacity: int
    width: int = 24  # 96-byte payload -> 100-byte rows like TeraSort
    dtype: np.dtype = np.dtype(np.int32)
    samples_per_shard: int = 64
    axis_name: str = "ex"
    impl: str = "auto"

    def resolve_impl(self, platform: Optional[str] = None) -> "SortSpec":
        """'auto' -> 'single' when one executor (sample sort degenerates to one
        local sort — no splitters, no exchange, HALF the sort work; any
        backend), else 'ragged' on TPU / 'dense' elsewhere.  'radix' swaps the
        n=1 local sort for the Pallas LSD radix kernel (ops/radix.py) whose
        scatter moves key+payload together by segment DMA — the explicit
        opt-in for beating the XLA argsort+gather floor (docs/PERF.md)."""
        if self.impl != "auto":
            return self
        if self.num_executors == 1 and self.recv_capacity >= self.capacity:
            return replace(self, impl="single")
        if platform is None:
            platform = jax.devices()[0].platform
        return replace(self, impl="ragged" if platform == "tpu" else "dense")

    def validate(self) -> None:
        if self.impl not in ("ragged", "dense", "single", "radix"):
            raise ValueError(f"unknown impl {self.impl!r}")
        if self.impl in ("single", "radix") and (
            self.num_executors != 1 or self.recv_capacity < self.capacity
        ):
            raise ValueError(
                f"impl={self.impl!r} needs num_executors=1 and recv_capacity >= capacity"
            )
        if np.dtype(self.dtype).itemsize != 4:
            raise ValueError("payload dtype must be 32-bit (keys bitcast through it)")
        if self.samples_per_shard < self.num_executors:
            raise ValueError("samples_per_shard must be >= num_executors")


def _global_splitters(spec: SortSpec, sorted_keys: jnp.ndarray, num_valid: jnp.ndarray):
    """Sample each shard's sorted prefix, gather, and pick n-1 range boundaries.

    This is the on-device analogue of Spark's RangePartitioner sketch: sizes are
    published before data moves, like the MapperInfo commit the reference sends
    ahead of block serving (NvkvShuffleMapOutputWriter.scala:116-148)."""
    n = spec.num_executors
    s = spec.samples_per_shard
    # Each shard's sample weight is proportional to its fill (num_valid /
    # capacity), so a near-empty shard doesn't drag the splitters toward its few
    # keys: it uses `used` of its s sample slots, the rest are KEY_MAX sentinels
    # that sort to the top and (given any non-degenerate fill) are never cut.
    # float32 ratio: ~1e-7 relative error is irrelevant for sampling weights and
    # avoids s*num_valid int32 overflow on huge shards.
    nv = num_valid.astype(jnp.int32)
    used = jnp.minimum(
        s, (nv.astype(jnp.float32) / spec.capacity * s).astype(jnp.int32) + (nv > 0)
    )
    # Evenly spaced positions over the valid prefix: (i*nv)//used, decomposed so
    # the product can't overflow int32 for i < used (i*(nv//used) <= nv).
    i = jnp.arange(s, dtype=jnp.int32)
    u = jnp.maximum(used, 1)
    pos = i * (nv // u) + (i * (nv % u)) // u
    local = jnp.where(i < used, sorted_keys[jnp.clip(pos, 0, spec.capacity - 1)], KEY_MAX)
    allsamp = jax.lax.all_gather(local, spec.axis_name, tiled=True)  # (n*s,)
    allsamp = jnp.sort(allsamp)
    # Cut at sample-quantiles of the *real* samples only (sentinels sorted last).
    total_used = jax.lax.psum(used, spec.axis_name)
    k = jnp.arange(1, n, dtype=jnp.int32)
    cut = k * (total_used // n) + (k * (total_used % n)) // n
    return allsamp[jnp.clip(cut, 0, n * s - 1)]  # (n-1,) splitters


def _sort_body(spec: SortSpec, keys: jnp.ndarray, payload: jnp.ndarray, num_valid: jnp.ndarray):
    n = spec.num_executors
    nv = num_valid[0]

    # 1. Local sort (padding KEY_MAX rows sort last; re-force in case the
    #    caller's padding was not sentinel-keyed).
    idx = jnp.arange(spec.capacity, dtype=jnp.int32)
    keys = jnp.where(idx < nv, keys, KEY_MAX)
    order = jnp.argsort(keys, stable=True)  # stability is the documented contract
    skeys = keys[order]
    spay = gather_rows(payload, order)

    # 2. Splitters -> per-row destination executor (padding rows -> n, never sent).
    splitters = _global_splitters(spec, skeys, nv)
    owners = jnp.searchsorted(splitters, skeys, side="right").astype(jnp.int32)
    owners = jnp.where(idx < nv, owners, n)

    # 3. One exchange moves key+payload together: key lane bitcast to dtype.
    rows = jnp.concatenate([jax.lax.bitcast_convert_type(skeys, spec.dtype)[:, None], spay], axis=1)
    # keys already sorted => owners are non-decreasing: rows are dest-contiguous.
    sizes, send_sizes, recv_sizes, output_offsets = size_matrix_from_owners(
        spec.axis_name, n, owners
    )
    cspec = ColumnarSpec(
        num_executors=n,
        capacity=spec.capacity,
        recv_capacity=spec.recv_capacity,
        width=spec.width + 1,
        dtype=spec.dtype,
        axis_name=spec.axis_name,
        impl=spec.impl,
    )
    xchg = columnar_shard_ragged if spec.impl == "ragged" else columnar_shard_dense
    recv, recv_sizes = xchg(cspec, rows, send_sizes, recv_sizes, output_offsets)

    # 4. Final local sort of the received range.
    total = recv_sizes.sum().astype(jnp.int32)
    rkeys = jax.lax.bitcast_convert_type(recv[:, 0], jnp.uint32)
    ridx = jnp.arange(spec.recv_capacity, dtype=jnp.int32)
    rkeys = jnp.where(ridx < total, rkeys, KEY_MAX)
    rorder = jnp.argsort(rkeys, stable=True)
    out_keys = rkeys[rorder]
    out_pay = gather_rows(recv[:, 1:], rorder)
    return out_keys, out_pay, total[None]


def _sort_body_single(spec: SortSpec, keys: jnp.ndarray, payload: jnp.ndarray, num_valid: jnp.ndarray):
    """n=1 degenerate sample sort: ONE local sort.

    The distributed body would sort locally, self-exchange ~100 B/row, and
    sort the (recv_capacity-padded) receive buffer again — twice the sort and
    a pointless copy; halving that gives ~2x, and measurement chaining on top
    shows ~21 M rows/s on a v5e chip (docs/PERF.md, sort row + floor note)."""
    nv = num_valid[0]
    idx = jnp.arange(spec.capacity, dtype=jnp.int32)
    keys = jnp.where(idx < nv, keys, KEY_MAX)
    order = jnp.argsort(keys, stable=True)
    out_keys = keys[order]
    # valid rows sort to the front (stable argsort, padding keys KEY_MAX), so
    # zeroing the tail matches the collective lowerings' output contract —
    # the caller's padding payload must not leak through the permutation
    out_pay = jnp.where((idx < nv)[:, None], gather_rows(payload, order), 0)
    pad = spec.recv_capacity - spec.capacity
    if pad:
        out_keys = jnp.concatenate([out_keys, jnp.full(pad, KEY_MAX, jnp.uint32)])
        out_pay = jnp.concatenate([out_pay, jnp.zeros((pad, spec.width), spec.dtype)])
    return out_keys, out_pay, nv[None].astype(jnp.int32)


def _sort_body_radix(spec: SortSpec, keys, payload, num_valid, *, interpret: bool):
    """n=1 path with the Pallas LSD radix sort (ops/radix.py): key and payload
    fuse into one row tile and move TOGETHER by segment DMA each pass —
    no XLA argsort, no permutation gather (the two measured walls of the
    'single' path, docs/PERF.md sort-floor analysis)."""
    from sparkucx_tpu.ops.radix import radix_sort_rows

    nv = num_valid[0]
    idx = jnp.arange(spec.capacity, dtype=jnp.int32)
    keys = jnp.where(idx < nv, keys, KEY_MAX)
    rows = jnp.concatenate(
        [jax.lax.bitcast_convert_type(keys, spec.dtype)[:, None], payload], axis=1
    )
    rows = radix_sort_rows(rows, interpret=interpret)
    out_keys = jax.lax.bitcast_convert_type(rows[:, 0], jnp.uint32)
    # invalid rows (forced KEY_MAX, input tail) sort stably to the back:
    # positions >= nv are exactly them; zero their payload like the other
    # lowerings so caller padding cannot leak through the permutation
    out_pay = jnp.where((idx < nv)[:, None], rows[:, 1:], 0)
    pad = spec.recv_capacity - spec.capacity
    if pad:
        out_keys = jnp.concatenate([out_keys, jnp.full(pad, KEY_MAX, jnp.uint32)])
        out_pay = jnp.concatenate([out_pay, jnp.zeros((pad, spec.width), spec.dtype)])
    return out_keys, out_pay, nv[None].astype(jnp.int32)


def build_distributed_sort(mesh: Mesh, spec: SortSpec):
    """Compile the full distributed sort for ``mesh``.

    Returns jitted ``fn(keys, payload, num_valid) -> (keys_out, payload_out, counts)``:

    * ``keys``: (n * capacity,) uint32, sharded over ``axis_name``;
    * ``payload``: (n * capacity, width) of ``dtype``, row-sharded (same row
      order as ``keys``);
    * ``num_valid``: (n,) int32, sharded — valid rows per shard (rest padding);
    * ``keys_out``: (n * recv_capacity,) uint32 — shard j = j-th global key
      range, ascending; concatenating valid prefixes in mesh order yields the
      fully sorted keys.  Padding tail is KEY_MAX.
    * ``payload_out``: rows permuted identically to ``keys_out``.  The sort is
      **stable**: rows with equal keys keep their global input order (this is
      a contract, not an accident — the n=1 lowering's padding handling
      already requires stable argsort, the exchange lands senders in rank
      order, and the differential fuzz asserts row-exact agreement with
      ``np.argsort(kind='stable')`` under heavy duplication);
    * ``counts``: (n,) int32 — valid rows per output shard.  Any value >
      ``recv_capacity`` means splitter skew overflowed the headroom; re-run
      with a larger ``recv_capacity``.
    """
    if spec.num_executors != mesh.devices.size:
        raise ValueError(f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}")
    spec = spec.resolve_impl(platform=mesh.devices.reshape(-1)[0].platform)
    spec.validate()
    ax = spec.axis_name

    if spec.impl == "radix":
        # the Pallas kernel needs real Mosaic for its dynamic-size DMAs; any
        # other backend runs the interpreter (CPU-mesh tests)
        interpret = mesh.devices.reshape(-1)[0].platform != "tpu"
        body = functools.partial(_sort_body_radix, interpret=interpret)
    else:
        body = _sort_body_single if spec.impl == "single" else _sort_body
    shard = shard_map(
        functools.partial(body, spec),
        mesh=mesh,
        in_specs=(P(ax), P(ax, None), P(ax)),
        out_specs=(P(ax), P(ax, None), P(ax)),
        check_vma=False,
    )
    fn = jax.jit(
        shard,
        in_shardings=(
            NamedSharding(mesh, P(ax)),
            NamedSharding(mesh, P(ax, None)),
            NamedSharding(mesh, P(ax)),
        ),
        out_shardings=(
            NamedSharding(mesh, P(ax)),
            NamedSharding(mesh, P(ax, None)),
            NamedSharding(mesh, P(ax)),
        ),
    )
    fn.spec = spec
    return fn


def oracle_sort(keys: np.ndarray, payload: np.ndarray):
    """CPU reference: globally sorted (keys, payload) for oracle checks."""
    order = np.argsort(keys, kind="stable")
    return keys[order], payload[order]


def _sort_one_batch(
    mesh: Mesh,
    spec: SortSpec,
    keys: np.ndarray,
    payload: np.ndarray,
    max_attempts: int,
    fns: dict,
):
    """One <=``n*capacity``-row chunk through the compiled sort: shard, run,
    retry with doubled ``recv_capacity`` on splitter-skew overflow, unpack the
    valid prefixes.  ``fns`` caches compiled sorts by full spec so callers
    looping over batches (run_external_sort) compile once per capacity."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = spec.num_executors
    pk, pv, nv = shard_rows_host(
        keys, payload, n, spec.capacity, key_fill=int(KEY_MAX), value_dtype=spec.dtype
    )
    key_sh = NamedSharding(mesh, P(spec.axis_name))
    row_sh = NamedSharding(mesh, P(spec.axis_name, None))
    gk = jax.device_put(pk, key_sh)
    gv = jax.device_put(pv, row_sh)
    gn = jax.device_put(nv, key_sh)

    attempt_spec = spec
    for _ in range(max_attempts):
        rc = attempt_spec.recv_capacity
        fn = fns.get(attempt_spec)  # keyed by the full spec: a reused cache
        if fn is None:              # with a different spec must recompile
            fn = fns[attempt_spec] = build_distributed_sort(mesh, attempt_spec)
        out_keys, out_pay, counts = fn(gk, gv, gn)
        counts_h = np.asarray(counts)
        if (counts_h <= rc).all():
            sk, sp = unpack_shard_prefixes((out_keys, out_pay), counts_h, rc)
            return sk, sp
        attempt_spec = replace(attempt_spec, recv_capacity=2 * rc)
    raise RuntimeError(
        f"sort overflowed recv_capacity {attempt_spec.recv_capacity // 2} after "
        f"{max_attempts} doublings — key distribution too skewed for range "
        f"partitioning (most keys identical?)"
    )


def run_distributed_sort(
    mesh: Mesh,
    spec: SortSpec,
    keys: np.ndarray,
    payload: np.ndarray,
    max_attempts: int = 3,
):
    """Host driver: shard, run the compiled sort, and retry with doubled
    ``recv_capacity`` when splitter skew overflows a shard — the re-run
    contract the spec documents, automated (the TeraSort job surface, like
    ``run_transitive_closure`` is SparkTC's).

    ``keys``: (T,) uint32; ``payload``: (T, width).  Returns (sorted keys,
    payload rows in the same order) as host arrays.  Raises after
    ``max_attempts`` doublings (pathological skew: most keys identical).
    """
    n = spec.num_executors
    total = keys.shape[0]
    cap = spec.capacity
    if total > n * cap:
        raise ValueError(f"{total} rows exceed {n} x {cap} capacity")
    if mesh.devices.size != n:
        raise ValueError(f"mesh size {mesh.devices.size} != num_executors {n}")
    return _sort_one_batch(mesh, spec, keys, payload, max_attempts, {})


def merge_sorted_runs(run_keys, run_payloads):
    """Stable host merge of sorted (keys, payload) runs into one sorted pair.

    Pairwise ``searchsorted`` merges over (key, global-row-index) only —
    log2(R) linear passes moving 8 B/row — then each run's payload is placed
    ONCE, read sequentially and scattered to its final positions (no
    concatenated intermediate; moving the wide payload through every level
    measured 5x slower, and the concat another ~1.7x on the final phase).
    Stability contract matches the device sort's: runs must be in row order
    (run i holds earlier input rows than run i+1); within a merge, equal keys
    from the later run land after the earlier run's (``side='right'`` ranks
    place them past the equal block)."""
    run_keys = [np.asarray(k) for k in run_keys]
    run_payloads = list(run_payloads)
    if not run_keys:
        raise ValueError("no runs to merge")
    if len(run_keys) != len(run_payloads) or any(
        len(k) != len(p) for k, p in zip(run_keys, run_payloads)
    ):
        raise ValueError(
            "run_keys and run_payloads must pair up row-for-row "
            f"({[len(k) for k in run_keys]} keys vs "
            f"{[len(p) for p in run_payloads]} payload rows)"
        )
    offsets = np.cumsum([0] + [len(k) for k in run_keys[:-1]])
    run_idx = [
        np.arange(len(k), dtype=np.int64) + off for k, off in zip(run_keys, offsets)
    ]
    while len(run_keys) > 1:
        nk, ni = [], []
        for i in range(0, len(run_keys) - 1, 2):
            k1, x1 = run_keys[i], run_idx[i]
            k2, x2 = run_keys[i + 1], run_idx[i + 1]
            # output position of each k2 element: its searchsorted-right rank
            # among k1 plus the k2 elements already placed before it
            pos2 = np.searchsorted(k1, k2, side="right") + np.arange(len(k2))
            total = len(k1) + len(k2)
            mk = np.empty(total, k1.dtype)
            mx = np.empty(total, np.int64)
            mask = np.ones(total, bool)
            mask[pos2] = False
            mk[pos2] = k2
            mx[pos2] = x2
            mk[mask] = k1
            mx[mask] = x1
            nk.append(mk)
            ni.append(mx)
        if len(run_keys) % 2:
            nk.append(run_keys[-1])
            ni.append(run_idx[-1])
        run_keys, run_idx = nk, ni
    perm = run_idx[0]
    if len(run_payloads) == 1:
        return run_keys[0], run_payloads[0][perm]
    total = len(perm)
    inv = np.empty(total, np.int64)
    inv[perm] = np.arange(total, dtype=np.int64)  # dest position per global row
    out = np.empty((total, run_payloads[0].shape[1]), run_payloads[0].dtype)
    for off, p in zip(offsets, run_payloads):
        out[inv[off : off + len(p)]] = p
    return run_keys[0], out


def run_external_sort(
    mesh: Mesh,
    spec: SortSpec,
    keys: np.ndarray,
    payload: np.ndarray,
    max_attempts: int = 3,
    fns: Optional[dict] = None,
):
    """Out-of-core TeraSort driver: datasets past device capacity are sorted
    in device batches of ``num_executors * capacity`` rows (one compiled sort
    reused across batches), then the sorted runs are merged on the host.

    The single-chip envelope is ~32M 100 B rows in HBM (docs/PERF.md); this
    driver is how the "TeraSort 10GB" workload (BASELINE.json configs[1])
    runs on hardware that can't hold the dataset: the device does the
    O(N log N) work per batch, the host does log2(runs) linear merge passes.
    Peak host memory is ~2.5x the dataset (input + runs being merged).

    Same contract as :func:`run_distributed_sort` (stable, oracle-exact),
    same skew-retry behavior per batch.  Pass a dict as ``fns`` to keep the
    compiled sorts across calls (repeat-measurement loops would otherwise
    re-trace every call and time compilation)."""
    n = spec.num_executors
    batch = n * spec.capacity
    total = keys.shape[0]
    if mesh.devices.size != n:
        raise ValueError(f"mesh size {mesh.devices.size} != num_executors {n}")
    if fns is None:
        fns = {}  # SortSpec -> compiled sort, reused across batches
    if total <= batch:
        return _sort_one_batch(mesh, spec, keys, payload, max_attempts, fns)

    run_keys, run_payloads = [], []
    for start in range(0, total, batch):
        sk, sp = _sort_one_batch(
            mesh, spec, keys[start : start + batch], payload[start : start + batch],
            max_attempts, fns,
        )
        run_keys.append(sk)
        run_payloads.append(sp)
    return merge_sorted_runs(run_keys, run_payloads)
