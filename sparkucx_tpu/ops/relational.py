"""Device-resident relational operators — grouped aggregation and hash join.

BASELINE.md's remaining workload configs are Spark SQL jobs: "TPC-H q5/q18
SF=10" and "TPC-DS SF=100".  Their physical plans are a small vocabulary:
hash-partition exchange + local aggregation (HashAggregateExec around a
ShuffleExchange) and hash-partition exchange of both sides + local join
(ShuffledHashJoinExec / SortMergeJoinExec).  The reference accelerates only the
exchange *transport* of those plans (the UCX block fetch under Spark SQL's
shuffle); here the whole operator runs on device, the way ops/sort.py runs all
of TeraSort on device:

    hash(key) -> owner  ->  columnar ragged all_to_all (ops/columnar.py)  ->
    local segment-reduce (GROUP BY) or sort-merge expansion (JOIN)

Everything is static-shaped (capacities are compile-time constants, row counts
are runtime data), so one compiled operator serves every batch of every query —
the XLA-friendly design SURVEY.md section 0 calls for, no data-dependent shapes.

Keys are uint32 and travel bitcast through the payload dtype lane exactly as in
ops/sort.py; rows whose index is past ``num_valid`` are padding and never
participate.  Both operators return actual totals so callers detect capacity
overflow and re-run with headroom — the same contract as SortSpec.recv_capacity
(ops/sort.py) and the multi-round spill path (transport/tpu.py).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops._compat import shard_map
from sparkucx_tpu.ops.columnar import (
    ColumnarSpec,
    columnar_body,
    shard_rows_host,
    unpack_shard_prefixes,
)
from sparkucx_tpu.ops.compress import QuantizeSpec, dequantize_rows, quantize_rows
from sparkucx_tpu.ops.exchange import exclusive_cumsum

#: Padding sort key (sorts last) — ops/sort.py's sentinel, same discipline:
#: valid rows may legitimately carry this key; because received rows are a
#: tight valid prefix, a *stable* sort keeps valid sentinel-keyed rows ahead of
#: padding within the tie, and validity masks do the rest (x64 stays off; no
#: int64 composite keys anywhere).
from sparkucx_tpu.ops.sort import KEY_MAX  # noqa: E402  (re-export)

#: Multiplicative hash constant (Knuth); uint32 wraparound is the mixing step.
_HASH_MULT = np.uint32(2654435761)

#: 'avg' is computed as a fused sum on device (the count is always produced
#: alongside), divided exactly in the host driver — Spark's partial-avg plan
#: (HashAggregateExec emits sum+count partials, the final stage divides).
#: 'count_distinct' counts distinct values of its column per group, on device.
VALID_AGGS = ("sum", "min", "max", "avg", "count_distinct")

#: join_type -> rows emitted per probe row with m build matches.  ONE table
#: serves both the device kernel (xp=jnp in expand_matches) and the host
#: capacity planner (xp=np in plan_join_capacities) so the two can never
#: drift — a divergence would make the exact host plan under-size out_cap.
_JOIN_EMIT = {
    "inner": lambda m, xp: m,
    "left_outer": lambda m, xp: xp.maximum(m, 1),
    "left_semi": lambda m, xp: xp.minimum(m, 1),
    "left_anti": lambda m, xp: 1 - xp.minimum(m, 1),
}

#: right/full outer decompose into a probe-driven base expansion plus an
#: appended pass over unmatched BUILD rows (a build-side match-flag scan —
#: probe-row emission counts alone cannot express them).
_OUTER_BASE = {"right_outer": "inner", "full_outer": "left_outer"}

#: join types whose compiled fn emits the extra ``out_matched`` output
#: (False = null-extended row: zeroed build lanes for an unmatched probe row,
#: zeroed probe lanes for an unmatched build row).
OUTER_JOIN_TYPES = ("left_outer", "right_outer", "full_outer")

JOIN_TYPES = tuple(_JOIN_EMIT) + tuple(_OUTER_BASE)


def _join_emit(join_type: str):
    fn = _JOIN_EMIT.get(join_type)
    if fn is None:
        raise ValueError(
            f"unknown join_type {join_type!r} (valid: {tuple(_JOIN_EMIT)})"
        )
    return fn


def hash_owners(keys: jnp.ndarray, num_executors: int, valid: jnp.ndarray) -> jnp.ndarray:
    """Destination executor per row: multiplicative hash of the uint32 key,
    mod n.  This is Spark SQL's HashPartitioning, computed on device.  Padding
    rows map to ``num_executors`` (the columnar shuffle's never-sent owner)."""
    mixed = (keys.astype(jnp.uint32) * _HASH_MULT) >> 16
    owner = (mixed % jnp.uint32(num_executors)).astype(jnp.int32)
    return jnp.where(valid, owner, num_executors)


def hash_owners_host(keys: "np.ndarray", num_executors: int) -> "np.ndarray":
    """Host-side twin of :func:`hash_owners` (bit-identical placement, numpy
    uint32 wraparound) — lets drivers plan receive capacities from the actual
    key distribution instead of guessing skew headroom."""
    mixed = (keys.astype(np.uint32) * _HASH_MULT) >> np.uint32(16)
    return (mixed % np.uint32(num_executors)).astype(np.int32)


def padded_keys(keys: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Force padding rows to the KEY_MAX sentinel so they sort last."""
    return jnp.where(valid, keys.astype(jnp.uint32), KEY_MAX)


def exchange_keyed_rows(spec: ColumnarSpec, keys, values, valid):
    """Hash-partition (key | values) rows through one columnar exchange.

    Returns (recv_keys uint32, recv_values, recv_valid, recv_total) with the
    received rows tight-packed; every row of a given key lands on exactly one
    executor.  ``recv_total`` is the TRUE row count routed to this shard — a
    value > ``recv_capacity`` means the buffer truncated (overflow the caller
    must surface, same contract as SortSpec.recv_capacity)."""
    rows = jnp.concatenate(
        [jax.lax.bitcast_convert_type(keys.astype(jnp.uint32), spec.dtype)[:, None], values],
        axis=1,
    )
    owners = hash_owners(keys, spec.num_executors, valid)
    recv, recv_sizes = columnar_body(spec, rows, owners)
    total = recv_sizes.sum().astype(jnp.int32)
    ridx = jnp.arange(spec.recv_capacity, dtype=jnp.int32)
    recv_valid = ridx < total
    recv_keys = jax.lax.bitcast_convert_type(recv[:, 0], jnp.uint32)
    return recv_keys, recv[:, 1:], recv_valid, total


# ----------------------------------------------------------------------------
# Grouped aggregation (GROUP BY)
# ----------------------------------------------------------------------------


@dataclass(frozen=True)
class AggregateSpec:
    """Static description of one compiled grouped aggregation.

    ``capacity``: per-executor input rows; ``recv_capacity``: per-executor rows
    after the hash exchange (>= worst-case skew of hash(key) % n — with K
    distinct keys expect ~total/n, so leave headroom like SortSpec does);
    ``aggs``: one of ``VALID_AGGS`` ('sum'|'min'|'max'|'avg'|'count_distinct')
    per value column — 'avg' is a fused sum on device divided by the count in
    the host driver, 'count_distinct' counts distinct column values per group.
    A per-group COUNT is always produced (it is also COUNT(*) when there are
    no value columns)."""

    num_executors: int
    capacity: int
    recv_capacity: int
    aggs: Tuple[str, ...]
    dtype: np.dtype = np.dtype(np.int32)
    axis_name: str = "ex"
    impl: str = "auto"
    #: True compiles the WHERE-pushdown variant: the jitted fn takes a fourth
    #: per-row bool input and filtered rows never enter the exchange (their
    #: owner is the never-sent n) — Spark SQL's Filter below the Exchange,
    #: on device instead of pre-filtered host tables.
    with_filter: bool = False
    #: True performs MAP-SIDE PARTIAL AGGREGATION below the exchange — Spark's
    #: HashAggregateExec(partial) under the ShuffleExchange: each shard first
    #: segment-reduces its own rows to at most one partial row per local
    #: distinct key (agg columns + a count), exchanges the PARTIALS, and the
    #: final merge re-reduces them (sum/min/max/avg compose; count becomes
    #: sum-of-counts).  For GroupByTest-shaped data (a small keyspace over
    #: millions of rows, buildlib/test.sh:163-173) this shrinks exchange
    #: traffic by the group-reduction factor — and it bounds hot-key skew:
    #: each shard sends at most ONE row per key, so a hot key lands
    #: ``num_executors`` partial rows on its owner, not the raw row count.
    #: Results are bit-identical for integer dtypes (int32 adds associate);
    #: 'count_distinct' is rejected (distinct counts do not compose by sum).
    partial: bool = False
    #: OPT-IN LOSSY tier-b payload reduction (ops/compress.py, conf
    #: ``quantize.mode``): 'off' | 'int8' | 'blockfloat'.  Block-quantizes the
    #: PARTIAL-aggregate float value columns around the exchange — quantize
    #: after the map-side reduce, ship int8x4-packed words (bitcast through
    #: the float lane, the count lane's transit trick), dequantize before the
    #: final merge.  Requires ``partial=True`` and a floating ``dtype``; keys
    #: and counts are NEVER quantized, so group identity and COUNT stay
    #: exact.  Per-partial-row error is bounded by
    #: ``QuantizeSpec.error_bound`` per block of ``quantize_block_size``.
    quantize_mode: str = "off"
    quantize_block_size: int = 128
    #: Receive-side COMPUTE-IN-EXCHANGE tier (ops/combine.py, conf
    #: ``exchange.fusedCombine``): 'off' | 'auto' | 'dense' | 'sorted'.
    #: 'dense' folds every landed exchange window into a fixed per-group
    #: accumulator as it arrives — post-exchange memory and drain bytes drop
    #: from O(rows) to O(groups), and the Pallas lowering runs the whole
    #: scheduled ring as ONE kernel launch.  It requires ``partial=True``
    #: (the windows are partial-aggregate rows) and every key to lie inside
    #: ``[0, combine_groups)``.  'sorted' is the high-cardinality fallback:
    #: a bounded per-superstep sort/merge into a (recv_capacity) accumulator —
    #: still O(recv_capacity) post-exchange, never the full landed grid.
    #: 'auto' resolves via :meth:`resolve_combine` (dense iff the accumulator
    #: undercuts the slot grid the exchange would otherwise drain);
    #: :func:`run_grouped_aggregate` fills ``combine_groups`` from the actual
    #: key domain first.  Exact dtypes are bit-identical to the unfused path
    #: (tests/test_fused_combine.py pins it); quantized payloads stay inside
    #: the per-row ``QuantizeSpec.error_bound``.
    combine: str = "off"
    #: dense key-domain size (pow2-bucketed — a compile-cache key dimension)
    combine_groups: int = 0
    #: ICI lowering of the fused exchange ('auto' | 'dma' | 'xla' |
    #: 'interpret' — ops/ici_exchange.resolve_ici_lowering vocabulary)
    combine_lowering: str = "auto"

    @property
    def width(self) -> int:
        return len(self.aggs)

    @property
    def qspec(self) -> QuantizeSpec:
        return QuantizeSpec(
            mode=self.quantize_mode, block_size=self.quantize_block_size
        )

    @classmethod
    def from_conf(cls, conf, **kwargs) -> "AggregateSpec":
        """Build a spec with cluster-level defaults taken from a
        ``TpuShuffleConf``: ``partial`` from ``conf.partial_aggregation`` (the
        ``partialAggregation`` Spark key — this is where that knob enters the
        plan), ``num_executors``/``axis_name`` from the conf unless given.
        Explicit kwargs always win.  count_distinct plans default to
        ``partial=False`` regardless of the conf (distinct counts do not
        compose by sum — validate() would reject the combination)."""
        if "count_distinct" in kwargs.get("aggs", ()):
            kwargs.setdefault("partial", False)
        kwargs.setdefault("partial", bool(conf.partial_aggregation))
        kwargs.setdefault("num_executors", conf.num_executors)
        kwargs.setdefault("axis_name", conf.mesh_axis_name)
        explicit_quantize = "quantize_mode" in kwargs
        kwargs.setdefault("quantize_mode", conf.quantize_mode)
        kwargs.setdefault("quantize_block_size", conf.quantize_block_size)
        explicit_combine = "combine" in kwargs
        kwargs.setdefault(
            "combine",
            "auto" if getattr(conf, "exchange_fused_combine", False) else "off",
        )
        spec = cls(**kwargs)
        if (
            not explicit_quantize
            and spec.quantize_mode != "off"
            and not (
                spec.partial and np.issubdtype(np.dtype(spec.dtype), np.floating)
            )
        ):
            # the conf knob is cluster-global; plans it cannot apply to
            # (non-partial, integer dtypes — exactness is the contract there)
            # silently keep the stock path instead of failing validate()
            spec = replace(spec, quantize_mode="off")
        if (
            not explicit_combine
            and spec.combine != "off"
            and (not spec.partial or spec.num_executors < 2)
        ):
            # same discipline as the quantize knob: the fused combine folds
            # PARTIAL rows across an exchange, so non-partial plans (incl.
            # count_distinct, which forces partial=False above) and
            # single-executor meshes keep the stock path silently
            spec = replace(spec, combine="off")
        return spec

    def resolve_combine(self) -> "AggregateSpec":
        """Resolve ``combine='auto'`` to a concrete tier: 'dense' when the
        per-group accumulator undercuts the fused slot grid the exchange
        would otherwise drain (the planner's ``_combine_tier`` rule, made
        spec-local for direct builder users), else the bounded 'sorted'
        fallback.  ``combine_groups`` must already hold the pow2-bucketed
        key-domain size — :func:`run_grouped_aggregate` measures it from the
        actual keys before calling this."""
        if self.combine != "auto":
            return self
        acc_bytes = self.combine_groups * (self.width * 4 + 4)
        staging_bytes = self.num_executors * self.capacity * (self.width + 2) * 4
        dense = self.combine_groups > 0 and acc_bytes < staging_bytes
        return replace(self, combine="dense" if dense else "sorted")

    @property
    def combine_cspec(self):
        """The ``ops/combine.CombineSpec`` of the dense tier (quantization
        rides inside it — one dispatch, both tiers compose)."""
        from sparkucx_tpu.ops.combine import CombineSpec

        return CombineSpec(
            num_groups=max(1, self.combine_groups),
            aggs=self.aggs,
            dtype=self.dtype,
            quantize_mode=self.quantize_mode,
            quantize_block=self.quantize_block_size,
        )

    def resolve_impl(self, platform: Optional[str] = None) -> "AggregateSpec":
        if self.impl != "auto":
            return self
        if platform is None:
            platform = jax.devices()[0].platform
        return replace(self, impl="ragged" if platform == "tpu" else "dense")

    def validate(self) -> None:
        if self.impl not in ("ragged", "dense"):
            raise ValueError(f"unknown impl {self.impl!r}")
        if np.dtype(self.dtype).itemsize != 4:
            raise ValueError("value dtype must be 32-bit (keys bitcast through it)")
        for a in self.aggs:
            if a not in VALID_AGGS:
                raise ValueError(f"unknown aggregation {a!r} (valid: {VALID_AGGS})")
        if self.partial and "count_distinct" in self.aggs:
            raise ValueError(
                "count_distinct cannot use partial aggregation (per-shard "
                "distinct counts do not compose by sum); use partial=False"
            )
        if self.quantize_mode != "off":
            self.qspec.validate()
            if not self.partial:
                raise ValueError(
                    "quantization rides the partial-aggregate exchange; "
                    "set partial=True (raw-row exchanges are never quantized)"
                )
            if not np.issubdtype(np.dtype(self.dtype), np.floating):
                raise ValueError(
                    "quantization needs a floating value dtype — integer "
                    "aggregates are exact by contract and stay unquantized"
                )
        if self.combine not in ("off", "auto", "dense", "sorted"):
            raise ValueError(
                f"unknown combine tier {self.combine!r} (off|auto|dense|sorted)"
            )
        if self.combine != "off":
            if not self.partial:
                raise ValueError(
                    "the fused combine folds PARTIAL aggregate rows across "
                    "the exchange; set partial=True (count_distinct can "
                    "therefore never use it)"
                )
            if self.combine == "dense" and self.combine_groups <= 0:
                raise ValueError(
                    "combine='dense' needs combine_groups > 0 (the dense key "
                    "domain; keys must lie in [0, combine_groups))"
                )


def _agg_identity(agg: str, dtype) -> jnp.ndarray:
    if agg in ("sum", "avg", "count_distinct"):
        return jnp.zeros((), dtype)
    info = jnp.finfo(dtype) if jnp.issubdtype(dtype, jnp.floating) else jnp.iinfo(dtype)
    return jnp.array(info.max if agg == "min" else info.min, dtype)


def _segment_reduce(
    aggs: Tuple[str, ...],
    out_cap: int,
    keys,
    vals,
    valid,
    counts=None,
    tight: bool = True,
):
    """Stable key-sort + segment-reduce — the GROUP BY kernel shared by the
    post-exchange final phase and the map-side partial phase.

    ``counts`` carries pre-aggregated row counts when the inputs are partial
    rows (group count = sum of partial counts); None counts raw rows.
    ``tight=True`` asserts valid rows form a prefix (post-exchange compaction
    guarantees it; so does an unmasked local shard) and sorts once; with a
    scattered validity pattern (WHERE-pushdown masks) an extra stable pass on
    the validity flag keeps valid sentinel-keyed rows ahead of invalid ones
    inside the KEY_MAX tie.  Returns (group_keys, group_vals, group_count,
    num_groups); groups are numbered in ascending key order.
    """
    pk = padded_keys(keys, valid)
    order = jnp.argsort(pk, stable=True)
    if not tight:
        order = order[jnp.argsort(jnp.logical_not(valid)[order], stable=True)]
    skeys = keys[order]
    svals = vals[order]
    svalid = valid[order]
    scounts = counts[order] if counts is not None else svalid.astype(jnp.int32)
    prev_differs = jnp.concatenate([jnp.ones(1, bool), skeys[1:] != skeys[:-1]])
    is_start = prev_differs & svalid
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    # Padding rows scatter out of range and are dropped.
    seg = jnp.where(svalid, seg, out_cap)
    num_groups = is_start.sum().astype(jnp.int32)

    group_keys = jnp.zeros(out_cap, jnp.uint32).at[seg].set(skeys, mode="drop")
    group_count = (
        jnp.zeros(out_cap, jnp.int32)
        .at[seg]
        .add(jnp.where(svalid, scounts, 0), mode="drop")
    )
    cols = []
    for c, agg in enumerate(aggs):
        if agg == "count_distinct":
            cols.append(
                _distinct_count_col(out_cap, pk, vals[:, c], valid).astype(svals.dtype)
            )
            continue
        ident = _agg_identity(agg, svals.dtype)
        col = jnp.where(svalid, svals[:, c], ident)
        acc = jnp.full(out_cap, ident)
        if agg in ("sum", "avg"):
            acc = acc.at[seg].add(col, mode="drop")
        elif agg == "min":
            acc = acc.at[seg].min(col, mode="drop")
        else:
            acc = acc.at[seg].max(col, mode="drop")
        cols.append(acc)
    group_vals = (
        jnp.stack(cols, axis=1) if cols else jnp.zeros((out_cap, 0), svals.dtype)
    )
    return group_keys, group_vals, group_count, num_groups


def _distinct_count_col(out_cap: int, pk, col, valid):
    """COUNT(DISTINCT col) per group: lexsort rows by (validity, key, value)
    — three stable argsorts, innermost first — so each group's values are
    contiguous AND sorted, then count (key, value) pair starts per segment.
    Group numbering (ascending distinct valid keys) matches
    :func:`_segment_reduce`'s, so the scattered counts align with its groups.
    """
    order = jnp.argsort(col, stable=True)
    order = order[jnp.argsort(pk[order], stable=True)]
    order = order[jnp.argsort(jnp.logical_not(valid)[order], stable=True)]
    sk = pk[order]
    sv = col[order]
    svalid = valid[order]
    key_start = jnp.concatenate([jnp.ones(1, bool), sk[1:] != sk[:-1]])
    is_start = key_start & svalid
    seg = jnp.cumsum(is_start.astype(jnp.int32)) - 1
    seg = jnp.where(svalid, seg, out_cap)
    pair_start = key_start | jnp.concatenate([jnp.ones(1, bool), sv[1:] != sv[:-1]])
    return (
        jnp.zeros(out_cap, jnp.int32)
        .at[seg]
        .add((pair_start & svalid).astype(jnp.int32), mode="drop")
    )


def _partial_rows(spec: AggregateSpec, qspec, cap, idx, keys, values, valid, tight):
    """Map-side partial aggregation (HashAggregateExec(partial) below the
    Exchange): reduce locally first, then exchange one row per local distinct
    key carrying (key | agg columns | count).  The count lane travels BITCAST
    through the value dtype, so it is exact for any 32-bit dtype (a float32
    cast would silently round counts > 2^24).  Shared by the unfused body and
    the fused-combine body so the two wire formats can never drift — the
    fused tiers' bit-equality against the unfused path rests on it."""
    lk, lv, lc, lng = _segment_reduce(spec.aggs, cap, keys, values, valid, tight=tight)
    if qspec is not None:
        # tier-b lossy opt-in: quantize the partial value columns on the
        # send side; the packed int32 payload bitcasts through the float
        # dtype lane (bit-preserving — the exchange only moves rows)
        lv = jax.lax.bitcast_convert_type(quantize_rows(qspec, lv), spec.dtype)
    packed = jnp.concatenate(
        [lv, jax.lax.bitcast_convert_type(lc, spec.dtype)[:, None]], axis=1
    )
    return lk, packed, idx < lng


def _aggregate_body(spec: AggregateSpec, keys, values, num_valid, mask=None, dq_acc=None):
    cap = spec.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    valid = idx < num_valid[0]
    if mask is not None:
        # WHERE pushdown: filtered rows are simply never-sent (owner n), so
        # invalidity may be scattered — everything downstream sees only the
        # compacted received prefix and is agnostic to the input pattern
        valid &= mask

    counts = None
    qspec = spec.qspec if (spec.partial and spec.quantize_mode != "off") else None
    if spec.partial:
        keys, values, valid = _partial_rows(
            spec, qspec, cap, idx, keys, values, valid, tight=(mask is None)
        )

    payload_width = (
        qspec.quantized_width(spec.width) if qspec is not None else spec.width
    )
    cspec = ColumnarSpec(
        num_executors=spec.num_executors,
        capacity=cap,
        recv_capacity=spec.recv_capacity,
        width=payload_width + (2 if spec.partial else 1),
        dtype=spec.dtype,
        axis_name=spec.axis_name,
        impl=spec.impl,
    )
    rkeys, rvals, rvalid, rtotal = exchange_keyed_rows(cspec, keys, values, valid)
    if spec.partial:
        counts = jax.lax.bitcast_convert_type(rvals[:, -1], jnp.int32)
        rvals = rvals[:, :-1]
        if qspec is not None:
            # receive side: dequantize before the final merge (zero-filled
            # buffer tails dequantize to zero rows; rvalid masks them anyway)
            rvals = dequantize_rows(
                qspec, jax.lax.bitcast_convert_type(rvals, jnp.int32), spec.width
            ).astype(spec.dtype)

    # Final GROUP BY on the received (raw or partial) rows: sum/min/max/avg
    # compose with themselves, counts compose by sum.
    group_keys, group_vals, group_count, num_groups = _segment_reduce(
        spec.aggs, spec.recv_capacity, rkeys, rvals, rvalid, counts=counts
    )
    out = (group_keys, group_vals, group_count, num_groups[None], rtotal[None])
    if dq_acc is not None:
        # donated dequantize accumulator: the extra output matches the
        # donated input's (recv_capacity, width) float geometry, so XLA
        # aliases the buffers and the dequantized merge input stops
        # double-buffering next to the received packed rows — the caller
        # threads the returned array back in on the next call
        return out + (rvals,)
    return out


def _sorted_combine_walk(spec: AggregateSpec, sched, slot_rows, flat, me):
    """High-cardinality fallback tier (``combine='sorted'``): walk the ring
    schedule and merge every landed window into a BOUNDED sorted accumulator
    of ``recv_capacity`` groups via :func:`_segment_reduce` — a per-superstep
    partial sort/merge.  Post-exchange memory is O(recv_capacity) instead of
    the full landed grid, and integer folds stay bit-identical to the unfused
    path (segment sums associate).  Overflow detection is unchanged: distinct
    keys on a shard never exceed its received partial rows, so the driver's
    ``recv_totals`` check still triggers the doubling retry first.

    Scheduled permutes only (``lowering='xla'``) — the bounded merge has no
    kernel epilogue form; the dense tier is the Pallas-fused one."""
    ax = spec.axis_name
    n = spec.num_executors
    qspec = spec.qspec if spec.quantize_mode != "off" else None
    out_cap = spec.recv_capacity
    lane = flat.shape[1]
    idx = jnp.arange(out_cap, dtype=jnp.int32)

    def fold(window, state):
        ak, av, ac, ang = state
        wkeys = jax.lax.bitcast_convert_type(window[:, 0], jnp.uint32)
        wc = jax.lax.bitcast_convert_type(window[:, -1:], jnp.int32)[:, 0]
        wp = window[:, 1:-1]
        if qspec is not None:
            wp = dequantize_rows(
                qspec, jax.lax.bitcast_convert_type(wp, jnp.int32), spec.width
            ).astype(spec.dtype)
        # accumulator rows are partial rows themselves (counts compose by
        # sum), so one segment reduce over [acc | window] IS the merge
        mk = jnp.concatenate([ak, wkeys])
        mv = jnp.concatenate([av, wp], axis=0)
        mc = jnp.concatenate([ac, wc])
        mvalid = jnp.concatenate([idx < ang, wc > 0])
        return _segment_reduce(spec.aggs, out_cap, mk, mv, mvalid, counts=mc, tight=False)

    state = (
        jnp.zeros(out_cap, jnp.uint32),
        jnp.zeros((out_cap, spec.width), spec.dtype),
        jnp.zeros(out_cap, jnp.int32),
        jnp.zeros((), jnp.int32),
    )
    # canonical fold order (ops/combine.py): own slot first, then schedule
    # items in step order
    own = jax.lax.dynamic_slice(flat, (me * slot_rows, 0), (slot_rows, lane))
    state = fold(own, state)
    w = slot_rows // sched.chunks
    for step in sched.steps:
        for item in step:
            d = item.offset
            send_row = ((me + d) % n) * slot_rows + item.chunk * w
            window = jax.lax.dynamic_slice(flat, (send_row, 0), (w, lane))
            got = jax.lax.ppermute(window, ax, [(i, (i + d) % n) for i in range(n)])
            state = fold(got, state)
    return state


def _fused_aggregate_body(
    spec: AggregateSpec, sched, lowering, keys, values, num_valid, mask=None
):
    """The COMPUTE-IN-EXCHANGE shard body (``spec.combine != 'off'``): local
    partial reduce, place the partial rows into per-destination slots of the
    sender-major ring grid, then fold every window into the accumulator AS IT
    LANDS (ops/ici_exchange.combine_axis_grid — one Pallas launch under the
    DMA lowering) instead of staging O(rows) received rows.  The dense tier
    compacts the (combine_groups,) accumulator through the same
    :func:`_segment_reduce` the unfused final phase uses — single-element
    segments are identity folds, so the output contract (ascending keys,
    counts, num_groups, recv_totals) is preserved bit-for-bit."""
    from sparkucx_tpu.ops.ici_exchange import combine_axis_grid

    cap = spec.capacity
    n = spec.num_executors
    ax = spec.axis_name
    idx = jnp.arange(cap, dtype=jnp.int32)
    valid = idx < num_valid[0]
    if mask is not None:
        valid &= mask
    qspec = spec.qspec if spec.quantize_mode != "off" else None
    keys, values, valid = _partial_rows(
        spec, qspec, cap, idx, keys, values, valid, tight=(mask is None)
    )

    # slot placement: owner-sorted rows land at (owner * cap + rank-within-
    # owner) — each destination's region is a tight valid prefix, the
    # all-zero tail is the count==0 padding the combine fold skips
    rows = jnp.concatenate(
        [jax.lax.bitcast_convert_type(keys.astype(jnp.uint32), spec.dtype)[:, None], values],
        axis=1,
    )
    owners = hash_owners(keys, n, valid)
    sizes = jnp.bincount(owners, length=n + 1)[:n].astype(jnp.int32)
    order = jnp.argsort(owners, stable=True)
    sowners = owners[order]
    start = exclusive_cumsum(sizes)
    pos = idx - start[jnp.clip(sowners, 0, n - 1)]
    dest = jnp.where(sowners < n, sowners * cap + pos, n * cap)
    slot = (
        jnp.zeros((n * cap, rows.shape[1]), spec.dtype)
        .at[dest]
        .set(rows[order], mode="drop")
    )

    me = jax.lax.axis_index(ax)
    # recv_totals keeps the unfused contract (TRUE partial rows hashed to
    # each shard) so the driver's overflow/retry behavior is identical
    sizes_mat = jax.lax.all_gather(sizes, ax)
    rtotal = jnp.sum(sizes_mat[:, me]).astype(jnp.int32)

    if spec.combine == "dense":
        accv, accc = combine_axis_grid(
            ax, n, cap, sched, slot, me, spec.combine_cspec, lowering
        )
        # compaction: one segment reduce over the dense domain — every group
        # is its own single-row segment (identity fold, exact for floats too)
        gk, gv, gc, ng = _segment_reduce(
            spec.aggs,
            spec.recv_capacity,
            jnp.arange(spec.combine_groups, dtype=jnp.uint32),
            accv,
            accc[:, 0] > 0,
            counts=accc[:, 0],
            tight=False,
        )
    else:
        gk, gv, gc, ng = _sorted_combine_walk(spec, sched, cap, slot, me)
    return gk, gv, gc, ng[None], rtotal[None]


def build_grouped_aggregate(mesh: Mesh, spec: AggregateSpec):
    """Compile the distributed GROUP BY for ``mesh``.

    Returns jitted ``fn(keys, values, num_valid) ->
    (group_keys, group_values, group_counts, num_groups, recv_totals)`` —
    with ``spec.with_filter`` the signature gains a trailing per-row bool
    ``mask`` (n * capacity,): False rows are dropped before the exchange
    (WHERE pushdown; they count in neither recv_totals nor any group):

    * ``keys``: (n * capacity,) uint32, sharded over ``axis_name``;
    * ``values``: (n * capacity, len(aggs)) of ``dtype``, row-sharded;
    * ``num_valid``: (n,) int32 sharded — valid rows per shard;
    * ``group_keys``: (n * recv_capacity,) uint32 — shard j's first
      ``num_groups[j]`` entries are its distinct keys (each key appears on
      exactly one shard, ascending within the shard);
    * ``group_values``: aggregated value per group/column (aligned rows).
      'avg' columns carry their SUM on device (the fused sum+count pair —
      counts are always produced); the host driver divides exactly;
      'count_distinct' columns carry the per-group distinct value count;
    * ``group_counts``: rows aggregated into each group (COUNT);
    * ``num_groups``: (n,) int32;
    * ``recv_totals``: (n,) int32 — TRUE rows hashed to each shard (with
      ``spec.partial``, PARTIAL rows: at most one per (sender, key) — the
      wire-traffic reduction is visible right here).  Any value
      > ``recv_capacity`` means that shard's exchange truncated and its groups
      are incomplete: re-run with headroom, like SortSpec.recv_capacity.

    With ``spec.combine != 'off'`` (and more than one executor) the exchange
    runs the COMPUTE-IN-EXCHANGE route (:func:`_fused_aggregate_body`):
    identical signature, identical outputs — bit-identical for exact dtypes,
    within ``QuantizeSpec.error_bound`` per partial row when quantized.
    """
    if spec.num_executors != mesh.devices.size:
        raise ValueError(f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}")
    platform = mesh.devices.reshape(-1)[0].platform
    spec = spec.resolve_impl(platform=platform)
    if spec.combine == "auto":
        spec = spec.resolve_combine()
    spec.validate()
    ax = spec.axis_name

    if spec.combine != "off" and spec.num_executors > 1:
        # compute-in-exchange route: the shard body IS the scheduled ring
        # (same FAST schedule the ICI exchange builds), folding windows into
        # the accumulator as they land instead of staging received rows
        from sparkucx_tpu.ops.hierarchy import device_slice_ids
        from sparkucx_tpu.ops.ici_exchange import (
            DEFAULT_CHUNKS_PER_DEST,
            resolve_ici_lowering,
            resolve_schedule_lowering,
            ring_schedule,
            schedule_chunks,
        )

        ids = device_slice_ids(mesh.devices.reshape(-1))
        kind = "ici" if ids is None or len(set(ids)) == 1 else "dcn"
        sched = ring_schedule(
            spec.num_executors,
            schedule_chunks(spec.capacity, DEFAULT_CHUNKS_PER_DEST),
            kind=kind,
        )
        if spec.combine == "sorted":
            low = "xla"  # the bounded merge rides scheduled permutes only
        else:
            low = resolve_schedule_lowering(
                resolve_ici_lowering(spec.combine_lowering, platform), kind
            )
        body = functools.partial(_fused_aggregate_body, spec, sched, low)
        reuse_dq = False
    else:
        body = functools.partial(_aggregate_body, spec)
        # the unfused quantized fallback reuses ONE donated dequantize
        # accumulator across calls instead of double-buffering the merge
        # input next to the packed received rows
        reuse_dq = spec.partial and spec.quantize_mode != "off"

    def _body(*args):
        args = list(args)
        dq = args.pop() if reuse_dq else None
        m = args.pop() if spec.with_filter else None
        if reuse_dq:
            return body(args[0], args[1], args[2], mask=m, dq_acc=dq)
        return body(args[0], args[1], args[2], mask=m)

    mask_in = (P(ax),) if spec.with_filter else ()
    dq_in = (P(ax, None),) if reuse_dq else ()
    shard = shard_map(
        _body,
        mesh=mesh,
        in_specs=(P(ax), P(ax, None), P(ax)) + mask_in + dq_in,
        out_specs=(P(ax), P(ax, None), P(ax), P(ax), P(ax))
        + ((P(ax, None),) if reuse_dq else ()),
        check_vma=False,
    )
    key_sh = NamedSharding(mesh, P(ax))
    row_sh = NamedSharding(mesh, P(ax, None))
    mask_sh = (key_sh,) if spec.with_filter else ()
    if not reuse_dq:
        fn = jax.jit(
            shard,
            in_shardings=(key_sh, row_sh, key_sh) + mask_sh,
            out_shardings=(key_sh, row_sh, key_sh, key_sh, key_sh),
        )
        fn.spec = spec
        return fn

    inner = jax.jit(
        shard,
        in_shardings=(key_sh, row_sh, key_sh) + mask_sh + (row_sh,),
        out_shardings=(key_sh, row_sh, key_sh, key_sh, key_sh, row_sh),
        donate_argnums=(3 + len(mask_sh),),
    )
    state = {"dq": None}

    def fn(*args):
        if state["dq"] is None:
            state["dq"] = jax.device_put(
                np.zeros(
                    (spec.num_executors * spec.recv_capacity, spec.width), spec.dtype
                ),
                row_sh,
            )
        *outs, dq = inner(*args, state["dq"])
        state["dq"] = dq
        return tuple(outs)

    fn.spec = spec
    return fn


def expand_matches(
    out_capacity: int,
    sbk: jnp.ndarray,
    btotal: jnp.ndarray,
    probe_keys: jnp.ndarray,
    probe_valid: jnp.ndarray,
    probe_cap: int,
    build_cap: int,
    join_type: str = "inner",
):
    """Sort-merge match expansion shared by the hash join and the transitive
    closure: given the build side's sorted (padded) keys ``sbk`` with
    ``btotal`` valid rows and the probe keys, emit per output row p its probe
    index ``j[p]`` and build index ``li[p]``.

    Returns (j, li, ok, unmatched, total): ``ok`` masks rows past the true
    emission count; ``unmatched`` marks left-outer null-extension rows (always
    all-False for inner); ``total`` is wrap-guarded — int32 cumsum wraps at
    ~2.1e9 matches, so a float32 shadow sum (exact enough for detection)
    saturates the reported total at int32 max so a caller's ``total >
    out_capacity`` overflow check cannot pass silently.

    Per-probe-row emission by ``join_type`` (m = its build-match count):
    'inner' m rows; 'left_outer' max(m, 1) — the extra row is null-extended
    (its ``li`` is meaningless, ``unmatched`` True, caller substitutes nulls
    for build lanes); 'left_semi' min(m, 1) — EXISTS (``li`` points at the
    first match in SORTED build order; SQL semi emits probe columns only, so
    callers should not read build lanes through it); 'left_anti' 1 if m == 0
    else 0 — NOT EXISTS, ``li`` meaningless and ``unmatched`` True on every
    emitted row."""
    lo = jnp.searchsorted(sbk, probe_keys, side="left").astype(jnp.int32)
    hi = jnp.minimum(jnp.searchsorted(sbk, probe_keys, side="right").astype(jnp.int32), btotal)
    matched = jnp.where(probe_valid, jnp.maximum(hi - lo, 0), 0)
    cnt = jnp.where(probe_valid, _join_emit(join_type)(matched, jnp), 0)
    offs = exclusive_cumsum(cnt)
    cum = jnp.cumsum(cnt)
    total = jnp.where(
        jnp.sum(cnt.astype(jnp.float32)) > jnp.float32(2**31 - 1),
        jnp.int32(np.iinfo(np.int32).max),
        cum[-1].astype(jnp.int32),
    )
    pos = jnp.arange(out_capacity, dtype=jnp.int32)
    j = jnp.clip(
        jnp.searchsorted(cum, pos, side="right").astype(jnp.int32), 0, probe_cap - 1
    )
    li = jnp.clip(lo[j] + (pos - offs[j]), 0, build_cap - 1)
    ok = pos < total
    # semantically all-False for inner/semi (their emitted rows always have a
    # match) — computed uniformly, the caller's null-substitution masks on it
    unmatched = ok & (matched[j] == 0)
    return j, li, ok, unmatched, total


# ----------------------------------------------------------------------------
# Hash join (inner equi-join)
# ----------------------------------------------------------------------------


@dataclass(frozen=True)
class JoinSpec:
    """Static description of one compiled equi-join.

    ``build_*`` is the hash-table (dimension) side, ``probe_*`` the streamed
    (fact) side.  In SQL terms the probe side is the LEFT operand:
    ``SELECT ... FROM probe [LEFT OUTER] JOIN build ON key``.  ``join_type``:

    * ``'inner'`` — m matches emit m rows;
    * ``'left_outer'`` — every valid probe row is preserved; a matchless one
      emits one null-extended output (zeroed build lanes, flagged False in
      the extra ``out_matched`` output).  TPC-H q13 (customer LEFT OUTER JOIN
      orders) puts customer on the probe side;
    * ``'left_semi'`` — EXISTS: each probe row with >= 1 match emits exactly
      one row, build lanes zeroed — SQL semi joins emit probe columns only
      (q4/q21's correlated EXISTS);
    * ``'left_anti'`` — NOT EXISTS: each matchless probe row emits one row,
      build lanes zeroed (q22's NOT EXISTS);
    * ``'right_outer'`` — every valid build row is preserved: inner expansion
      plus one row per matchless build row (zeroed probe lanes, flagged False
      in ``out_matched``);
    * ``'full_outer'`` — both sides preserved: left_outer expansion plus the
      matchless build rows (TPC-DS q97's store/catalog FULL OUTER JOIN).

    ``out_capacity``: per-executor output rows — bound the many-to-many
    expansion (for PK-FK joins like TPC-H's, probe_recv_capacity is enough)."""

    num_executors: int
    build_capacity: int
    build_recv_capacity: int
    build_width: int
    probe_capacity: int
    probe_recv_capacity: int
    probe_width: int
    out_capacity: int
    dtype: np.dtype = np.dtype(np.int32)
    axis_name: str = "ex"
    impl: str = "auto"
    #: True compiles the WHERE-pushdown variant: the jitted fn takes two extra
    #: per-row bool inputs (build_mask, probe_mask) and filtered rows never
    #: enter either exchange — the filtered-join shape of TPC-H q3/q5.
    with_filters: bool = False
    join_type: str = "inner"

    def resolve_impl(self, platform: Optional[str] = None) -> "JoinSpec":
        if self.impl != "auto":
            return self
        if platform is None:
            platform = jax.devices()[0].platform
        return replace(self, impl="ragged" if platform == "tpu" else "dense")

    def validate(self) -> None:
        if self.impl not in ("ragged", "dense"):
            raise ValueError(f"unknown impl {self.impl!r}")
        if np.dtype(self.dtype).itemsize != 4:
            raise ValueError("value dtype must be 32-bit (keys bitcast through it)")
        if self.join_type not in JOIN_TYPES:
            raise ValueError(
                f"unknown join_type {self.join_type!r} (valid: {JOIN_TYPES})"
            )


def _join_body(spec: JoinSpec, bkeys, bvals, bnum, pkeys, pvals, pnum,
               bmask=None, pmask=None):
    n = spec.num_executors

    def cspec(cap, recv_cap, width):
        return ColumnarSpec(
            num_executors=n,
            capacity=cap,
            recv_capacity=recv_cap,
            width=width + 1,
            dtype=spec.dtype,
            axis_name=spec.axis_name,
            impl=spec.impl,
        )

    bvalid = jnp.arange(spec.build_capacity, dtype=jnp.int32) < bnum[0]
    pvalid = jnp.arange(spec.probe_capacity, dtype=jnp.int32) < pnum[0]
    if bmask is not None:  # WHERE pushdown (see AggregateSpec.with_filter)
        bvalid &= bmask
        pvalid &= pmask

    # Hash-partition both sides: equal keys co-locate.
    rbk, rbv, rbvalid, rbtotal = exchange_keyed_rows(
        cspec(spec.build_capacity, spec.build_recv_capacity, spec.build_width),
        bkeys, bvals, bvalid,
    )
    rpk, rpv, rpvalid, rptotal = exchange_keyed_rows(
        cspec(spec.probe_capacity, spec.probe_recv_capacity, spec.probe_width),
        pkeys, pvals, pvalid,
    )

    # Sort the build side; padding rows (forced KEY_MAX, stable) occupy exactly
    # the tail [btotal, cap), even when valid rows carry the sentinel key.
    btotal = rbvalid.sum().astype(jnp.int32)
    border = jnp.argsort(padded_keys(rbk, rbvalid), stable=True)
    sbk = padded_keys(rbk, rbvalid)[border]
    sbv = rbv[border]

    # Match range per probe row (hi clamped at btotal so a KEY_MAX probe key
    # never matches build padding), expanded into the static output.  Right
    # and full outer run their probe-driven BASE expansion here; the build
    # side's unmatched rows are appended after it.
    base_type = _OUTER_BASE.get(spec.join_type, spec.join_type)
    j, li, ok, unmatched, total = expand_matches(
        spec.out_capacity, sbk, btotal, rpk, rpvalid,
        spec.probe_recv_capacity, spec.build_recv_capacity,
        join_type=base_type,
    )
    zero = jnp.zeros((), spec.dtype)
    out_keys = jnp.where(ok, rpk[j], jnp.uint32(0))
    if spec.join_type in ("left_semi", "left_anti"):
        # SQL semi/anti joins emit probe columns only — and "the" build match
        # is ambiguous for semi (sorted-build order != host input order)
        out_build = jnp.zeros((spec.out_capacity, spec.build_width), spec.dtype)
    else:
        out_build = jnp.where((ok & ~unmatched)[:, None], sbv[li], zero)
    out_probe = jnp.where(ok[:, None], rpv[j], zero)
    out_matched = ok & ~unmatched
    if spec.join_type in _OUTER_BASE:
        # Build-side match-flag pass: sort the probe keys, binary-search each
        # valid build row, and append the matchless build rows (zeroed probe
        # lanes, matched=False) compacted after the base expansion.  Equal
        # keys are indistinguishable, so clamping the right bound at ptotal
        # handles valid-KEY_MAX vs padding exactly as expand_matches does.
        ptotal = rpvalid.sum().astype(jnp.int32)
        spk = jnp.sort(padded_keys(rpk, rpvalid))
        lob = jnp.searchsorted(spk, sbk, side="left").astype(jnp.int32)
        hib = jnp.minimum(
            jnp.searchsorted(spk, sbk, side="right").astype(jnp.int32), ptotal
        )
        bvalid_sorted = (
            jnp.arange(spec.build_recv_capacity, dtype=jnp.int32) < btotal
        )
        build_unmatched = bvalid_sorted & (jnp.maximum(hib - lob, 0) == 0)
        dest = jnp.where(
            build_unmatched,
            total + exclusive_cumsum(build_unmatched.astype(jnp.int32)),
            spec.out_capacity,  # matched/padding rows scatter out of range
        )
        out_keys = out_keys.at[dest].set(sbk, mode="drop")
        out_build = out_build.at[dest].set(sbv, mode="drop")
        # out_probe and out_matched stay zeros/False on the appended rows.
        ub = build_unmatched.sum().astype(jnp.int32)
        imax = jnp.int32(np.iinfo(np.int32).max)
        total = jnp.where(total > imax - ub, imax, total + ub)  # keep saturation
    outs = (out_keys, out_build, out_probe, total[None], jnp.stack([rbtotal, rptotal])[None, :])
    if spec.join_type in OUTER_JOIN_TYPES:
        outs += (out_matched,)  # out_matched: False = null-extended row
    return outs


def build_hash_join(mesh: Mesh, spec: JoinSpec):
    """Compile the distributed equi-join (``spec.join_type``) for ``mesh``.

    Returns jitted ``fn(build_keys, build_values, build_num, probe_keys,
    probe_values, probe_num) ->
    (out_keys, out_build, out_probe, out_counts, recv_totals)`` — with
    ``spec.with_filters`` the signature gains trailing per-row bool
    ``(build_mask, probe_mask)``: False rows never enter either exchange
    (the filtered-join WHERE pushdown); with an outer ``spec.join_type``
    (left_outer / right_outer / full_outer) the outputs gain a sixth
    ``out_matched`` (n * out_capacity,) bool — False marks a null-extended
    row (zeroed build lanes for an unmatched probe row; zeroed probe lanes
    for an unmatched build row of a right/full outer join):

    * inputs are sharded like build_grouped_aggregate's (keys uint32, values
      (rows, width) of ``dtype``, num (n,) int32);
    * ``out_keys``: (n * out_capacity,) uint32 — join key per output row;
    * ``out_build`` / ``out_probe``: matched value rows, aligned;
    * ``out_counts``: (n,) int32 — emitted rows on each shard.  A count >
      ``out_capacity`` means the emitted prefix was truncated: re-run with a
      larger ``out_capacity`` (same overflow contract as SortSpec);
    * ``recv_totals``: (n, 2) int32 — TRUE (build, probe) rows hashed to each
      shard; a value above the side's recv_capacity means that exchange
      truncated and matches were lost.
    """
    if spec.num_executors != mesh.devices.size:
        raise ValueError(f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}")
    spec = spec.resolve_impl(platform=mesh.devices.reshape(-1)[0].platform)
    spec.validate()
    ax = spec.axis_name

    extra_in = (P(ax), P(ax)) if spec.with_filters else ()
    extra_out = (P(ax),) if spec.join_type in OUTER_JOIN_TYPES else ()
    shard = shard_map(
        functools.partial(_join_body, spec),
        mesh=mesh,
        in_specs=(P(ax), P(ax, None), P(ax)) * 2 + extra_in,
        out_specs=(P(ax), P(ax, None), P(ax, None), P(ax), P(ax, None)) + extra_out,
        check_vma=False,
    )
    key_sh = NamedSharding(mesh, P(ax))
    row_sh = NamedSharding(mesh, P(ax, None))
    fn = jax.jit(
        shard,
        in_shardings=(key_sh, row_sh, key_sh) * 2
        + ((key_sh, key_sh) if spec.with_filters else ()),
        out_shardings=(key_sh, row_sh, row_sh, key_sh, row_sh)
        + ((key_sh,) if spec.join_type in OUTER_JOIN_TYPES else ()),
    )
    fn.spec = spec
    return fn


def run_grouped_aggregate(
    mesh: Mesh,
    spec: AggregateSpec,
    keys: np.ndarray,
    values: np.ndarray,
    max_attempts: int = 3,
    mask: Optional[np.ndarray] = None,
):
    """Host driver: shard, run the compiled GROUP BY, retry with doubled
    ``recv_capacity`` when hash skew overflows a shard — the GroupByTest job
    surface (run_distributed_sort's contract for aggregation).

    ``keys``: (T,) uint32; ``values``: (T, len(aggs)).  With a
    ``spec.with_filter`` spec, ``mask`` (T,) bool is required: False rows are
    dropped on device before the exchange.  Returns (group keys ascending,
    aggregated columns, counts) as host arrays.  When any column is 'avg' the
    value array comes back float64 with avg columns divided exactly by the
    group counts (the device computes the fused sum; counts ride along free).
    """
    n = spec.num_executors
    total = keys.shape[0]
    cap = spec.capacity
    if total > n * cap:
        raise ValueError(f"{total} rows exceed {n} x {cap} capacity")
    if mesh.devices.size != n:
        raise ValueError(f"mesh size {mesh.devices.size} != num_executors {n}")
    if spec.with_filter != (mask is not None):
        raise ValueError(
            "spec.with_filter=True needs a mask argument (and a mask needs "
            "with_filter=True): the compiled signatures differ"
        )

    if spec.combine == "auto":
        # host-side dense-domain detection: the dense fused combine needs
        # every key inside [0, G); measure G from the ACTUAL keys (pow2-
        # bucketed — a compile-cache key dimension) and let resolve_combine
        # keep it only when the accumulator undercuts the exchanged slot
        # grid, else take the bounded sorted fallback
        if keys.size:
            g = 1 << int(np.max(keys)).bit_length()  # pow2 ceil of max+1
            spec = replace(spec, combine_groups=int(g)).resolve_combine()
        else:
            spec = replace(spec, combine="sorted")

    pk, pv, nv = shard_rows_host(keys, values, n, cap, value_dtype=spec.dtype)

    key_sh = NamedSharding(mesh, P(spec.axis_name))
    row_sh = NamedSharding(mesh, P(spec.axis_name, None))
    gk = jax.device_put(pk, key_sh)
    gv = jax.device_put(pv, row_sh)
    gn = jax.device_put(nv, key_sh)
    extra = ()
    if mask is not None:
        # the mask rides the same contiguous deal as its rows; padding = False
        pm, _, _ = shard_rows_host(
            mask.astype(np.uint32), np.zeros((total, 0), np.int32), n, cap
        )
        extra = (jax.device_put(pm.astype(bool), key_sh),)

    attempt_spec = spec
    for _ in range(max_attempts):
        fn = build_grouped_aggregate(mesh, attempt_spec)
        out_k, out_v, out_c, num_groups, recv_totals = fn(gk, gv, gn, *extra)
        if (np.asarray(recv_totals) <= attempt_spec.recv_capacity).all():
            keys_h, vals_h, cnts_h = unpack_shard_prefixes(
                (out_k, out_v, out_c), np.asarray(num_groups),
                attempt_spec.recv_capacity,
            )
            order = np.argsort(keys_h)
            keys_h, vals_h, cnts_h = keys_h[order], vals_h[order], cnts_h[order]
            if "avg" in spec.aggs:
                vals_h = vals_h.astype(np.float64)
                for c, agg in enumerate(spec.aggs):
                    if agg == "avg":
                        vals_h[:, c] /= np.maximum(cnts_h, 1)
            return keys_h, vals_h, cnts_h
        attempt_spec = replace(
            attempt_spec, recv_capacity=2 * attempt_spec.recv_capacity
        )
    raise RuntimeError(
        f"aggregation overflowed recv_capacity {attempt_spec.recv_capacity // 2} "
        f"after {max_attempts} doublings — hash(key) distribution too skewed"
    )


def run_plan_grouped_aggregate(
    mesh: Mesh,
    spec: AggregateSpec,
    plan,
    keys: np.ndarray,
    values: np.ndarray,
    mask: Optional[np.ndarray] = None,
    stats=None,
):
    """Drive one partial grouped aggregation through an ``ExchangePlan`` with
    the UNIFIED EXECUTOR — the compute-in-exchange route composed with quota
    sub-rounds (``plan.chunks_per_round``), exactly the engine the transports
    run raw shuffles through:

    * stage A (once): one jitted shard body does the map-side partial reduce
      and seals the partial rows into the staging slot layout
      (``slot = capacity`` rows per destination, count==0 padding);
    * stage B (per sub-round, via ``transport.executor.execute_plan``): slice
      the quota window out of the sealed payload ON DEVICE
      (``skew.slice_subround``), run the fused-combine exchange
      ``transport.executor.build_plan_exchange`` lowered for the plan
      (``plan.combine == 'dense'`` routes to ``build_combine_exchange``), and
      merge each sub-round's identity-seeded accumulator into the running one
      in ``finish_round`` (``ops/combine.merge_accumulators``, running
      accumulator first — deterministic float order).  The drain ships the
      O(groups) accumulator, never the landed rows;
    * stage C (once): dense compaction through the same
      :func:`_segment_reduce` the single-shot fused body uses.

    Integer results are bit-identical to :func:`run_grouped_aggregate` with
    any quota (segment sums associate).  Only the dense tier composes with
    sub-round chunking (a bounded sorted accumulator cannot merge across
    sub-rounds without a second full sort); plans with ``combine != 'dense'``
    fall back to :func:`run_grouped_aggregate`.
    """
    from sparkucx_tpu.ops.combine import acc_init, merge_accumulators
    from sparkucx_tpu.ops.skew import chunk_size_rows, slice_subround
    from sparkucx_tpu.transport.executor import build_plan_exchange, execute_plan

    if plan.combine != "dense":
        return run_grouped_aggregate(mesh, spec, keys, values, mask=mask)
    if spec.combine == "auto":
        spec = spec.resolve_combine()
    spec = spec.resolve_impl(platform=mesh.devices.reshape(-1)[0].platform)
    spec = replace(spec, combine="dense")
    spec.validate()
    if len(plan.chunks_per_round) != 1:
        raise ValueError(
            "one aggregation is one staging round — plan the quota as "
            f"chunks_per_round=(k,), got {plan.chunks_per_round}"
        )
    n = spec.num_executors
    cap = spec.capacity
    ax = spec.axis_name
    cspec = spec.combine_cspec
    lane = cspec.row_width
    if spec.width + 2 != lane and spec.quantize_mode == "off":
        raise ValueError(f"row lane mismatch: {spec.width + 2} != {lane}")
    q = int(plan.slot_rows)
    G = cspec.num_groups

    key_sh = NamedSharding(mesh, P(ax))
    row_sh = NamedSharding(mesh, P(ax, None))

    # ---- stage A: partial reduce + slot sealing (once) ----
    def _seal(keys, values, num_valid, mask=None):
        idx = jnp.arange(cap, dtype=jnp.int32)
        valid = idx < num_valid[0]
        if mask is not None:
            valid &= mask
        qspec = spec.qspec if spec.quantize_mode != "off" else None
        keys, values, valid = _partial_rows(
            spec, qspec, cap, idx, keys, values, valid, tight=(mask is None)
        )
        rows = jnp.concatenate(
            [
                jax.lax.bitcast_convert_type(keys.astype(jnp.uint32), spec.dtype)[:, None],
                values,
            ],
            axis=1,
        )
        owners = hash_owners(keys, n, valid)
        sizes = jnp.bincount(owners, length=n + 1)[:n].astype(jnp.int32)
        order = jnp.argsort(owners, stable=True)
        sowners = owners[order]
        start = exclusive_cumsum(sizes)
        pos = idx - start[jnp.clip(sowners, 0, n - 1)]
        dest = jnp.where(sowners < n, sowners * cap + pos, n * cap)
        slot = (
            jnp.zeros((n * cap, lane), spec.dtype).at[dest].set(rows[order], mode="drop")
        )
        return slot, sizes[None, :]

    mask_in = (P(ax),) if spec.with_filter else ()
    seal = jax.jit(
        shard_map(
            _seal,
            mesh=mesh,
            in_specs=(P(ax), P(ax, None), P(ax)) + mask_in,
            out_specs=(P(ax, None), P(ax, None)),
            check_vma=False,
        ),
        in_shardings=(key_sh, row_sh, key_sh)
        + ((key_sh,) if spec.with_filter else ()),
        out_shardings=(row_sh, row_sh),
    )

    # ---- stage B: the plan's sub-rounds through the unified executor ----
    exchange = build_plan_exchange(
        mesh,
        num_executors=n,
        send_rows=n * q,
        lane=lane,
        axis_name=ax,
        impl=plan.lowering,
        combine=cspec,
    )

    # one compiled slicer per chunk index (the window offset is static — the
    # plan has few chunks, all pow2-bucketed, so this stays a tiny cache)
    slicers = {}

    def _slicer(chunk: int):
        if chunk not in slicers:

            def _slice(payload, size_row, *, _c=chunk):
                return (
                    slice_subround(payload, n, _c, q, xp=jnp),
                    chunk_size_rows(size_row, _c, q, xp=jnp),
                )

            slicers[chunk] = jax.jit(
                shard_map(
                    _slice,
                    mesh=mesh,
                    in_specs=(P(ax, None), P(ax, None)),
                    out_specs=(P(ax, None), P(ax, None)),
                    check_vma=False,
                ),
                in_shardings=(row_sh, row_sh),
                out_shardings=(row_sh, row_sh),
            )
        return slicers[chunk]

    # identity seed, replicated host-side once — each sub-round donates a
    # fresh device copy to the exchange (merge_accumulators folds them)
    av0, ac0 = acc_init(cspec)
    av_host = np.tile(np.asarray(av0), (n, 1))
    ac_host = np.tile(np.asarray(ac0), (n, 1))

    merge = jax.jit(
        lambda av, ac, bv, bc: merge_accumulators(cspec, (av, ac), (bv, bc)),
        donate_argnums=(0, 1),
    )

    total = keys.shape[0]
    if total > n * cap:
        raise ValueError(f"{total} rows exceed {n} x {cap} capacity")
    if spec.with_filter != (mask is not None):
        raise ValueError("spec.with_filter and mask must agree (see run_grouped_aggregate)")
    pk, pv, nv = shard_rows_host(keys, values, n, cap, value_dtype=spec.dtype)
    extra = ()
    if mask is not None:
        pm, _, _ = shard_rows_host(
            mask.astype(np.uint32), np.zeros((total, 0), np.int32), n, cap
        )
        extra = (jax.device_put(pm.astype(bool), key_sh),)
    payload, size_row = seal(
        jax.device_put(pk, key_sh),
        jax.device_put(pv, row_sh),
        jax.device_put(nv, key_sh),
        *extra,
    )

    def submit(rnd, chunk, nchunks):
        sub_payload, sub_sizes = _slicer(chunk)(payload, size_row)
        return exchange(
            sub_payload,
            sub_sizes,
            jax.device_put(av_host, row_sh),
            jax.device_put(ac_host, row_sh),
        )

    def finish_round(rnd, nchunks, parts):
        accv, accc, recv = parts[0]
        for bv, bc, brecv in parts[1:]:
            accv, accc = merge(accv, accc, bv, bc)
            recv = recv + brecv
        return accv, accc, recv

    results = execute_plan(
        plan,
        submit=submit,
        drain_chunk=lambda rnd, chunk, nchunks, ticket: ticket,
        finish_round=finish_round,
        # the drain-side telemetry now counts the O(groups) accumulator, not
        # O(rows) received rows — the fused route's headline memory win
        result_bytes=lambda r: int(r[0].nbytes + r[1].nbytes),
        occupancy=lambda r: (int(np.asarray(r[2]).sum()), n * cap),
        stats=stats,
        name="aggregate.fused",
    )
    accv, accc, recv_sizes = results[0]

    # ---- stage C: compaction (once) + host finish ----
    def _compact(accv, accc):
        gk, gv, gc, ng = _segment_reduce(
            spec.aggs,
            spec.recv_capacity,
            jnp.arange(G, dtype=jnp.uint32),
            accv,
            accc[:, 0] > 0,
            counts=accc[:, 0],
            tight=False,
        )
        return gk, gv, gc, ng[None]

    compact = jax.jit(
        shard_map(
            _compact,
            mesh=mesh,
            in_specs=(P(ax, None), P(ax, None)),
            out_specs=(P(ax), P(ax, None), P(ax), P(ax)),
            check_vma=False,
        ),
        in_shardings=(row_sh, row_sh),
        out_shardings=(key_sh, row_sh, key_sh, key_sh),
    )
    out_k, out_v, out_c, num_groups = compact(accv, accc)
    if (np.asarray(num_groups) > spec.recv_capacity).any():
        raise RuntimeError(
            f"dense compaction overflowed recv_capacity {spec.recv_capacity}; "
            "re-plan with headroom"
        )
    keys_h, vals_h, cnts_h = unpack_shard_prefixes(
        (out_k, out_v, out_c), np.asarray(num_groups), spec.recv_capacity
    )
    order = np.argsort(keys_h)
    keys_h, vals_h, cnts_h = keys_h[order], vals_h[order], cnts_h[order]
    if "avg" in spec.aggs:
        vals_h = vals_h.astype(np.float64)
        for c, agg in enumerate(spec.aggs):
            if agg == "avg":
                vals_h[:, c] /= np.maximum(cnts_h, 1)
    return keys_h, vals_h, cnts_h


# ----------------------------------------------------------------------------
# CPU oracles
# ----------------------------------------------------------------------------


def oracle_aggregate(
    keys: np.ndarray, values: np.ndarray, aggs: Sequence[str]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """numpy reference: (distinct keys ascending, aggregated columns, counts).
    Mirrors run_grouped_aggregate's output conventions: 'avg' columns are
    exact float64 sum/count (and flip the whole value array to float64);
    'count_distinct' columns carry per-group distinct value counts."""
    uniq, inv, counts = np.unique(keys, return_inverse=True, return_counts=True)
    cols = []
    for c, agg in enumerate(aggs):
        if agg in ("sum", "avg"):
            s = np.bincount(inv, weights=values[:, c].astype(np.float64), minlength=len(uniq))
            cols.append((s / counts) if agg == "avg" else s.astype(values.dtype))
        elif agg == "count_distinct":
            nd = np.zeros(len(uniq), np.int64)
            for g in range(len(uniq)):
                nd[g] = len(np.unique(values[inv == g, c]))
            cols.append(nd.astype(values.dtype))
        else:
            red = np.minimum if agg == "min" else np.maximum
            ident = (
                np.finfo(values.dtype).max
                if np.issubdtype(values.dtype, np.floating)
                else np.iinfo(values.dtype).max
            )
            if agg == "max":
                ident = -ident if np.issubdtype(values.dtype, np.floating) else np.iinfo(values.dtype).min
            acc = np.full(len(uniq), ident, values.dtype)
            red.at(acc, inv, values[:, c])
            cols.append(acc)
    out = np.stack(cols, axis=1) if cols else np.zeros((len(uniq), 0), values.dtype)
    return uniq, out, counts.astype(np.int32)


def plan_join_capacities(
    build_keys: np.ndarray,
    probe_keys: np.ndarray,
    num_executors: int,
    join_type: str = "inner",
) -> Tuple[int, int, int]:
    """Exact per-shard (build_recv, probe_recv, out) capacities for a hash
    join of these keys, from the host twin of the device placement hash —
    what any driver should do instead of guessing skew headroom.  Key k's
    rows land on its owner shard and emit ``pcount(k) * f(bcount(k))``
    rows there, with f per the join type (inner: b; left_outer: max(b, 1);
    left_semi: min(b, 1); left_anti: b == 0); right/full outer additionally
    emit each probe-matchless build row once on its key's owner shard."""
    n = num_executors
    brecv = max(1, int(np.bincount(hash_owners_host(build_keys, n), minlength=n).max()))
    precv = max(1, int(np.bincount(hash_owners_host(probe_keys, n), minlength=n).max()))
    uk_b, cb = np.unique(build_keys, return_counts=True)
    uk_p, cp = np.unique(probe_keys, return_counts=True)
    present = np.isin(uk_p, uk_b)
    bcount = np.zeros(len(uk_p), np.int64)
    bcount[present] = cb[np.searchsorted(uk_b, uk_p[present])]
    base_type = _OUTER_BASE.get(join_type, join_type)
    per_key = cp * _join_emit(base_type)(bcount, np)
    per_shard = np.zeros(n, np.int64)
    if len(uk_p):
        np.add.at(per_shard, hash_owners_host(uk_p, n), per_key)
    if join_type in _OUTER_BASE:
        only_build = ~np.isin(uk_b, uk_p)
        if only_build.any():
            np.add.at(
                per_shard, hash_owners_host(uk_b[only_build], n), cb[only_build]
            )
    return brecv, precv, max(1, int(per_shard.max()))


def run_hash_join(
    mesh: Mesh,
    build_keys: np.ndarray,
    build_vals: np.ndarray,
    probe_keys: np.ndarray,
    probe_vals: np.ndarray,
    axis_name: str = "ex",
    impl: str = "auto",
    build_capacity: Optional[int] = None,
    probe_capacity: Optional[int] = None,
    join_type: str = "inner",
):
    """Host driver for the equi-join: plan receive/output capacities exactly
    from the placement hash (:func:`plan_join_capacities`), shard both sides,
    run the compiled join, and verify the device placement agreed with the
    host plan.  Returns flat (keys, build_rows, probe_rows) in
    shard-concatenated order — compare as a multiset (``oracle_join`` returns
    one); with an outer ``join_type`` (left/right/full) a fourth ``matched``
    bool array is returned (False rows are null-extended: zeroed build lanes
    for unmatched probe rows, zeroed probe lanes for unmatched build rows).
    ``'left_semi'``/``'left_anti'`` keep the 3-tuple with build lanes zeroed
    (SQL semi/anti emit probe columns only).  The
    capacity-planning + unpack half every join caller needs, like
    run_grouped_aggregate is for GROUP BY.  ``build_capacity``/
    ``probe_capacity`` override the tight per-shard input capacities (callers
    that over-provision exercise the padding paths; tests do)."""
    if build_vals.dtype != probe_vals.dtype:
        raise ValueError(
            f"build/probe value dtypes must match (keys bitcast through them): "
            f"{build_vals.dtype} != {probe_vals.dtype}"
        )
    n = int(mesh.devices.size)
    bcap = build_capacity or max(1, -(-len(build_keys) // n))
    pcap = probe_capacity or max(1, -(-len(probe_keys) // n))
    brecv, precv, out_cap = plan_join_capacities(
        build_keys, probe_keys, n, join_type=join_type
    )
    spec = JoinSpec(
        num_executors=n,
        build_capacity=bcap, build_recv_capacity=brecv,
        build_width=build_vals.shape[1],
        probe_capacity=pcap, probe_recv_capacity=precv,
        probe_width=probe_vals.shape[1],
        out_capacity=out_cap,
        dtype=build_vals.dtype,
        axis_name=axis_name,
        impl=impl,
        join_type=join_type,
    )
    fn = build_hash_join(mesh, spec)
    bk, bv, bn = shard_rows_host(build_keys, build_vals, n, bcap, value_dtype=spec.dtype)
    pk, pv, pn = shard_rows_host(probe_keys, probe_vals, n, pcap, value_dtype=spec.dtype)
    key_sh = NamedSharding(mesh, P(axis_name))
    row_sh = NamedSharding(mesh, P(axis_name, None))
    outs = fn(
        jax.device_put(bk, key_sh), jax.device_put(bv, row_sh), jax.device_put(bn, key_sh),
        jax.device_put(pk, key_sh), jax.device_put(pv, row_sh), jax.device_put(pn, key_sh),
    )
    ok, ob, op_, oc, rt = outs[:5]
    rt = np.asarray(rt)
    if not ((rt[:, 0] <= brecv).all() and (rt[:, 1] <= precv).all()):
        raise RuntimeError(
            f"device hash placement diverged from the host plan (build "
            f"{rt[:, 0].max()}/{brecv}, probe {rt[:, 1].max()}/{precv})"
        )
    oc = np.asarray(oc)
    if not (oc <= out_cap).all():
        raise RuntimeError(
            f"join output overflowed the exact host plan ({oc.max()} > {out_cap})"
        )
    if join_type in OUTER_JOIN_TYPES:
        keys, brows, prows, matched = unpack_shard_prefixes(
            (ok, ob, op_, outs[5]), oc, out_cap
        )
        return keys, brows, prows, matched
    keys, brows, prows = unpack_shard_prefixes((ok, ob, op_), oc, out_cap)
    return keys, brows, prows


def oracle_join(
    build_keys: np.ndarray,
    build_vals: np.ndarray,
    probe_keys: np.ndarray,
    probe_vals: np.ndarray,
    join_type: str = "inner",
):
    """numpy reference equi-join: rows (key, build_row, probe_row), as a
    sorted multiset of tuples for order-insensitive comparison.  With an
    outer ``join_type`` a fourth ``matched`` bool array is returned and
    null-extended rows zero the missing side (run_hash_join's convention):
    'left_outer' emits one zero-build row per matchless probe row,
    'right_outer' inner matches plus one zero-probe row per matchless build
    row, 'full_outer' both; ``'left_semi'`` emits each matched probe row once
    and ``'left_anti'`` each matchless probe row once, both with zeroed build
    lanes (SQL semi/anti emit probe columns only)."""
    from collections import defaultdict

    base_type = _OUTER_BASE.get(join_type, join_type)
    left_outer = base_type == "left_outer"
    by_key = defaultdict(list)
    for k, row in zip(build_keys, build_vals):
        by_key[int(k)].append(row)
    zero_build = np.zeros(build_vals.shape[1], build_vals.dtype)
    keys, brows, prows, matched = [], [], [], []
    for k, prow in zip(probe_keys, probe_vals):
        hits = by_key.get(int(k), ())
        if base_type == "left_semi":
            # probe columns only: one zero-build row per matched probe row
            hits = [zero_build] if hits else []
        elif base_type == "left_anti":
            if not hits:
                keys.append(int(k))
                brows.append(zero_build)
                prows.append(prow)
                matched.append(False)
            continue
        for brow in hits:
            keys.append(int(k))
            brows.append(brow)
            prows.append(prow)
            matched.append(True)
        if left_outer and not hits:
            keys.append(int(k))
            brows.append(zero_build)
            prows.append(prow)
            matched.append(False)
    if join_type in _OUTER_BASE:
        # right/full outer: append each probe-matchless build row once
        probe_keyset = {int(k) for k in probe_keys}
        zero_probe = np.zeros(probe_vals.shape[1], probe_vals.dtype)
        for k, brow in zip(build_keys, build_vals):
            if int(k) not in probe_keyset:
                keys.append(int(k))
                brows.append(brow)
                prows.append(zero_probe)
                matched.append(False)
    outer = join_type in OUTER_JOIN_TYPES
    if not keys:
        out = (
            np.zeros(0, np.uint32),
            np.zeros((0, build_vals.shape[1]), build_vals.dtype),
            np.zeros((0, probe_vals.shape[1]), probe_vals.dtype),
        )
        return out + (np.zeros(0, bool),) if outer else out
    out = (np.array(keys, np.uint32), np.stack(brows), np.stack(prows))
    return out + (np.array(matched),) if outer else out
