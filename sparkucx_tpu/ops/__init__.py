"""Device-resident operators: the shuffle collective and the workloads on it.

Everything here is a compiled SPMD program over the executor mesh — specs are
static (capacities, widths), data is runtime (sizes, validity) — so one
compilation serves every batch.  See each module's docstring for the reference
behavior it reproduces.
"""

from sparkucx_tpu.ops.combine import CombineSpec
from sparkucx_tpu.ops.columnar import (
    ColumnarSpec,
    build_columnar_shuffle,
    run_columnar_shuffle,
    shard_rows_host,
    unpack_shard_prefixes,
)
from sparkucx_tpu.ops.exchange import (
    ExchangeSpec,
    build_exchange,
    gather_rows,
    make_mesh,
    oracle_exchange,
    pack_chunks_slots,
    unpack_received,
)
from sparkucx_tpu.ops.hierarchy import (
    build_hierarchical_exchange,
    make_hierarchical_mesh,
)
from sparkucx_tpu.ops.pallas_kernels import build_block_gather, pack_plan
from sparkucx_tpu.ops.skew import (
    ExchangePlan,
    chunk_size_rows,
    plan_exchange,
    quota_slot_rows,
    reassemble_round,
    slice_subround,
    staging_occupancy,
)
from sparkucx_tpu.ops.relational import (
    AggregateSpec,
    JoinSpec,
    build_grouped_aggregate,
    build_hash_join,
    hash_owners_host,
    oracle_aggregate,
    oracle_join,
    plan_join_capacities,
    run_grouped_aggregate,
    run_hash_join,
    run_plan_grouped_aggregate,
)
from sparkucx_tpu.ops.sort import (
    SortSpec,
    build_distributed_sort,
    merge_sorted_runs,
    oracle_sort,
    run_distributed_sort,
    run_external_sort,
)
from sparkucx_tpu.ops.tc import (
    TcSpec,
    build_tc_prep,
    build_tc_step,
    oracle_tc,
    run_transitive_closure,
)

__all__ = [
    "ColumnarSpec",
    "build_columnar_shuffle",
    "run_columnar_shuffle",
    "shard_rows_host",
    "unpack_shard_prefixes",
    "ExchangeSpec",
    "build_exchange",
    "gather_rows",
    "make_mesh",
    "oracle_exchange",
    "pack_chunks_slots",
    "unpack_received",
    "build_hierarchical_exchange",
    "make_hierarchical_mesh",
    "build_block_gather",
    "pack_plan",
    "ExchangePlan",
    "chunk_size_rows",
    "plan_exchange",
    "quota_slot_rows",
    "reassemble_round",
    "slice_subround",
    "staging_occupancy",
    "AggregateSpec",
    "JoinSpec",
    "build_grouped_aggregate",
    "build_hash_join",
    "hash_owners_host",
    "oracle_aggregate",
    "oracle_join",
    "plan_join_capacities",
    "run_grouped_aggregate",
    "run_hash_join",
    "SortSpec",
    "build_distributed_sort",
    "merge_sorted_runs",
    "oracle_sort",
    "run_distributed_sort",
    "run_external_sort",
    "TcSpec",
    "build_tc_prep",
    "build_tc_step",
    "oracle_tc",
    "run_transitive_closure",
]
