"""Hierarchical (multi-slice) shuffle exchange — ICI + DCN two-phase routing.

SURVEY.md section 5.8's TPU-native mapping for the reference's transport calls
for "ICI for intra-slice, DCN for multi-slice".  The flat exchange
(ops/exchange.py) runs ONE all_to_all over every executor pair — on a
multi-slice deployment that means S*C*(S-1)*C point-to-point DCN flows of
block granularity.  This lowering factors the executor mesh into
``(dcn: slices, ici: chips-per-slice)`` and routes in two phases:

    phase A (ICI):  all_to_all over the chip axis, grouping every chip's
                    payload by DESTINATION CHIP INDEX — after it, chip c of
                    slice s holds everything its slice sends to chip c of any
                    slice;
    phase B (DCN):  all_to_all over the slice axis delivers those aggregates —
                    each datum crosses the slower DCN exactly once, in messages
                    C x bigger than the flat lowering's (the aggregation that
                    makes DCN all-to-alls viable);
    compaction:     the received slot grid is packed into the same tight
                    sender-major layout the flat lowerings produce.

The phases move whole slots (dense) — intra-slice ICI bandwidth is cheap and
XLA overlaps the two collectives; the contract (inputs, outputs, layouts) is
IDENTICAL to ``build_exchange``, and the CPU-mesh tests assert bit-equality
against the flat lowering on a factored mesh.

Flat executor id convention: ``executor = slice * chips_per_slice + chip``
(dcn-major), matching ``Mesh(devices.reshape(S, C), ("dcn", "ici"))``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops._compat import shard_map
from sparkucx_tpu.ops.exchange import ExchangeSpec, exclusive_cumsum


def device_slice_ids(devices) -> "list":
    """Per-device slice ids from the runtime topology, or None when the
    runtime exposes none (CPU meshes, single-slice TPUs without the attr).

    TPU devices carry ``slice_index`` on multi-slice deployments; this is the
    probe the mesh factorization and hop classification derive from.  Pure
    python over device attributes — unit-testable with stand-in objects."""
    ids = [getattr(d, "slice_index", None) for d in devices]
    if any(i is None for i in ids):
        return None
    return [int(i) for i in ids]


def probe_topology(devices):
    """(num_slices, chips_per_slice, devices-in-slice-major-order).

    Derives the (dcn, ici) factorization from ``slice_index`` when the
    runtime exposes it — devices are GROUPED by slice (stable within a
    slice), so each mesh row is one physical slice whatever enumeration
    order ``jax.devices()`` used.  Without slice ids (the pure-python
    fallback: CPU meshes, tests) the flat order is taken as a single slice.
    Raises if the slices are ragged — a (dcn, ici) mesh needs equal rows."""
    devs = list(devices)
    ids = device_slice_ids(devs)
    if ids is None:
        return 1, len(devs), devs
    order = sorted(set(ids))
    groups = [[d for d, i in zip(devs, ids) if i == s] for s in order]
    chips = len(groups[0])
    if any(len(g) != chips for g in groups):
        raise ValueError(
            f"ragged slices: {[len(g) for g in groups]} devices per slice_index"
        )
    return len(groups), chips, [d for g in groups for d in g]


def make_hierarchical_mesh(
    num_slices: int, chips_per_slice: int, devices=None
) -> Mesh:
    """(dcn, ici) mesh over the first S*C devices, slice-major.

    When the devices report a genuinely multi-slice topology
    (``slice_index`` with more than one distinct value) the rows follow the
    PHYSICAL slices (probe_topology groups them), not the flat enumeration
    order.  A request that disagrees with the probed factorization is still
    accepted when it is COMPATIBLE — the requested ``chips_per_slice``
    divides the physical one, so every ici row stays inside one physical
    slice (e.g. splitting a 2x8 deployment as 4x4; the extra dcn hops between
    same-slice rows just ride the conservative DCN path).  An incompatible
    request — one that would put chips of different slices on one ici row,
    where remote DMA cannot reach — raises.  Devices with no slice ids — or
    all on one slice — take the requested factorization as a LOGICAL split
    (CPU meshes, and single-slice tests of the two-phase route)."""
    devs = list(devices if devices is not None else jax.devices())
    n = num_slices * chips_per_slice
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    devs = devs[:n]
    ids = device_slice_ids(devs)
    if ids is not None and len(set(ids)) > 1:
        s, c, devs = probe_topology(devs)  # slice-major order either way
        if (s, c) != (num_slices, chips_per_slice) and c % chips_per_slice:
            raise ValueError(
                f"runtime topology is {s}x{c} (slice_index); a "
                f"{num_slices}x{chips_per_slice} factorization would mix "
                f"slices on one ici row — chips_per_slice must divide {c}"
            )
    return Mesh(
        np.array(devs).reshape(num_slices, chips_per_slice), ("dcn", "ici")
    )


def hop_kinds(devices) -> np.ndarray:
    """(n, n) hop classification between executors: 'local' | 'ici' | 'dcn'.

    Same-slice pairs ride ICI, cross-slice pairs cross DCN; without slice
    ids every pair is ICI (single-slice fallback).  Pure python + numpy —
    the unit-testable core of the topology probe."""
    devs = list(devices)
    ids = device_slice_ids(devs) or [0] * len(devs)
    n = len(devs)
    kinds = np.empty((n, n), dtype=object)
    for i in range(n):
        for j in range(n):
            kinds[i, j] = (
                "local" if i == j else ("ici" if ids[i] == ids[j] else "dcn")
            )
    return kinds


def hop_schedule(mesh: Mesh, *, chunks_per_dest: int = 1, slot_rows=None):
    """Flow schedule(s) for ``mesh``, classified by fabric — the input the
    scheduled exchange kernel (ops/ici_exchange.py) consumes.

    * (dcn, ici) mesh: a :class:`HierarchicalSchedule` — a ring schedule per
      phase, so intra-slice ICI hops and inter-slice DCN hops get DISTINCT
      schedules (different dims, different chunking, different fabrics).
    * flat mesh, single slice (or no topology attrs): one ICI ring schedule.
    * flat mesh spanning slices: one ring schedule with every hop
      conservatively classified 'dcn' (some source crosses DCN at every
      offset under flat ordering) — use the hierarchical mesh to split them.

    ``chunks_per_dest`` is clamped per phase to a pow2 divisor of that
    phase's transfer-group rows when ``slot_rows`` is given
    (``schedule_chunks``)."""
    from sparkucx_tpu.ops.ici_exchange import (
        HierarchicalSchedule,
        ring_schedule,
        schedule_chunks,
    )

    def clamp(group_rows):
        if group_rows is None:
            return max(1, int(chunks_per_dest))
        return schedule_chunks(group_rows, chunks_per_dest)

    if set(mesh.axis_names) == {"dcn", "ici"}:
        s, c = mesh.shape["dcn"], mesh.shape["ici"]
        # the ici phase is intra-slice ICI only if every mesh row really
        # stays inside one physical slice (make_hierarchical_mesh guarantees
        # it; a hand-built mesh may not) — a mixed row is conservatively
        # 'dcn' so the lowering guard keeps remote DMA off it
        ids = device_slice_ids(mesh.devices.reshape(-1))
        ici_kind = "ici"
        if ids is not None and any(
            len(set(ids[r * c : (r + 1) * c])) > 1 for r in range(s)
        ):
            ici_kind = "dcn"
        ici_group = s * slot_rows if slot_rows is not None else None
        dcn_group = c * slot_rows if slot_rows is not None else None
        ici = ring_schedule(c, clamp(ici_group), kind=ici_kind) if c > 1 else None
        dcn = ring_schedule(s, clamp(dcn_group), kind="dcn") if s > 1 else None
        return HierarchicalSchedule(num_slices=s, chips_per_slice=c, ici=ici, dcn=dcn)
    n = mesh.devices.size
    ids = device_slice_ids(mesh.devices.reshape(-1))
    kind = "ici" if ids is None or len(set(ids)) == 1 else "dcn"
    return ring_schedule(n, clamp(slot_rows), kind=kind)


def region_permutation(order_outer: int, order_inner: int, slot: int) -> jnp.ndarray:
    """Row indices permuting a slot grid from (inner-major regions) to
    (outer-major): new region k = outer*inner_count... returns (rows,) int32.

    Used to regroup regions (a, b) -> (b, a): region at old index
    ``a * order_inner + b`` moves to new index ``b * order_outer + a``."""
    idx = np.empty(order_outer * order_inner * slot, dtype=np.int32)
    pos = 0
    for b in range(order_inner):
        for a in range(order_outer):
            start = (a * order_inner + b) * slot
            idx[pos : pos + slot] = np.arange(start, start + slot, dtype=np.int32)
            pos += slot
    return jnp.asarray(idx)


def compact_slots(flat: jnp.ndarray, recv_sizes: jnp.ndarray, slot: int, recv_rows: int):
    """Pack a sender-major slot grid into the tight layout (the dense
    lowering's compaction, shared shape — ops/exchange.py)."""
    n = recv_sizes.shape[0]
    starts = exclusive_cumsum(recv_sizes)
    cum = jnp.cumsum(recv_sizes)
    total = cum[-1]
    pos = jnp.arange(recv_rows, dtype=jnp.int32)
    k = jnp.clip(jnp.searchsorted(cum, pos, side="right").astype(jnp.int32), 0, n - 1)
    src = k * slot + (pos - starts[k])
    valid = pos < total
    rows = flat[jnp.clip(src, 0, n * slot - 1)]
    return jnp.where(valid[:, None], rows, jnp.zeros((), dtype=flat.dtype))


def _hier_shard(spec: ExchangeSpec, num_slices: int, chips: int, data, size_row):
    slot = spec.slot_rows
    s_idx = jax.lax.axis_index("dcn")
    c_idx = jax.lax.axis_index("ici")
    me = s_idx * chips + c_idx

    # full size matrix: gather over both axes, dcn-major = flat executor order
    sizes = jax.lax.all_gather(size_row, ("dcn", "ici"), tiled=True)  # (n, n)
    recv_sizes = sizes[:, me]

    # phase A prep: regions are dest-flat-major (s' outer, c' inner); regroup
    # to c'-outer so each ICI peer's group is contiguous
    perm_a = region_permutation(num_slices, chips, slot)  # (s',c') -> (c',s')
    grouped = data[perm_a]

    # phase A: ICI all_to_all over the chip axis — after it, this chip holds
    # its slice's aggregate for chip index c_idx of every slice
    a = jax.lax.all_to_all(
        grouped.reshape(chips, num_slices * slot, spec.lane),
        "ici", split_axis=0, concat_axis=0, tiled=True,
    ).reshape(chips * num_slices * slot, spec.lane)
    # layout now: (c_src, s') regions — regroup to s'-outer for the DCN phase
    perm_b = region_permutation(chips, num_slices, slot)  # (c_src,s') -> (s',c_src)
    staged = a[perm_b]

    # phase B: DCN all_to_all over the slice axis — one crossing per datum,
    # messages aggregated across the whole source slice
    b = jax.lax.all_to_all(
        staged.reshape(num_slices, chips * slot, spec.lane),
        "dcn", split_axis=0, concat_axis=0, tiled=True,
    ).reshape(num_slices * chips * slot, spec.lane)
    # layout: (s_src, c_src) regions = flat sender id ascending — compact
    out = compact_slots(b, recv_sizes, slot, spec.recv_rows)
    return out, recv_sizes[None, :]


def build_hierarchical_exchange(mesh: Mesh, spec: ExchangeSpec):
    """Compile the two-phase exchange for a (dcn, ici) mesh.

    Same contract as ``build_exchange`` (ops/exchange.py): jitted
    ``fn(data, size_matrix) -> (recv, recv_sizes)`` with data/sizes sharded
    over the FLAT executor order (slice-major product of the two mesh axes).
    ``spec.num_executors`` must equal S*C.
    """
    if set(mesh.axis_names) != {"dcn", "ici"}:
        raise ValueError(f"mesh axes must be ('dcn', 'ici'), got {mesh.axis_names}")
    num_slices = mesh.shape["dcn"]
    chips = mesh.shape["ici"]
    if spec.num_executors != num_slices * chips:
        raise ValueError(
            f"spec.num_executors={spec.num_executors} != {num_slices}x{chips} mesh"
        )
    spec.validate()

    shard = shard_map(
        functools.partial(_hier_shard, spec, num_slices, chips),
        mesh=mesh,
        in_specs=(P(("dcn", "ici"), None), P(("dcn", "ici"), None)),
        out_specs=(P(("dcn", "ici"), None), P(("dcn", "ici"), None)),
        check_vma=False,
    )
    sharding = NamedSharding(mesh, P(("dcn", "ici"), None))
    donate = (0,) if spec.send_rows == spec.recv_rows else ()
    fn = jax.jit(
        shard,
        in_shardings=(sharding, sharding),
        out_shardings=(sharding, sharding),
        donate_argnums=donate,
    )
    fn.spec = spec
    return fn
