"""Device LSD radix sort with a fused key+payload scatter (Pallas TPU).

Why this exists (docs/PERF.md "sort floor"): XLA's sort primitive runs at
~23 M keys/s on a v5e chip (2M uint32 keys ≈ 87 ms — compare/lane-shuffle
bound, three orders of magnitude off bandwidth), and the payload permutation
gather runs at ~4-5 GB/s, so argsort+gather caps the device TeraSort step at
~21 M rows/s.  The only fast data-movement primitive measured on this chip is
the DMA engine on *contiguous segments* (137-265 GB/s, ops/pallas_kernels.py)
— so a faster sort must move rows in segments, never through an XLA gather.

This module is that sort: least-significant-digit radix over the uint32 key
(lane 0 of the fused row, bitcast — the same key-travels-with-payload layout
as ops/sort.py), ``32 / BITS`` stable counting passes.  Each pass:

1. **XLA side** (cheap, fused): extract the pass digit per row, per-tile
   histograms, and the global destination offset of every (tile, bucket)
   segment — two small exclusive cumsums.  This is the MapperInfo-style
   size-exchange of the collective data plane, at kernel scale.
2. **Pallas kernel** (grid over row tiles): load the tile's rows into VMEM,
   group them stably by digit IN VMEM, and issue one dynamic-size DMA per
   bucket straight to the rows' final positions in HBM — key and payload move
   together, once, in ``tile_rows / B``-row segments (~50 KiB at the default
   shape: real DMA territory, not per-row scatter).

The in-VMEM stable grouping never calls sort or scatter (Mosaic has neither).
It uses the two dynamic-gather shapes Mosaic *does* lower
(``jnp.take_along_axis`` along either axis of a 2D tile):

* build the bucket-major one-hot of the digits, flat-cumsum it along lanes
  (log2 shifted adds) — entry ``b*T + i`` then holds the number of rows with
  digit <= b up to row i, i.e. every row's stable output slot, and the
  permutation we need is this staircase's *inverse*;
* invert by binary search: output slot d is filled by the row at the first
  flat index whose running count reaches d+1 — 17 ``take_along_axis`` probes
  along the lane axis;
* apply the permutation to the whole row tile with ONE ``take_along_axis``
  along the sublane axis (``tpu.dynamic_gather``), then DMA each bucket's now
  contiguous run.

Stability: within a bucket band the flat index is the row index, so equal
digits keep row order — each pass is a stable counting sort, hence LSD works
and the whole sort is stable (the contract ops/sort.py documents).

CPU testing: ``interpret=True`` replaces the dynamic-size segment DMAs with
row-granular static copies (the Pallas interpreter cannot express
dynamic-size DMA — same limitation as _gather_dma_kernel) and runs the rest
as plain jnp, so the full pass structure is differentially fuzzed against
``np.argsort(kind='stable')`` in CI; tests also AOT-lower the kernel for the
TPU target to pin Mosaic compatibility without a chip.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from sparkucx_tpu.ops._compat import tpu_compiler_params

#: Digit width per pass.  4 bits = 16 buckets x 8 passes: the widest digit
#: whose per-(tile, bucket) DMA segments stay large (tile_rows/16 rows) while
#: the flat cumsum/search band (B * tile_rows lanes) stays a few hundred KiB
#: of VMEM.  256 buckets would halve the passes but shrink segments 16x and
#: blow the band to 2M lanes.
BITS = 4
NUM_BUCKETS = 1 << BITS
NUM_PASSES = 32 // BITS

def _default_tile_rows() -> int:
    """Rows per kernel tile, overridable via SPARKUCX_RADIX_TILE for on-chip
    tuning sweeps (scripts/hw_session.sh) — the trade is DMA segment size
    (tile/16 rows per bucket) vs VMEM footprint and per-tile search width.
    A malformed or out-of-range value must not torch a scarce hardware
    window with an import-time traceback: warn and fall back to 8192."""
    raw = os.environ.get("SPARKUCX_RADIX_TILE")
    if raw is None:
        return 8192
    try:
        val = int(raw)
    except ValueError:
        val = -1
    if val < 8 or val % 8:
        import warnings

        warnings.warn(
            f"SPARKUCX_RADIX_TILE={raw!r} is not a multiple of 8 >= 8; "
            "using the 8192 default"
        )
        return 8192
    return val


DEFAULT_TILE_ROWS = _default_tile_rows()


def _cumsum_lanes(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive cumsum along the lane (last) axis of a (1, M) int32 vector,
    as log2(M) statically-shifted adds — Mosaic has no scan primitive."""
    m = x.shape[-1]
    shift = 1
    while shift < m:
        shifted = jnp.pad(x, ((0, 0), (shift, 0)))[:, :m]
        x = x + shifted
        shift *= 2
    return x


def _gather_lanes(table: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Element gather along the lane (last) axis, batched over the sublane
    axis: ``out[s, j] = table[s, idx[s, j]]``.  Built as a raw ``lax.gather``
    with exactly the dimension numbers Mosaic's TPU lowering maps to
    ``tpu.dynamic_gather(dims=[1])`` (jnp.take_along_axis constructs a
    different but equivalent spelling that its rule rejects)."""
    dnums = jax.lax.GatherDimensionNumbers(
        offset_dims=(),
        collapsed_slice_dims=(1,),
        start_index_map=(1,),
        operand_batching_dims=(0,),
        start_indices_batching_dims=(0,),
    )
    return jax.lax.gather(
        table, idx[..., None], dnums, slice_sizes=(1, 1),
        mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
    )


def _gather_sublanes(rows: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Element gather along the sublane (first) axis, batched over lanes:
    ``out[i, l] = rows[idx[i, l], l]`` — applies a row permutation to a 2D
    tile when ``idx`` broadcasts the permutation across lanes.  Raw
    ``lax.gather`` in Mosaic's ``tpu.dynamic_gather(dims=[0])`` spelling."""
    dnums = jax.lax.GatherDimensionNumbers(
        offset_dims=(),
        collapsed_slice_dims=(0,),
        start_index_map=(0,),
        operand_batching_dims=(1,),
        start_indices_batching_dims=(1,),
    )
    return jax.lax.gather(
        rows, idx[..., None], dnums, slice_sizes=(1, 1),
        mode=jax.lax.GatherScatterMode.PROMISE_IN_BOUNDS,
    )


def _searchsorted_lanes(cum: jnp.ndarray, queries: jnp.ndarray) -> jnp.ndarray:
    """First index r (per lane) with ``cum[0, r] >= queries[0, lane]`` — a
    vectorized lower-bound over a non-decreasing (1, M) table, via binary
    search whose probes are lane gathers (``tpu.dynamic_gather``).  Returns M
    where no index qualifies."""
    m = cum.shape[-1]
    lo = jnp.zeros_like(queries)
    hi = jnp.full_like(queries, m)
    # the search interval spans m+1 candidate answers (0..m inclusive), so
    # ceil(log2(m+1)) = m.bit_length() halvings are needed — one short left
    # unresolved 2-wide intervals and returned lo-1 on some lanes
    steps = max(1, m.bit_length())
    for _ in range(steps):
        mid = (lo + hi) // 2
        probe = _gather_lanes(cum, jnp.minimum(mid, m - 1))
        ge = probe >= queries
        hi = jnp.where(ge, mid, hi)
        lo = jnp.where(ge, lo, mid + 1)
    return lo


def _bin_kernel(dests_ref, rows_ref, out_ref, scratch_ref, sems, *, shift: int,
                tile_rows: int, interpret: bool):
    """One tile of one radix pass: stable-group rows by this pass's digit in
    VMEM, then DMA each bucket's contiguous run to its global destination."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    t = pl.program_id(0)
    big = tile_rows * NUM_BUCKETS
    rows = rows_ref[...]  # (T, L) VMEM
    # NOTE every index below is a static lax.slice — jnp integer indexing
    # lowers through dynamic_slice, which Mosaic does not implement.
    key_lane = jax.lax.slice(rows, (0, 0), (tile_rows, 1)).reshape(tile_rows)
    keys = jax.lax.bitcast_convert_type(key_lane, jnp.uint32)
    digit = jax.lax.shift_right_logical(keys, jnp.uint32(shift)).astype(jnp.int32) & (
        NUM_BUCKETS - 1
    )

    # Bucket-major one-hot band, flat over lanes: entry b*T + i is 1 iff row i
    # has digit b.  Its inclusive cumsum is the stable-slot staircase.
    oh = (digit[None, :] == jax.lax.broadcasted_iota(jnp.int32, (NUM_BUCKETS, 1), 0)).astype(jnp.int32)
    cum = _cumsum_lanes(oh.reshape(1, big))

    # Bucket counts / local starts from the band boundaries (static slices).
    band_end = jax.lax.slice(
        cum.reshape(NUM_BUCKETS, tile_rows), (0, tile_rows - 1), (NUM_BUCKETS, tile_rows)
    ).reshape(NUM_BUCKETS)                                  # inclusive totals
    head = jax.lax.slice(band_end, (0,), (NUM_BUCKETS - 1,))
    local_start = jnp.concatenate([jnp.zeros(1, jnp.int32), head])
    counts = band_end - local_start

    # Invert the staircase: output slot d <- row at the first flat index whose
    # running count is d+1; its row index is that flat index mod T.
    queries = jax.lax.broadcasted_iota(jnp.int32, (1, big), 1) + 1
    first = _searchsorted_lanes(cum, queries)
    perm = jax.lax.slice(
        jax.lax.rem(first, tile_rows), (0, 0), (1, tile_rows)
    ).reshape(tile_rows)                                    # only slots < T real

    # ONE fused key+payload move: the dim-0 dynamic_gather applies the stable
    # grouping to the whole row tile.
    idx = jnp.broadcast_to(perm[:, None], rows.shape).astype(jnp.int32)
    scratch_ref[...] = _gather_sublanes(rows, idx)

    def _scalar(vec, b):  # static-index scalar read without dynamic_slice
        return jax.lax.slice(vec, (b,), (b + 1,)).reshape(())

    def seg_dma(b):
        return pltpu.make_async_copy(
            scratch_ref.at[pl.ds(_scalar(local_start, b), _scalar(counts, b))],
            out_ref.at[pl.ds(dests_ref[t * NUM_BUCKETS + b], _scalar(counts, b))],
            sems.at[b],
        )

    if not interpret:
        # start all bucket segments, then drain: up to B copies in flight per
        # tile (the DMA engine as IO pool, like _gather_dma_kernel); the grid
        # is sequential so scratch is not reused until every DMA completed.
        for b in range(NUM_BUCKETS):
            @pl.when(_scalar(counts, b) > 0)
            def _start(b=b):
                seg_dma(b).start()
        for b in range(NUM_BUCKETS):
            @pl.when(_scalar(counts, b) > 0)
            def _wait(b=b):
                seg_dma(b).wait()
    else:
        # interpreter cannot express dynamic-size DMA: row-granular copies
        # preserve the exact data flow for CPU correctness tests
        def row_copy(b, r):
            dma = pltpu.make_async_copy(
                scratch_ref.at[pl.ds(_scalar(local_start, b) + r, 1)],
                out_ref.at[pl.ds(dests_ref[t * NUM_BUCKETS + b] + r, 1)],
                sems.at[b],
            )
            dma.start()
            dma.wait()

        for b in range(NUM_BUCKETS):
            jax.lax.fori_loop(
                0, _scalar(counts, b), lambda r, _, b=b: (row_copy(b, r), 0)[1], 0
            )


def _radix_pass(rows: jnp.ndarray, shift: int, tile_rows: int, interpret: bool):
    """One stable counting pass: XLA-side histograms/offsets + the Pallas
    binning kernel.  ``rows.shape[0]`` must be a tile multiple."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, lanes = rows.shape
    tiles = n // tile_rows
    keys = jax.lax.bitcast_convert_type(rows[:, 0], jnp.uint32)
    digit = jax.lax.shift_right_logical(keys, jnp.uint32(shift)).astype(jnp.int32) & (
        NUM_BUCKETS - 1
    )
    tiled = digit.reshape(tiles, tile_rows)
    hist = (tiled[:, :, None] == jnp.arange(NUM_BUCKETS, dtype=jnp.int32)).astype(
        jnp.int32
    ).sum(axis=1)                                         # (tiles, B)
    bucket_total = hist.sum(axis=0)
    bucket_start = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(bucket_total)[:-1].astype(jnp.int32)]
    )
    tile_prefix = jnp.concatenate(
        [jnp.zeros((1, NUM_BUCKETS), jnp.int32),
         jnp.cumsum(hist, axis=0)[:-1].astype(jnp.int32)]
    )                                                     # rows of bucket b in tiles < t
    dests = (bucket_start[None, :] + tile_prefix).reshape(-1)  # (tiles*B,)

    kernel = functools.partial(
        _bin_kernel, shift=shift, tile_rows=tile_rows, interpret=interpret
    )
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, lanes), rows.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(tiles,),
            in_specs=[
                pl.BlockSpec((tile_rows, lanes), lambda t, dests: (t, 0)),
            ],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[
                pltpu.VMEM((tile_rows, lanes), rows.dtype),
                pltpu.SemaphoreType.DMA((NUM_BUCKETS,)),
            ],
        ),
        compiler_params=tpu_compiler_params(has_side_effects=True),
        interpret=interpret,
    )(dests, rows)


def clamped_tile_rows(tile_rows: int, n: int) -> int:
    """Shrink an oversized tile toward ``n`` while staying a sublane (8-row)
    multiple — ``min(tile_rows, n)`` alone can produce a tile (e.g. 1001) that
    the module's own SPARKUCX_RADIX_TILE validation would reject and whose
    sublane layout Mosaic can't express."""
    return min(tile_rows, -(-max(8, n) // 8) * 8)


def radix_sort_rows(
    rows: jnp.ndarray,
    tile_rows: int = DEFAULT_TILE_ROWS,
    interpret: bool = False,
) -> jnp.ndarray:
    """Stable-sort fused (key | payload) rows by the uint32 key bitcast in
    lane 0 — 8 LSD counting passes, rows moved by segment DMA each pass.

    ``rows``: (N, L) of any 32-bit dtype (the key is bitcast, never value-
    cast).  N not a tile multiple is padded with KEY_MAX rows (zero payload)
    that sort last and are sliced off — callers with their own padding
    discipline (ops/sort.py) keep theirs intact because the sort is stable
    and appended padding stays behind equal-keyed real rows.
    """
    n = rows.shape[0]
    tile_rows = clamped_tile_rows(tile_rows, n)
    padded = -(-n // tile_rows) * tile_rows
    if padded != n:
        # KEY_MAX pad keys must be BITCAST into the row dtype — a value cast
        # (jnp.full) would turn 0xFFFFFFFF into e.g. float32 -1.0's bit
        # pattern, pad rows would sort into the middle, and the final [:n]
        # slice would drop real rows
        pad_keys = jax.lax.bitcast_convert_type(
            jnp.full((padded - n, 1), 0xFFFFFFFF, jnp.uint32), rows.dtype
        )
        pad_rows = jnp.concatenate(
            [pad_keys, jnp.zeros((padded - n, rows.shape[1] - 1), rows.dtype)],
            axis=1,
        )
        rows = jnp.concatenate([rows, pad_rows])
    for p in range(NUM_PASSES):
        rows = _radix_pass(rows, p * BITS, tile_rows, interpret)
    return rows[:n]


def build_radix_sort(
    n_rows: int,
    lanes: int,
    tile_rows: int = DEFAULT_TILE_ROWS,
    interpret: bool = False,
):
    """Compile ``fn(rows (n_rows, lanes) int32) -> stably sorted rows`` (by
    the uint32 key bitcast in lane 0)."""
    fn = jax.jit(
        functools.partial(radix_sort_rows, tile_rows=tile_rows, interpret=interpret)
    )
    fn.impl = "radix"
    return fn
