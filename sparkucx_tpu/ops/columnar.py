"""Device-resident columnar shuffle — the ``GpuColumnarExchange`` analogue.

BASELINE.md lists "RAPIDS GpuColumnarExchange columnar shuffle -> TPU HBM" as a
target config: on GPU Spark, columnar batches are shuffled device-to-device
without ever landing in host memory.  This module is that capability on TPU —
and it is the *most* TPU-native path in the framework: map output that is
already a ``jax.Array`` (a Spark-SQL-style columnar batch, model activations,
any fixed-width rows) is repartitioned entirely in HBM:

    rows sorted by destination (on device)  ->  ragged all_to_all over ICI  ->
    each executor holds exactly its rows, still in HBM

No byte store, no staging regions, no host round-trip — one jitted function.
The row-granular size matrix is computed on device from the owner vector
(``bincount``), playing the MapperInfo role entirely inside the collective.

Like ops/exchange.py it has two bit-identical lowerings (``ragged`` for TPU,
``dense`` for backends without a ragged-all-to-all kernel), selected the same
way.  Layout here is *tight* (rows contiguous after the sort), not slot —
there are no pre-carved regions to respect.

Payload reduction (ops/compress.py) composes with this module on both rails:
rows that spill to the striped TCP wire travel through the per-chunk lossless
codec transparently (``compress.codec`` — the transport encodes/decodes at
the chunk layer, so shuffled bytes are bit-identical either way), and the
partial-aggregate exchange built on these shuffles (ops/relational.py) can
opt into lossy block quantization of its float value lanes
(``quantize.mode``); keys travel bitcast and are never quantized.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparkucx_tpu.ops._compat import ragged_all_to_all, shard_map
from sparkucx_tpu.ops.exchange import exclusive_cumsum, gather_rows, ragged_params


@dataclass(frozen=True)
class ColumnarSpec:
    """Static description of one compiled columnar shuffle.

    ``capacity`` / ``recv_capacity`` are per-executor row counts (static shapes;
    pad the input with ``owner = num_executors`` rows — they are never sent).
    ``width`` is the row width in elements of ``dtype``.
    """

    num_executors: int
    capacity: int
    recv_capacity: int
    width: int
    dtype: np.dtype = np.dtype(np.float32)
    axis_name: str = "ex"
    impl: str = "auto"

    def resolve_impl(self, platform: Optional[str] = None) -> "ColumnarSpec":
        if self.impl != "auto":
            return self
        if platform is None:
            platform = jax.devices()[0].platform
        return replace(self, impl="ragged" if platform == "tpu" else "dense")


def size_matrix_from_owners(axis_name: str, num_executors: int, owners: jnp.ndarray):
    """Gather the global (n, n) size matrix from each shard's owner vector and
    derive this shard's send/recv sizes and landing offsets — the collective
    MapperInfo analogue shared by the columnar shuffle and the distributed sort.

    Rows with ``owner == num_executors`` are padding and counted nowhere."""
    n = num_executors
    me = jax.lax.axis_index(axis_name)
    counts = jnp.bincount(owners, length=n + 1)[:n].astype(jnp.int32)  # rows me -> j
    sizes = jax.lax.all_gather(counts[None, :], axis_name, tiled=True)  # (n, n)
    # compact-layout ragged params — ONE formula source (exchange.ragged_params)
    # shared with the exchange and covered by tests/test_ragged_plan.py
    _, send_sizes, output_offsets, recv_sizes = ragged_params(sizes, me, None)
    return sizes, send_sizes, recv_sizes, output_offsets


def _sort_and_sizes(spec: ColumnarSpec, rows: jnp.ndarray, owners: jnp.ndarray):
    """Sort rows by destination executor; gather the global size matrix."""
    order = jnp.argsort(owners, stable=True)  # padding (owner == n) sorts last
    sorted_rows = gather_rows(rows, order)
    sorted_owners = owners[order]
    _, send_sizes, recv_sizes, output_offsets = size_matrix_from_owners(
        spec.axis_name, spec.num_executors, owners
    )
    return sorted_rows, sorted_owners, send_sizes, recv_sizes, output_offsets


def columnar_shard_ragged(spec: ColumnarSpec, payload, send_sizes, recv_sizes, output_offsets):
    input_offsets = exclusive_cumsum(send_sizes)
    out = jnp.zeros((spec.recv_capacity, payload.shape[1]), dtype=payload.dtype)
    out = ragged_all_to_all(
        payload,
        out,
        input_offsets.astype(jnp.int32),
        send_sizes.astype(jnp.int32),
        output_offsets.astype(jnp.int32),
        recv_sizes.astype(jnp.int32),
        axis_name=spec.axis_name,
    )
    return out, recv_sizes


def columnar_shard_dense(spec: ColumnarSpec, payload, send_sizes, recv_sizes, output_offsets):
    """Portable lowering: scatter sorted rows into fixed slots, tiled
    all_to_all, then compaction — same receive layout as the ragged path."""
    n = spec.num_executors
    slot = spec.capacity  # worst case: every row goes to one destination
    starts = exclusive_cumsum(send_sizes)

    # slot grid (n, slot, W): row k of dest j's slot <- sorted row starts[j]+k
    k = jnp.arange(slot, dtype=jnp.int32)
    src = starts[:, None] + k[None, :]                        # (n, slot)
    valid = k[None, :] < send_sizes[:, None]
    src = jnp.clip(src, 0, payload.shape[0] - 1)
    slots = jnp.where(valid[..., None], payload[src], jnp.zeros((), dtype=payload.dtype))

    received = jax.lax.all_to_all(slots, spec.axis_name, split_axis=0, concat_axis=0, tiled=True)
    flat = received.reshape(n * slot, payload.shape[1])

    rstarts = exclusive_cumsum(recv_sizes)
    cum = jnp.cumsum(recv_sizes)
    total = cum[-1]
    pos = jnp.arange(spec.recv_capacity, dtype=jnp.int32)
    sender = jnp.clip(jnp.searchsorted(cum, pos, side="right").astype(jnp.int32), 0, n - 1)
    gsrc = sender * slot + (pos - rstarts[sender])
    ok = pos < total
    gathered = gather_rows(flat, jnp.clip(gsrc, 0, n * slot - 1))
    out = jnp.where(ok[:, None], gathered, jnp.zeros((), dtype=payload.dtype))
    return out, recv_sizes


def columnar_body(spec: ColumnarSpec, rows, owners):
    """Shared body: sort once, then exchange the sorted payload."""
    sorted_rows, _, send_sizes, recv_sizes, output_offsets = _sort_and_sizes(spec, rows, owners)
    body = columnar_shard_ragged if spec.impl == "ragged" else columnar_shard_dense
    out, recv_sizes = body(spec, sorted_rows, send_sizes, recv_sizes, output_offsets)
    return out, recv_sizes[None, :]


def build_columnar_shuffle(mesh: Mesh, spec: ColumnarSpec):
    """Compile the device-resident columnar shuffle.

    Returns jitted ``fn(rows, owners) -> (recv_rows, recv_counts)``:

    * ``rows``: (n * capacity, width) of ``dtype``, row-sharded — executor i's
      local rows (padding rows allowed anywhere);
    * ``owners``: (n * capacity,) int32, sharded — destination executor per row;
      use ``num_executors`` for padding rows (never sent);
    * ``recv_rows``: (n * recv_capacity, width) row-sharded — executor j's shard
      holds all rows destined to it, sender-major, each sender's rows in that
      sender's stable pre-sort order;
    * ``recv_counts``: (n, n) int32 row-sharded — rows j received from each i.
    """
    if spec.num_executors != mesh.devices.size:
        raise ValueError(f"spec.num_executors={spec.num_executors} != mesh size {mesh.devices.size}")
    spec = spec.resolve_impl(platform=mesh.devices.reshape(-1)[0].platform)
    ax = spec.axis_name

    shard = shard_map(
        functools.partial(columnar_body, spec),
        mesh=mesh,
        in_specs=(P(ax, None), P(ax)),
        out_specs=(P(ax, None), P(ax, None)),
        check_vma=False,
    )
    rows_sharding = NamedSharding(mesh, P(ax, None))
    owners_sharding = NamedSharding(mesh, P(ax))
    counts_sharding = NamedSharding(mesh, P(ax, None))
    fn = jax.jit(
        shard,
        in_shardings=(rows_sharding, owners_sharding),
        out_shardings=(rows_sharding, counts_sharding),
    )
    fn.spec = spec
    return fn


def run_columnar_shuffle(
    mesh: Mesh,
    spec: ColumnarSpec,
    rows,
    owners,
    max_attempts: int = 3,
):
    """Overflow-retry wrapper (the job surface of run_distributed_sort /
    run_grouped_aggregate, for data already resident on device): runs the
    compiled shuffle and doubles ``recv_capacity`` when a destination's row
    count exceeds it.

    ``rows``/``owners`` may be host or device arrays shaped per
    ``build_columnar_shuffle``.  Returns (recv_rows, recv_counts) with the
    final (possibly enlarged) capacity.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    rows = jax.device_put(rows, NamedSharding(mesh, P(spec.axis_name, None)))
    owners = jax.device_put(owners, NamedSharding(mesh, P(spec.axis_name)))
    attempt_spec = spec
    for _ in range(max_attempts):
        fn = build_columnar_shuffle(mesh, attempt_spec)
        recv, counts = fn(rows, owners)
        per_dest = np.asarray(counts).sum(axis=1)
        if (per_dest <= attempt_spec.recv_capacity).all():
            return recv, counts
        attempt_spec = replace(attempt_spec, recv_capacity=2 * attempt_spec.recv_capacity)
    raise RuntimeError(
        f"columnar shuffle overflowed recv_capacity {attempt_spec.recv_capacity // 2} "
        f"after {max_attempts} doublings — destination skew too extreme"
    )


def shard_rows_host(
    keys: np.ndarray,
    values: np.ndarray,
    num_shards: int,
    capacity: int,
    key_fill: int = 0,
    value_dtype=None,
):
    """Deal host (keys, value-rows) into the padded per-shard layout every
    mesh-op driver feeds ``device_put``: contiguous near-equal shares, shard s
    padded to ``capacity`` with ``key_fill`` keys / zero rows.  Returns
    (padded_keys (n*cap,) uint32, padded_values (n*cap, width), num_valid
    (n,) int32).  Shared by run_distributed_sort, run_grouped_aggregate, and
    tests — one definition of the sharding convention."""
    n, cap = num_shards, capacity
    total = len(keys)
    if values.shape[0] != total:
        raise ValueError(
            f"keys/values row mismatch: {total} keys vs {values.shape[0]} value rows"
        )
    if total > n * cap:
        raise ValueError(f"{total} rows exceed {n} x {cap} capacity")
    width = values.shape[1]
    pk = np.full(n * cap, key_fill, np.uint32)
    pv = np.zeros((n * cap, width), value_dtype or values.dtype)
    nv = np.zeros(n, np.int32)
    base, rem = divmod(total, n)
    start = 0
    for s in range(n):
        take = base + (1 if s < rem else 0)
        pk[s * cap : s * cap + take] = keys[start : start + take]
        pv[s * cap : s * cap + take] = values[start : start + take]
        nv[s] = take
        start += take
    return pk, pv, nv


def unpack_shard_prefixes(arrays, counts, capacity: int):
    """Inverse of :func:`shard_rows_host`: concatenate each shard's valid
    prefix from per-shard padded layouts.  ``arrays``: host arrays shaped
    (n * capacity, ...); ``counts``: (n,) valid rows per shard.  Returns the
    unpacked arrays in shard order — with shard_rows_host, the one definition
    of the sharding convention's pack/unpack pair."""
    n = len(counts)
    outs = []
    for a in arrays:
        a2 = np.asarray(a).reshape(n, capacity, *np.asarray(a).shape[1:])
        outs.append(np.concatenate([a2[s, : counts[s]] for s in range(n)]))
    return outs


def owners_from_partitions(
    partition_ids: jnp.ndarray, num_partitions: int, num_executors: int
) -> jnp.ndarray:
    """Map reduce-partition ids to owning executors (the contiguous ranges of
    store/hbm_store.default_peer_ranges, computed on device).  Padding rows
    (partition_id < 0 or >= num_partitions) map to ``num_executors``."""
    base, rem = divmod(num_partitions, num_executors)
    # partition p belongs to executor e iff start(e) <= p < start(e+1)
    starts = jnp.array(
        [e * base + min(e, rem) for e in range(num_executors + 1)], dtype=jnp.int32
    )
    owner = jnp.searchsorted(starts, partition_ids, side="right").astype(jnp.int32) - 1
    invalid = (partition_ids < 0) | (partition_ids >= num_partitions)
    return jnp.where(invalid, num_executors, owner)
