"""JAX version-compat resolver for the ops layer.

One place where API moves between pinned JAX versions are absorbed:

* ``shard_map`` — jax >= 0.6 exports ``jax.shard_map`` with the replication
  check spelled ``check_vma``; jax 0.4.x-0.5.x ships it as
  ``jax.experimental.shard_map.shard_map`` with the same semantics spelled
  ``check_rep``.  Eight call sites
  (exchange/hierarchy/relational/sort/columnar/tc) bind through here.
* ``ragged_all_to_all`` — absent before jax 0.5; ``HAS_RAGGED_ALL_TO_ALL``
  lets callers (and tests) gate the ragged lowering, and the fallback binding
  raises a targeted error instead of an AttributeError mid-trace.
* ``tpu_compiler_params`` — Pallas renamed ``pltpu.TPUCompilerParams`` to
  ``pltpu.CompilerParams`` and grew fields (``has_side_effects``); the helper
  builds whichever class exists, dropping kwargs the old dataclass lacks.
* ``enable_cpu_cross_process_collectives`` — multi-process CPU runs need the
  gloo cross-process collectives backend selected before the backend client
  exists; older jaxlibs otherwise fail with "Multiprocess computations aren't
  implemented on the CPU backend".

The resolver is computed once at import (CI runs it under the pinned JAX so a
future API break fails fast at the import step, not deep inside a trace).
``SHARD_MAP_SOURCE`` records which spelling was bound — surfaced by the CI
compat step and useful in bug reports.
"""

from __future__ import annotations

import inspect

import jax

if hasattr(jax, "shard_map"):
    _shard_map_impl = jax.shard_map
    SHARD_MAP_SOURCE = "jax.shard_map"
else:
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    SHARD_MAP_SOURCE = "jax.experimental.shard_map.shard_map"

#: the replication-check kwarg was renamed check_rep -> check_vma; bind to
#: whichever this JAX accepts (signature-inspected, not version-sniffed)
_CHECK_KW = (
    "check_vma"
    if "check_vma" in inspect.signature(_shard_map_impl).parameters
    else "check_rep"
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across JAX versions (kwarg-for-kwarg the modern API)."""
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **{_CHECK_KW: check_vma}
    )


#: True when this JAX can trace the ragged collective at all (added in 0.5).
HAS_RAGGED_ALL_TO_ALL = hasattr(jax.lax, "ragged_all_to_all")

if HAS_RAGGED_ALL_TO_ALL:
    ragged_all_to_all = jax.lax.ragged_all_to_all
else:

    def ragged_all_to_all(
        operand, output, input_offsets, send_sizes, output_offsets, recv_sizes, *, axis_name
    ):
        raise NotImplementedError(
            f"jax.lax.ragged_all_to_all is not available in jax {jax.__version__} "
            "(added in 0.5); the ragged exchange lowering cannot trace here — "
            "use impl='dense' (what resolve_impl picks on CPU) or upgrade jax"
        )


def tpu_compiler_params(**kwargs):
    """Build ``pltpu.CompilerParams`` (``TPUCompilerParams`` before the rename).

    Fields the running version's dataclass lacks (e.g. ``has_side_effects`` on
    jax 0.4.x) are dropped: they are advisory compiler hints, and every kernel
    here consumes its outputs so DCE protection is not load-bearing.
    """
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    accepted = inspect.signature(cls.__init__).parameters
    return cls(**{k: v for k, v in kwargs.items() if k in accepted})


def enable_cpu_cross_process_collectives() -> bool:
    """Select the gloo cross-process collectives backend for the CPU client.

    Must run before the CPU backend client is created (i.e. before
    ``jax.distributed.initialize`` triggers backend init).  Without it, older
    jaxlibs reject multi-process CPU programs outright.  Returns False when
    this JAX has no such knob (in which case multi-process CPU either works
    natively or is genuinely unsupported).
    """
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        return False
    return True
