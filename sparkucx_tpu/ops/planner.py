"""Exchange planners — legacy knobs, optimization passes, telemetry feedback.

``ExchangePlan`` (ops/skew.py) is the declarative exchange interface: rounds,
per-round chunking, lowering tier, overlap depth, and the serve-plane tiers
(streams, codec, quantization, hedge delay).  This module produces plans:

* :class:`StaticPlanner` — the legacy conf knobs mapped 1:1 onto a plan.
  ``slot_quota_rows == 0`` becomes the single-shot plan (whole padded slots,
  donation, elastic recovery); ``> 0`` becomes the chunked plan
  (``plan_exchange``).  With ``conf.planner_optimize`` off (the default) the
  mapping is EXACT: the unified executor interpreting a static plan is
  byte-identical to the pre-plan engines (tests/test_planner.py pins it).
* Plan-optimization passes — pure plan->plan rewrites gated behind
  ``conf.planner_optimize`` / the adaptive planner, because they change the
  schedule geometry (never the bytes): pow2 slot bucketing (idempotent over
  ``plan_exchange`` output, a safety net for hand-built plans), chunk
  coalescing (grow the slot while total staged rows don't grow — fewer
  collective launches for the same wire bytes), and staging-footprint
  sub-round reordering after "Memory-efficient array redistribution through
  portable collective communication" (arXiv:2112.01075) — lighter staging
  rounds submit first so the depth-d in-flight window's peak co-resident
  footprint shrinks.
* :class:`AdaptivePlanner` — re-plans per shuffle per epoch from the
  telemetry the obs plane (PR 11/12) already exports, instead of ~20 static
  knobs: predicted padding (from the sealed size matrices) picks the quota,
  ``rx_stall_p99_ns`` + peer health set the hedge delay, observed
  compression ratios keep or drop the codec, credit stalls widen the wire
  stripes, and drain-lane occupancy deepens the pipeline.

SPMD lockstep: every multi-controller process must derive the identical
collective schedule.  The adaptive planner therefore splits its inputs —
anything that shapes the COLLECTIVE schedule (quota, chunking, ordering,
lowering) is a pure function of :class:`PlanContext` fields the SPMD executor
all-gathers (round maxes, used-row totals), while :class:`PlanSignals`
telemetry (which may differ per host) only steers serve-plane fields that
never enter a collective (hedge, codec, streams).  ``pipeline_depth`` may
vary per host safely: depth changes WHEN stages overlap, never the order
collectives are submitted in.

This invariant is no longer prose-only: the analyzer's ``lockstep-taint``
pass (docs/ANALYSIS.md) taint-tracks telemetry through this module and the
SPMD transport and fails CI when a tainted value reaches a field declared
collective in ``analysis/config.py::COLLECTIVE_FIELDS`` — the registry is
itself cross-checked against the :class:`ExchangePlan` dataclass.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

from sparkucx_tpu.ops.skew import (
    ExchangePlan,
    plan_exchange,
    quota_slot_rows,
)


def _pow2_ceil(rows: int) -> int:
    bucket = 1
    while bucket < rows:
        bucket <<= 1
    return bucket


@dataclass(frozen=True)
class PlanSignals:
    """The metric snapshot a plan was justified by — the planner-relevant
    slice of a ``MetricsRegistry.snapshot()``.  All fields default to the
    'healthy, nothing observed yet' reading, so a cold cluster plans exactly
    like the static mapping."""

    #: staged-slot padding observed on past exchanges (ops family,
    #: padded / (used + padded) over exchange.pipeline.drain)
    padding_fraction: float = 0.0
    #: drain-lane occupancy: drain time / submit time over past exchanges
    #: (> 1 means the host-side drain is the bottleneck, worth more overlap)
    drain_occupancy: float = 0.0
    #: worst per-lane receive stall tail across the wire plane, ns
    rx_stall_p99_ns: int = 0
    #: time fetch readers spent blocked on the credit gate, ns
    credit_stall_ns: int = 0
    #: minimum peer health EWMA across remotes ([0, 1]; 1 = healthy)
    worst_peer_health: float = 1.0
    #: circuit breakers currently open across remotes
    breakers_open: int = 0
    #: observed wire compression ratio (raw / encoded; 1.0 = incompressible
    #: or codec off — below ~1.05 the encode cost buys nothing)
    compression_ratio: float = 1.0

    @classmethod
    def from_registry(cls, registry) -> "PlanSignals":
        """Distill one registry snapshot into planner signals.  Unknown or
        absent families simply keep their defaults — the planner must work
        against any subset of providers (SPMD hosts register fewer)."""
        padding = drain_occ = None
        used = padded = 0.0
        submit_ns = drain_ns = 0.0
        rx_stall = credit_stall = 0
        health = None
        breakers = 0
        raw_bytes = encoded_bytes = 0.0
        for s in registry.snapshot():
            kind = dict(s.labels).get("kind", "")
            if s.family == "ops" and kind == "exchange.pipeline.drain":
                if s.name == "used_rows_total":
                    used = s.value
                elif s.name == "padded_rows_total":
                    padded = s.value
                elif s.name == "total_ns_total":
                    drain_ns = s.value
            elif s.family == "ops" and kind == "exchange.pipeline.submit":
                if s.name == "total_ns_total":
                    submit_ns = s.value
            elif s.family == "wire":
                if s.name == "rx_stall_p99_ns":
                    rx_stall = max(rx_stall, int(s.value))
                elif s.name == "credit_stall_ns":
                    credit_stall = max(credit_stall, int(s.value))
                elif s.name == "peer_health":
                    health = s.value if health is None else min(health, s.value)
                elif s.name == "breaker_open":
                    breakers += int(s.value)
            elif s.family == "compress":
                if s.name in ("raw_bytes", "tx_raw_bytes"):
                    raw_bytes += s.value
                elif s.name in ("encoded_bytes", "tx_encoded_bytes"):
                    encoded_bytes += s.value
        if used + padded > 0:
            padding = padded / (used + padded)
        if submit_ns > 0:
            drain_occ = drain_ns / submit_ns
        return cls(
            padding_fraction=padding if padding is not None else 0.0,
            drain_occupancy=drain_occ if drain_occ is not None else 0.0,
            rx_stall_p99_ns=rx_stall,
            credit_stall_ns=credit_stall,
            worst_peer_health=health if health is not None else 1.0,
            breakers_open=breakers,
            compression_ratio=raw_bytes / encoded_bytes if encoded_bytes > 0 else 1.0,
        )

    def describe(self) -> dict:
        """JSON-safe flat view for the ``exchange.plan`` trace event."""
        return {
            "padding_fraction": round(self.padding_fraction, 4),
            "drain_occupancy": round(self.drain_occupancy, 4),
            "rx_stall_p99_ns": int(self.rx_stall_p99_ns),
            "credit_stall_ns": int(self.credit_stall_ns),
            "worst_peer_health": round(self.worst_peer_health, 4),
            "breakers_open": int(self.breakers_open),
            "compression_ratio": round(self.compression_ratio, 4),
        }


@dataclass(frozen=True)
class PlanContext:
    """What a planner sees about one shuffle, all host ints — the same
    metadata-before-data discipline as the seal itself.  In the SPMD
    deployment every field except ``signals`` is derived from all-gathered
    quantities, so every process constructs an identical context and hence an
    identical collective schedule."""

    num_executors: int
    #: rows per peer slot as sealed (send_rows // n)
    staging_slot_rows: int
    #: per staging round, the cluster-wide hottest (sender, dest) lane rows
    round_max_rows: Tuple[int, ...]
    #: total used rows across all executors/rounds/lanes (0 = unknown)
    used_rows_total: int = 0
    row_bytes: int = 128
    platform: str = "cpu"
    #: the shuffle carries a partial grouped aggregation (an ``AggregateSpec``
    #: with ``partial=True``) — the only traffic whose landed rows are
    #: combinable inside the exchange.  Static spec geometry, identical on
    #: every SPMD process by construction.
    agg_partial: bool = False
    #: dense key-domain size (groups) when the aggregation keys are
    #: dense-representable, else 0 (forces the sorted fallback)
    agg_groups: int = 0
    #: aggregate payload lanes (value columns; key/count lanes excluded)
    agg_width: int = 0
    #: bytes per aggregate value-lane element
    agg_itemsize: int = 4
    #: local telemetry — serve-plane decisions only (see module docstring)
    signals: PlanSignals = PlanSignals()

    @property
    def num_rounds(self) -> int:
        return len(self.round_max_rows)

    @property
    def recv_staging_bytes(self) -> int:
        """Bytes one receiver's sender-major grid stages per sub-round — what
        the dense combine accumulator must undercut to be worth fusing."""
        return self.num_executors * self.staging_slot_rows * self.row_bytes

    @property
    def combine_acc_bytes(self) -> int:
        """Bytes of the dense per-group accumulator (``agg_width`` value
        lanes plus one int32 count lane per group)."""
        return self.agg_groups * (self.agg_width * self.agg_itemsize + 4)

    def predicted_padding(self, slot_rows: int) -> float:
        """Padding fraction the single-shot plan would stage at ``slot_rows``
        per peer slot — derivable before any exchange runs (the adaptive
        quota decision must not depend on per-host telemetry; see the SPMD
        lockstep note in the module docstring)."""
        staged = self.num_executors * self.num_executors * slot_rows * max(
            self.num_rounds, 1
        )
        if staged <= 0 or self.used_rows_total <= 0:
            return 0.0
        return max(0.0, 1.0 - self.used_rows_total / staged)

    @property
    def mean_lane_rows(self) -> float:
        """Mean used rows per (sender, dest) lane across the shuffle."""
        lanes = self.num_executors * self.num_executors * max(self.num_rounds, 1)
        return self.used_rows_total / lanes if lanes else 0.0


def _combine_tier(conf, ctx: PlanContext, *, dense_only: bool = False) -> str:
    """The ``combine`` plan field: receive-side compute-in-exchange tier.

    Derived from conf plus all-gathered spec geometry ONLY (``agg_*`` fields
    are static properties of the cluster-wide ``AggregateSpec``), so every
    SPMD process lands on the same tier — the fused combine changes the
    collective's output shape, which must agree in lockstep.  ``dense`` needs
    a dense-representable key domain whose accumulator undercuts the recv
    staging it replaces; otherwise the static planner honors the knob with
    the bounded ``sorted`` fallback while the adaptive planner
    (``dense_only=True``) declines — fusing without the O(groups) memory win
    is pure dispatch-tax speculation it cannot justify from geometry."""
    if not (getattr(conf, "exchange_fused_combine", False) and ctx.agg_partial):
        return "off"
    if ctx.agg_groups > 0 and ctx.combine_acc_bytes < ctx.recv_staging_bytes:
        return "dense"
    return "off" if dense_only else "sorted"


# ----------------------------------------------------------------------
# plan-optimization passes (pure plan -> plan; geometry only, never bytes)


def pass_pow2_bucket(plan: ExchangePlan, ctx: PlanContext) -> ExchangePlan:
    """Pow2-bucket the slot: ``plan_exchange`` output is already a fixed
    point, so this is the safety net for hand-built plans — a non-pow2 slot
    would fragment the compile cache (the bucketing discipline the
    cache-hygiene analyzer pass enforces on the transports)."""
    bucket = _pow2_ceil(max(1, plan.slot_rows))
    if bucket == plan.slot_rows:
        return plan
    chunks = tuple(
        max(1, -(-int(m) // bucket)) for m in _round_needs(plan)
    )
    return dataclasses.replace(plan, slot_rows=bucket, chunks_per_round=chunks)


def _round_needs(plan: ExchangePlan) -> Tuple[int, ...]:
    """Per-round row need implied by the plan itself (chunks x slot) — an
    upper bound on the true round max, used when re-bucketing a plan whose
    context is unknown."""
    return tuple(c * plan.slot_rows for c in plan.chunks_per_round)


def pass_coalesce_chunks(plan: ExchangePlan, ctx: PlanContext) -> ExchangePlan:
    """Chunk coalescing: repeatedly double the slot while the total staged
    rows do not grow — e.g. 2 chunks of q collapse into 1 chunk of 2q (same
    wire bytes, half the collective launches and their dispatch overhead).
    Rounds with odd chunk counts keep the smaller slot (3 chunks of q would
    become 2 of 2q = more padding), because ``staged_rows`` would grow.
    Single-shot plans are already one launch per round — left untouched."""
    if plan.single_shot or not plan.chunks_per_round:
        return plan
    ceiling = quota_slot_rows(max(ctx.staging_slot_rows, 1), 0)
    best = plan
    while best.slot_rows < ceiling:
        q2 = best.slot_rows * 2
        chunks2 = tuple(
            max(1, -(-int(m) // q2)) for m in ctx.round_max_rows
        ) if ctx.round_max_rows else tuple(
            max(1, -(-need // q2)) for need in _round_needs(best)
        )
        cand = dataclasses.replace(best, slot_rows=q2, chunks_per_round=chunks2)
        if cand.staged_rows(ctx.num_executors) > best.staged_rows(ctx.num_executors):
            break
        if cand.num_subrounds >= best.num_subrounds:
            break  # no launch saved either: stop before inflating the bucket
        best = cand
    return best


def pass_reorder_rounds(plan: ExchangePlan, ctx: PlanContext) -> ExchangePlan:
    """Staging-footprint sub-round reordering (arXiv:2112.01075): submit
    staging rounds in ascending footprint (chunk count, then round index for
    stability), so the depth-d pipeline window co-resides the small rounds'
    buffers first and the peak transient footprint is set by one heavy round
    instead of several adjacent ones.  Results are re-emitted in natural
    round order by the executor, so consumers never observe the permutation."""
    nrounds = len(plan.chunks_per_round)
    if nrounds <= 1:
        return plan
    order = tuple(
        sorted(range(nrounds), key=lambda r: (plan.chunks_per_round[r], r))
    )
    if order == tuple(range(nrounds)):
        return plan
    return dataclasses.replace(plan, round_order=order)


DEFAULT_PASSES: Tuple[Callable[[ExchangePlan, PlanContext], ExchangePlan], ...] = (
    pass_pow2_bucket,
    pass_coalesce_chunks,
    pass_reorder_rounds,
)


def optimize_plan(
    plan: ExchangePlan,
    ctx: PlanContext,
    passes: Optional[Sequence[Callable]] = None,
) -> ExchangePlan:
    """Run the optimization pipeline over a plan.  Every pass preserves
    coverage (each round's chunks x slot still covers its hottest lane) and
    therefore bytes; only schedule geometry changes."""
    for p in DEFAULT_PASSES if passes is None else passes:
        plan = p(plan, ctx)
    return plan


# ----------------------------------------------------------------------
# planners


class StaticPlanner:
    """Legacy conf knobs -> plan, 1:1.

    ``slot_quota_rows == 0`` maps to the single-shot plan (the pow2 slot
    bucket, one chunk per round, whole padded shards retained — including
    donation of device-sealed payloads and elastic degraded recovery);
    ``> 0`` maps to ``plan_exchange``'s chunked schedule (tight spliced
    shards, exactly the retired quota engine).  Every other plan field copies
    its conf knob verbatim, so existing configs produce byte-identical
    exchanges and wire frames through the unified executor
    (tests/test_planner.py golden gate)."""

    def __init__(self, conf) -> None:
        self.conf = conf

    def plan(self, ctx: PlanContext) -> ExchangePlan:
        conf = self.conf
        if conf.slot_quota_rows > 0:
            base = plan_exchange(
                ctx.round_max_rows, ctx.staging_slot_rows, conf.slot_quota_rows
            )
            plan = dataclasses.replace(base, single_shot=False)
        else:
            plan = ExchangePlan(
                slot_rows=quota_slot_rows(max(ctx.staging_slot_rows, 1), 0),
                chunks_per_round=(1,) * max(ctx.num_rounds, 1),
                single_shot=True,
            )
        plan = dataclasses.replace(
            plan,
            lowering=conf.exchange_impl,
            pipeline_depth=max(1, int(conf.pipeline_depth)),
            streams=conf.wire_streams,
            codec=conf.wire_compress_codec,
            quantize_mode=conf.quantize_mode,
            quantize_block=conf.quantize_block_size,
            hedge_ms=conf.fetch_hedge_ms,
            combine=_combine_tier(conf, ctx),
        )
        if getattr(conf, "planner_optimize", False):
            plan = optimize_plan(plan, ctx)
        return plan


class AdaptivePlanner:
    """Telemetry-fed planner: per shuffle per epoch, pick the schedule from
    what the obs plane measured instead of static knobs.

    Decisions (all deterministic; thresholds are the ``planner.*`` knobs):

    * quota/chunking — when no static quota is forced and the single-shot
      plan's PREDICTED padding (from the sealed size matrices — agreed
      cluster-wide, never local telemetry) exceeds
      ``planner_target_padding``, search the pow2 quotas in
      [``planner_min_quota_rows``, slot] for the one minimizing predicted
      staged rows (``sum ceil(max_r / q) * q`` per round — the exact staging
      and dense-wire footprint ``plan_exchange`` will realize), breaking
      ties toward the larger quota (fewer collective launches).  The search
      returning the full slot means chunking cannot shrink the footprint
      (hottest lane already at a pow2 boundary) and the plan stays
      single-shot.
    * combine — keep the receive-side fused combine only when the dense
      accumulator's predicted bytes undercut the recv staging it replaces
      (spec geometry — agreed cluster-wide); never the sorted fallback.
    * hedge delay — with degraded peers (health EWMA < 0.5 or an open
      breaker) and an observed stall tail, hedge at ~2x the p99 stall,
      clamped to [conf.fetch_hedge_ms, conf.fetch_hedge_max_ms].
    * codec — drop a configured codec when the observed ratio says the
      encode cost buys < 5% shrink; keep it otherwise.
    * streams — double the stripes (up to 8) when fetch readers spent real
      time blocked on the credit gate.
    * depth — one extra overlap round (up to 4) when the drain lane is the
      bottleneck (occupancy > 1).

    The optimization pipeline always runs on adaptive plans."""

    def __init__(self, conf) -> None:
        self.conf = conf
        self._static = StaticPlanner(conf)

    def plan(self, ctx: PlanContext) -> ExchangePlan:
        conf = self.conf
        sig = ctx.signals
        plan = self._static.plan(ctx)
        # -- collective schedule: derived from agreed geometry only --------
        if conf.slot_quota_rows == 0 and ctx.round_max_rows:
            slot = quota_slot_rows(max(ctx.staging_slot_rows, 1), 0)
            if ctx.predicted_padding(slot) > conf.planner_target_padding:
                # pow2-quota search: minimize predicted staged rows (exactly
                # what plan_exchange will stage: ceil(max/q) chunks of q per
                # round), ties to the LARGER quota — fewer launches for the
                # same footprint.  q == slot reproduces the single-shot
                # footprint, so "search says slot" means chunking can't help.
                def _staged(q: int) -> int:
                    return sum(
                        max(1, -(-int(m) // q)) * q for m in ctx.round_max_rows
                    )

                floor = _pow2_ceil(max(1, conf.planner_min_quota_rows))
                candidates = []
                q = floor
                while q < slot:
                    candidates.append(q)
                    q <<= 1
                candidates.append(slot)
                quota = min(reversed(candidates), key=_staged, default=slot)
                if quota < slot:
                    base = plan_exchange(
                        ctx.round_max_rows, ctx.staging_slot_rows, quota
                    )
                    plan = dataclasses.replace(
                        plan,
                        slot_rows=base.slot_rows,
                        chunks_per_round=base.chunks_per_round,
                        single_shot=False,
                        round_order=(),
                    )
        if plan.combine != "off":
            # adaptive keeps the fusion only when the dense accumulator is a
            # predicted memory win (all-gathered geometry — lockstep-safe);
            # the sorted fallback's dispatch-tax bet is left to the static
            # knob mapping
            plan = dataclasses.replace(
                plan, combine=_combine_tier(conf, ctx, dense_only=True)
            )
        # -- serve plane: local telemetry is safe here ---------------------
        degraded = sig.worst_peer_health < 0.5 or sig.breakers_open > 0
        if degraded and sig.rx_stall_p99_ns > 0:
            hedge = max(conf.fetch_hedge_ms, int(sig.rx_stall_p99_ns * 2 // 1_000_000))
            if conf.fetch_hedge_max_ms:
                hedge = min(hedge, conf.fetch_hedge_max_ms)
            plan = dataclasses.replace(plan, hedge_ms=hedge)
        if plan.codec != "off" and sig.compression_ratio < 1.05:
            plan = dataclasses.replace(plan, codec="off")
        if sig.credit_stall_ns > 1_000_000:
            plan = dataclasses.replace(plan, streams=min(max(plan.streams, 1) * 2, 8))
        if sig.drain_occupancy > 1.0:
            plan = dataclasses.replace(
                plan, pipeline_depth=min(plan.pipeline_depth + 1, 4)
            )
        return optimize_plan(plan, ctx)


def make_planner(conf):
    """The conf-selected planner (``spark.shuffle.tpu.planner.mode``)."""
    if getattr(conf, "planner_mode", "static") == "adaptive":
        return AdaptivePlanner(conf)
    return StaticPlanner(conf)


# ----------------------------------------------------------------------
# Lineage hashing (query/ cross-query shuffle reuse)
#
# The lineage cache (sparkucx_tpu/query/lineage.py) keys a sealed shuffle by
# input fingerprint + canonical plan serialization + the conf tiers that
# affect the exchanged BYTES.  The helpers live here because this module owns
# the plan vocabulary: which ExchangePlan fields shape result bytes and which
# are serve-plane overlap/transport tuning is exactly the COLLECTIVE vs
# SERVE_PLANE split the lockstep-taint pass pins (analysis/config.py), and
# keeping the serializer next to the planners means a new plan field fails
# the lineage property tests (tests/test_query.py) before it can silently
# ride — or silently skip — a cache key.


def canonical_plan(plan: ExchangePlan, fields: Optional[Sequence[str]] = None) -> str:
    """Deterministic serialization of a plan (sorted keys, no whitespace).

    ``fields`` restricts the view — the lineage cache passes the
    byte-affecting field set so two plans differing only in serve-plane
    tuning (hedge delay, stripe width, overlap depth) canonicalize
    identically, while any collective-schedule or lossy-tier difference
    yields distinct bytes."""
    import json

    d = plan.describe()
    if fields is not None:
        keep = set(fields)
        d = {k: v for k, v in d.items() if k in keep}
    return json.dumps(d, sort_keys=True, separators=(",", ":"))


def lineage_hash(*parts: str) -> str:
    """SHA-256 over length-prefixed parts — the lineage key combinator.

    Length-prefixing keeps the encoding injective (``("ab", "c")`` and
    ``("a", "bc")`` hash differently), so dag canonicalizations, input
    fingerprints, and conf signatures can be folded in any fixed order
    without delimiter collisions."""
    import hashlib

    h = hashlib.sha256()
    for part in parts:
        data = part.encode()
        h.update(str(len(data)).encode())
        h.update(b":")
        h.update(data)
    return h.hexdigest()
