"""Unified metrics registry with Prometheus text exposition.

Before this plane, telemetry lived in per-subsystem accessors an operator had
to know by name: ``StatsAggregator`` summaries, ``wire_lane_stats()``,
``compress_stats()``, ``replica_stats``, ``elastic_stats``,
``eviction_stats()``, per-reader failover counters.  The registry inverts the
dependency: each subsystem registers a *provider* (a zero-arg callable
returning :class:`MetricSample` rows), and one ``snapshot()`` walks them all.
Exposition is Prometheus text format 0.0.4, served three ways:

* ``registry.prometheus_text()`` locally,
* over the peer wire via the METRICS_PULL Active Message (every executor's
  BlockServer answers with its registry's text — ``TpuShuffleCluster
  .metrics_text()`` concatenates the mesh),
* an optional local HTTP scrape endpoint (:func:`start_http_server`, behind
  ``spark.shuffle.tpu.obs.metricsPort``; default 0 = off).

Naming scheme (docs/OBSERVABILITY.md): ``sparkucx_tpu_<family>_<metric>``
with snake_case metric names and labels for dimensions (``executor``,
``lane``, ``kind``, ``app``...).  Families mirror the subsystems: ``wire``,
``replica``, ``compress``, ``elastic``, ``eviction``, ``store``, ``tenant``,
``reader``, ``ops``, ``obs`` (the plane's own health: ring drops).

Lock discipline: ``_lock`` guards only the provider list and is never held
while a provider runs — providers take their subsystems' own locks (store
lock, ``_tag_lock``, ``_compress_lock``...), so keeping the registry lock a
leaf keeps the whole-program lock graph acyclic (analysis/lockgraph).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Mapping, Optional, Tuple

PREFIX = "sparkucx_tpu"

#: A provider returns an iterable of samples; registered per subsystem.
Provider = Callable[[], Iterable["MetricSample"]]


@dataclass(frozen=True)
class MetricSample:
    """One exposition row: ``<prefix>_<family>_<name>{labels} value``."""

    family: str  # subsystem family: wire / replica / elastic / ...
    name: str  # snake_case metric name within the family
    value: float
    labels: Tuple[Tuple[str, str], ...] = ()
    kind: str = "gauge"  # prometheus TYPE: "counter" | "gauge"
    help: str = ""

    @property
    def full_name(self) -> str:
        return f"{PREFIX}_{self.family}_{self.name}"


def sample(
    family: str,
    name: str,
    value,
    labels: Optional[Mapping[str, object]] = None,
    kind: str = "gauge",
    help: str = "",
) -> MetricSample:
    """Convenience constructor: dict labels, any numeric value."""
    lab = tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))
    return MetricSample(family=family, name=name, value=float(value), labels=lab, kind=kind, help=help)


class MetricsRegistry:
    """Provider registry + snapshot/exposition.  One per executor (the
    loopback cluster builds one per virtual executor so METRICS_PULL views
    stay distinct); providers are closures over their subsystem."""

    def __init__(self, executor_id: Optional[int] = None) -> None:
        self.executor_id = executor_id
        self._lock = threading.Lock()
        self._providers: List[Tuple[str, Provider]] = []  #: guarded by self._lock
        self._provider_errors = 0  #: guarded by self._lock

    def register(self, name: str, provider: Provider) -> None:
        """Add a named provider; re-registering a name replaces it (transports
        re-init across shuffles and must not double-report)."""
        with self._lock:
            self._providers = [(n, p) for n, p in self._providers if n != name]
            self._providers.append((name, provider))

    def unregister(self, name: str) -> None:
        with self._lock:
            self._providers = [(n, p) for n, p in self._providers if n != name]

    def provider_names(self) -> List[str]:
        with self._lock:
            return [n for n, _ in self._providers]

    def snapshot(self) -> List[MetricSample]:
        """Walk every provider OUTSIDE the registry lock (providers take
        subsystem locks; the registry lock stays a leaf).  A provider that
        raises is skipped and counted — scraping must never take a serving
        plane down."""
        with self._lock:
            providers = list(self._providers)
        out: List[MetricSample] = []
        errors = 0
        for name, provider in providers:
            try:
                out.extend(provider())
            except Exception:
                errors += 1
        if errors:
            with self._lock:
                self._provider_errors += errors
        with self._lock:
            total_errors = self._provider_errors
        out.append(
            sample(
                "obs",
                "provider_errors_total",
                total_errors,
                kind="counter",
                help="metric providers that raised during snapshot()",
            )
        )
        if self.executor_id is not None:
            out = [
                MetricSample(
                    family=s.family,
                    name=s.name,
                    value=s.value,
                    labels=_with_executor(s.labels, self.executor_id),
                    kind=s.kind,
                    help=s.help,
                )
                for s in out
            ]
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition format 0.0.4 — deterministic order
        (grouped by metric name, label-sorted) so goldens can pin it."""
        samples = self.snapshot()
        by_name: Dict[str, List[MetricSample]] = {}
        for s in samples:
            by_name.setdefault(s.full_name, []).append(s)
        lines: List[str] = []
        for full_name in sorted(by_name):
            rows = by_name[full_name]
            head = rows[0]
            if head.help:
                lines.append(f"# HELP {full_name} {head.help}")
            lines.append(f"# TYPE {full_name} {head.kind}")
            for s in sorted(rows, key=lambda r: r.labels):
                if s.labels:
                    labels = ",".join(f'{k}="{_escape(v)}"' for k, v in s.labels)
                    lines.append(f"{full_name}{{{labels}}} {_fmt(s.value)}")
                else:
                    lines.append(f"{full_name} {_fmt(s.value)}")
        return "\n".join(lines) + "\n"


def _with_executor(labels: Tuple[Tuple[str, str], ...], eid: int) -> Tuple[Tuple[str, str], ...]:
    if any(k == "executor" for k, _ in labels):
        return labels
    return tuple(sorted(labels + (("executor", str(eid)),)))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(value: float) -> str:
    # counters and byte totals read better as integers; keep floats for rates
    return str(int(value)) if float(value).is_integer() else repr(value)


# -- stock providers -------------------------------------------------------
# Adapters from the pre-obs accessor shapes to sample rows, so subsystems
# register one closure instead of re-deriving the naming scheme.


def stats_aggregator_provider(agg) -> Provider:
    """Adapt a utils/stats.py StatsAggregator: per-kind op summaries land as
    ``ops_*`` rows, free-form counters keep their names."""

    def provide() -> List[MetricSample]:
        out: List[MetricSample] = []
        for kind in agg.kinds():
            s = agg.summary(kind)
            lab = {"kind": kind}
            out.append(sample("ops", "count_total", s.ops, lab, kind="counter"))
            out.append(sample("ops", "bytes_total", s.bytes, lab, kind="counter"))
            out.append(sample("ops", "total_ns_total", s.total_ns, lab, kind="counter"))
            if s.p50_ns is not None:
                out.append(sample("ops", "latency_p50_ns", s.p50_ns, lab))
            if s.p99_ns is not None:
                out.append(sample("ops", "latency_p99_ns", s.p99_ns, lab))
            if s.used_rows or s.padded_rows:
                out.append(sample("ops", "used_rows_total", s.used_rows, lab, kind="counter"))
                out.append(sample("ops", "padded_rows_total", s.padded_rows, lab, kind="counter"))
            for cname, cval in agg.counters(kind).items():
                out.append(sample("ops", f"{cname}_total", cval, lab, kind="counter"))
        return out

    return provide


def counter_dict_provider(family: str, fn: Callable[[], Mapping[str, object]]) -> Provider:
    """Adapt a flat ``{counter_name: value}`` accessor (replica_stats,
    compress_snapshot, eviction_stats, elastic_stats...)."""

    def provide() -> List[MetricSample]:
        out: List[MetricSample] = []
        for name, value in fn().items():
            if isinstance(value, bool):
                value = int(value)
            if isinstance(value, (int, float)):
                out.append(sample(family, name, value))
        return out

    return provide


def wire_lane_provider(fn: Callable[[], Iterable[Mapping]]) -> Provider:
    """Adapt ``PeerTransport.wire_lane_stats()`` (a list of per-lane dicts
    with executor/slot/lane keys): the remote end and lane become labels."""

    def provide() -> List[MetricSample]:
        out: List[MetricSample] = []
        for s in fn():
            lab = {"peer": s["executor"], "slot": s["slot"], "lane": s["lane"]}
            for name, value in s.items():
                if name in ("executor", "slot", "lane"):
                    continue
                kind = "gauge" if name.endswith("p99_ns") else "counter"
                suffix = "" if name.endswith("p99_ns") else "_total"
                out.append(sample("wire", f"{name}{suffix}", value, lab, kind=kind))
        return out

    return provide


def tracer_provider(tracer) -> Provider:
    """The obs plane's own health: ring occupancy and drop count."""

    def provide() -> List[MetricSample]:
        return [
            sample("obs", "trace_events", len(tracer.events)),
            sample(
                "obs",
                "trace_dropped_total",
                tracer.dropped,
                kind="counter",
                help="events evicted from the flight-recorder ring",
            ),
        ]

    return provide


# -- HTTP scrape endpoint --------------------------------------------------


def start_http_server(registry: MetricsRegistry, port: int, host: str = "127.0.0.1"):
    """Serve ``GET /metrics`` from a daemon thread; returns the server
    (``.server_address``, ``.shutdown()``).  Port 0 asks the OS for a free
    port — the conf knob's 0 means OFF and callers never pass it through."""
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
            if self.path not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = registry.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # silence per-request stderr noise
            pass

    server = http.server.ThreadingHTTPServer((host, port), _Handler)
    thread = threading.Thread(target=server.serve_forever, name="obs-metrics-http", daemon=True)
    thread.start()
    server.obs_thread = thread  # joined by close_http_server
    return server


def close_http_server(server) -> None:
    server.shutdown()
    server.server_close()
    thread = getattr(server, "obs_thread", None)
    if thread is not None:
        thread.join(timeout=5)
