"""Always-on flight recorder: postmortem capture for the fault paths.

The recorder keeps the tracer's bounded ring warm (``Tracer.recording``) even
when full tracing is off, so when a fault-tolerance path fires there is
always a trace tail to look at.  On a trigger — any ``TransportError``
construction (core/operation.py failure hooks), an elastic recovery
(transport/tpu.py), or a chaos-harness fault (testing/faults.py) — it
assembles a *postmortem bundle*:

* the trace tail (the newest ``tail_events`` ring entries + drop counter),
* a metrics snapshot (Prometheus text, when a registry is attached),
* the membership epoch/suspect view (when a membership getter is attached),
* the trigger's reason and free-form context.

Bundles land in memory (``last_postmortem``, ``postmortems``) by default;
``spark.shuffle.tpu.obs.postmortemDir`` additionally writes each bundle as a
JSON file.  In-memory default matters: the test suite raises TransportError
on purpose constantly, and a default-on file dump would spray the filesystem.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, List, Optional

from sparkucx_tpu.core import operation as _operation
from sparkucx_tpu.testing import faults
from sparkucx_tpu.utils.trace import TRACER, Tracer

#: Keep bundles bounded: the recorder is always on and chaos tests trigger
#: hundreds of captures — only the newest N stay resident.
MAX_BUNDLES = 16
#: Trace-tail size per bundle: enough to see the failing exchange, small
#: enough that capture on the error path stays cheap.
TAIL_EVENTS = 256


class FlightRecorder:
    """One per executor-ish scope (the cluster keeps one for the whole
    loopback mesh).  ``attach_*`` wire in the optional legs; ``install()``
    hooks TransportError construction; ``close()`` unhooks."""

    def __init__(
        self,
        tracer: Optional[Tracer] = None,
        executor_id: Optional[int] = None,
        postmortem_dir: Optional[str] = None,
        ring_capacity: Optional[int] = None,
        tail_events: int = TAIL_EVENTS,
    ) -> None:
        self.tracer = tracer if tracer is not None else TRACER
        self.executor_id = executor_id
        self.postmortem_dir = postmortem_dir
        self.tail_events = tail_events
        self._lock = threading.Lock()
        self.postmortems: List[dict] = []  #: guarded by self._lock
        self._captures = 0  #: guarded by self._lock
        self._registry = None
        self._membership: Optional[Callable[[], Optional[dict]]] = None
        self._installed = False
        self._capturing = threading.local()
        if ring_capacity:
            self.tracer.set_capacity(ring_capacity)
        # the "always-on" half: recording survives tracing being disabled
        self.tracer.recording = True

    # -- wiring ------------------------------------------------------------

    def attach_registry(self, registry) -> None:
        self._registry = registry

    def attach_membership(self, getter: Callable[[], Optional[dict]]) -> None:
        """``getter`` returns ``{"epoch": int, "suspected": [...]}`` or None."""
        self._membership = getter

    def install(self) -> None:
        """Register the TransportError failure hook and the chaos-harness
        fault observer (idempotent)."""
        if not self._installed:
            _operation.register_failure_hook(self._on_transport_error)
            faults.on_fault.append(self._on_fault)
            self._installed = True

    def close(self) -> None:
        if self._installed:
            _operation.unregister_failure_hook(self._on_transport_error)
            try:
                faults.on_fault.remove(self._on_fault)
            except ValueError:
                pass
            self._installed = False

    # -- triggers ----------------------------------------------------------

    def _on_fault(self, point: str, **ctx) -> None:
        # chaos-harness fault fired: light capture (the fault's own action —
        # sever/garble — runs next, possibly under the instrumented point's
        # locks, so no metric-provider walk here either)
        self.capture(
            f"fault:{point}", include_metrics=False, include_membership=False, **ctx
        )

    def _on_transport_error(self, exc: BaseException) -> None:
        # LIGHT capture: the hook fires inside TransportError.__init__, i.e.
        # potentially under arbitrary subsystem locks — walking the metric
        # providers (which take those same non-reentrant locks) from here
        # could self-deadlock, so the error-path bundle is trace-tail only.
        self.capture(
            "transport_error",
            include_metrics=False,
            include_membership=False,
            error=f"{type(exc).__name__}: {exc}",
        )

    def capture(
        self,
        reason: str,
        include_metrics: bool = True,
        include_membership: bool = True,
        **context,
    ) -> Optional[dict]:
        """Assemble and store one postmortem bundle.  Re-entrant triggers
        (a metrics provider raising TransportError mid-capture) are dropped —
        the recorder must never recurse on the error path."""
        if getattr(self._capturing, "busy", False):
            return None
        self._capturing.busy = True
        try:
            bundle = {
                "reason": reason,
                "wall_time": time.time(),
                "executor": self.executor_id,
                "context": {k: _jsonable(v) for k, v in context.items()},
                "trace_tail": self.tracer.tail(self.tail_events),
                "trace_dropped": self.tracer.dropped,
                "metrics": (
                    self._registry.prometheus_text()
                    if (include_metrics and self._registry)
                    else None
                ),
                "membership": (
                    self._membership() if (include_membership and self._membership) else None
                ),
            }
            with self._lock:
                self._captures += 1
                bundle["seq"] = self._captures
                self.postmortems.append(bundle)
                del self.postmortems[:-MAX_BUNDLES]
            if self.postmortem_dir:
                self._dump(bundle)
            return bundle
        finally:
            self._capturing.busy = False

    # -- inspection --------------------------------------------------------

    @property
    def last_postmortem(self) -> Optional[dict]:
        with self._lock:
            return self.postmortems[-1] if self.postmortems else None

    @property
    def captures(self) -> int:
        with self._lock:
            return self._captures

    # -- dump --------------------------------------------------------------

    def _dump(self, bundle: dict) -> None:
        try:
            os.makedirs(self.postmortem_dir, exist_ok=True)
            eid = "x" if self.executor_id is None else str(self.executor_id)
            path = os.path.join(
                self.postmortem_dir,
                f"postmortem-e{eid}-{bundle['seq']:04d}-{bundle['reason']}.json",
            )
            with open(path, "w") as f:
                json.dump(bundle, f)
            bundle["path"] = path
        except OSError:
            pass  # postmortem capture must never become a second failure


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)
