"""Unified observability plane (PR 14): distributed tracing glue, the
process-wide metrics registry, and the always-on flight recorder.

Three legs, one import surface:

* ``utils/trace.py`` grew real trace/span ids and the bounded event ring;
  this package adds the cross-executor parts — TRACE_PULL merging
  (``transport/tpu.py::export_trace``) rides on :func:`merge_events`.
* :class:`MetricsRegistry` — transports/stores/services register providers;
  one typed snapshot, Prometheus text exposition, served over the peer wire
  (METRICS_PULL) and an optional local HTTP scrape endpoint
  (``spark.shuffle.tpu.obs.metricsPort``).
* :class:`FlightRecorder` — keeps the trace ring warm even with tracing off
  and auto-dumps a postmortem bundle (trace tail + metrics snapshot +
  membership epoch) on TransportError, elastic recovery, and chaos faults.
"""

from sparkucx_tpu.obs.metrics import MetricSample, MetricsRegistry, start_http_server
from sparkucx_tpu.obs.recorder import FlightRecorder

__all__ = [
    "MetricSample",
    "MetricsRegistry",
    "FlightRecorder",
    "start_http_server",
]
