"""QueryRunner — executes a StageDag end-to-end on the cluster, per tenant.

The runner compiles each ``exchange`` stage onto the existing manager SPI
(register / staged-store writers / one collective superstep via the
``ExchangePlan`` executor / windowed readers) and runs the per-partition
compute stages (aggregate / join / sort) on the exchanged partitions with
the deterministic numpy reference ops, so TeraSort-style (scan → exchange →
sort) and TPC-H-shaped (scan → exchange → aggregate, scan ×2 → exchange ×2 →
join) pipelines run whole, not one shuffle at a time.

Perf headline — cross-query shuffle reuse: with
``spark.shuffle.tpu.query.cacheEnabled`` the runner keys every sealed
exchange by its lineage hash (query/lineage.py) and a repeat serves straight
from the store/eviction/serve tiers: no register, no map writes, no
collective — just the windowed read.  Cached rounds stay charged to the
owning tenant's HBM quota (admission control); entries die on
input-fingerprint change or ``unregister_shuffle`` (the runner holds a
manager teardown hook, so external removals invalidate too); quota pressure
triggers the footprint-aware keep/recompute pass (largest first,
arXiv:2112.01075 — see LineageCache.plan_eviction).

Off path: with the knob off (default) every exchange executes and is
unregistered when the query finishes — no cache, no retained shuffles, no
tenant charges, byte-identical to a cache-less runner.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sparkucx_tpu.core.operation import TenantQuotaExceededError
from sparkucx_tpu.obs.metrics import counter_dict_provider
from sparkucx_tpu.ops.relational import hash_owners_host, oracle_aggregate, oracle_join
from sparkucx_tpu.ops.sort import oracle_sort
from sparkucx_tpu.query.dag import Stage, StageDag
from sparkucx_tpu.query.lineage import (
    LineageCache,
    fingerprint_rows,
    lineage_key,
)
from sparkucx_tpu.shuffle.reader import serialize_records
from sparkucx_tpu.utils.trace import instant

#: Runner-allocated shuffle ids live far above hand-numbered test/benchmark
#: sids and below the tenant-translated namespace (TENANT_SID_BASE = 1<<20).
_QUERY_SID_BASE = 1 << 16
_sid_counter = itertools.count(_QUERY_SID_BASE)
_sid_lock = threading.Lock()


def _next_sid() -> int:
    with _sid_lock:
        return next(_sid_counter)


Row = Tuple[int, ...]


class QueryRunner:
    """Per-tenant DAG executor over one TpuShuffleManager.

    ``cache`` may be shared between runners (one per app on the same
    cluster): entries are app-namespaced, so tenants never see each other's
    cached shuffles, but the keep/recompute eviction pass weighs the whole
    resident footprint.
    """

    def __init__(
        self,
        manager,
        app_id: str = "default",
        tenants=None,
        cache: Optional[LineageCache] = None,
    ) -> None:
        self.manager = manager
        self.conf = manager.conf
        self.app_id = app_id
        self.tenants = tenants
        if tenants is not None and not tenants.known(app_id):
            tenants.register(app_id)
        self.cache_enabled = bool(getattr(self.conf, "query_cache_enabled", False))
        self.cache = None
        if self.cache_enabled:
            self.cache = cache if cache is not None else LineageCache(
                max_bytes=self.conf.query_cache_max_bytes
            )
            self.cache.attach(manager)
        self._counters: Dict[str, int] = {
            "queries": 0,
            "stages": 0,
            "exchanges_executed": 0,
            "exchanges_reused": 0,
            "uncached_rounds": 0,
            "stale_invalidations": 0,
        }
        self._counters_lock = threading.Lock()
        #: optional observer fn(stage_name, op, ms) — the perf harness taps
        #: per-stage latency here without scraping the trace plane
        self.on_stage = None
        metrics = getattr(manager.cluster, "metrics", None)
        if metrics is not None:
            metrics.register(f"query:{app_id}", counter_dict_provider("query", self._snapshot))

    def _snapshot(self) -> Dict[str, int]:
        with self._counters_lock:
            out = dict(self._counters)
        if self.cache is not None:
            out.update(self.cache.snapshot())
        return out

    def _bump(self, name: str, n: int = 1) -> None:
        with self._counters_lock:
            self._counters[name] += n

    # -- execution ----------------------------------------------------------

    def run(self, dag: StageDag, inputs: Dict[str, List[Row]]):
        """Execute the DAG; returns the sink stage's result.

        ``inputs`` maps each scan stage name to its rows ((key, value) int
        tuples).  Exchange results are lists of per-partition row lists;
        aggregate/join keep that partitioning; sort returns one flat,
        globally ordered row list.
        """
        results: Dict[str, object] = {}
        fingerprints: Dict[str, str] = {}
        ephemeral: List[int] = []  #: sids to unregister when the query ends
        try:
            for st in dag.stages:
                t0 = time.perf_counter()
                if st.op == "scan":
                    rows = list(inputs[st.name])
                    fingerprints[st.name] = fingerprint_rows(serialize_records(rows))
                    results[st.name] = rows
                elif st.op == "exchange":
                    results[st.name] = self._run_exchange(
                        dag, st, results[st.inputs[0]], fingerprints, ephemeral
                    )
                elif st.op == "aggregate":
                    results[st.name] = self._run_aggregate(st, results[st.inputs[0]])
                elif st.op == "join":
                    results[st.name] = self._run_join(
                        st, results[st.inputs[0]], results[st.inputs[1]]
                    )
                else:  # sort
                    results[st.name] = self._run_sort(st, results[st.inputs[0]])
                self._bump("stages")
                ms = (time.perf_counter() - t0) * 1e3
                instant("query.stage", app=self.app_id, stage=st.name, op=st.op, ms=ms)
                if self.on_stage is not None:
                    self.on_stage(st.name, st.op, ms)
        finally:
            for sid in ephemeral:
                self.manager.unregister_shuffle(sid)
        self._bump("queries")
        return results[dag.sink.name]

    # -- exchange (the cacheable stage) -------------------------------------

    def _run_exchange(
        self,
        dag: StageDag,
        st: Stage,
        upstream,
        fingerprints: Dict[str, str],
        ephemeral: List[int],
    ) -> List[List[Row]]:
        rows = _flatten(upstream)
        num_reducers = int(st.param("partitions", self.manager.num_executors))
        key = lineage_key(dag, st.name, fingerprints, self.conf)

        if self.cache is not None:
            entry = self.cache.lookup(self.app_id, key)
            if entry is not None:
                # reuse: the sealed shuffle serves from store/eviction/serve
                # tiers — no register, no writes, no collective.
                self._bump("exchanges_reused")
                instant(
                    "query.cache_hit",
                    app=self.app_id,
                    stage=st.name,
                    shuffle_id=entry.shuffle_id,
                    hits=entry.hits,
                )
                return self._read_partitions(entry.shuffle_id, num_reducers)

        sid, nbytes = self._execute_exchange(rows, num_reducers)
        self._bump("exchanges_executed")

        if self.cache is None:
            ephemeral.append(sid)
        else:
            structure = dag.canonical(st.name)  # fingerprint-free
            # input changed under the same query shape: those entries can
            # never hit again — tear them down through the manager so every
            # tier (store, ServeCache, encoded-chunk pool) drops the blocks.
            for stale in self.cache.stale_entries(self.app_id, structure, key):
                self._drop_entry(stale)
                self._bump("stale_invalidations")
            if self._admit(key, sid, nbytes, structure):
                pass  # retained: serves future hits, stays tenant-charged
            else:
                self._bump("uncached_rounds")
                ephemeral.append(sid)
        return self._read_partitions(sid, num_reducers)

    def _execute_exchange(self, rows: List[Row], num_reducers: int) -> Tuple[int, int]:
        """Register / write / superstep one hash exchange; returns
        (shuffle_id, serialized map-output bytes)."""
        m = self.manager
        num_mappers = m.num_executors
        sid = _next_sid()
        m.register_shuffle(sid, num_mappers, num_reducers)
        if rows:
            keys = np.array([r[0] for r in rows], np.uint32)
            owners = hash_owners_host(keys, num_reducers)
        else:
            owners = np.zeros(0, np.int32)
        nbytes = 0
        for map_id in range(num_mappers):
            chunk = rows[map_id::num_mappers]
            chunk_owners = owners[map_id::num_mappers]
            writer = m.get_writer(sid, map_id)
            for r in range(num_reducers):
                part = [row for row, o in zip(chunk, chunk_owners) if int(o) == r]
                if not part:
                    continue
                payload = serialize_records(part)
                nbytes += len(payload)
                with writer.get_partition_writer(r).open_stream() as stream:
                    stream.write(payload)
            writer.commit_all_partitions()
        m.run_exchange(sid)
        return sid, nbytes

    def _read_partitions(self, sid: int, num_reducers: int) -> List[List[Row]]:
        return [
            [tuple(rec) for rec in self.manager.get_reader(sid, r, r + 1).read()]
            for r in range(num_reducers)
        ]

    # -- admission control ---------------------------------------------------

    def _admit(self, key: str, sid: int, nbytes: int, structure: str) -> bool:
        """Charge the owning tenant and (on success) retain the shuffle.
        Quota pressure triggers the footprint-aware keep/recompute pass;
        an unadmittable round stays uncached (caller unregisters it)."""
        cache = self.cache
        if cache.max_bytes and nbytes > cache.max_bytes:
            return False
        # runner-level byte budget: evict largest-first until this fits
        if cache.max_bytes:
            over = cache.cached_bytes() + nbytes - cache.max_bytes
            if over > 0:
                self._evict(cache.plan_eviction(over))
        if not self._charge(sid, nbytes):
            # tenant quota pressure: recompute the biggest residents instead
            self._evict(cache.plan_eviction(nbytes))
            if not self._charge(sid, nbytes):
                return False
        cache.admit(self.app_id, key, sid, nbytes, structure)
        return True

    def _charge(self, sid: int, nbytes: int) -> bool:
        if self.tenants is None:
            return True
        try:
            self.tenants.charge(self.app_id, sid, nbytes)  #: balanced by release
            return True
        except TenantQuotaExceededError:
            return False

    def _evict(self, doomed) -> None:
        for e in doomed:
            self._drop_entry(e)
            if self.cache is not None:
                self.cache.note_eviction()

    def _drop_entry(self, entry) -> None:
        """Tear one cached shuffle down: manager unregister drops every tier
        (store, ServeCache decoded blocks, encoded-chunk pool) and fires the
        teardown hook that removes the cache entry; then refund the tenant."""
        self.manager.unregister_shuffle(entry.shuffle_id)
        if self.tenants is not None:
            self.tenants.release(entry.app_id, entry.nbytes)

    # -- local per-partition compute stages ----------------------------------

    def _run_aggregate(self, st: Stage, parts) -> List[List[Row]]:
        aggs = tuple(st.param("aggs", ("sum",)))
        out: List[List[Row]] = []
        for part in _as_partitions(parts):
            if not part:
                out.append([])
                continue
            keys = np.array([r[0] for r in part], np.uint32)
            vals = np.array([[r[1]] for r in part])
            uniq, cols, _counts = oracle_aggregate(keys, vals, aggs)
            out.append([(int(k), _scalar(cols[i, 0])) for i, k in enumerate(uniq)])
        return out

    def _run_join(self, st: Stage, build_parts, probe_parts) -> List[List[Row]]:
        join_type = str(st.param("join_type", "inner"))
        b, p = _as_partitions(build_parts), _as_partitions(probe_parts)
        if len(b) != len(p):
            raise ValueError(
                f"stage {st.name!r}: join sides have {len(b)} vs {len(p)} partitions"
            )
        out: List[List[Row]] = []
        for bp, pp in zip(b, p):
            bk = np.array([r[0] for r in bp], np.uint32)
            bv = np.array([[r[1]] for r in bp]) if bp else np.zeros((0, 1), np.int64)
            pk = np.array([r[0] for r in pp], np.uint32)
            pv = np.array([[r[1]] for r in pp]) if pp else np.zeros((0, 1), np.int64)
            joined = oracle_join(bk, bv, pk, pv, join_type)
            keys, brows, prows = joined[0], joined[1], joined[2]
            out.append(
                [
                    (int(k), _scalar(brows[i, 0]), _scalar(prows[i, 0]))
                    for i, k in enumerate(keys)
                ]
            )
        return out

    def _run_sort(self, st: Stage, upstream) -> List[Row]:
        rows = _flatten(upstream)
        if not rows:
            return []
        keys = np.array([r[0] for r in rows], np.uint32)
        payload = np.array([r[1] for r in rows])
        sk, sp = oracle_sort(keys, payload)
        return [(int(k), _scalar(v)) for k, v in zip(sk, sp)]


def _as_partitions(result) -> List[List[Row]]:
    if result and not isinstance(result[0], list):
        return [list(result)]  # flat input: one logical partition
    return list(result) if result else [[]]


def _flatten(result) -> List[Row]:
    if result and isinstance(result[0], list):
        return [row for part in result for row in part]
    return list(result)


def _scalar(v):
    """Native int/float for numpy scalars (keeps rows codec-serializable)."""
    f = float(v)
    return int(f) if f.is_integer() else f
