"""Lineage-keyed cross-query shuffle reuse.

At production query volume concurrent queries repeat sub-DAGs, and the
fastest shuffle is the one never re-executed.  This module keys every sealed
shuffle by a **lineage hash** —

    sha256( canonical sub-DAG rooted at the exchange   (structure + params
                                                        + scan fingerprints)
          , canonical byte-affecting conf/plan tiers )

— so a repeated exchange is served straight from the store/eviction/serve
tiers instead of re-running the collective.

Which conf tiers enter the key is NOT a judgement call: the analyzer's
lockstep-taint registries (analysis/config.py) already split every
``ExchangePlan`` field into COLLECTIVE (SPMD-lockstep schedule) and
SERVE_PLANE (per-host serving), and the repo-wide bit-identity invariant
(tests/test_planner.py and friends) pins that pure *schedule* geometry —
quota, chunking, round order, lowering — never changes result bytes.  What
remains byte-affecting is exactly the lossy/content tiers: the wire codec,
the quantization mode/block, and fused receive-side combine.  The three
tuples below partition the plan vocabulary accordingly, derived from the
analyzer registries so the two cannot drift (tests/test_query.py pins the
partition is exact and total).

Entries are admission-controlled — a cached round keeps real HBM resident,
so it charges the owning tenant's quota like any live shuffle — and
invalidated on input-fingerprint change or ``remove_shuffle``.  Under quota
pressure the keep/recompute decision follows the restage cost model of
"Memory-efficient array redistribution through portable collective
communication" (arXiv:2112.01075), already used by service/eviction.py:
recomputing a shuffle costs roughly its footprint in staging traffic, so the
*largest*-footprint entries are recomputed (evicted) first and the cache
keeps the many small shuffles whose per-byte reuse value is highest.
"""

from __future__ import annotations

import hashlib
import json
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from sparkucx_tpu.analysis.config import COLLECTIVE_FIELDS, SERVE_PLANE_FIELDS
from sparkucx_tpu.ops.planner import canonical_plan, lineage_hash

#: Plan fields that change the exchanged BYTES: the lossy/content tiers.
#: Everything else is schedule geometry or serve-plane tuning (see module
#: docstring); tests/test_query.py cross-checks this partition against the
#: analyzer's COLLECTIVE/SERVE_PLANE registries.
BYTE_AFFECTING_PLAN_FIELDS = ("codec", "combine", "quantize_block", "quantize_mode")

#: Collective-schedule fields pinned bit-identical by the plan executor's
#: invariant: they shape WHEN/HOW bytes move, never the bytes.
SCHEDULE_ONLY_PLAN_FIELDS = tuple(
    f for f in COLLECTIVE_FIELDS if f not in BYTE_AFFECTING_PLAN_FIELDS
)

#: Serve-plane tuning that never enters a collective or the payload.
SERVE_ONLY_PLAN_FIELDS = tuple(
    f for f in SERVE_PLANE_FIELDS if f not in BYTE_AFFECTING_PLAN_FIELDS
)


def conf_byte_signature(conf) -> str:
    """Canonical serialization of the conf tiers that affect shuffle bytes,
    in the plan-field vocabulary (same keys ``plan_byte_signature`` keeps),
    so the conf-derived and plan-derived views of "what shapes the bytes"
    cannot diverge silently."""
    return json.dumps(
        {
            "codec": conf.wire_compress_codec,
            "combine": bool(conf.exchange_fused_combine),
            "quantize_block": int(conf.quantize_block_size),
            "quantize_mode": conf.quantize_mode,
        },
        sort_keys=True,
        separators=(",", ":"),
    )


def plan_byte_signature(plan) -> str:
    """The byte-affecting view of a concrete ExchangePlan: two plans
    differing only in schedule or serve-plane fields sign identically."""
    return canonical_plan(plan, BYTE_AFFECTING_PLAN_FIELDS)


def fingerprint_rows(payload: bytes) -> str:
    """Content hash of a scan's serialized rows (the input fingerprint)."""
    return hashlib.sha256(payload).hexdigest()


def lineage_key(dag, root: str, fingerprints: Dict[str, str], conf) -> str:
    """The cache key for the exchange stage ``root``: sub-DAG identity plus
    the byte-affecting conf tiers."""
    return lineage_hash(dag.canonical(root, fingerprints), conf_byte_signature(conf))


@dataclass
class CacheEntry:
    """One retained sealed shuffle."""

    app_id: str
    key: str  #: lineage hash (hex)
    shuffle_id: int
    nbytes: int  #: serialized map-output footprint charged to the tenant
    structure_sig: str  #: fingerprint-free canonical sub-DAG (staleness probe)
    hits: int = 0


class LineageCache:
    """App-namespaced lineage-key -> sealed-shuffle map with admission
    counters.  A leaf lock (no calls out under it): eviction DECISIONS are
    returned to the caller, which tears the doomed shuffles down through the
    manager (so the store/serve/encoded-chunk tiers all drop them) and then
    confirms with :meth:`invalidate_shuffle`."""

    def __init__(self, max_bytes: int = 0) -> None:
        self.max_bytes = int(max_bytes)  #: 0 = no runner-level cap
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str], CacheEntry] = {}
        self._by_sid: Dict[int, Tuple[str, str]] = {}
        self._attached: set = set()  #: id() of managers whose hook we hold
        self.hits = 0
        self.misses = 0
        self.admissions = 0
        self.invalidations = 0
        self.evictions = 0

    def attach(self, manager) -> None:
        """Subscribe to the manager's shuffle teardown exactly once, so ANY
        ``unregister_shuffle`` — ours or an external caller's — invalidates
        the entry before a stale hit can be served."""
        with self._lock:
            if id(manager) in self._attached:
                return
            self._attached.add(id(manager))
        manager.add_unregister_hook(self.invalidate_shuffle)

    def lookup(self, app_id: str, key: str) -> Optional[CacheEntry]:
        with self._lock:
            e = self._entries.get((app_id, key))
            if e is None:
                self.misses += 1
                return None
            e.hits += 1
            self.hits += 1
            return e

    def admit(
        self, app_id: str, key: str, shuffle_id: int, nbytes: int, structure_sig: str
    ) -> CacheEntry:
        e = CacheEntry(app_id, key, shuffle_id, int(nbytes), structure_sig)
        with self._lock:
            self._entries[(app_id, key)] = e
            self._by_sid[shuffle_id] = (app_id, key)
            self.admissions += 1
        return e

    def invalidate_shuffle(self, shuffle_id: int) -> Optional[CacheEntry]:
        """Drop the entry holding ``shuffle_id`` (manager unregister hook)."""
        with self._lock:
            k = self._by_sid.pop(shuffle_id, None)
            if k is None:
                return None
            e = self._entries.pop(k, None)
            if e is not None:
                self.invalidations += 1
            return e

    def stale_entries(self, app_id: str, structure_sig: str, current_key: str) -> List[CacheEntry]:
        """Entries for the same query structure whose lineage key differs —
        the input fingerprint (or a byte tier) changed, so they will never
        hit again.  The caller unregisters their shuffles (which confirms the
        invalidation through the teardown hook) and releases the tenant."""
        with self._lock:
            return [
                e
                for e in self._entries.values()
                if e.app_id == app_id and e.structure_sig == structure_sig and e.key != current_key
            ]

    def plan_eviction(self, needed: int, protect: Tuple[str, str] = ("", "")) -> List[CacheEntry]:
        """Keep/recompute decision under pressure: pick entries to recompute
        (= evict) until ``needed`` bytes free, LARGEST footprint first —
        the arXiv:2112.01075 cost model says footprint approximates restage
        cost, so per-byte the small popular entries are worth keeping.  Ties
        break toward fewer hits, then key order (determinism)."""
        with self._lock:
            candidates = [
                e for e in self._entries.values() if (e.app_id, e.key) != protect
            ]
        candidates.sort(key=lambda e: (-e.nbytes, e.hits, e.key))
        doomed: List[CacheEntry] = []
        freed = 0
        for e in candidates:
            if freed >= needed:
                break
            doomed.append(e)
            freed += e.nbytes
        return doomed

    def note_eviction(self, n: int = 1) -> None:
        with self._lock:
            self.evictions += n

    def cached_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "cache_hits": self.hits,
                "cache_misses": self.misses,
                "cache_admissions": self.admissions,
                "cache_invalidations": self.invalidations,
                "cache_evictions": self.evictions,
                "cached_entries": len(self._entries),
                "cached_bytes": sum(e.nbytes for e in self._entries.values()),
            }
