"""End-to-end query DAG runner with lineage-keyed cross-query shuffle reuse.

* :mod:`sparkucx_tpu.query.dag` — StageDag (scan/exchange/aggregate/join/sort)
  and its canonical serialization.
* :mod:`sparkucx_tpu.query.lineage` — the lineage hash and the admission-
  controlled LineageCache of sealed shuffles.
* :mod:`sparkucx_tpu.query.runner` — QueryRunner, compiling DAGs onto the
  manager SPI / ExchangePlan executor, per tenant.
"""

from sparkucx_tpu.query.dag import Stage, StageDag
from sparkucx_tpu.query.lineage import (
    BYTE_AFFECTING_PLAN_FIELDS,
    SCHEDULE_ONLY_PLAN_FIELDS,
    SERVE_ONLY_PLAN_FIELDS,
    CacheEntry,
    LineageCache,
    conf_byte_signature,
    fingerprint_rows,
    lineage_key,
    plan_byte_signature,
)
from sparkucx_tpu.query.runner import QueryRunner

__all__ = [
    "Stage",
    "StageDag",
    "LineageCache",
    "CacheEntry",
    "QueryRunner",
    "BYTE_AFFECTING_PLAN_FIELDS",
    "SCHEDULE_ONLY_PLAN_FIELDS",
    "SERVE_ONLY_PLAN_FIELDS",
    "conf_byte_signature",
    "fingerprint_rows",
    "lineage_key",
    "plan_byte_signature",
]
