"""StageDag — the minimal multi-stage query plan the runner executes.

A query is a DAG of named stages over (key, value) record streams:

* ``scan``      — a named input; rows are supplied at run time.
* ``exchange``  — hash-partition the upstream rows across the cluster through
  one real shuffle (register / write / collective superstep / windowed read).
  The only distributed stage, and the only cacheable one: its sealed output
  is what the lineage cache (query/lineage.py) can serve on a repeat.
* ``aggregate`` — per-partition grouped aggregation (``aggs`` param, default
  ``("sum",)``) over an exchange output; hash partitioning already co-located
  equal keys, so per-partition results are exact.
* ``join``      — per-partition equi-join of two inputs partitioned by the
  SAME hash exchange (build side first).
* ``sort``      — total order over the concatenated upstream rows (the
  TeraSort tail).

The canonical serialization below is the identity half of the lineage key:
two queries whose sub-DAGs rooted at an exchange canonicalize identically —
same structure, same params, same scan fingerprints — will shuffle identical
bytes (stage compute is deterministic), so the sealed shuffle of one can be
served to the other.  Determinism of the serialization (sorted keys, sorted
params, no whitespace) is load-bearing: it feeds a hash.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Tuple

STAGE_OPS = ("scan", "exchange", "aggregate", "join", "sort")

#: inputs arity per op (None = any >= 1)
_ARITY = {"scan": 0, "exchange": 1, "aggregate": 1, "join": 2, "sort": 1}


@dataclass(frozen=True)
class Stage:
    """One DAG node.  ``params`` is a sorted tuple of (key, value) pairs so
    stages hash/compare structurally and serialize deterministically."""

    name: str
    op: str
    inputs: Tuple[str, ...] = ()
    params: Tuple[Tuple[str, object], ...] = ()

    @staticmethod
    def make(name: str, op: str, inputs=(), **params) -> "Stage":
        return Stage(
            name=name,
            op=op,
            inputs=tuple(inputs),
            params=tuple(sorted(params.items())),
        )

    def param(self, key: str, default=None):
        for k, v in self.params:
            if k == key:
                return v
        return default


class StageDag:
    """Validated, ordered stage list (stages may only reference earlier
    stages, so list order is already a topological order)."""

    def __init__(self, stages: List[Stage]) -> None:
        if not stages:
            raise ValueError("empty dag")
        self.stages: Tuple[Stage, ...] = tuple(stages)
        self.by_name: Dict[str, Stage] = {}
        for st in self.stages:
            if st.op not in STAGE_OPS:
                raise ValueError(f"stage {st.name!r}: unknown op {st.op!r}")
            if st.name in self.by_name:
                raise ValueError(f"duplicate stage name {st.name!r}")
            arity = _ARITY[st.op]
            if arity is not None and len(st.inputs) != arity:
                raise ValueError(
                    f"stage {st.name!r}: op {st.op!r} takes {arity} input(s), got {len(st.inputs)}"
                )
            for dep in st.inputs:
                if dep not in self.by_name:
                    raise ValueError(
                        f"stage {st.name!r}: input {dep!r} undefined (or defined later)"
                    )
            self.by_name[st.name] = st

    @property
    def sink(self) -> Stage:
        return self.stages[-1]

    def subdag(self, root: str) -> List[Stage]:
        """The stages reachable from ``root`` (root last), in dag order."""
        st = self.by_name.get(root)
        if st is None:
            raise KeyError(f"unknown stage {root!r}")
        keep = {root}
        for s in reversed(self.stages):
            if s.name in keep:
                keep.update(s.inputs)
        return [s for s in self.stages if s.name in keep]

    def canonical(self, root: str, fingerprints: Optional[Mapping[str, str]] = None) -> str:
        """Deterministic serialization of the sub-DAG rooted at ``root``.

        ``fingerprints`` maps scan-stage names to content hashes of their
        input rows; with them the string identifies the exchange's BYTES
        (structure + params + inputs), without them it identifies only the
        STRUCTURE — the lineage cache uses the latter to spot a repeated
        query shape whose inputs changed (stale entry, must invalidate)."""
        fps = fingerprints or {}
        nodes = []
        for s in self.subdag(root):
            node = {
                "name": s.name,
                "op": s.op,
                "inputs": list(s.inputs),
                "params": [[k, v] for k, v in s.params],
            }
            if s.op == "scan" and s.name in fps:
                node["fingerprint"] = fps[s.name]
            nodes.append(node)
        return json.dumps({"root": root, "stages": nodes}, sort_keys=True, separators=(",", ":"))
